"""Public REST API over the daemon's beacon chains.

Counterpart of `http/server.go`: per-chain-hash handler registry
(`:46-74,114-155`) with routes (`:91-100`)

    GET /{chainhash}/public/{round}
    GET /{chainhash}/public/latest
    GET /{chainhash}/info
    GET /public/{round} | /public/latest | /info   (default chain)
    GET /health
    GET /chains

JSON shapes and CDN-friendly Cache-Control/Expires headers follow the
reference (`:346-460`): fixed rounds are immutable (long max-age), latest
expires at the next round boundary.
"""

from __future__ import annotations

import asyncio
import json
import time

from aiohttp import web

from drand_tpu import log as dlog
log = dlog.get("http")

# Upper bound on a latest long-poll (seconds of real time): fake-clock
# tests and pathological period configs must not pin HTTP workers.
_LATEST_WAIT_MAX = 30.0


class _LatestWatch:
    """Live `latest` subscription for one beacon process.

    The reference serves /public/latest from a client-stack watch with a
    timeout fallback to polling (`http/server.go:177-243`); re-reading
    store.last() per GET instead adds up to a period of staleness behind
    a relay.  This subscribes to the chain store's callback fan-out and
    wakes pending GETs the moment the next beacon lands.  Callbacks run
    on the CallbackStore worker pool, so the wake marshals onto the
    event loop."""

    def __init__(self, store, loop):
        self.store = store
        self.loop = loop
        self._event = asyncio.Event()
        self._cb_id = f"http-latest-{id(self)}"
        # tail callback: waiters only re-read last() on wake, so one
        # wake per COMMIT (segment tail on batched sync commits) is
        # equivalent to one per beacon — without fanning 16384 pool
        # submissions + cross-thread wakeups per sync chunk
        if hasattr(store, "add_tail_callback"):
            store.add_tail_callback(self._cb_id, self._on_beacon)
        else:
            store.add_callback(self._cb_id, self._on_beacon)

    def _on_beacon(self, beacon) -> None:
        try:
            self.loop.call_soon_threadsafe(self._fire)
        except RuntimeError:
            pass                     # loop closed during shutdown

    def _fire(self) -> None:
        ev, self._event = self._event, asyncio.Event()
        ev.set()

    def next_event(self) -> asyncio.Event:
        """The event that fires on the NEXT stored beacon (grab before
        re-checking the store to avoid the lost-wakeup race)."""
        return self._event

    def close(self) -> None:
        self.store.remove_callback(self._cb_id)


def _beacon_json(beacon) -> dict:
    out = {
        "round": beacon.round,
        "randomness": beacon.randomness().hex(),
        "signature": beacon.signature.hex(),
    }
    if beacon.previous_sig:
        out["previous_signature"] = beacon.previous_sig.hex()
    return out


class PublicHTTPServer:
    def __init__(self, daemon, listen: str):
        self.daemon = daemon
        host, _, port = listen.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self.app = web.Application()
        self.app.add_routes([
            web.get("/chains", self.handle_chains),
            web.get("/health", self.handle_health),
            web.get("/info", self.handle_info),
            web.get("/public/latest", self.handle_latest),
            web.get("/public/{round}", self.handle_round),
            web.get("/{chainhash}/info", self.handle_info),
            web.get("/{chainhash}/public/latest", self.handle_latest),
            web.get("/{chainhash}/public/{round}", self.handle_round),
        ])
        self._runner: web.AppRunner | None = None
        self._watches: dict[str, _LatestWatch] = {}

    async def start(self):
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        log.info("public HTTP API on %s:%d", self.host, self.port)

    async def stop(self):
        for w in self._watches.values():
            try:
                w.close()
            except Exception:
                pass
        self._watches.clear()
        if self._runner is not None:
            await self._runner.cleanup()

    def _watch(self, bp) -> _LatestWatch:
        """Get-or-create the live watch for a process; a reshare swaps
        the engine (and its store), so re-subscribe when the store
        changed."""
        w = self._watches.get(bp.beacon_id)
        if w is None or w.store is not bp._store:
            if w is not None:
                try:
                    w.close()
                except Exception:
                    pass
            w = _LatestWatch(bp._store, asyncio.get_event_loop())
            self._watches[bp.beacon_id] = w
        return w

    # -- chain resolution ---------------------------------------------------

    def _chain(self, request):
        ch = request.match_info.get("chainhash")
        if ch:
            bid = self.daemon.chain_hashes.get(ch)
            if bid is None:
                raise web.HTTPNotFound(text=f"unknown chain hash {ch}")
        else:
            bid = "default"
        bp = self.daemon.processes.get(bid)
        if bp is None or bp.group is None:
            raise web.HTTPNotFound(text=f"no chain for beacon id {bid}")
        return bp

    # -- handlers -----------------------------------------------------------

    async def handle_chains(self, request):
        return web.json_response(sorted(self.daemon.chain_hashes.keys()))

    async def handle_info(self, request):
        bp = self._chain(request)
        info = bp.chain_info()
        return web.Response(body=info.to_json(),
                            content_type="application/json",
                            headers={"Cache-Control": "max-age=604800"})

    async def handle_round(self, request):
        bp = self._chain(request)
        try:
            round_ = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        try:
            # sqlite read OFF the event loop (VERDICT r4 weak #7): a deep
            # /public/{round} scrape must not contend with the protocol
            # loop; the store stack is thread-safe (thread-local conns)
            beacon = await asyncio.to_thread(bp._store.get, round_)
        except Exception:
            raise web.HTTPNotFound(text=f"round {round_} not available")
        # fixed rounds never change: cache aggressively (server.go:346-460)
        return web.json_response(
            _beacon_json(beacon),
            headers={"Cache-Control": "public, max-age=31536000, immutable"})

    async def handle_latest(self, request):
        bp = self._chain(request)
        group = bp.group
        from drand_tpu.chain.time import current_round
        watch = self._watch(bp)
        ev = watch.next_event()      # grab BEFORE reading (no lost wakeup)
        try:
            beacon = await asyncio.to_thread(bp._store.last)
        except Exception:
            beacon = None
        expected = current_round(self.daemon.config.clock.now(),
                                 group.period, group.genesis_time)
        if beacon is None or beacon.round < expected:
            # The current round is pending: long-poll the store watch so
            # the response carries the NEW beacon the moment it lands,
            # with a timeout fallback to whatever the store has
            # (http/server.go:177-243).  LOOP on the event (ADVICE r4):
            # any stored beacon wakes it — including catch-up/repair
            # commits at or below the head we already saw, which must NOT
            # end the poll early.  Resolve on genuine progress (a round
            # past the head seen at GET time — the reference's
            # serve-the-freshest watch behavior) or on reaching the
            # expected round; otherwise keep polling until the deadline.
            start_head = beacon.round if beacon is not None else 0
            loop = asyncio.get_event_loop()
            deadline = loop.time() + min(float(group.period),
                                         _LATEST_WAIT_MAX)
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    break
                ev = watch.next_event()   # re-arm BEFORE reading
                try:
                    beacon = await asyncio.to_thread(bp._store.last)
                except Exception:
                    beacon = None
                if beacon is not None and (beacon.round >= expected
                                           or beacon.round > start_head):
                    break
            if beacon is None or beacon.round < expected:
                try:
                    beacon = await asyncio.to_thread(bp._store.last)
                except Exception:
                    beacon = None
        if beacon is None:
            raise web.HTTPNotFound(text="no beacon yet")
        from drand_tpu.chain.time import time_of_round
        next_t = time_of_round(group.period, group.genesis_time,
                               beacon.round + 1)
        max_age = max(int(next_t - self.daemon.config.clock.now()), 0)
        return web.json_response(
            _beacon_json(beacon),
            headers={"Cache-Control": f"public, max-age={max_age}",
                     "Expires": time.strftime(
                         "%a, %d %b %Y %H:%M:%S GMT",
                         time.gmtime(next_t))})

    async def handle_health(self, request):
        """Expected vs actual round (server.go:491-535): 200 with
        `{current, expected}` while the stored tip is within one round
        of what the clock says should exist, 503 Service Unavailable
        when behind (the reference's StatusServiceUnavailable).  Reads
        the ChainStore tip cache — a health probe must not contend with
        the protocol loop on a sqlite read — and refreshes
        `drand_beacon_lag_rounds` as a side effect (health/model.py)."""
        from drand_tpu.health import check_process
        try:
            bp = self._chain(request)
        except web.HTTPNotFound:
            return web.json_response({"current": 0, "expected": 0},
                                     status=503)
        st = check_process(bp, self.daemon.config.clock)
        if st is None:
            return web.json_response({"current": 0, "expected": 0},
                                     status=503)
        return web.json_response(st.to_dict(),
                                 status=200 if st.healthy else 503)
