"""Public REST API over the daemon's beacon chains.

Counterpart of `http/server.go`: per-chain-hash handler registry
(`:46-74,114-155`) with routes (`:91-100`)

    GET /{chainhash}/public/{round}
    GET /{chainhash}/public/latest
    GET /{chainhash}/info
    GET /public/{round} | /public/latest | /info   (default chain)
    GET /health
    GET /chains

JSON shapes and CDN-friendly Cache-Control/Expires headers follow the
reference (`:346-460`): fixed rounds are immutable (long max-age), latest
expires at the next round boundary.

Hot paths ride the encode-once fast lane (http/response_cache.py,
ISSUE 14): each process's committed beacons are encoded ONCE on the
committing thread into body bytes + strong ETag, so steady-state
`/public/latest` is admission slot → memory read → response — zero
store reads, zero thread hops, zero encodes — with ``If-None-Match`` →
304 for polling edges.  Cold fixed rounds take ONE stampede-guarded
store read into a bounded LRU; `/info` and `/chains` serve cached
bodies invalidated on reshare / chain-set change.  ``X-Drand-Cache:
hit|miss|bypass`` reports the lane per response;
``DRAND_TPU_SERVE_CACHE=0`` bypasses it (the bench A/B lever).

Every public route runs behind the admission stage
(drand_tpu/resilience/admission.py): bounded handler concurrency plus a
bounded pending queue, shed as 503 + ``Retry-After`` past the bounds.
`/health` rides its own priority lane — a load balancer's probe never
queues behind randomness traffic, so an overloaded-but-live node keeps
answering 200 while it sheds.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from drand_tpu import log as dlog
from drand_tpu.http import response_cache as rc
from drand_tpu.resilience import admission
from drand_tpu.resilience.admission import AdmissionController, \
    AdmissionShedError

log = dlog.get("http")

# Upper bound on a latest long-poll (seconds of real time): fake-clock
# tests and pathological period configs must not pin HTTP workers.
_LATEST_WAIT_MAX = 30.0

# Upper bound on /public/rounds batch size: one sealed objectsync
# segment (the verify throughput bucket) — larger asks re-slice client
# side, same ceiling as the gRPC wire's SYNC_CHUNK_MAX.
_ROUNDS_COUNT_MAX = 16384


def _parse_byte_range(header: str, size: int):
    """One ``bytes=a-b`` / ``bytes=a-`` / ``bytes=-n`` range ->
    inclusive (lo, hi), or None when unsatisfiable/malformed (multipart
    ranges are not worth serving for resumable segment fetches)."""
    if not header.startswith("bytes=") or "," in header:
        return None
    spec = header[len("bytes="):].strip()
    lo_s, sep, hi_s = spec.partition("-")
    if not sep:
        return None
    try:
        if not lo_s:                      # suffix form: last n bytes
            n = int(hi_s)
            if n <= 0:
                return None
            return max(size - n, 0), size - 1
        lo = int(lo_s)
        hi = int(hi_s) if hi_s else size - 1
    except ValueError:
        return None
    if lo >= size or hi < lo:
        return None
    return lo, min(hi, size - 1)


def _limits_from_env():
    """Operator tuning for daemons started via the CLI (no constructor
    seam): ``DRAND_SERVE_CONCURRENCY`` / ``DRAND_SERVE_QUEUE`` size the
    public lane; unset keeps the ClassLimits defaults."""
    import os
    from drand_tpu.resilience.admission import ClassLimits
    c = os.environ.get("DRAND_SERVE_CONCURRENCY", "")
    q = os.environ.get("DRAND_SERVE_QUEUE", "")
    if not c and not q:
        return None
    base = ClassLimits()
    return {admission.PUBLIC: ClassLimits(
        max_concurrency=int(c or base.max_concurrency),
        max_queue=int(q or base.max_queue))}


def shed_response(exc: AdmissionShedError) -> web.Response:
    """503 + Retry-After (whole seconds, floored at 1): the overload
    contract clients and relays close the loop on
    (resilience.RetryPolicy honors the hint, capped at its deadline)."""
    return web.Response(
        status=503, text=f"overloaded ({exc.reason}), retry later",
        headers={"Retry-After": str(max(int(round(exc.retry_after_s)), 1))})


class _WatchSub:
    """One client's live `latest` subscription: a single-slot pending
    buffer (drop-oldest-keep-latest — only the freshest beacon matters)
    plus its wake event.  Per-client memory is O(1) no matter how far
    the client falls behind the chain."""

    __slots__ = ("pending", "event")

    def __init__(self):
        self.pending: int | None = None     # freshest unconsumed round
        self.event = asyncio.Event()

    async def wait(self, timeout: float) -> bool:
        """True when a beacon notification is pending within `timeout`."""
        if self.pending is None:
            try:
                await asyncio.wait_for(self.event.wait(), timeout)
            except asyncio.TimeoutError:
                return False
        return self.pending is not None

    def take(self) -> int | None:
        r, self.pending = self.pending, None
        self.event.clear()
        return r


class _LatestWatch:
    """Live `latest` fan-out for one beacon process.

    The reference serves /public/latest from a client-stack watch with a
    timeout fallback to polling (`http/server.go:177-243`); re-reading
    store.last() per GET instead adds up to a period of staleness behind
    a relay.  This subscribes ONCE to the chain store's callback fan-out
    and wakes every pending GET's subscription the moment the next
    beacon lands.  Callbacks run on the CallbackStore worker pool, so
    the wake marshals onto the event loop — one marshal per commit, then
    a loop-side fan-out to the per-client single-slot buffers (an
    overwritten unconsumed slot counts into
    ``drand_queue_dropped_total{queue="watch_fanout"}``)."""

    def __init__(self, store, loop):
        self.store = store
        self.loop = loop
        self._subs: set[_WatchSub] = set()
        self._cb_id = f"http-latest-{id(self)}"
        # tail callback: waiters only re-read last() on wake, so one
        # wake per COMMIT (segment tail on batched sync commits) is
        # equivalent to one per beacon — without fanning 16384 pool
        # submissions + cross-thread wakeups per sync chunk
        if hasattr(store, "add_tail_callback"):
            store.add_tail_callback(self._cb_id, self._on_beacon)
        else:
            store.add_callback(self._cb_id, self._on_beacon)

    def _on_beacon(self, beacon) -> None:
        try:
            self.loop.call_soon_threadsafe(self._fire, beacon.round)
        except RuntimeError:
            pass                     # loop closed during shutdown

    def _fire(self, round_: int) -> None:
        dropped = 0
        for sub in self._subs:
            if sub.pending is not None:
                dropped += 1         # overwritten: drop-oldest-keep-latest
            sub.pending = round_
            sub.event.set()
        if dropped:
            try:
                from drand_tpu import metrics as M
                M.QUEUE_DROPPED.labels("watch_fanout").inc(dropped)
            except Exception:
                pass

    def subscribe(self) -> _WatchSub:
        """Subscribe BEFORE reading the store (no lost wakeup)."""
        sub = _WatchSub()
        self._subs.add(sub)
        return sub

    def unsubscribe(self, sub: _WatchSub) -> None:
        self._subs.discard(sub)

    def subscriber_count(self) -> int:
        return len(self._subs)

    def close(self) -> None:
        self.store.remove_callback(self._cb_id)
        self._subs.clear()


def _beacon_json(beacon) -> dict:
    # the one beacon JSON shape, shared with the encode-once cache so
    # cached bytes are bit-identical to a fresh encode by construction
    return rc.beacon_fields(beacon.round, beacon.randomness(),
                            beacon.signature, beacon.previous_sig)


class PublicHTTPServer:
    def __init__(self, daemon, listen: str, admission_limits=None):
        self.daemon = daemon
        host, _, port = listen.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)  # owner: server start (rebound once to the bound port)
        if admission_limits is None:
            admission_limits = _limits_from_env()
        self.admission = AdmissionController(admission_limits)
        self.app = web.Application()
        self.app.add_routes([
            web.get("/chains", self.handle_chains),
            web.get("/health", self.handle_health),
            web.get("/info", self.handle_info),
            web.get("/public/latest", self.handle_latest),
            # /public/rounds must register BEFORE /public/{round}, or
            # aiohttp matches "rounds" into the {round} pattern
            web.get("/public/rounds", self.handle_rounds),
            web.get("/public/{round}", self.handle_round),
            web.get("/{chainhash}/info", self.handle_info),
            web.get("/{chainhash}/public/latest", self.handle_latest),
            web.get("/{chainhash}/public/rounds", self.handle_rounds),
            web.get("/{chainhash}/public/{round}", self.handle_round),
        ])
        self._runner: web.AppRunner | None = None
        self._watches: dict[str, _LatestWatch] = {}
        # encode-once fast lane (ISSUE 14): checked once at construction
        # so a bench A/B flips the env var between server instances
        self._cache_on = rc.cache_enabled()
        # /chains body, keyed on the daemon's chain-set version counter
        self._chains_cache: "tuple[int, rc.EncodedBody] | None" = None

    async def start(self):
        # handler_cancellation: a client dropping a long-poll must
        # cancel its handler NOW (unsubscribing its watch slot and
        # freeing its admission slot) — aiohttp's default lets the
        # abandoned handler run to timeout, which under watch fan-out
        # is a slow leak of exactly the bounded resources the
        # admission stage protects
        self._runner = web.AppRunner(self.app, handler_cancellation=True)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        log.info("public HTTP API on %s:%d", self.host, self.port)

    async def stop(self):
        for w in self._watches.values():
            try:
                w.close()
            except Exception:
                pass
        self._watches.clear()
        if self._runner is not None:
            await self._runner.cleanup()

    def _watch(self, bp) -> _LatestWatch:
        """Get-or-create the live watch for a process; a reshare swaps
        the engine (and its store), so re-subscribe when the store
        changed."""
        w = self._watches.get(bp.beacon_id)
        if w is None or w.store is not bp._store:
            if w is not None:
                try:
                    w.close()
                except Exception:
                    pass
            w = _LatestWatch(bp._store, asyncio.get_running_loop())
            self._watches[bp.beacon_id] = w
        return w

    # -- chain resolution ---------------------------------------------------

    def _chain(self, request):
        ch = request.match_info.get("chainhash")
        if ch:
            bid = self.daemon.chain_hashes.get(ch)
            if bid is None:
                raise web.HTTPNotFound(text=f"unknown chain hash {ch}")
        else:
            bid = "default"
        bp = self.daemon.processes.get(bid)
        if bp is None or bp.group is None:
            raise web.HTTPNotFound(text=f"no chain for beacon id {bid}")
        return bp

    # -- encode-once fast lane (ISSUE 14) -----------------------------------

    def _cache(self, bp) -> "rc.ResponseCache | None":
        """The process's response cache, or None when the fast lane is
        bypassed (env gate off, or a process without one — stub daemons
        in tests): every such request serves the legacy path and counts
        as event="bypass"."""
        if not self._cache_on:
            return None
        return getattr(bp, "response_cache", None)

    def _respond(self, request, enc: "rc.EncodedBody", headers: dict,
                 route: str, event: str) -> web.Response:
        if route in ("round", "latest") and enc.round is not None:
            # round-journey "first served byte" hop: one dict probe per
            # request, and only the FIRST serve of a round records
            # (profiling/journey) — the fast lane stays read-only
            try:
                from drand_tpu.profiling import journey
                journey.note_serve(self._chain(request).beacon_id,
                                   enc.round)
            except Exception:
                pass
        return rc.respond(request, enc, headers, route, event)

    def _latest_headers(self, group, round_: int) -> dict:
        """CDN headers for a mutable `latest` answer: fresh until the
        next round boundary.  ``max-age`` and ``Expires`` derive from
        the SAME reading of the injected clock seam, so the pair cannot
        disagree when that clock skews from wall time — a fake-clock
        test pins both deterministically."""
        from drand_tpu.chain.time import time_of_round
        next_t = time_of_round(group.period, group.genesis_time, round_ + 1)
        now = self.daemon.config.clock.now()
        max_age = max(int(next_t - now), 0)
        return {"Cache-Control": f"public, max-age={max_age}",
                "Expires": rc.http_date(now + max_age)}

    async def _read_latest(self, bp, cache) -> "rc.EncodedBody | None":
        """Freshest encoded beacon: the shared cache body when the
        commit fan-out already populated it, else ONE counted store
        read (off the loop) that re-warms the cache."""
        if cache is not None:
            enc = cache.latest()
            if enc is not None:
                return enc
        try:
            from drand_tpu import metrics as M
            M.SERVE_STORE_READS.labels("latest").inc()
        except Exception:
            pass
        try:
            beacon = await asyncio.to_thread(bp._store.last)
        except Exception:
            return None
        enc = rc.encode_beacon(beacon)
        if cache is not None:
            cache.note_encoded(enc)
        return enc

    # -- handlers -----------------------------------------------------------

    async def handle_chains(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "chains"):
                # small fix (ISSUE 14): don't re-sort + re-encode the
                # chain-hash set per request — serve a body keyed on the
                # daemon's chain-set version (bumped on add/remove)
                version = getattr(self.daemon, "chains_version", None)
                if not self._cache_on or version is None:
                    enc = rc.EncodedBody(rc.encode_json(
                        sorted(self.daemon.chain_hashes.keys())))
                    return self._respond(request, enc, {}, "chains",
                                         "bypass")
                cached = self._chains_cache
                if cached is not None and cached[0] == version:
                    return self._respond(request, cached[1], {}, "chains",
                                         "hit")
                enc = rc.EncodedBody(rc.encode_json(
                    sorted(self.daemon.chain_hashes.keys())))
                self._chains_cache = (version, enc)
                return self._respond(request, enc, {}, "chains", "miss")
        except AdmissionShedError as exc:
            return shed_response(exc)

    async def handle_info(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "info"):
                bp = self._chain(request)
                info = bp.chain_info()
                headers = {"Cache-Control": "max-age=604800"}
                cache = self._cache(bp)
                if cache is None:
                    return self._respond(request, rc.EncodedBody(
                        info.to_json()), headers, "info", "bypass")
                enc, event = cache.info_body(info.to_json)
                return self._respond(request, enc, headers, "info", event)
        except AdmissionShedError as exc:
            return shed_response(exc)

    async def handle_round(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "round"):
                return await self._serve_round(request)
        except AdmissionShedError as exc:
            return shed_response(exc)

    async def _serve_round(self, request):
        bp = self._chain(request)
        try:
            round_ = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        # fixed rounds never change: cache aggressively (server.go:346-460)
        headers = {"Cache-Control": "public, max-age=31536000, immutable"}
        cache = self._cache(bp)

        async def load() -> "rc.EncodedBody | None":
            try:
                from drand_tpu import metrics as M
                M.SERVE_STORE_READS.labels("round").inc()
            except Exception:
                pass
            try:
                # sqlite read OFF the event loop (VERDICT r4 weak #7): a
                # deep /public/{round} scrape must not contend with the
                # protocol loop; the store stack is thread-safe
                # (thread-local conns)
                beacon = await asyncio.to_thread(bp._store.get, round_)
            except Exception:
                return None
            return rc.encode_beacon(beacon)

        if cache is None:
            enc = await load()
            event = "bypass"
        else:
            # cold rounds stampede-guard onto ONE store read: N
            # concurrent misses for the same round coalesce on the
            # in-flight load (the LRU serves everyone after)
            enc, event = await cache.get_or_load_round(round_, load)
        if enc is None:
            raise web.HTTPNotFound(text=f"round {round_} not available")
        return self._respond(request, enc, headers, "round", event)

    async def handle_rounds(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "rounds"):
                return await self._serve_rounds(request)
        except AdmissionShedError as exc:
            return shed_response(exc)

    async def _serve_rounds(self, request):
        """Batched range read (ISSUE 18): ``?start=&count=`` served as
        the SAME length-prefixed codec-row bytes the objectsync segment
        objects carry (drand_tpu/objectsync/format.py), straight off
        ``read_fields`` — no Beacon materialization, no JSON.  Strong
        ETag + If-None-Match and single-range ``Range: bytes=`` support
        make the identical bytes cacheable and resumable at any edge;
        a fully-satisfied sealed range is immutable (its content can
        never change), a short read at the tip is not."""
        bp = self._chain(request)
        try:
            start = int(request.query["start"])
            count = int(request.query["count"])
        except (KeyError, ValueError):
            raise web.HTTPBadRequest(
                text="start and count integer query params required")
        if start < 0 or count < 1 or count > _ROUNDS_COUNT_MAX:
            raise web.HTTPBadRequest(
                text=f"need start >= 0 and 1 <= count <= "
                     f"{_ROUNDS_COUNT_MAX}")
        try:
            from drand_tpu import metrics as M
            M.SERVE_STORE_READS.labels("rounds").inc()
        except Exception:
            pass

        def load():
            from drand_tpu.chain.store import StoreError
            try:
                return bp._store.read_fields(start, count)
            except StoreError as exc:
                # damaged local row: serve the good prefix below it —
                # same contract as serve_sync_chain on the gRPC wire
                bad = getattr(exc, "round", None)
                if bad is not None and bad > start:
                    try:
                        return bp._store.read_fields(start, bad - start)
                    except StoreError:
                        return []
                return []

        # sqlite read OFF the event loop, same as _serve_round
        rows = await asyncio.to_thread(load)
        if not rows:
            raise web.HTTPNotFound(
                text=f"no rounds available from {start}")
        from drand_tpu.objectsync import format as ofmt
        body = ofmt.encode_rows(rows)
        etag = rc.etag_for(body)
        sealed = (len(rows) == count and rows[0][0] == start
                  and rows[-1][0] == start + count - 1)
        headers = {
            "ETag": etag,
            "Accept-Ranges": "bytes",
            "Cache-Control": "public, max-age=31536000, immutable"
            if sealed else "public, max-age=1",
            "X-Drand-Rounds": f"{rows[0][0]}-{rows[-1][0]}",
        }
        if rc.etag_matches(request.headers.get("If-None-Match", ""), etag):
            return web.Response(status=304, headers=headers)
        rng = request.headers.get("Range", "")
        if rng:
            # If-Range: only honor the range against the entity it was
            # measured on; a changed body serves the full 200
            if_range = request.headers.get("If-Range", "")
            if not if_range or if_range == etag:
                span = _parse_byte_range(rng, len(body))
                if span is None:
                    return web.Response(
                        status=416, headers={
                            "Content-Range": f"bytes */{len(body)}",
                            "ETag": etag})
                lo, hi = span
                headers["Content-Range"] = f"bytes {lo}-{hi}/{len(body)}"
                return web.Response(
                    status=206, body=body[lo:hi + 1], headers=headers,
                    content_type="application/octet-stream")
        return web.Response(body=body, headers=headers,
                            content_type="application/octet-stream")

    async def handle_latest(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "latest"):
                return await self._serve_latest(request)
        except AdmissionShedError as exc:
            return shed_response(exc)

    async def _serve_latest(self, request):
        bp = self._chain(request)
        group = bp.group
        from drand_tpu.chain.time import current_round
        cache = self._cache(bp)
        expected = current_round(self.daemon.config.clock.now(),
                                 group.period, group.genesis_time)
        if cache is not None:
            # steady-state fast lane: the commit fan-out already encoded
            # this body — admission slot → memory read → response, zero
            # store reads, zero thread hops, zero encodes
            enc = cache.latest()
            if enc is not None and enc.round >= expected:
                return self._respond(request, enc,
                                     self._latest_headers(group, enc.round),
                                     "latest", "hit")
        watch = self._watch(bp)
        sub = watch.subscribe()      # subscribe BEFORE reading (no lost
        try:                         # wakeup); always unsubscribed below
            enc = await self._read_latest(bp, cache)
            if enc is None or enc.round < expected:
                # The current round is pending: long-poll the store watch
                # so the response carries the NEW beacon the moment it
                # lands, with a timeout fallback to whatever the store has
                # (http/server.go:177-243).  LOOP on the subscription
                # (ADVICE r4): any stored beacon wakes it — including
                # catch-up/repair commits at or below the head we already
                # saw, which must NOT end the poll early.  Resolve on
                # genuine progress (a round past the head seen at GET time
                # — the reference's serve-the-freshest watch behavior) or
                # on reaching the expected round; otherwise keep polling
                # until the deadline.  On wake, every pending watcher
                # reads the ONE shared encoded body the commit produced —
                # 150 woken long-polls are 150 memory reads, not 150
                # store reads + encodes.
                start_head = enc.round if enc is not None else 0
                loop = asyncio.get_running_loop()
                deadline = loop.time() + min(float(group.period),
                                             _LATEST_WAIT_MAX)
                while True:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    if not await sub.wait(remaining):
                        break
                    sub.take()       # consume BEFORE reading (re-arm)
                    got = await self._read_latest(bp, cache)
                    if got is not None:
                        enc = got
                        if enc.round >= expected or enc.round > start_head:
                            break
                if enc is None or enc.round < expected:
                    got = await self._read_latest(bp, cache)
                    if got is not None:
                        enc = got
        finally:
            watch.unsubscribe(sub)
        if enc is None:
            raise web.HTTPNotFound(text="no beacon yet")
        return self._respond(request, enc,
                             self._latest_headers(group, enc.round),
                             "latest", "miss" if cache is not None
                             else "bypass")

    async def handle_health(self, request):
        """Expected vs actual round (server.go:491-535): 200 with
        `{current, expected}` while the stored tip is within one round
        of what the clock says should exist, 503 Service Unavailable
        when behind (the reference's StatusServiceUnavailable).  Reads
        the ChainStore tip cache — a health probe must not contend with
        the protocol loop on a sqlite read — and refreshes
        `drand_beacon_lag_rounds` as a side effect (health/model.py).
        Runs in the PROBE admission lane: its own concurrency bound, no
        shared queue — public overload cannot make this probe flap."""
        from drand_tpu.health import check_process
        try:
            async with self.admission.slot(admission.PROBE, "health"):
                try:
                    bp = self._chain(request)
                except web.HTTPNotFound:
                    return web.json_response({"current": 0, "expected": 0},
                                             status=503)
                st = check_process(bp, self.daemon.config.clock)
                if st is None:
                    return web.json_response({"current": 0, "expected": 0},
                                             status=503)
                return web.json_response(st.to_dict(),
                                         status=200 if st.healthy else 503)
        except AdmissionShedError as exc:
            return shed_response(exc)
