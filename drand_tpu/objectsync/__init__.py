"""Content-addressed packed-segment sync over dumb object storage
(ISSUE 18; supersedes the per-round JSON uploads of `cmd/relay-s3`,
SURVEY layer 9).

The chain as static objects: immutable 16k-round segment objects named
by content hash plus one small mutable ``manifest.json`` — publishable
to a directory, an S3-compatible endpoint, or anything a CDN can front.
Clients verify everything locally against their own anchor, so the
storage tier is fully untrusted.

  format.py     object layout + manifest (the wire/at-rest contract)
  backends.py   ObjectStore seam: filesystem, plain-HTTP, legacy adapter
  publisher.py  daemon-side sealed-segment publisher
  client.py     verify-then-commit sync client
"""

from drand_tpu.objectsync.backends import (FilesystemBackend, HTTPBackend,
                                           ObjectNotFound, ObjectStore,
                                           ObjectStoreError, SyncAdapter,
                                           as_object_store)
from drand_tpu.objectsync.client import (CorruptObjectError,
                                         ObjectSyncClient, ObjectSyncError,
                                         SyncResult)
from drand_tpu.objectsync.format import (DEFAULT_SEGMENT_ROUNDS,
                                         MANIFEST_NAME, Manifest,
                                         ManifestEntry, ObjectFormatError,
                                         Segment, content_hash,
                                         decode_rows, decode_segment,
                                         encode_rows, encode_segment,
                                         object_name)
from drand_tpu.objectsync.publisher import ObjectPublisher, PublisherError

__all__ = [
    "FilesystemBackend", "HTTPBackend", "ObjectNotFound", "ObjectStore",
    "ObjectStoreError", "SyncAdapter", "as_object_store",
    "CorruptObjectError", "ObjectSyncClient", "ObjectSyncError",
    "SyncResult", "DEFAULT_SEGMENT_ROUNDS", "MANIFEST_NAME", "Manifest",
    "ManifestEntry", "ObjectFormatError", "Segment", "content_hash",
    "decode_rows", "decode_segment", "encode_rows", "encode_segment",
    "object_name", "ObjectPublisher", "PublisherError",
]
