"""Object layout for CDN-distributable chain sync (ISSUE 18).

The chain is published as IMMUTABLE, content-addressed segment objects
plus exactly one small mutable ``manifest.json``.  A segment object
carries a contiguous run of store rows (16384 by default — the verify
throughput bucket) in the versioned row codec (drand_tpu/chain/codec.py),
named by the sha256 of its own bytes:

    segments/{start:012d}-{hash}.drs

Immutability is what makes the layout safe behind any dumb object store
or CDN: a segment object can be cached forever (its name commits to its
content), and only the manifest — chain identity, segment size, tip,
and the published-segment index — needs a short TTL.  Nothing here is
trusted by consumers: the client re-verifies every row cryptographically
against its OWN chain anchor (client.py), so a poisoned cache or a lying
origin fails verification instead of poisoning a store.

Layout v1 (all little-endian):

    magic b"DOS1" | u16 version | u16 row_codec | u64 start_round
    | u32 count | u16 chain_hash_len | u16 scheme_len
    | chain_hash | scheme_id
    | count x (u32 row_len | row)

Rows are individually length-prefixed and decoded through the store
codec's sniff-byte dispatch, so binary-v1 and legacy JSON rows can ride
the same object layout (mixed codec-version objects decode fine — the
``row_codec`` header field records the writer, it does not gate reads).
"""

from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass, field

from drand_tpu.chain import codec as row_codec

MAGIC = b"DOS1"
OBJECT_VERSION = 1
ROW_CODEC_BINARY = 1
ROW_CODEC_JSON = 2

# header: magic, version, row_codec, start_round, count, hash_len, scheme_len
_HDR = struct.Struct("<4sHHQIHH")
_ROW_LEN = struct.Struct("<I")

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1
DEFAULT_SEGMENT_ROUNDS = 16384
NAME_TEMPLATE = "segments/{start:012d}-{hash}.drs"


class ObjectFormatError(ValueError):
    """An object that is not a valid segment/manifest — truncated,
    bit-rotted, wrong chain, or internally inconsistent."""


def content_hash(data: bytes) -> str:
    """The content address: sha256 over the FULL object bytes (header
    included), hex.  Stable across processes and platforms — the object
    name commits to every byte served."""
    return hashlib.sha256(data).hexdigest()


def object_name(start_round: int, hash_hex: str,
                template: str = NAME_TEMPLATE) -> str:
    return template.format(start=start_round, hash=hash_hex)


def _encode_row(round_: int, sig: bytes, prev: bytes, codec: str) -> bytes:
    if codec == "json":
        from drand_tpu.chain.beacon import Beacon
        return Beacon(round=round_, signature=sig,
                      previous_sig=prev).to_json()
    return row_codec.encode_fields(round_, sig, prev)


def encode_rows(rows: list[tuple[int, bytes, bytes]],
                codec: str = "binary") -> bytes:
    """Length-prefixed codec rows — the shared body format of segment
    objects AND the ``/public/rounds`` HTTP range route, so edge caches
    hold one byte representation of a round range, not two."""
    out = []
    for (r, sig, prev) in rows:
        blob = _encode_row(r, sig, prev, codec)
        out.append(_ROW_LEN.pack(len(blob)))
        out.append(blob)
    return b"".join(out)


def decode_rows(data: bytes, offset: int = 0,
                count: int | None = None) -> list[tuple[int, bytes, bytes]]:
    """Parse a length-prefixed row stream -> (round, sig, prev) tuples.
    ``count=None`` reads to the end of ``data``; any truncation or codec
    failure raises ObjectFormatError (a damaged object must fail loudly,
    never yield a short silent prefix)."""
    rows: list[tuple[int, bytes, bytes]] = []
    n = len(data)
    while offset < n and (count is None or len(rows) < count):
        if offset + _ROW_LEN.size > n:
            raise ObjectFormatError(
                f"row length prefix truncated at byte {offset}")
        (row_len,) = _ROW_LEN.unpack_from(data, offset)
        offset += _ROW_LEN.size
        if offset + row_len > n:
            raise ObjectFormatError(
                f"row truncated: declared {row_len} bytes, "
                f"{n - offset} remain")
        try:
            rows.append(row_codec.decode_fields(data[offset:offset + row_len]))
        except row_codec.CodecError as exc:
            raise ObjectFormatError(f"bad row at byte {offset}: {exc}") \
                from exc
        offset += row_len
    if count is not None and len(rows) != count:
        raise ObjectFormatError(
            f"object carries {len(rows)} rows, header declares {count}")
    return rows


@dataclass
class Segment:
    """A decoded segment object."""
    chain_hash: bytes
    scheme_id: str
    start_round: int
    rows: list[tuple[int, bytes, bytes]]
    row_codec_id: int = ROW_CODEC_BINARY

    @property
    def count(self) -> int:
        return len(self.rows)

    @property
    def end_round(self) -> int:
        return self.start_round + len(self.rows) - 1


def encode_segment(chain_hash: bytes, scheme_id: str,
                   rows: list[tuple[int, bytes, bytes]],
                   codec: str = "binary") -> bytes:
    """Serialize one sealed segment.  Rows must be a contiguous,
    ascending run — the layout commits to [start, start+count) and a gap
    would let a range lie about what it covers."""
    if not rows:
        raise ObjectFormatError("empty segment")
    start = rows[0][0]
    for i, (r, _, _) in enumerate(rows):
        if r != start + i:
            raise ObjectFormatError(
                f"non-contiguous rows: round {r} at index {i} "
                f"(expected {start + i})")
    scheme = scheme_id.encode()
    codec_id = ROW_CODEC_JSON if codec == "json" else ROW_CODEC_BINARY
    hdr = _HDR.pack(MAGIC, OBJECT_VERSION, codec_id, start, len(rows),
                    len(chain_hash), len(scheme))
    return hdr + chain_hash + scheme + encode_rows(rows, codec=codec)


def decode_segment(data: bytes) -> Segment:
    """Parse + structurally validate one segment object.  This is the
    cheap integrity layer (magic, declared lengths, round contiguity);
    cryptographic trust comes ONLY from the client's own verify pass."""
    if len(data) < _HDR.size:
        raise ObjectFormatError(f"object truncated at {len(data)} bytes")
    magic, version, codec_id, start, count, hash_len, scheme_len = \
        _HDR.unpack_from(data)
    if magic != MAGIC:
        raise ObjectFormatError(f"bad magic {magic!r}")
    if version != OBJECT_VERSION:
        raise ObjectFormatError(f"unsupported object version {version}")
    off = _HDR.size
    if len(data) < off + hash_len + scheme_len:
        raise ObjectFormatError("header fields truncated")
    chain_hash = data[off:off + hash_len]
    off += hash_len
    scheme_id = data[off:off + scheme_len].decode()
    off += scheme_len
    rows = decode_rows(data, offset=off, count=count)
    for i, (r, _, _) in enumerate(rows):
        if r != start + i:
            raise ObjectFormatError(
                f"row {i} decodes to round {r}, header declares "
                f"{start + i}")
    return Segment(chain_hash=chain_hash, scheme_id=scheme_id,
                   start_round=start, rows=rows, row_codec_id=codec_id)


@dataclass
class ManifestEntry:
    start: int
    count: int
    hash: str        # content hash (hex) — doubles as the name component
    name: str

    @property
    def end(self) -> int:
        return self.start + self.count - 1

    def to_dict(self) -> dict:
        return {"start": self.start, "count": self.count,
                "hash": self.hash, "name": self.name}


@dataclass
class Manifest:
    """The ONE mutable object.  Everything a cold client needs to plan a
    sync: chain identity, segment size, published tip, and the ordered
    segment index (content hashes included, so a CDN serving a stale
    segment body under a fresh name is caught before decode)."""
    chain_hash: str                 # hex
    scheme_id: str
    segment_rounds: int = DEFAULT_SEGMENT_ROUNDS
    tip: int = 0                    # last round covered by a segment
    template: str = NAME_TEMPLATE
    segments: list[ManifestEntry] = field(default_factory=list)
    version: int = MANIFEST_VERSION

    def validate(self) -> None:
        prev_end = None
        for s in self.segments:
            if s.count < 1:
                raise ObjectFormatError(f"segment at {s.start}: empty")
            if prev_end is not None and s.start != prev_end + 1:
                raise ObjectFormatError(
                    f"manifest gap: segment at {s.start} after round "
                    f"{prev_end}")
            prev_end = s.end
        if self.segments and self.tip != self.segments[-1].end:
            raise ObjectFormatError(
                f"manifest tip {self.tip} != last segment end "
                f"{self.segments[-1].end}")

    def append(self, entry: ManifestEntry) -> None:
        self.segments.append(entry)
        self.tip = entry.end
        self.validate()

    def next_start(self, first_round: int = 1) -> int:
        return self.segments[-1].end + 1 if self.segments else first_round

    def to_json(self) -> bytes:
        return json.dumps({
            "version": self.version,
            "chain_hash": self.chain_hash,
            "scheme_id": self.scheme_id,
            "segment_rounds": self.segment_rounds,
            "tip": self.tip,
            "template": self.template,
            "segments": [s.to_dict() for s in self.segments],
        }, sort_keys=True, indent=1).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Manifest":
        try:
            d = json.loads(data)
        except (ValueError, UnicodeDecodeError) as exc:
            raise ObjectFormatError(f"bad manifest JSON: {exc}") from exc
        try:
            m = cls(
                chain_hash=str(d["chain_hash"]),
                scheme_id=str(d["scheme_id"]),
                segment_rounds=int(d["segment_rounds"]),
                tip=int(d["tip"]),
                template=str(d.get("template", NAME_TEMPLATE)),
                segments=[ManifestEntry(
                    start=int(s["start"]), count=int(s["count"]),
                    hash=str(s["hash"]), name=str(s["name"]))
                    for s in d.get("segments", [])],
                version=int(d.get("version", MANIFEST_VERSION)),
            )
        except (KeyError, TypeError, ValueError) as exc:
            raise ObjectFormatError(f"bad manifest field: {exc}") from exc
        if m.version != MANIFEST_VERSION:
            raise ObjectFormatError(
                f"unsupported manifest version {m.version}")
        m.validate()
        return m
