"""Client sync path over dumb object storage (ISSUE 18).

``ObjectSyncClient`` catches a store up from published segment objects:
fetch the manifest, fetch each needed segment over plain HTTP (or any
ObjectStore backend), verify it LOCALLY, commit it transactionally.
The trust model is identical to the gRPC sync path — object contents
are never believed:

  - the content hash pinned in the manifest must match the fetched
    bytes (catches truncation/bit-rot/stale-CDN cheaply, before any
    crypto);
  - every row is then cryptographically verified through
    ``ChainVerifier.verify_packed_segment_async`` against the prev
    column CONSTRUCTED from the client's own chain anchor — a segment
    whose linkage or signatures lie fails verification wholesale;
  - commits go through the store's transactional ``put_many`` (PR 15),
    so a failed segment commits NOTHING from itself or later.

Commit order is strict FIFO over the manifest's segment index.  Fetches
run ahead through a small prefetch window (out-of-order ARRIVAL is
fine; out-of-order COMMIT never happens), mirroring the gRPC catch-up
pipeline's contract.  Any failure — fetch, decode, hash mismatch,
verify — stops the sync at the last verified segment boundary: the
store holds exactly a verified prefix, like the recovery scan after a
crash.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from drand_tpu import log as dlog
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.segment import PackedBeacons
from drand_tpu.chain.store import BeaconNotFound
from drand_tpu.objectsync import format as ofmt
from drand_tpu.objectsync.backends import ObjectStore

log = dlog.get("objectsync")

PREFETCH_DEPTH = 2     # segments fetched ahead of the verify/commit head


class ObjectSyncError(Exception):
    pass


class CorruptObjectError(ObjectSyncError):
    """An object whose bytes do not match its manifest content hash, or
    that fails structural decode — damaged in storage or in transit."""


class SyncResult:
    def __init__(self, ok: bool, synced_to: int, segments: int,
                 rounds: int, error: str = ""):
        self.ok = ok
        self.synced_to = synced_to
        self.segments = segments
        self.rounds = rounds
        self.error = error

    def to_dict(self) -> dict:
        return {"ok": self.ok, "synced_to": self.synced_to,
                "segments": self.segments, "rounds": self.rounds,
                "error": self.error}


class ObjectSyncClient:
    def __init__(self, backend: ObjectStore, store, verifier,
                 chain_hash: bytes | None = None, resilience=None,
                 prefetch: int = PREFETCH_DEPTH):
        """backend: where the objects live; store: the DECORATED chain
        store to commit through; verifier: ChainVerifier for the pinned
        chain; chain_hash: trust root — a manifest or segment for a
        different chain is rejected before any commit; resilience: the
        shared hub — fetches retry through its RetryPolicy when wired."""
        self.backend = backend
        self.store = store
        self.verifier = verifier
        self.chain_hash = chain_hash
        self.resilience = resilience
        self.prefetch = max(prefetch, 1)
        # per-stage host seconds + throughput, same shape as
        # SyncManager.stats so the bench compares like for like
        self.stats = {"fetch_s": 0.0, "verify_s": 0.0, "commit_s": 0.0,
                      "segments": 0, "rounds": 0}

    async def _get(self, name: str) -> bytes:
        if self.resilience is not None:
            return await self.resilience.retry.call(
                "objectsync.get", lambda attempt: self.backend.get(name),
                key=name)
        return await self.backend.get(name)

    async def manifest(self) -> ofmt.Manifest:
        m = ofmt.Manifest.from_json(await self._get(ofmt.MANIFEST_NAME))
        if self.chain_hash is not None \
                and m.chain_hash != self.chain_hash.hex():
            raise ObjectSyncError(
                f"manifest is for chain {m.chain_hash}, pinned "
                f"{self.chain_hash.hex()}")
        return m

    async def _fetch_segment(self, entry: ofmt.ManifestEntry) -> bytes:
        t0 = time.perf_counter()
        data = await self._get(entry.name)
        self.stats["fetch_s"] += time.perf_counter() - t0
        if ofmt.content_hash(data) != entry.hash:
            raise CorruptObjectError(
                f"object {entry.name}: content hash mismatch "
                f"({len(data)} bytes)")
        return data

    def _packed(self, entry: ofmt.ManifestEntry, data: bytes,
                skip_to: int) -> PackedBeacons | list[Beacon]:
        """Decode + structurally validate one segment against its
        manifest entry, dropping rounds at/below ``skip_to`` (a segment
        partially behind the local tip).  The object's OWN prev column
        is discarded: linkage is reconstructed from the caller's anchor
        at verify/commit time."""
        seg = ofmt.decode_segment(data)
        if self.chain_hash is not None and seg.chain_hash != self.chain_hash:
            raise CorruptObjectError(
                f"object {entry.name}: wrong chain "
                f"{seg.chain_hash.hex()}")
        if seg.start_round != entry.start or seg.count != entry.count:
            raise CorruptObjectError(
                f"object {entry.name}: covers {seg.start_round}+"
                f"{seg.count}, manifest says {entry.start}+{entry.count}")
        rows = seg.rows
        if skip_to >= seg.start_round:
            rows = rows[skip_to - seg.start_round + 1:]
        if not rows:
            return []
        chained = not self.verifier.scheme.decouple_prev_sig
        sig_len = len(rows[0][1])
        if any(len(sig) != sig_len for (_, sig, _) in rows):
            raise CorruptObjectError(
                f"object {entry.name}: non-uniform signature lengths")
        sigs = np.frombuffer(b"".join(sig for (_, sig, _) in rows),
                             dtype=np.uint8).reshape(len(rows), sig_len)
        return PackedBeacons(start_round=rows[0][0], sigs=sigs,
                             chained=chained)

    async def sync(self, up_to: int = 0) -> SyncResult:
        """Catch the local store up from the backend.  Returns instead
        of raising on a poisoned object: the caller reads ``ok`` /
        ``error`` and the store holds exactly the verified prefix."""
        try:
            last = self.store.last()
        except BeaconNotFound:
            return SyncResult(False, -1, 0, 0,
                              "store has no anchor (seed genesis first)")
        try:
            m = await self.manifest()
        except Exception as exc:
            return SyncResult(False, last.round, 0, 0,
                              f"manifest fetch failed: {exc}")
        todo = [e for e in m.segments
                if e.end > last.round and (not up_to or e.start <= up_to)]
        anchor_round, anchor_sig = last.round, last.signature
        segments = rounds = 0

        # prefetch window: fetches for segments k..k+depth run while
        # segment k verifies/commits; commit order stays strict FIFO
        tasks: list[asyncio.Task] = [
            asyncio.ensure_future(self._fetch_segment(e))
            for e in todo[:self.prefetch]]
        error = ""
        try:
            for i, entry in enumerate(todo):
                nxt = i + self.prefetch
                if nxt < len(todo):
                    tasks.append(asyncio.ensure_future(
                        self._fetch_segment(todo[nxt])))
                try:
                    data = await tasks[i]
                    packed = self._packed(entry, data, anchor_round)
                except Exception as exc:
                    error = f"segment {entry.name}: {exc}"
                    break
                if isinstance(packed, list) and not packed:
                    continue               # fully behind the local tip
                if up_to and packed.end_round > up_to:
                    if up_to < packed.start_round:
                        break
                    packed = packed.truncate(up_to)
                t0 = time.perf_counter()
                try:
                    resolver = self.verifier.verify_packed_segment_async(
                        packed, anchor_sig)
                    ok = np.asarray(await asyncio.to_thread(resolver))
                except Exception as exc:
                    error = f"segment {entry.name}: verify error: {exc}"
                    break
                self.stats["verify_s"] += time.perf_counter() - t0
                if not bool(np.all(ok)):
                    bad = [int(packed.start_round + j)
                           for j in np.nonzero(~ok)[0][:5]]
                    error = (f"segment {entry.name}: verification failed "
                             f"at rounds {bad}")
                    break
                t0 = time.perf_counter()
                beacons = packed.beacons(anchor_sig=anchor_sig)
                try:
                    await asyncio.to_thread(self.store.put_many, beacons)
                except Exception as exc:
                    error = f"segment {entry.name}: commit failed: {exc}"
                    break
                self.stats["commit_s"] += time.perf_counter() - t0
                self.stats["segments"] += 1
                self.stats["rounds"] += len(beacons)
                segments += 1
                rounds += len(beacons)
                anchor_round = packed.end_round
                anchor_sig = packed.tail_sig
                if up_to and anchor_round >= up_to:
                    break
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            # reap cancellations so nothing leaks into the caller's loop
            await asyncio.gather(*tasks, return_exceptions=True)
        if error:
            log.warning("objectsync client stopped at round %d: %s",
                        anchor_round, error)
        return SyncResult(not error, anchor_round, segments, rounds, error)
