"""Daemon-side segment publisher (ISSUE 18).

``ObjectPublisher`` watches a chain store and keeps an object backend
holding the chain as sealed, content-addressed segment objects plus the
one mutable manifest.  Drive model:

  - a TAIL callback on the CallbackStore (synchronous on the committing
    thread, O(1): record the tip, wake the loop) — the same cheap-hook
    contract the serve cache and /public/latest watch use;
  - the publish loop runs on the event loop and does every heavy step
    off it: ``read_fields`` (no Beacon materialization) in a worker
    thread, backend writes through the async ObjectStore seam.

A segment is published only when SEALED — a full ``segment_rounds`` run
exists past the last published segment — so every object is immutable
forever and the manifest is the only thing a CDN must re-validate.

Restart is idempotent by construction: the manifest IS the durable
cursor.  On start the publisher reads it back, validates chain identity,
and resumes at ``tip + 1``; re-putting an already-published object
writes identical bytes to the identical content-addressed name.

A damaged local row (CorruptRowError from the store) STOPS publishing at
the verified prefix and surfaces in the snapshot/metrics — the publisher
never ships bytes it could not read cleanly; the startup scan / fsck
owns healing, after which publishing resumes where it stopped.
"""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu.chain.store import StoreError
from drand_tpu.objectsync import format as ofmt
from drand_tpu.objectsync.backends import ObjectNotFound, ObjectStore

log = dlog.get("objectsync")

_CB_ID = "objectsync-pub"


class PublisherError(Exception):
    pass


class ObjectPublisher:
    def __init__(self, store, backend: ObjectStore, chain_hash: bytes,
                 scheme_id: str,
                 segment_rounds: int = ofmt.DEFAULT_SEGMENT_ROUNDS,
                 beacon_id: str = "default", first_round: int = 1):
        """store: anything with ``read_fields`` (the decorated chain
        store or a bare SqliteStore); backend: the ObjectStore seam;
        chain_hash/scheme_id: the published chain's identity, pinned
        into every object and the manifest."""
        self.store = store
        self.backend = backend
        self.chain_hash = chain_hash
        self.scheme_id = scheme_id
        self.segment_rounds = segment_rounds
        self.beacon_id = beacon_id
        self.first_round = first_round
        self.manifest: ofmt.Manifest | None = None  # owner: publish loop / one-shot caller
        self.last_error: str = ""  # owner: publish loop / one-shot caller
        self._tip = 0                 # freshest committed round seen
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._attached = False

    # -- store hook (committing thread; must stay O(1)) ---------------------

    def attach(self) -> None:
        """Register the tail callback.  Stores without the callback seam
        (bare SqliteStore in one-shot CLI use) just skip the live drive;
        ``publish_sealed`` still works on demand."""
        if self._attached or not hasattr(self.store, "add_tail_callback"):
            return
        loop = asyncio.get_running_loop()

        def note_tail(beacon) -> None:
            self._tip = max(self._tip, beacon.round)
            try:
                loop.call_soon_threadsafe(self._wake.set)
            except RuntimeError:
                pass                     # loop closed during shutdown
        self.store.add_tail_callback(_CB_ID, note_tail)
        self._attached = True

    def detach(self) -> None:
        if self._attached:
            try:
                self.store.remove_callback(_CB_ID)
            except Exception:
                pass
            self._attached = False

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self.attach()
        await self.load_manifest()
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    def cancel(self) -> None:
        """Synchronous teardown for engine-shutdown paths: detach the
        store hook and cancel the loop task without awaiting it."""
        self.detach()
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def stop(self) -> None:
        task = self._task
        self.cancel()
        if task is not None:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _run(self) -> None:
        while True:
            try:
                await self.publish_sealed()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # keep the loop alive: a transient backend failure heals
                # on the next commit wake; the error is visible in the
                # snapshot until then
                self.last_error = str(exc)
                log.warning("objectsync publish failed: %s", exc)
            await self._wake.wait()
            self._wake.clear()

    # -- manifest cursor ----------------------------------------------------

    async def load_manifest(self) -> ofmt.Manifest:
        """Read the durable cursor back from the backend; a fresh backend
        starts an empty manifest.  A manifest for a DIFFERENT chain is a
        hard error — never interleave two chains in one prefix."""
        try:
            body = await self.backend.get(ofmt.MANIFEST_NAME)
            m = ofmt.Manifest.from_json(body)
        except ObjectNotFound:
            m = ofmt.Manifest(chain_hash=self.chain_hash.hex(),
                              scheme_id=self.scheme_id,
                              segment_rounds=self.segment_rounds)
        if m.chain_hash != self.chain_hash.hex():
            raise PublisherError(
                f"backend holds manifest for chain {m.chain_hash}, "
                f"publishing {self.chain_hash.hex()}")
        if m.segment_rounds != self.segment_rounds:
            # the cursor wins: changing segment size mid-chain would
            # break the contiguity every published object commits to
            log.warning("objectsync: manifest pins segment_rounds=%d "
                        "(configured %d); keeping the manifest's",
                        m.segment_rounds, self.segment_rounds)
            self.segment_rounds = m.segment_rounds
        self.manifest = m
        return m

    # -- publishing ---------------------------------------------------------

    async def publish_sealed(self) -> int:
        """Publish every currently-sealed segment; returns how many
        objects were written.  Idempotent and resumable at any point:
        object writes are content-addressed, and the manifest is only
        advanced AFTER its segment object is durably in the backend."""
        if self.manifest is None:
            await self.load_manifest()
        m = self.manifest
        published = 0
        while True:
            start = m.next_start(self.first_round)
            try:
                rows = await asyncio.to_thread(
                    self.store.read_fields, start, self.segment_rounds)
            except StoreError as exc:
                # damaged local row: stop at the verified prefix — never
                # publish bytes we could not read cleanly
                self.last_error = f"store read stopped publishing: {exc}"
                log.warning("objectsync: %s", self.last_error)
                break
            if (len(rows) < self.segment_rounds
                    or rows[0][0] != start
                    or rows[-1][0] != start + self.segment_rounds - 1):
                break                      # not sealed yet (or a gap)
            blob = ofmt.encode_segment(self.chain_hash, self.scheme_id,
                                       rows)
            hash_hex = ofmt.content_hash(blob)
            name = ofmt.object_name(start, hash_hex, m.template)
            await self.backend.put(name, blob)
            m.append(ofmt.ManifestEntry(start=start,
                                        count=self.segment_rounds,
                                        hash=hash_hex, name=name))
            await self.backend.put(ofmt.MANIFEST_NAME, m.to_json())
            published += 1
            self.last_error = ""
            log.info("objectsync: published rounds %d..%d as %s",
                     start, m.tip, name)
            try:
                from drand_tpu import metrics as M
                M.OBJECTSYNC_PUBLISHED.labels(self.beacon_id).inc()
            except Exception:
                pass
        self._update_lag()
        return published

    def _store_tip(self) -> int:
        if self._tip:
            return self._tip
        try:
            return self.store.last().round
        except Exception:
            return 0

    def _update_lag(self) -> None:
        lag = max(self._store_tip()
                  - (self.manifest.tip if self.manifest else 0), 0)
        try:
            from drand_tpu import metrics as M
            M.OBJECTSYNC_LAG.labels(self.beacon_id).set(lag)
        except Exception:
            pass

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time publisher state for /debug/objectsync."""
        m = self.manifest
        tip = self._store_tip()
        published_tip = m.tip if m else 0
        return {
            "backend": self.backend.describe(),
            "segment_rounds": self.segment_rounds,
            "published_segments": len(m.segments) if m else 0,
            "published_tip": published_tip,
            "store_tip": tip,
            "lag_rounds": max(tip - published_tip, 0),
            "attached": self._attached,
            "last_error": self.last_error,
        }
