"""Pluggable object stores for the objectsync tier (ISSUE 18).

The seam the publisher writes through and the client reads through:
``put(name, body)`` / ``get(name) -> bytes`` as coroutines, nothing
else.  Two real backends:

  - :class:`FilesystemBackend` — a directory; tests, CI, and the
    "publish to a dir, serve it with any static file server / rsync it
    to a bucket" operational path.  Writes are atomic (tmp + rename) so
    a crashed publisher never leaves a half-written object where a
    client could fetch it.
  - :class:`HTTPBackend` — plain HTTP GET/PUT against an S3-compatible
    endpoint (or any WebDAV-ish store).  No AWS SDK: the image doesn't
    carry boto3, and content-addressed immutable objects need nothing
    beyond PUT-if-absent semantics that a plain PUT already gives
    (re-putting identical bytes is idempotent by construction).

``SyncAdapter`` bridges legacy sync ``put(key, body)`` backends (the
relay/s3.py seam: boto3 buckets, the old FileStoreBackend) onto this
interface so existing operator config keeps working.
"""

from __future__ import annotations

import asyncio
import os

from drand_tpu import log as dlog

log = dlog.get("objectsync")


class ObjectStoreError(Exception):
    pass


class ObjectNotFound(ObjectStoreError):
    def __init__(self, name: str):
        super().__init__(f"object {name!r} not found")
        self.name = name


class ObjectStore:
    """Abstract backend: named blobs, nothing more.  Implementations
    must tolerate re-put of identical bytes (content-addressed objects
    make every retry idempotent)."""

    async def put(self, name: str, body: bytes) -> None:
        raise NotImplementedError

    async def get(self, name: str) -> bytes:
        raise NotImplementedError

    async def close(self) -> None:
        pass

    def describe(self) -> str:
        return type(self).__name__


class FilesystemBackend(ObjectStore):
    """A directory as the object store."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        path = os.path.normpath(os.path.join(self.root, name))
        root = os.path.abspath(self.root)
        if not os.path.abspath(path).startswith(root + os.sep):
            raise ObjectStoreError(f"object name escapes root: {name!r}")
        return path

    def put_sync(self, name: str, body: bytes) -> None:
        """Atomic write: a reader (or a crash) can observe the old
        object or the new one, never a torn middle — the same contract
        sqlite gives the chain store."""
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, path)

    def get_sync(self, name: str) -> bytes:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise ObjectNotFound(name) from None

    async def put(self, name: str, body: bytes) -> None:
        await asyncio.to_thread(self.put_sync, name, body)

    async def get(self, name: str) -> bytes:
        return await asyncio.to_thread(self.get_sync, name)

    def describe(self) -> str:
        return f"fs:{self.root}"


class HTTPBackend(ObjectStore):
    """Plain-HTTP object access: GET for reads (any static server or
    CDN edge), PUT for writes (S3-compatible endpoints with the bucket
    in the URL, pre-signed or IAM-fronted).  A read-only deployment just
    never calls put."""

    def __init__(self, base_url: str, headers: dict | None = None,
                 timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.headers = dict(headers or {})
        self.timeout_s = timeout_s
        self._session = None

    async def _sess(self):
        import aiohttp
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    def _url(self, name: str) -> str:
        return f"{self.base_url}/{name}"

    async def put(self, name: str, body: bytes) -> None:
        sess = await self._sess()
        async with sess.put(self._url(name), data=body,
                            headers=self.headers) as resp:
            if resp.status >= 400:
                raise ObjectStoreError(
                    f"PUT {name}: HTTP {resp.status}")

    async def get(self, name: str) -> bytes:
        sess = await self._sess()
        async with sess.get(self._url(name), headers=self.headers) as resp:
            if resp.status == 404:
                raise ObjectNotFound(name)
            if resp.status >= 400:
                raise ObjectStoreError(f"GET {name}: HTTP {resp.status}")
            return await resp.read()

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def describe(self) -> str:
        return f"http:{self.base_url}"


class SyncAdapter(ObjectStore):
    """Adapt a legacy sync backend — anything with ``put(key, body)``
    and optionally ``get(key)`` — to the async ObjectStore seam.  The
    relay/s3.py shim and the CLI's boto3 adapter ride through here."""

    def __init__(self, inner):
        self.inner = inner

    async def put(self, name: str, body: bytes) -> None:
        await asyncio.to_thread(self.inner.put, name, body)

    async def get(self, name: str) -> bytes:
        getter = getattr(self.inner, "get", None)
        if getter is None:
            raise ObjectStoreError(
                f"{type(self.inner).__name__} is write-only (no get)")
        try:
            return await asyncio.to_thread(getter, name)
        except FileNotFoundError:
            raise ObjectNotFound(name) from None

    def describe(self) -> str:
        return f"adapter:{type(self.inner).__name__}"


def as_object_store(backend) -> ObjectStore:
    """Normalize any accepted backend shape to the async seam."""
    if isinstance(backend, ObjectStore):
        return backend
    if hasattr(backend, "put"):
        return SyncAdapter(backend)
    raise TypeError(f"not an object store backend: {type(backend)!r}")
