"""Runtime asyncio sanitizer: the dynamic half of the race tooling.

The static analyzers (tools/lint: await-race, domain-flow) prove shapes;
this module watches the live event loop — the Python stand-in for the
reference daemon's `go test -race` CI leg.  Opt-in via
``DRAND_TPU_ASYNC_SANITIZE=1`` (or arming explicitly); disarmed cost is
one module-global load per hook, the same contract as chaos/failpoints.

Two probes:

**Loop-block detection.**  While armed, every event-loop callback is
timed (a wrap of ``asyncio.events.Handle._run``).  A watchdog thread
samples the in-flight callback; one that overruns the threshold gets its
stack captured *live* via ``sys._current_frames()`` — the report shows
the line that is actually blocking, not just the callback name.  A
callback that finishes over-threshold between samples is still reported,
with callback provenance instead of a live stack.

**Cross-task / unlocked mutation detection.**  Instrumented objects
(ChainStore, PartialCache, ResponseCache) wrap their mutation critical
sections in ``sanitizer.mutating(obj, label, single_writer=...)``:

  - two contexts *inside* the section at once means the section is not
    actually serialized — an unlocked concurrent mutation, reported with
    both stacks' worth of context;
  - for ``single_writer=True`` sections, a second distinct writer task
    violates the declared ownership (the PR 3 partial-cache contract:
    only the aggregator task appends) and is reported even if the
    interleaving happened to be clean this run.

Wired into the chaos runner, every existing chaos schedule doubles as a
dynamic race probe: the tier-1 scenario matrix runs sanitized and
asserts zero reports.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import sys
import threading
import time
import traceback
from dataclasses import dataclass

ENV_FLAG = "DRAND_TPU_ASYNC_SANITIZE"
ENV_THRESHOLD = "DRAND_TPU_ASYNC_SANITIZE_THRESHOLD"

DEFAULT_BLOCK_THRESHOLD_S = 0.25


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0")


def env_threshold() -> float:
    try:
        return float(os.environ[ENV_THRESHOLD])
    except (KeyError, ValueError):
        return DEFAULT_BLOCK_THRESHOLD_S


@dataclass
class Report:
    kind: str       # "loop-block" | "unlocked-mutation" | "cross-task-write"
    what: str       # callback / object.op identification
    detail: str     # duration, writers, threshold
    stack: str = ""

    def render(self) -> str:
        head = f"[sanitizer:{self.kind}] {self.what} — {self.detail}"
        return head + (f"\n{self.stack}" if self.stack else "")


class _Slot:
    """Per-thread in-flight callback record (written lock-free: only the
    running thread writes, the watchdog only reads)."""
    __slots__ = ("t0", "label", "reported")

    def __init__(self, t0: float, label: str):
        self.t0 = t0
        self.label = label
        self.reported = False


def _callback_label(handle) -> str:
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):      # a coroutine step, not a plain cb
        coro = owner.get_coro()
        where = getattr(coro, "__qualname__", None) or repr(coro)
        return f"task {owner.get_name()} ({where})"
    name = getattr(cb, "__qualname__", None) or repr(cb)
    return f"callback {name}"


class AsyncSanitizer:
    """One armed sanitizing session; collect with :attr:`reports`."""

    def __init__(self, block_threshold_s: float | None = None):
        self.block_threshold_s = (env_threshold() if block_threshold_s is None
                                  else block_threshold_s)
        self.reports: list[Report] = []
        self.callbacks_run = 0
        self.slowest: tuple[float, str] = (0.0, "")
        self._slots: dict[int, _Slot] = {}        # thread id -> in-flight
        self._mut: dict[tuple, dict] = {}         # (obj id, label) -> rec
        self._book = threading.Lock()
        self._orig_run = None
        self._watch: threading.Thread | None = None
        self._stop = threading.Event()

    # ---------------- loop-block probe --------------------------------

    def _install(self) -> None:
        san = self
        self._orig_run = asyncio.events.Handle._run

        def _run(handle):  # replaces Handle._run while armed
            tid = threading.get_ident()
            slot = _Slot(time.monotonic(), _callback_label(handle))
            san._slots[tid] = slot
            try:
                return san._orig_run(handle)
            finally:
                san._slots.pop(tid, None)
                dur = time.monotonic() - slot.t0
                san.callbacks_run += 1
                if dur > san.slowest[0]:
                    san.slowest = (dur, slot.label)
                if dur >= san.block_threshold_s and not slot.reported:
                    san._report(Report(
                        "loop-block", slot.label,
                        f"blocked the event loop for {dur * 1e3:.0f} ms "
                        f"(threshold {san.block_threshold_s * 1e3:.0f} ms; "
                        f"finished between watchdog samples)"))

        asyncio.events.Handle._run = _run
        interval = min(0.25, max(0.01, self.block_threshold_s / 4))
        self._stop.clear()
        self._watch = threading.Thread(
            target=self._watchdog, args=(interval,),
            name="async-sanitizer-watchdog", daemon=True)
        self._watch.start()

    def _uninstall(self) -> None:
        if self._orig_run is not None:
            asyncio.events.Handle._run = self._orig_run
            self._orig_run = None
        self._stop.set()
        if self._watch is not None:
            self._watch.join(timeout=2.0)
            self._watch = None

    def _watchdog(self, interval: float) -> None:
        while not self._stop.wait(interval):
            now = time.monotonic()
            for tid, slot in list(self._slots.items()):
                if slot.reported or now - slot.t0 < self.block_threshold_s:
                    continue
                slot.reported = True
                frame = sys._current_frames().get(tid)
                stack = "".join(traceback.format_stack(frame)) if frame \
                    else ""
                self._report(Report(
                    "loop-block", slot.label,
                    f"still blocking the event loop after "
                    f"{(now - slot.t0) * 1e3:.0f} ms (threshold "
                    f"{self.block_threshold_s * 1e3:.0f} ms); live stack "
                    f"captured", stack))

    # ---------------- mutation probe -----------------------------------

    def _mutating(self, obj, label: str, single_writer: bool):
        key = (id(obj), label)
        what = f"{type(obj).__name__}.{label}"
        try:
            task = asyncio.current_task()
        except RuntimeError:
            task = None
        writer = (threading.get_ident(),
                  task.get_name() if task is not None else None)
        with self._book:
            # the strong ref pins the object so id() can't be recycled
            # onto a new instance mid-run (writer sets would merge)
            rec = self._mut.setdefault(
                key, {"active": 0, "writers": set(), "flagged": set(),
                      "obj": obj})
            rec["active"] += 1
            if rec["active"] > 1 and "overlap" not in rec["flagged"]:
                rec["flagged"].add("overlap")
                self._report(Report(
                    "unlocked-mutation", what,
                    f"{rec['active']} concurrent contexts inside the "
                    f"mutation critical section — it is not serialized",
                    "".join(traceback.format_stack(limit=12))))
            rec["writers"].add(writer)
            if single_writer and len(rec["writers"]) > 1 \
                    and "writers" not in rec["flagged"]:
                rec["flagged"].add("writers")
                names = sorted(str(w[1] or f"thread-{w[0]}")
                               for w in rec["writers"])
                self._report(Report(
                    "cross-task-write", what,
                    f"declared single-writer but mutated by: "
                    f"{', '.join(names)}",
                    "".join(traceback.format_stack(limit=12))))

        @contextlib.contextmanager
        def section():
            try:
                yield
            finally:
                with self._book:
                    rec["active"] -= 1

        return section()

    def _report(self, report: Report) -> None:
        self.reports.append(report)


# ---------------- module-global arm state (failpoints discipline) ------

_active: AsyncSanitizer | None = None
_NULL = contextlib.nullcontext()


def armed() -> bool:
    return _active is not None


def active() -> AsyncSanitizer | None:
    return _active


def arm(san: AsyncSanitizer | None = None) -> AsyncSanitizer:
    """Install a sanitizer (idempotent: re-arming replaces)."""
    global _active
    if _active is not None:
        disarm()
    _active = san if san is not None else AsyncSanitizer()
    _active._install()
    return _active


def disarm() -> None:
    global _active
    if _active is not None:
        _active._uninstall()
        _active = None


def mutating(obj, label: str, single_writer: bool = False):
    """Cooperative hook: instrumented classes wrap each mutation
    critical section in ``with sanitizer.mutating(self, "op"):``.
    Disarmed, this is one global load and a shared nullcontext."""
    san = _active
    if san is None:
        return _NULL
    return san._mutating(obj, label, single_writer)
