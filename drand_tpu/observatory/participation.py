"""Signer participation ledger (ISSUE 19, tentpole surface 1).

The aggregation path always knew which signer indices stood behind every
recovered round — ``PartialCache`` keys partials by index and
``_recover`` Lagrange-combines exactly that set — but nothing recorded
it.  This ledger is the single book of record for signer liveness:

  - the Handler's accept seam feeds every VALID partial (on-time and
    late) through :meth:`note_partial` / :meth:`note_late`;
  - the aggregator's recovery hook feeds the recovered contributor set
    and the time-to-threshold through :meth:`note_recovery`.

From those two feeds it derives, per round, a contributor bitmap, the
threshold margin at recovery (``partials_at_recovery − t``), the FINAL
margin (distinct on-time ∪ late contributors − t, sealed when a later
round recovers — the robust "how close did we come" signal, since
recovery triggers exactly at threshold so the at-recovery margin is
almost always 0), and per-signer participation rates over a bounded
rolling window.

The watchdog's per-peer partial recency reads :attr:`newest` through
``Handler.partial_seen`` — the ledger IS that feed now, so the two
surfaces can never disagree (ISSUE 19 satellite: one accept-event feed).

Everything here runs on the event loop (accept path, aggregator hook,
watchdog tick, debug routes) — no locks needed.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field

from drand_tpu import log as dlog
from drand_tpu import metrics as M

log = dlog.get("observatory", "participation")

DEFAULT_WINDOW = 256
# keep at most this many un-recovered rounds of on-time observations
# (partials for rounds that never recover — e.g. during a stall — must
# not grow the ledger unboundedly)
MAX_OPEN_ROUNDS = 64


@dataclass
class RoundRecord:
    """One recovered round's participation picture."""

    round: int
    on_time: set[int] = field(default_factory=set)   # accepted pre-recovery
    recovered: tuple[int, ...] = ()                  # indices in the combine
    late: set[int] = field(default_factory=set)      # accepted post-recovery
    count_at_recovery: int = 0
    margin_at_recovery: int = 0
    time_to_threshold_s: float = 0.0
    final_margin: int | None = None                  # sealed by a later round

    @property
    def contributors(self) -> set[int]:
        return self.on_time | self.late | set(self.recovered)

    def to_dict(self) -> dict:
        return {
            "round": self.round,
            "contributors": sorted(self.contributors),
            "recovered": sorted(self.recovered),
            "late": sorted(self.late),
            "count_at_recovery": self.count_at_recovery,
            "margin_at_recovery": self.margin_at_recovery,
            "time_to_threshold_s": round(self.time_to_threshold_s, 6),
            "final_margin": self.final_margin,
        }


class ParticipationLedger:
    """Bounded rolling book of per-round signer participation."""

    def __init__(self, group_size: int, threshold: int,
                 beacon_id: str = "default", own_index: int = -1,
                 window: int = DEFAULT_WINDOW):
        self.group_size = group_size
        self.threshold = threshold
        self.beacon_id = beacon_id
        self.own_index = own_index
        self.window = max(int(window), 1)
        # newest round a VALID partial (or recovery membership) was seen
        # from, per signer index — the watchdog's missed-partials feed
        self.newest: dict[int, int] = {}
        # on-time observations for rounds not yet recovered
        self._open: dict[int, set[int]] = {}
        # recovered-but-not-finalized + finalized records, newest last
        self._records: "OrderedDict[int, RoundRecord]" = OrderedDict()
        # finalized window: per-record contributor sets, oldest first
        self._final: deque[tuple[int, frozenset[int]]] = deque()
        self._contrib_count: dict[int, int] = {}     # signer -> hits in window
        self._miss_streak: dict[int, int] = {}       # consecutive misses
        self.rounds_recovered = 0
        self.late_partials = 0
        self.last_final_margin: int | None = None
        self.last_time_to_threshold_s: float | None = None

    # -- feeds (Handler accept seam + aggregator recovery hook) -------------

    def note_partial(self, idx: int, round_: int) -> None:
        """A VALID partial accepted for a live (unsettled) round."""
        self.newest[idx] = max(round_, self.newest.get(idx, 0))
        obs = self._open.get(round_)
        if obs is None:
            if len(self._open) >= MAX_OPEN_ROUNDS:
                self._open.pop(min(self._open), None)
            obs = self._open[round_] = set()
        obs.add(idx)

    def note_late(self, idx: int, round_: int) -> None:
        """A VALID partial that arrived after its round settled."""
        self.newest[idx] = max(round_, self.newest.get(idx, 0))
        self.late_partials += 1
        rec = self._records.get(round_)
        if rec is not None and rec.final_margin is None:
            rec.late.add(idx)

    def note_recovery(self, round_: int, indices, count: int,
                      elapsed_s: float) -> None:
        """Round ``round_`` recovered from ``count`` cached partials whose
        signer indices are ``indices``; ``elapsed_s`` is seconds from the
        round's scheduled time to recovery (time-to-threshold)."""
        recovered = tuple(sorted(int(i) for i in indices))
        for i in recovered:
            self.newest[i] = max(round_, self.newest.get(i, 0))
        rec = RoundRecord(
            round=round_,
            on_time=self._open.pop(round_, set()),
            recovered=recovered,
            count_at_recovery=count,
            margin_at_recovery=count - self.threshold,
            time_to_threshold_s=max(elapsed_s, 0.0))
        self._records[round_] = rec
        self._records.move_to_end(round_)
        self.rounds_recovered += 1
        self.last_time_to_threshold_s = rec.time_to_threshold_s
        M.TIME_TO_THRESHOLD.labels(self.beacon_id).observe(
            rec.time_to_threshold_s)
        # observations for rounds at/below the new tip can never grow
        self._open = {r: s for r, s in self._open.items() if r > round_}
        self._finalize_before(round_)
        while len(self._records) > 2 * self.window:
            self._records.popitem(last=False)

    # -- finalization (a later recovery seals earlier rounds) ----------------

    def _finalize_before(self, round_: int) -> None:
        for r in list(self._records):
            rec = self._records[r]
            if r >= round_ or rec.final_margin is not None:
                continue
            contributors = frozenset(rec.contributors)
            rec.final_margin = len(contributors) - self.threshold
            self.last_final_margin = rec.final_margin
            self._final.append((r, contributors))
            for i in contributors:
                self._contrib_count[i] = self._contrib_count.get(i, 0) + 1
            for i in range(self.group_size):
                if i in contributors:
                    self._miss_streak[i] = 0
                else:
                    self._miss_streak[i] = self._miss_streak.get(i, 0) + 1
            while len(self._final) > self.window:
                _, old = self._final.popleft()
                for i in old:
                    n = self._contrib_count.get(i, 0) - 1
                    if n <= 0:
                        self._contrib_count.pop(i, None)
                    else:
                        self._contrib_count[i] = n
            M.THRESHOLD_MARGIN.labels(self.beacon_id).set(rec.final_margin)
            for i in range(self.group_size):
                M.SIGNER_PARTICIPATION.labels(
                    self.beacon_id, str(i)).set(self.rate(i))

    # -- derived views -------------------------------------------------------

    def is_counted(self, idx: int, round_: int) -> bool:
        """True when this signer is already on the books for this round
        — the Handler's late-path dedup (one signature check per
        (signer, round), ever)."""
        rec = self._records.get(round_)
        if rec is None:
            return False
        return idx in rec.on_time or idx in rec.late or idx in rec.recovered

    def rate(self, idx: int) -> float:
        """Fraction of the finalized window this signer contributed to."""
        n = len(self._final)
        if n == 0:
            return 1.0            # nothing judged yet: presume innocent
        return self._contrib_count.get(idx, 0) / n

    def miss_streak(self, idx: int) -> int:
        return self._miss_streak.get(idx, 0)

    def missing_signers(self, min_rounds: int = 3) -> list[int]:
        """Indices absent from the last ``min_rounds`` finalized rounds
        (chronically missing — the watchdog's loud-transition feed)."""
        if len(self._final) < min_rounds:
            return []
        return sorted(i for i in range(self.group_size)
                      if self._miss_streak.get(i, 0) >= min_rounds)

    def snapshot(self, limit: int = 32) -> dict:
        recent = [rec.to_dict()
                  for rec in list(self._records.values())[-limit:]]
        return {
            "beacon_id": self.beacon_id,
            "group_size": self.group_size,
            "threshold": self.threshold,
            "own_index": self.own_index,
            "window": self.window,
            "rounds_recovered": self.rounds_recovered,
            "finalized": len(self._final),
            "late_partials": self.late_partials,
            "last_final_margin": self.last_final_margin,
            "last_time_to_threshold_s": self.last_time_to_threshold_s,
            "signers": {
                str(i): {
                    "rate": round(self.rate(i), 4),
                    "newest_round": self.newest.get(i, 0),
                    "miss_streak": self.miss_streak(i),
                } for i in range(self.group_size)},
            "missing": self.missing_signers(),
            "rounds": recent,
        }
