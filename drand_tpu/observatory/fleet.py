"""Fleet metric federation (ISSUE 19, tentpole surface 3).

The ``/peers/{addr}/metrics`` proxy (drand_tpu/metrics.py) is the
single-peer half of the reference's metrics federation (SURVEY §5.5,
`metrics.Client` over the protocol channels).  This module is the other
half: scrape EVERY group peer's exposition through that same
authenticated gRPC seam, parse the families the ops plane cares about,
and fold them into one typed :class:`FleetSnapshot` — per-node tip/lag,
breaker states, serve shed, dispatch fill, signer participation —
served at ``/debug/fleet`` and rendered by ``drand-tpu util fleet``.

Collection is on-demand (a scrape fans out when asked), concurrent, and
per-peer bounded: one dead peer costs one timeout, never the snapshot.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from drand_tpu import log as dlog

log = dlog.get("observatory", "fleet")

PEER_SCRAPE_TIMEOUT_S = 5.0


def parse_exposition(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Minimal Prometheus text-format parser: family name -> list of
    (labels, value) samples.  Tolerates anything it does not understand
    (a fleet scrape must survive a peer running a newer build)."""
    out: dict[str, list[tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, value_s = line.rsplit(None, 1)
            value = float(value_s)
        except ValueError:
            continue
        labels: dict[str, str] = {}
        name = metric
        if "{" in metric and metric.endswith("}"):
            name, _, rest = metric.partition("{")
            body = rest[:-1]
            # label values are quoted and may contain escaped quotes;
            # split on '",' boundaries instead of bare commas
            for part in body.split('",'):
                if not part:
                    continue
                if not part.endswith('"'):
                    part += '"'
                k, _, v = part.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    continue
                labels[k.strip()] = v[1:-1].replace('\\"', '"') \
                    .replace("\\\\", "\\").replace("\\n", "\n")
        out.setdefault(name, []).append((labels, value))
    return out


def _sum(families, name: str) -> float:
    return sum(v for _, v in families.get(name, ()))


def _by_label(families, name: str, label: str) -> dict[str, float]:
    return {lbl.get(label, ""): v for lbl, v in families.get(name, ())}


@dataclass
class NodeView:
    """One node's slice of the fleet picture, parsed from its
    exposition."""

    address: str
    ok: bool = False
    error: str = ""
    is_self: bool = False
    tip: int = -1                               # max over its beacons
    lag_rounds: float = 0.0
    beacons: dict = field(default_factory=dict)  # beacon_id -> tip
    breakers: dict = field(default_factory=dict)  # peer -> state
    breakers_open: int = 0
    serve_inflight: float = 0.0
    serve_shed: float = 0.0
    dispatch_fill: dict = field(default_factory=dict)  # seam -> ratio
    participation: dict = field(default_factory=dict)  # signer -> ratio
    threshold_margin: float | None = None
    tip_skew: dict = field(default_factory=dict)  # peer -> skew rounds
    forks_detected: float = 0.0

    @classmethod
    def from_exposition(cls, address: str, text: str,
                        is_self: bool = False) -> "NodeView":
        fams = parse_exposition(text)
        view = cls(address=address, ok=True, is_self=is_self)
        view.beacons = {lbl.get("beacon_id", ""): int(v)
                        for lbl, v in fams.get("drand_last_beacon_round", ())}
        view.tip = max(view.beacons.values(), default=-1)
        view.lag_rounds = _sum(fams, "drand_beacon_lag_rounds")
        view.breakers = _by_label(fams, "drand_breaker_state", "peer")
        view.breakers_open = sum(1 for s in view.breakers.values() if s != 0)
        view.serve_inflight = _sum(fams, "drand_serve_inflight")
        view.serve_shed = _sum(fams, "drand_serve_shed_total")
        view.dispatch_fill = _by_label(fams, "drand_dispatch_fill_ratio",
                                       "seam")
        view.participation = _by_label(
            fams, "drand_signer_participation_ratio", "signer")
        margins = [v for _, v in fams.get("drand_threshold_margin", ())]
        view.threshold_margin = min(margins) if margins else None
        view.tip_skew = _by_label(fams, "drand_fleet_tip_skew_rounds", "peer")
        view.forks_detected = _sum(fams, "drand_fleet_fork_detected_total")
        return view

    def to_dict(self) -> dict:
        return {
            "address": self.address, "ok": self.ok, "error": self.error,
            "is_self": self.is_self, "tip": self.tip,
            "lag_rounds": self.lag_rounds, "beacons": self.beacons,
            "breakers": self.breakers, "breakers_open": self.breakers_open,
            "serve_inflight": self.serve_inflight,
            "serve_shed": self.serve_shed,
            "dispatch_fill": self.dispatch_fill,
            "participation": self.participation,
            "threshold_margin": self.threshold_margin,
            "tip_skew": self.tip_skew,
            "forks_detected": self.forks_detected,
        }


@dataclass
class FleetSnapshot:
    """The whole deployment's health in one object."""

    nodes: list[NodeView] = field(default_factory=list)
    groups: dict = field(default_factory=dict)  # beacon_id -> {size, thr}

    @property
    def reachable(self) -> int:
        return sum(1 for n in self.nodes if n.ok)

    @property
    def max_tip(self) -> int:
        return max((n.tip for n in self.nodes if n.ok), default=-1)

    def to_dict(self) -> dict:
        return {
            "nodes": [n.to_dict() for n in self.nodes],
            "groups": self.groups,
            "reachable": self.reachable,
            "total": len(self.nodes),
            "max_tip": self.max_tip,
        }


async def collect_fleet(daemon,
                        timeout_s: float = PEER_SCRAPE_TIMEOUT_S
                        ) -> FleetSnapshot:
    """Scrape this node + every group peer concurrently into one
    snapshot.  Peer scrapes ride the authenticated gRPC metrics channel
    (daemon.fetch_peer_metrics) with a bounded per-peer timeout."""
    from drand_tpu import metrics as M
    snap = FleetSnapshot()
    own_addrs: set[str] = set()
    peer_addrs: list[str] = []
    for bid, bp in daemon.processes.items():
        if bp.group is None:
            continue
        snap.groups[bid] = {"size": bp.group.size,
                            "threshold": bp.group.threshold}
        own = bp.keypair.public.address if bp.keypair else ""
        own_addrs.add(own)
        for n in bp.group.nodes:
            if n.address != own and n.address not in peer_addrs:
                peer_addrs.append(n.address)
    self_addr = next(iter(sorted(own_addrs)), "self")
    try:
        snap.nodes.append(NodeView.from_exposition(
            self_addr, M.exposition(daemon).decode(), is_self=True))
    except Exception as exc:
        snap.nodes.append(NodeView(address=self_addr, ok=False,
                                   error=str(exc), is_self=True))

    async def scrape(addr: str) -> NodeView:
        try:
            payload = await asyncio.wait_for(
                daemon.fetch_peer_metrics(addr), timeout_s)
            return NodeView.from_exposition(addr, payload.decode())
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError:
            return NodeView(address=addr, ok=False, error="scrape timeout")
        except Exception as exc:
            return NodeView(address=addr, ok=False, error=str(exc))

    snap.nodes.extend(await asyncio.gather(*[scrape(a) for a in peer_addrs]))
    return snap


def render_table(snapshot: dict) -> str:
    """ASCII table for `drand-tpu util fleet` from a /debug/fleet JSON
    payload (accepts the to_dict shape, so the CLI needs no imports
    beyond aiohttp)."""
    headers = ["node", "ok", "tip", "margin", "min-part", "brk-open",
               "shed", "skew", "forks"]
    rows = [headers]
    for n in snapshot.get("nodes", ()):
        part = n.get("participation") or {}
        min_part = min(part.values()) if part else None
        skews = n.get("tip_skew") or {}
        worst_skew = min(skews.values()) if skews else 0
        margin = n.get("threshold_margin")
        rows.append([
            n.get("address", "?") + (" *" if n.get("is_self") else ""),
            "up" if n.get("ok") else f"DOWN ({n.get('error', '')[:24]})",
            str(n.get("tip", -1)),
            "-" if margin is None else str(int(margin)),
            "-" if min_part is None else f"{min_part:.2f}",
            str(n.get("breakers_open", 0)),
            str(int(n.get("serve_shed", 0))),
            str(int(worst_skew)),
            str(int(n.get("forks_detected", 0))),
        ])
    widths = [max(len(r[i]) for r in rows) for i in range(len(headers))]
    lines = []
    for i, row in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    groups = snapshot.get("groups", {})
    for bid, g in sorted(groups.items()):
        lines.append(f"group {bid}: n={g.get('size')} t={g.get('threshold')}"
                     f"  reachable {snapshot.get('reachable')}/"
                     f"{snapshot.get('total')}  max tip "
                     f"{snapshot.get('max_tip')}")
    return "\n".join(lines)
