"""Cross-node consistency probes (ISSUE 19, tentpole surface 2).

A single periodic task per daemon (interval on the injectable Clock,
like the health watchdog) that, each tick and per beacon process,
samples every group peer over the cached node-to-node channels
(net/client.py):

  - **tip skew** — the peer's chain tip vs ours
    (``drand_fleet_tip_skew_rounds{beacon_id,peer}``);
  - **stale peers** — a peer whose tip stopped moving while ours
    advances (logged as a state TRANSITION, watchdog style);
  - **fork / equivocation** — the peer's signature at a common round
    differs from our committed one.  Two valid-looking signatures for
    the same round is the one condition threshold BLS is supposed to
    make impossible, so detection is a loud typed :class:`ForkReport`
    plus ``drand_fleet_fork_detected_total`` — never a debug line.

The signature sample sits behind the ``probe.sample`` failpoint
(chaos/failpoints.py): ``drop`` suppresses the probe (peer invisible to
the prober), ``delay`` slows it, and ``error`` is CAUGHT here and
interpreted as the sampled peer serving a forged divergent signature —
the deterministic injection vector the ``fork-detect`` chaos scenario
drives (the forged bytes derive only from the round, so replays are
byte-identical).

Tip sampling deliberately rides a direct Status RPC rather than
``network.status`` — the latter's ``net.ping`` failpoint feeds the
watchdog's connectivity verdicts, and a second caller would perturb
times-capped ping rules in seeded scenarios.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from drand_tpu import log as dlog
from drand_tpu import metrics as M

log = dlog.get("observatory", "consistency")

DEFAULT_INTERVAL_S = 5.0
PROBE_TIMEOUT_S = 5.0           # real seconds; RPCs resolve in real time
# a peer is "stale" once its tip has not moved for this many probe
# ticks while our own tip advanced past it
STALE_TICKS = 2
MAX_FORKS = 100                 # bounded typed-report ring


@dataclass(frozen=True)
class ForkReport:
    """One detected equivocation: a peer served a different signature
    than the one we committed for the same round."""

    beacon_id: str
    peer: str
    round: int
    local_sig: str              # hex prefix, enough to diff in a log
    peer_sig: str
    tip_at_detection: int

    def to_dict(self) -> dict:
        return {"beacon_id": self.beacon_id, "peer": self.peer,
                "round": self.round, "local_sig": self.local_sig,
                "peer_sig": self.peer_sig,
                "tip_at_detection": self.tip_at_detection}


class ConsistencyProber:
    """One daemon's periodic cross-node consistency judge."""

    def __init__(self, daemon, interval_s: float | None = None):
        self.daemon = daemon
        self.clock = daemon.config.clock
        self.interval_s = interval_s if interval_s is not None else \
            getattr(daemon.config, "health_interval_s", DEFAULT_INTERVAL_S)
        self.forks: list[ForkReport] = []
        self._fork_seen: set[tuple[str, str, int]] = set()
        # (beacon_id, peer) -> rolling probe state
        self._peers: dict[tuple[str, str], dict] = {}
        self.probes = 0
        self.probe_errors = 0
        self.samples_suppressed = 0
        self._task: asyncio.Task | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.tick_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the judge must outlive whatever it is judging
                log.exception("consistency probe tick failed")
            await self.clock.sleep(self.interval_s)

    # -- the periodic probe --------------------------------------------------

    async def tick_once(self) -> None:
        for bid, bp in list(self.daemon.processes.items()):
            group = bp.group
            if group is None or bp.chain_store is None:
                continue
            own = bp.keypair.public.address if bp.keypair else ""
            local_tip = bp.chain_store.tip_round()
            peers = [n for n in group.nodes if n.address != own]
            if not peers:
                continue
            await asyncio.gather(
                *[self._probe_one(bid, bp, n, own, local_tip)
                  for n in peers])

    async def _probe_one(self, bid: str, bp, node, own: str,
                         local_tip: int) -> None:
        from drand_tpu.net.client import make_metadata
        from drand_tpu.protogen import drand_pb2
        entry = self._peers.setdefault((bid, node.address), {
            "tip": -1, "skew": 0, "stale_ticks": 0, "stale": False,
            "reachable": None, "probes": 0, "errors": 0,
            "last_common_round": -1})
        entry["probes"] += 1
        self.probes += 1
        stub = bp.peers.protocol(node.address, getattr(node, "tls", False))
        try:
            resp = await asyncio.wait_for(
                stub.Status(drand_pb2.StatusRequest(
                    metadata=make_metadata(bid)), timeout=PROBE_TIMEOUT_S),
                PROBE_TIMEOUT_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            entry["errors"] += 1
            entry["reachable"] = False
            self.probe_errors += 1
            return
        entry["reachable"] = True
        peer_tip = int(resp.chain_store.last_round)
        skew = peer_tip - local_tip
        M.FLEET_TIP_SKEW.labels(bid, node.address).set(skew)
        # stale = the peer's tip is frozen while ours moves past it —
        # logged only on the state TRANSITION (watchdog discipline)
        if peer_tip == entry["tip"] and local_tip > peer_tip:
            entry["stale_ticks"] += 1
        else:
            entry["stale_ticks"] = 0
        was_stale = entry["stale"]
        entry["stale"] = entry["stale_ticks"] >= STALE_TICKS
        if entry["stale"] and not was_stale:
            log.warning("beacon %s: peer %s is STALE at round %d "
                        "(local tip %d)", bid, node.address, peer_tip,
                        local_tip)
        elif was_stale and not entry["stale"]:
            log.info("beacon %s: peer %s tip moving again (round %d)",
                     bid, node.address, peer_tip)
        entry["tip"] = peer_tip
        entry["skew"] = skew
        common = min(local_tip, peer_tip)
        if common < 1:
            return              # genesis-only: nothing to cross-check
        entry["last_common_round"] = common
        await self._sample_signature(bid, bp, node, own, common, local_tip)

    async def _sample_signature(self, bid: str, bp, node, own: str,
                                common: int, local_tip: int) -> None:
        """Fetch the peer's signature at `common` and diff it against our
        committed row.  The probe.sample failpoint governs this step —
        see the module docstring for the kind semantics."""
        from drand_tpu.chaos import failpoints as chaos
        from drand_tpu.net.client import make_metadata
        from drand_tpu.protogen import drand_pb2
        try:
            local = await asyncio.to_thread(bp._store.get, common)
        except Exception:
            return              # our own row vanished: fsck territory
        try:
            await chaos.failpoint("probe.sample", src=own, dst=node.address)
        except chaos.PacketDropped:
            self.samples_suppressed += 1
            return
        except chaos.FaultInjectedError:
            # injected equivocation: the peer "served" a forged divergent
            # signature.  Deterministic bytes (round-derived only) keep
            # seeded scenario replays byte-identical.
            peer_sig = b"chaos-forged-" + common.to_bytes(8, "big")
        else:
            stub = bp.peers.public(node.address,
                                   getattr(node, "tls", False))
            try:
                resp = await asyncio.wait_for(
                    stub.PublicRand(drand_pb2.PublicRandRequest(
                        round=common, metadata=make_metadata(bid)),
                        timeout=PROBE_TIMEOUT_S),
                    PROBE_TIMEOUT_S)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.probe_errors += 1
                return
            if int(resp.round) != common:
                return          # peer answered a different round: skip
            peer_sig = bytes(resp.signature)
        if peer_sig and local.signature and peer_sig != local.signature:
            self._record_fork(bid, node.address, common,
                              local.signature, peer_sig, local_tip)

    def _record_fork(self, bid: str, peer: str, round_: int,
                     local_sig: bytes, peer_sig: bytes, tip: int) -> None:
        key = (bid, peer, round_)
        if key in self._fork_seen:
            return              # loud exactly once per (peer, round)
        self._fork_seen.add(key)
        report = ForkReport(
            beacon_id=bid, peer=peer, round=round_,
            local_sig=local_sig.hex()[:32], peer_sig=peer_sig.hex()[:32],
            tip_at_detection=tip)
        self.forks.append(report)
        del self.forks[:-MAX_FORKS]
        M.FLEET_FORK_DETECTED.inc()
        log.error("beacon %s: FORK DETECTED — peer %s serves a different "
                  "signature for round %d (local %s… peer %s…, tip %d)",
                  bid, peer, round_, report.local_sig[:16],
                  report.peer_sig[:16], tip)

    # -- debug surface -------------------------------------------------------

    def snapshot(self) -> dict:
        beacons: dict[str, dict] = {}
        for (bid, peer), entry in sorted(self._peers.items()):
            beacons.setdefault(bid, {})[peer] = dict(entry)
        return {
            "interval_s": self.interval_s,
            "probes": self.probes,
            "probe_errors": self.probe_errors,
            "samples_suppressed": self.samples_suppressed,
            "fork_count": len(self._fork_seen),
            "forks": [f.to_dict() for f in self.forks],
            "beacons": beacons,
        }
