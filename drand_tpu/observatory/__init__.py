"""Fleet observatory (ISSUE 19): the group-wide health plane.

Three connected surfaces, all fed from seams that already existed but
were never recorded:

  - :mod:`participation` — per-round signer contribution ledger fed
    from the Handler's partial-accept path and the aggregator's
    recovery set: who actually signed each round, how close the group
    came to missing threshold, and how long threshold took.
  - :mod:`consistency` — a periodic cross-node probe over the cached
    node-to-node channels: tip skew, stale peers, and fork/
    equivocation detection (same round, different signature).
  - :mod:`fleet` — group-wide metric federation: every peer's
    exposition (through the existing peer-metrics proxy seam)
    aggregated into one typed FleetSnapshot, served at ``/debug/fleet``
    and rendered by ``drand-tpu util fleet``.

The reference daemon federates peer metrics over its protocol channels
(SURVEY §5.5, `metrics.Client`); the participation ledger and the fork
probe have no reference equivalent.
"""

from drand_tpu.observatory.consistency import ConsistencyProber, ForkReport
from drand_tpu.observatory.fleet import (FleetSnapshot, NodeView,
                                         collect_fleet, parse_exposition,
                                         render_table)
from drand_tpu.observatory.participation import ParticipationLedger

__all__ = [
    "ParticipationLedger",
    "ConsistencyProber", "ForkReport",
    "FleetSnapshot", "NodeView", "collect_fleet", "parse_exposition",
    "render_table",
]
