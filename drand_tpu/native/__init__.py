"""Native (C++) BLS12-381 verification tier.

Compiles drand_tpu/native/bls381.cpp with the baked-in g++ toolchain at
first use and exposes ctypes wrappers.  The build probes flag sets in
preference order — `-O3 -march=native` first, portable `-O2` fallback —
and caches the .so keyed on a CONTENT hash of (source, constants.h,
chosen flags) recorded in a sidecar meta file, so a flag change or an
mtime-preserving checkout can never serve a stale library.  The chosen
flags/hash are exposed through `build_info()` (the smoke harness records
them next to its latency numbers).  `DRAND_TPU_NATIVE_LIB` overrides the
whole build step with a prebuilt .so path — the sanitizer CI stage uses
it to run the parity suite against an ASan/UBSan build.

The golden model remains the oracle — tests/test_native.py compares this
library against it point-for-point and against the pinned RFC 9380
vectors — but the HOST latency path (single-beacon verify, per-partial
checks on machines without an accelerator) runs here at ~3-4 ms instead
of the golden model's ~175 ms.

`available()` is False (and everything falls back to the golden model)
when no C++ toolchain exists or the build fails; nothing else imports
this module eagerly.
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import os
import subprocess
import threading
import time

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bls381.cpp")
_HDR = os.path.join(_DIR, "constants.h")
_LIB = os.path.join(_DIR, "_libdrandbls.so")
_META = _LIB + ".meta.json"

# probed in order; the first set that compiles wins and is recorded in
# the sidecar meta so build_info() reports what actually ran
_FLAG_SETS = (("-O3", "-march=native"), ("-O2",))

_lock = threading.Lock()
_lib = None
_tried = False
_build_info: dict | None = None


def _source_hash() -> "hashlib._Hash | None":
    h = hashlib.sha256()
    try:
        for path in (_SRC, _HDR):
            with open(path, "rb") as f:
                h.update(f.read())
            h.update(b"\0")
    except OSError:
        return None
    return h


def _read_meta() -> dict | None:
    try:
        with open(_META, encoding="utf-8") as f:
            meta = json.load(f)
        return meta if isinstance(meta, dict) else None
    except (OSError, ValueError):
        return None


def _build() -> dict | None:
    """Return build metadata ({hash, flags, ...}) or None on failure."""
    base = _source_hash()
    if base is None:
        return None
    meta = _read_meta()
    for flags in _FLAG_SETS:
        h = base.copy()
        h.update(" ".join(flags).encode())
        key = h.hexdigest()
        if (meta and meta.get("hash") == key
                and list(meta.get("flags", ())) == list(flags)
                and os.path.exists(_LIB)):
            return {**meta, "cached": True}
        tmp = f"{_LIB}.{os.getpid()}.tmp"   # per-process: concurrent first-
        try:                                # use builds must not corrupt it
            subprocess.run(
                ["g++", *flags, "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=300)
        except subprocess.CalledProcessError:
            continue                        # e.g. -march=native unsupported
        except Exception:
            return None                     # no g++ / timeout: no fallback
        new_meta = {"hash": key, "flags": list(flags)}
        try:
            os.replace(tmp, _LIB)
            mtmp = f"{_META}.{os.getpid()}.tmp"
            with open(mtmp, "w", encoding="utf-8") as f:
                json.dump(new_meta, f, indent=2, sort_keys=True)
            os.replace(mtmp, _META)
        except OSError:
            for p in (tmp, f"{_META}.{os.getpid()}.tmp"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            return None
        return {**new_meta, "cached": False}
    return None


def _set_available_gauge(up: bool) -> None:
    try:
        from drand_tpu import metrics
        metrics.NATIVE_AVAILABLE.set(1 if up else 0)
    except Exception:
        pass   # metrics layer absent (e.g. sanitizer parity runner)


def _load():
    global _lib, _tried, _build_info
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DRAND_TPU_NO_NATIVE"):
            _set_available_gauge(False)
            return None
        override = os.environ.get("DRAND_TPU_NATIVE_LIB")
        if override:
            lib_path = override
            _build_info = {"lib": lib_path, "override": True,
                           "flags": None, "hash": None, "cached": False}
        else:
            meta = _build()
            if meta is None:
                _set_available_gauge(False)
                return None
            lib_path = _LIB
            _build_info = {"lib": lib_path, "override": False, **meta}
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            _build_info = None
            _set_available_gauge(False)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        for name, args in [
            ("drand_bls_verify_g2",
             [u8p, u8p, ctypes.c_size_t, u8p, u8p, ctypes.c_size_t]),
            ("drand_bls_verify_g1",
             [u8p, u8p, ctypes.c_size_t, u8p, u8p, ctypes.c_size_t]),
            ("drand_tbls_verify_partial",
             [u8p, ctypes.c_int, u8p, ctypes.c_size_t, u8p, ctypes.c_size_t,
              u8p, ctypes.c_size_t]),
            ("drand_g2_lincomb", [u8p, u8p, ctypes.c_int, u8p]),
            ("drand_test_tower_op", [ctypes.c_int, u8p, u8p, u8p]),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = ctypes.c_int
        for name in ("drand_hash_to_g2_compressed",
                     "drand_hash_to_g1_compressed"):
            fn = getattr(lib, name)
            fn.argtypes = [u8p, u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
            fn.restype = None
        _lib = lib
        _set_available_gauge(True)
        return _lib


def available() -> bool:
    return _load() is not None


def build_info() -> dict | None:
    """Metadata of the loaded library: {lib, flags, hash, cached,
    override}.  None when the native tier is unavailable."""
    _load()
    return dict(_build_info) if _build_info is not None else None


def _buf(b: bytes):
    return (ctypes.c_uint8 * len(b)).from_buffer_copy(b)


def _observe(scheme: str, seconds: float) -> None:
    try:
        from drand_tpu import metrics
        metrics.NATIVE_VERIFY.labels(scheme=scheme).observe(seconds)
    except Exception:
        pass
    # native single-verify is the unbatched seam of the dispatch flight
    # recorder: n = bucket = 1 (fill 1.0 by definition) — what the
    # amortized device-path µs/round is measured against
    try:
        from drand_tpu.profiling import record_dispatch
        record_dispatch("native", 1, 1, seconds, scheme=scheme)
    except Exception:
        pass


def verify_g2(pk48: bytes, msg: bytes, sig96: bytes, dst: bytes) -> bool:
    # wire bytes are attacker-controlled: length-gate BEFORE the C call,
    # which reads fixed-size buffers (the golden path rejects via
    # ValueError; here a short buffer would be an out-of-bounds read)
    if len(pk48) != 48 or len(sig96) != 96:
        return False
    lib = _load()
    assert lib is not None
    t0 = time.perf_counter()
    ok = bool(lib.drand_bls_verify_g2(
        _buf(pk48), _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(sig96), _buf(dst), len(dst)))
    _observe("g2", time.perf_counter() - t0)
    return ok


def verify_g1(pk96: bytes, msg: bytes, sig48: bytes, dst: bytes) -> bool:
    if len(pk96) != 96 or len(sig48) != 48:
        return False
    lib = _load()
    assert lib is not None
    t0 = time.perf_counter()
    ok = bool(lib.drand_bls_verify_g1(
        _buf(pk96), _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(sig48), _buf(dst), len(dst)))
    _observe("g1", time.perf_counter() - t0)
    return ok


def verify_partial(commits48: list[bytes], msg: bytes, partial: bytes,
                   dst: bytes) -> bool:
    if len(partial) != 98 or not commits48 or \
            any(len(c) != 48 for c in commits48):
        return False
    lib = _load()
    assert lib is not None
    cat = b"".join(commits48)
    t0 = time.perf_counter()
    ok = bool(lib.drand_tbls_verify_partial(
        _buf(cat), len(commits48),
        _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(partial), len(partial), _buf(dst), len(dst)))
    _observe("partial", time.perf_counter() - t0)
    return ok


def g2_lincomb(sigs96: list[bytes], scalars32: list[bytes]) -> bytes | None:
    """sum(scalar_i * sig_i) over G2, compressed — the native
    threshold-recovery combine.  Returns None on malformed points or an
    infinity result."""
    if not sigs96 or len(sigs96) != len(scalars32) or \
            any(len(s) != 96 for s in sigs96) or \
            any(len(c) != 32 for c in scalars32):
        return None
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 96)()
    ok = lib.drand_g2_lincomb(_buf(b"".join(sigs96)),
                              _buf(b"".join(scalars32)),
                              len(sigs96), out)
    return bytes(out) if ok else None


def hash_to_g2(msg: bytes, dst: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 96)()
    lib.drand_hash_to_g2_compressed(
        out, _buf(msg) if msg else _buf(b"\0"), len(msg), _buf(dst), len(dst))
    return bytes(out)


def hash_to_g1(msg: bytes, dst: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 48)()
    lib.drand_hash_to_g1_compressed(
        out, _buf(msg) if msg else _buf(b"\0"), len(msg), _buf(dst), len(dst))
    return bytes(out)


# expected operand sizes per tower_op opcode (b = 0 for sqr-style ops
# that ignore it); the output is always the same size as operand a
_TOWER_A_LEN = {0: 48, 1: 48, 2: 96, 3: 96, 4: 288, 5: 288, 6: 576,
                7: 576, 8: 576, 9: 576}
_TOWER_B_LEN = {0: 48, 1: 0, 2: 96, 3: 0, 4: 288, 5: 0, 6: 576, 7: 0,
                8: 0, 9: 240}


def tower_op(op: int, a: bytes, b: bytes = b"") -> bytes | None:
    """Test-only hook into the lazy tower arithmetic: run opcode `op`
    on big-endian canonical coefficients (see drand_test_tower_op in
    bls381.cpp for the opcode table).  Returns None on bad sizes or
    non-canonical input — mirrors the C-side gate."""
    lib = _load()
    assert lib is not None
    if op not in _TOWER_A_LEN or len(a) != _TOWER_A_LEN[op] \
            or len(b) != _TOWER_B_LEN[op]:
        return None
    out = (ctypes.c_uint8 * len(a))()
    ok = lib.drand_test_tower_op(
        op, _buf(a), _buf(b) if b else _buf(b"\0"), out)
    return bytes(out) if ok else None
