"""Native (C++) BLS12-381 verification tier.

Compiles drand_tpu/native/bls381.cpp with the baked-in g++ toolchain at
first use (cached as _libdrandbls.so next to the source; rebuilt when the
source or generated constants change), and exposes ctypes wrappers.  The
golden model remains the oracle — tests/test_native.py compares this
library against it point-for-point and against the pinned RFC 9380
vectors — but the HOST latency path (single-beacon verify, per-partial
checks on machines without an accelerator) runs here at ~2-5 ms instead
of the golden model's ~175 ms.

`available()` is False (and everything falls back to the golden model)
when no C++ toolchain exists or the build fails; nothing else imports
this module eagerly.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bls381.cpp")
_HDR = os.path.join(_DIR, "constants.h")
_LIB = os.path.join(_DIR, "_libdrandbls.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        src_m = max(os.path.getmtime(_SRC), os.path.getmtime(_HDR))
    except OSError:
        return False
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_m:
        return True
    tmp = f"{_LIB}.{os.getpid()}.tmp"   # per-process: concurrent first-use
    try:                                # builds must not corrupt the .so
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=300)
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DRAND_TPU_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        for name, args in [
            ("drand_bls_verify_g2",
             [u8p, u8p, ctypes.c_size_t, u8p, u8p, ctypes.c_size_t]),
            ("drand_bls_verify_g1",
             [u8p, u8p, ctypes.c_size_t, u8p, u8p, ctypes.c_size_t]),
            ("drand_tbls_verify_partial",
             [u8p, ctypes.c_int, u8p, ctypes.c_size_t, u8p, ctypes.c_size_t,
              u8p, ctypes.c_size_t]),
            ("drand_g2_lincomb", [u8p, u8p, ctypes.c_int, u8p]),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = ctypes.c_int
        for name in ("drand_hash_to_g2_compressed",
                     "drand_hash_to_g1_compressed"):
            fn = getattr(lib, name)
            fn.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _buf(b: bytes):
    return (ctypes.c_uint8 * len(b)).from_buffer_copy(b)


def verify_g2(pk48: bytes, msg: bytes, sig96: bytes, dst: bytes) -> bool:
    # wire bytes are attacker-controlled: length-gate BEFORE the C call,
    # which reads fixed-size buffers (the golden path rejects via
    # ValueError; here a short buffer would be an out-of-bounds read)
    if len(pk48) != 48 or len(sig96) != 96:
        return False
    lib = _load()
    assert lib is not None
    return bool(lib.drand_bls_verify_g2(
        _buf(pk48), _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(sig96), _buf(dst), len(dst)))


def verify_g1(pk96: bytes, msg: bytes, sig48: bytes, dst: bytes) -> bool:
    if len(pk96) != 96 or len(sig48) != 48:
        return False
    lib = _load()
    assert lib is not None
    return bool(lib.drand_bls_verify_g1(
        _buf(pk96), _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(sig48), _buf(dst), len(dst)))


def verify_partial(commits48: list[bytes], msg: bytes, partial: bytes,
                   dst: bytes) -> bool:
    if len(partial) != 98 or not commits48 or \
            any(len(c) != 48 for c in commits48):
        return False
    lib = _load()
    assert lib is not None
    cat = b"".join(commits48)
    return bool(lib.drand_tbls_verify_partial(
        _buf(cat), len(commits48),
        _buf(msg) if msg else _buf(b"\0"), len(msg),
        _buf(partial), len(partial), _buf(dst), len(dst)))


def g2_lincomb(sigs96: list[bytes], scalars32: list[bytes]) -> bytes | None:
    """sum(scalar_i * sig_i) over G2, compressed — the native
    threshold-recovery combine.  Returns None on malformed points or an
    infinity result."""
    if not sigs96 or len(sigs96) != len(scalars32) or \
            any(len(s) != 96 for s in sigs96) or \
            any(len(c) != 32 for c in scalars32):
        return None
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 96)()
    ok = lib.drand_g2_lincomb(_buf(b"".join(sigs96)),
                              _buf(b"".join(scalars32)),
                              len(sigs96), out)
    return bytes(out) if ok else None


def hash_to_g2(msg: bytes, dst: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 96)()
    lib.drand_hash_to_g2_compressed(
        out, _buf(msg) if msg else _buf(b"\0"), len(msg), _buf(dst), len(dst))
    return bytes(out)


def hash_to_g1(msg: bytes, dst: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    out = (ctypes.c_uint8 * 48)()
    lib.drand_hash_to_g1_compressed(
        out, _buf(msg) if msg else _buf(b"\0"), len(msg), _buf(dst), len(dst))
    return bytes(out)
