// BLS12-381 verification, native host tier.
//
// The reference's hot CPU path is kilic/bls12-381 x86-64 assembly behind
// kyber (`key/curve.go:24`).  This library is the drand_tpu equivalent for
// the LATENCY side of the dual backend: single-beacon and per-partial
// verification on the daemon host (the THROUGHPUT side is the batched
// JAX/Pallas device engine).  It is a faithful port of the validated
// pure-Python golden model in drand_tpu/crypto/bls12381/ -- same tower
// layout, same SSWU+Velu-isogeny hash-to-curve, same e(P,Q)^3 pairing
// convention -- and is tested point-for-point against it plus the pinned
// RFC 9380 vectors (tests/test_native.py).  Every constant comes from
// constants.h, GENERATED from the golden model by
// tools/gen_native_constants.py.
//
// Build: g++ -O3 -march=native -shared -fPIC bls381.cpp -o _libdrandbls.so
// (driven by drand_tpu/native/__init__.py at first import, which probes
// -O3 -march=native and falls back to portable -O2; the chosen flag set
// is recorded in the sidecar build-meta file — native.build_info()).

#include <stdint.h>
#include <string.h>

#include "constants.h"

typedef unsigned __int128 u128;

// ---------------------------------------------------------------------------
// Fp: 6x64-bit limbs, Montgomery form (R = 2^384)
// ---------------------------------------------------------------------------

static inline int fp_is_zero(const fp *a) {
  uint64_t o = 0;
  for (int i = 0; i < 6; i++) o |= a->l[i];
  return o == 0;
}

static inline int fp_eq(const fp *a, const fp *b) {
  uint64_t o = 0;
  for (int i = 0; i < 6; i++) o |= a->l[i] ^ b->l[i];
  return o == 0;
}

static inline int fp_cmp(const fp *a, const fp *b) {  // -1,0,1
  for (int i = 5; i >= 0; i--) {
    if (a->l[i] < b->l[i]) return -1;
    if (a->l[i] > b->l[i]) return 1;
  }
  return 0;
}

static inline void fp_sub_raw(fp *r, const fp *a, const fp *b) {
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a->l[i] - b->l[i] - borrow;
    r->l[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static inline void fp_add(fp *r, const fp *a, const fp *b) {
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a->l[i] + b->l[i] + carry;
    r->l[i] = (uint64_t)s;
    carry = s >> 64;
  }
  if (carry || fp_cmp(r, &BLS_MOD) >= 0) fp_sub_raw(r, r, &BLS_MOD);
}

static inline void fp_sub(fp *r, const fp *a, const fp *b) {
  if (fp_cmp(a, b) >= 0) {
    fp_sub_raw(r, a, b);
  } else {
    fp t;
    fp_sub_raw(&t, b, a);
    fp_sub_raw(r, &BLS_MOD, &t);
  }
}

static inline void fp_neg(fp *r, const fp *a) {
  if (fp_is_zero(a)) { *r = *a; return; }
  fp_sub_raw(r, &BLS_MOD, a);
}

// Non-reducing add/sub for LAZY-REDUCTION operand prep only: results are
// < 2p (p < 2^382, so 2p fits 384 bits) and feed mul_wide, never fp_mul
// (whose no-carry CIOS bound below needs canonical < p inputs).
static inline void fp_add_nored(fp *r, const fp *a, const fp *b) {
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a->l[i] + b->l[i] + carry;
    r->l[i] = (uint64_t)s;
    carry = s >> 64;
  }
}
// a - b + p, in [1, 2p): congruent to a-b without a canonicalizing branch
static inline void fp_sub_nored(fp *r, const fp *a, const fp *b) {
  fp pb;
  fp_sub_raw(&pb, &BLS_MOD, b);  // b < p, so no borrow
  fp_add_nored(r, a, &pb);
}

// Unrolled 6x6 CIOS Montgomery multiplication (Acar et al., the
// "no-carry" variant: BLS12-381's top modulus word 0x1a01... < 2^61
// leaves enough headroom that the running value stays < 2p and the
// seventh accumulator limb never materializes).  One interleaved
// reduction per operand limb; all carries live in registers.
static void fp_mul(fp *r, const fp *a, const fp *b) {
  uint64_t t0 = 0, t1 = 0, t2 = 0, t3 = 0, t4 = 0, t5 = 0;
  u128 z;
#define FP_CIOS_ROUND(AI)                                                     \
  {                                                                           \
    const uint64_t ai = (AI);                                                 \
    uint64_t c1, c2, m;                                                       \
    z = (u128)ai * b->l[0] + t0; t0 = (uint64_t)z; c1 = (uint64_t)(z >> 64);  \
    m = t0 * BLS_INV;                                                         \
    z = (u128)m * BLS_MOD.l[0] + t0; c2 = (uint64_t)(z >> 64);                \
    z = (u128)ai * b->l[1] + t1 + c1; t1 = (uint64_t)z; c1 = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[1] + t1 + c2; t0 = (uint64_t)z; c2 = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[2] + t2 + c1; t2 = (uint64_t)z; c1 = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[2] + t2 + c2; t1 = (uint64_t)z; c2 = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[3] + t3 + c1; t3 = (uint64_t)z; c1 = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[3] + t3 + c2; t2 = (uint64_t)z; c2 = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[4] + t4 + c1; t4 = (uint64_t)z; c1 = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[4] + t4 + c2; t3 = (uint64_t)z; c2 = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[5] + t5 + c1; t5 = (uint64_t)z; c1 = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[5] + t5 + c2; t4 = (uint64_t)z; c2 = (uint64_t)(z >> 64); \
    t5 = c1 + c2;                                                             \
  }
  FP_CIOS_ROUND(a->l[0])
  FP_CIOS_ROUND(a->l[1])
  FP_CIOS_ROUND(a->l[2])
  FP_CIOS_ROUND(a->l[3])
  FP_CIOS_ROUND(a->l[4])
  FP_CIOS_ROUND(a->l[5])
#undef FP_CIOS_ROUND
  fp out = {{t0, t1, t2, t3, t4, t5}};
  if (fp_cmp(&out, &BLS_MOD) >= 0) fp_sub_raw(&out, &out, &BLS_MOD);
  *r = out;
}

// ---------------------------------------------------------------------------
// Double-width (768-bit) lane for lazy reduction: full products are
// accumulated unreduced and pay ONE Montgomery reduction per output
// coefficient (Aranha et al.).  Contract: every value handed to
// redc_wide is < p*2^384, so the reduction output is < 2p and one
// conditional subtraction canonicalizes — results stay bit-identical to
// the reduce-per-fp_mul path (same residue, same canonical form).
// ---------------------------------------------------------------------------

typedef struct { uint64_t l[12]; } fpw;

// 768-bit schoolbook product, rows unrolled (operands may be the
// non-reduced <2p sums from fp_add_nored/fp_sub_nored: 2p*2p < p*2^384).
static void mul_wide(fpw *w, const fp *a, const fp *b) {
  memset(w->l, 0, sizeof(w->l));
  u128 z;
#define MW_ROW(I)                                                             \
  {                                                                           \
    const uint64_t ai = a->l[I];                                              \
    uint64_t cc = 0;                                                          \
    z = (u128)ai * b->l[0] + w->l[I + 0] + cc; w->l[I + 0] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[1] + w->l[I + 1] + cc; w->l[I + 1] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[2] + w->l[I + 2] + cc; w->l[I + 2] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[3] + w->l[I + 3] + cc; w->l[I + 3] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[4] + w->l[I + 4] + cc; w->l[I + 4] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)ai * b->l[5] + w->l[I + 5] + cc; w->l[I + 5] = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    w->l[I + 6] = cc;                                                         \
  }
  MW_ROW(0) MW_ROW(1) MW_ROW(2) MW_ROW(3) MW_ROW(4) MW_ROW(5)
#undef MW_ROW
}

// 768-bit square exploiting partial-product symmetry: 15 distinct cross
// products doubled by one shift, plus 6 diagonal squares — 21 64x64
// multiplies instead of mul_wide's 36.
static void sqr_wide(fpw *w, const fp *a) {
  memset(w->l, 0, sizeof(w->l));
  u128 z;
#define SW_ROW(I, J0)                                                         \
  {                                                                           \
    const uint64_t ai = a->l[I];                                              \
    uint64_t cc = 0;                                                          \
    for (int j = (J0); j < 6; j++) {                                          \
      z = (u128)ai * a->l[j] + w->l[I + j] + cc;                              \
      w->l[I + j] = (uint64_t)z;                                              \
      cc = (uint64_t)(z >> 64);                                               \
    }                                                                         \
    w->l[I + 6] = cc;                                                         \
  }
  SW_ROW(0, 1) SW_ROW(1, 2) SW_ROW(2, 3) SW_ROW(3, 4) SW_ROW(4, 5)
#undef SW_ROW
  // double the cross half (top cross limb is l[10]; carry stays in-range)
  uint64_t hi = 0;
  for (int i = 0; i < 12; i++) {
    uint64_t v = w->l[i];
    w->l[i] = (v << 1) | hi;
    hi = v >> 63;
  }
  // add the diagonal a_i^2 at limb 2i
  uint64_t cc = 0;
  for (int i = 0; i < 6; i++) {
    z = (u128)a->l[i] * a->l[i];
    u128 s = (u128)w->l[2 * i] + (uint64_t)z + cc;
    w->l[2 * i] = (uint64_t)s;
    s = (u128)w->l[2 * i + 1] + (uint64_t)(z >> 64) + (uint64_t)(s >> 64);
    w->l[2 * i + 1] = (uint64_t)s;
    cc = (uint64_t)(s >> 64);
  }
}

// Montgomery reduction of a 768-bit value < p*2^384: six unrolled m*p
// elimination rounds sliding the window up, output canonical.
static void redc_wide(fp *r, const fpw *w) {
  uint64_t t0 = w->l[0], t1 = w->l[1], t2 = w->l[2], t3 = w->l[3],
           t4 = w->l[4], t5 = w->l[5];
  uint64_t hicarry = 0;
  u128 z;
#define RW_ROUND(I)                                                           \
  {                                                                           \
    const uint64_t m = t0 * BLS_INV;                                          \
    uint64_t cc;                                                              \
    z = (u128)m * BLS_MOD.l[0] + t0; cc = (uint64_t)(z >> 64);                \
    z = (u128)m * BLS_MOD.l[1] + t1 + cc; t0 = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[2] + t2 + cc; t1 = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[3] + t3 + cc; t2 = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[4] + t4 + cc; t3 = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)m * BLS_MOD.l[5] + t5 + cc; t4 = (uint64_t)z; cc = (uint64_t)(z >> 64); \
    z = (u128)w->l[6 + (I)] + cc + hicarry;                                   \
    t5 = (uint64_t)z; hicarry = (uint64_t)(z >> 64);                          \
  }
  RW_ROUND(0) RW_ROUND(1) RW_ROUND(2) RW_ROUND(3) RW_ROUND(4) RW_ROUND(5)
#undef RW_ROUND
  // input < p*2^384 => result < 2p: hicarry is 0 here, one cond-sub
  fp out = {{t0, t1, t2, t3, t4, t5}};
  if (fp_cmp(&out, &BLS_MOD) >= 0) fp_sub_raw(&out, &out, &BLS_MOD);
  *r = out;
}

static inline void fpw_add(fpw *r, const fpw *a, const fpw *b) {
  u128 carry = 0;
  for (int i = 0; i < 12; i++) {
    u128 s = (u128)a->l[i] + b->l[i] + carry;
    r->l[i] = (uint64_t)s;
    carry = s >> 64;
  }
}

static inline void fpw_sub(fpw *r, const fpw *a, const fpw *b) {  // a >= b
  u128 borrow = 0;
  for (int i = 0; i < 12; i++) {
    u128 d = (u128)a->l[i] - b->l[i] - borrow;
    r->l[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static inline void fpw_dbl(fpw *r, const fpw *a) {
  uint64_t hi = 0;
  for (int i = 0; i < 12; i++) {
    uint64_t v = a->l[i];
    r->l[i] = (v << 1) | hi;
    hi = v >> 63;
  }
}

// p^2 as a 768-bit integer (exps_init): the offset that keeps wide
// Karatsuba differences non-negative (x + p^2 - y with y < p^2; p^2 is
// 0 mod p, so the residue — hence the canonical result — is unchanged).
static fpw WIDE_PP2;

static inline void fpw_sub_pp2(fpw *r, const fpw *a, const fpw *b) {
  fpw t;
  fpw_add(&t, a, &WIDE_PP2);
  fpw_sub(r, &t, b);
}

static inline void fp_sqr(fp *r, const fp *a) {
  fpw w;
  sqr_wide(&w, a);
  redc_wide(r, &w);
}

// a^e where e is a plain exponent given as 6 limbs (le).
// 4-bit fixed-window MSB-first: ~381 squarings + <=95 table multiplies
// (the same windowing the device engine's pow_const scan uses).
static void fp_pow_limbs(fp *r, const fp *a, const uint64_t e[6]) {
  int top = 5;
  while (top >= 0 && e[top] == 0) top--;
  if (top < 0) { *r = BLS_ONE_M; return; }
  fp tab[16];
  tab[0] = BLS_ONE_M;
  tab[1] = *a;
  for (int i = 2; i < 16; i++) fp_mul(&tab[i], &tab[i - 1], a);
  int nbits = 64 * top + 64 - __builtin_clzll(e[top]);
  int ndig = (nbits + 3) / 4;
  fp acc = BLS_ONE_M;
  int started = 0;
  for (int d = ndig - 1; d >= 0; d--) {
    if (started)
      for (int s = 0; s < 4; s++) fp_sqr(&acc, &acc);
    unsigned dig = (unsigned)((e[(4 * d) / 64] >> ((4 * d) % 64)) & 0xF);
    if (dig) {
      if (started)
        fp_mul(&acc, &acc, &tab[dig]);
      else
        acc = tab[dig];
      started = 1;
    } else if (!started) {
      continue;
    }
  }
  *r = acc;
}

static uint64_t EXP_PM2[6];     // p - 2
static uint64_t EXP_P14[6];     // (p + 1) / 4
static uint64_t EXP_P12[6];     // (p - 1) / 2

static void exps_init(void) {
  uint64_t borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)BLS_MOD.l[i] - ((i == 0) ? 2 : 0) - borrow;
    EXP_PM2[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  // (p+1)/4: p+1 then >>2 (p+1 doesn't overflow 384 bits)
  uint64_t p1[6];
  u128 carry = 1;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)BLS_MOD.l[i] + carry;
    p1[i] = (uint64_t)s;
    carry = s >> 64;
  }
  for (int i = 0; i < 6; i++) {
    uint64_t hi = (i < 5) ? p1[i + 1] : 0;
    EXP_P14[i] = (p1[i] >> 2) | (hi << 62);
  }
  // (p-1)/2
  uint64_t pm1[6];
  borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)BLS_MOD.l[i] - ((i == 0) ? 1 : 0) - borrow;
    pm1[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  for (int i = 0; i < 6; i++) {
    uint64_t hi = (i < 5) ? pm1[i + 1] : 0;
    EXP_P12[i] = (pm1[i] >> 1) | (hi << 63);
  }
  // p^2 (plain integer arithmetic; mul_wide is form-agnostic)
  mul_wide(&WIDE_PP2, &BLS_MOD, &BLS_MOD);
}

static inline void fp_inv(fp *r, const fp *a) { fp_pow_limbs(r, a, EXP_PM2); }

static int fp_sqrt(fp *r, const fp *a) {  // 1 = ok
  if (fp_is_zero(a)) { *r = BLS_ZERO; return 1; }
  fp c, c2;
  fp_pow_limbs(&c, a, EXP_P14);
  fp_sqr(&c2, &c);
  if (!fp_eq(&c2, a)) return 0;
  *r = c;
  return 1;
}

static int fp_is_square(const fp *a) {
  if (fp_is_zero(a)) return 1;
  fp ls;
  fp_pow_limbs(&ls, a, EXP_P12);
  return fp_eq(&ls, &BLS_ONE_M);
}

// Montgomery <-> plain/bytes
static void fp_from_mont(fp *r, const fp *a) {
  fp one = {{1, 0, 0, 0, 0, 0}};
  fp_mul(r, a, &one);
}

static void fp_to_mont(fp *r, const fp *a) { fp_mul(r, a, &BLS_R2); }

static int fp_from_be48(fp *r, const uint8_t b[48]) {  // 1 = canonical
  fp v;
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | b[(5 - i) * 8 + j];
    v.l[i] = w;
  }
  if (fp_cmp(&v, &BLS_MOD) >= 0) return 0;
  fp_to_mont(r, &v);
  return 1;
}

static void fp_to_be48(uint8_t b[48], const fp *a) {
  fp v;
  fp_from_mont(&v, a);
  for (int i = 0; i < 6; i++)
    for (int j = 0; j < 8; j++)
      b[(5 - i) * 8 + j] = (uint8_t)(v.l[i] >> (56 - 8 * j));
}

static int fp_sgn0(const fp *a) {
  fp v;
  fp_from_mont(&v, a);
  return (int)(v.l[0] & 1);
}

static int fp_gt_half(const fp *a) {  // a > (p-1)/2, plain compare
  fp v;
  fp_from_mont(&v, a);
  return fp_cmp(&v, &BLS_HALF_P) > 0;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)  (mirrors golden fp.py)
// ---------------------------------------------------------------------------

static const fp2 FP2_ZERO_C = {{{0}}, {{0}}};

static inline void fp2_add(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_add(&r->c0, &a->c0, &b->c0);
  fp_add(&r->c1, &a->c1, &b->c1);
}
static inline void fp2_sub(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_sub(&r->c0, &a->c0, &b->c0);
  fp_sub(&r->c1, &a->c1, &b->c1);
}
static inline void fp2_neg(fp2 *r, const fp2 *a) {
  fp_neg(&r->c0, &a->c0);
  fp_neg(&r->c1, &a->c1);
}
static inline void fp2_conj(fp2 *r, const fp2 *a) {
  r->c0 = a->c0;
  fp_neg(&r->c1, &a->c1);
}
// Lazy Karatsuba: three double-width products, ONE reduction per output
// coefficient (vs three in the reduce-every-fp_mul form).  c0 rides the
// p^2 offset (t1 < p^2, so t0 + p^2 - t1 stays in [0, 2p^2)); c1 uses
// the exact integer identity (sa*sb = t0 + t1 + cross), both < p*2^384.
static void fp2_mul(fp2 *r, const fp2 *a, const fp2 *b) {
  fp sa, sb;
  fp_add_nored(&sa, &a->c0, &a->c1);
  fp_add_nored(&sb, &b->c0, &b->c1);
  fpw t0, t1, m, w;
  mul_wide(&t0, &a->c0, &b->c0);
  mul_wide(&t1, &a->c1, &b->c1);
  mul_wide(&m, &sa, &sb);
  fp2 out;
  fpw_sub_pp2(&w, &t0, &t1);
  redc_wide(&out.c0, &w);
  fpw_sub(&w, &m, &t0);
  fpw_sub(&w, &w, &t1);
  redc_wide(&out.c1, &w);
  *r = out;
}
// (a0+a1)(a0-a1+p) = a0^2 - a1^2 + p(a0+a1): same residue, < 4p^2, and
// one wide product per coefficient.
static void fp2_sqr(fp2 *r, const fp2 *a) {
  fp s, d;
  fp_add_nored(&s, &a->c0, &a->c1);
  fp_sub_nored(&d, &a->c0, &a->c1);
  fpw w, m;
  fp2 out;
  mul_wide(&w, &s, &d);
  redc_wide(&out.c0, &w);
  mul_wide(&m, &a->c0, &a->c1);
  fpw_dbl(&m, &m);
  redc_wide(&out.c1, &m);
  *r = out;
}
static void fp2_mul_fp(fp2 *r, const fp2 *a, const fp *s) {
  fp_mul(&r->c0, &a->c0, s);
  fp_mul(&r->c1, &a->c1, s);
}
static void fp2_mul_small(fp2 *r, const fp2 *a, int k) {  // k in 1..13
  fp2 acc = *a;
  for (int i = 1; i < k; i++) fp2_add(&acc, &acc, a);
  *r = acc;
}
static void fp2_mul_xi(fp2 *r, const fp2 *a) {  // * (1+u)
  fp2 out;
  fp_sub(&out.c0, &a->c0, &a->c1);
  fp_add(&out.c1, &a->c0, &a->c1);
  *r = out;
}
static inline int fp2_is_zero(const fp2 *a) {
  return fp_is_zero(&a->c0) && fp_is_zero(&a->c1);
}
static inline int fp2_eq(const fp2 *a, const fp2 *b) {
  return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1);
}
static void fp2_inv(fp2 *r, const fp2 *a) {
  fp n, t, ninv;
  fp_sqr(&n, &a->c0);
  fp_sqr(&t, &a->c1);
  fp_add(&n, &n, &t);
  fp_inv(&ninv, &n);
  fp2 out;
  fp_mul(&out.c0, &a->c0, &ninv);
  fp nc1;
  fp_neg(&nc1, &a->c1);
  fp_mul(&out.c1, &nc1, &ninv);
  *r = out;
}
static void fp2_norm(fp *r, const fp2 *a) {
  fp t0, t1;
  fp_sqr(&t0, &a->c0);
  fp_sqr(&t1, &a->c1);
  fp_add(r, &t0, &t1);
}
static int fp2_is_square(const fp2 *a) {
  fp n;
  fp2_norm(&n, a);
  return fp_is_square(&n);
}
// golden fp.py fp2_sqrt (complex method, p = 3 mod 4)
static int fp2_sqrt(fp2 *r, const fp2 *a) {
  if (fp2_is_zero(a)) { *r = FP2_ZERO_C; return 1; }
  if (fp_is_zero(&a->c1)) {
    fp s;
    if (fp_sqrt(&s, &a->c0)) {
      r->c0 = s;
      r->c1 = BLS_ZERO;
      return 1;
    }
    fp na;
    fp_neg(&na, &a->c0);
    if (!fp_sqrt(&s, &na)) return 0;
    r->c0 = BLS_ZERO;
    r->c1 = s;
    return 1;
  }
  fp alpha, n;
  fp2_norm(&n, a);
  if (!fp_sqrt(&alpha, &n)) return 0;
  // inv2 = (p+1)/2 as field element: (1/2) mod p
  fp two = BLS_ONE_M, inv2;
  fp_add(&two, &two, &BLS_ONE_M);
  fp_inv(&inv2, &two);
  fp delta, x0;
  fp_add(&delta, &a->c0, &alpha);
  fp_mul(&delta, &delta, &inv2);
  if (!fp_sqrt(&x0, &delta)) {
    fp_sub(&delta, &a->c0, &alpha);
    fp_mul(&delta, &delta, &inv2);
    if (!fp_sqrt(&x0, &delta)) return 0;
  }
  fp x0i, x1;
  fp_inv(&x0i, &x0);
  fp_mul(&x1, &a->c1, &inv2);
  fp_mul(&x1, &x1, &x0i);
  fp2 cand = {x0, x1}, chk;
  fp2_sqr(&chk, &cand);
  if (!fp2_eq(&chk, a)) return 0;
  *r = cand;
  return 1;
}
static int fp2_sgn0(const fp2 *a) {  // RFC 9380 sgn0, m=2
  int s0 = fp_sgn0(&a->c0);
  int z0 = fp_is_zero(&a->c0);
  int s1 = fp_sgn0(&a->c1);
  return s0 | (z0 & s1);
}
static int fp2_gt_half(const fp2 *a) {  // ZCash lexicographic sign rule
  if (!fp_is_zero(&a->c1)) return fp_gt_half(&a->c1);
  return fp_gt_half(&a->c0);
}

// Double-width Fp2: a pair of unreduced 768-bit accumulators.  Tower
// formulas sum several of these and reduce ONCE per output coefficient.
// Bounds (units of p^2, budget p*2^384 ~ 9.8 p^2): fp2_mulw (2,2),
// fp2_mulw_fp (1,1), fp2_mulw_fp_xi (2,2) — so a three-term sum tops out
// at 6p^2, comfortably inside the redc_wide contract.
typedef struct { fpw c0, c1; } fp2w;

static void fp2_mulw(fp2w *w, const fp2 *a, const fp2 *b) {
  fp sa, sb;
  fp_add_nored(&sa, &a->c0, &a->c1);
  fp_add_nored(&sb, &b->c0, &b->c1);
  fpw t0, t1, m;
  mul_wide(&t0, &a->c0, &b->c0);
  mul_wide(&t1, &a->c1, &b->c1);
  mul_wide(&m, &sa, &sb);
  fpw_sub_pp2(&w->c0, &t0, &t1);
  fpw_sub(&m, &m, &t0);
  fpw_sub(&w->c1, &m, &t1);
}

static void fp2_mulw_fp(fp2w *w, const fp2 *a, const fp *s) {  // a * (s, 0)
  mul_wide(&w->c0, &a->c0, s);
  mul_wide(&w->c1, &a->c1, s);
}

// a * xi*(s, 0) = a * (s, s) = (s*(a0 - a1), s*(a0 + a1))
static void fp2_mulw_fp_xi(fp2w *w, const fp2 *a, const fp *s) {
  fp d, su;
  fp_sub_nored(&d, &a->c0, &a->c1);
  fp_add_nored(&su, &a->c0, &a->c1);
  mul_wide(&w->c0, &d, s);
  mul_wide(&w->c1, &su, s);
}

static inline void fp2w_add(fp2w *r, const fp2w *a) {
  fpw_add(&r->c0, &r->c0, &a->c0);
  fpw_add(&r->c1, &r->c1, &a->c1);
}

static inline void fp2w_redc(fp2 *r, const fp2w *w) {
  redc_wide(&r->c0, &w->c0);
  redc_wide(&r->c1, &w->c1);
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - xi),  Fp12 = Fp6[w]/(w^2 - v)   (mirrors fp.py)
// ---------------------------------------------------------------------------

typedef struct { fp2 a0, a1, a2; } fp6;
typedef struct { fp6 b0, b1; } fp12;

static void fp6_add(fp6 *r, const fp6 *a, const fp6 *b) {
  fp2_add(&r->a0, &a->a0, &b->a0);
  fp2_add(&r->a1, &a->a1, &b->a1);
  fp2_add(&r->a2, &a->a2, &b->a2);
}
static void fp6_sub(fp6 *r, const fp6 *a, const fp6 *b) {
  fp2_sub(&r->a0, &a->a0, &b->a0);
  fp2_sub(&r->a1, &a->a1, &b->a1);
  fp2_sub(&r->a2, &a->a2, &b->a2);
}
static void fp6_neg(fp6 *r, const fp6 *a) {
  fp2_neg(&r->a0, &a->a0);
  fp2_neg(&r->a1, &a->a1);
  fp2_neg(&r->a2, &a->a2);
}
static void fp6_mul(fp6 *r, const fp6 *a, const fp6 *b) {
  fp2 s1, s2, m, x;
  // c0 = a0 b0 + xi((a1+a2)(b1+b2) - t1 - t2)
  fp2 p0, p1, p2;
  fp2_mul(&p0, &a->a0, &b->a0);
  fp2_mul(&p1, &a->a1, &b->a1);
  fp2_mul(&p2, &a->a2, &b->a2);
  fp6 out;
  fp2_add(&s1, &a->a1, &a->a2);
  fp2_add(&s2, &b->a1, &b->a2);
  fp2_mul(&m, &s1, &s2);
  fp2_sub(&m, &m, &p1);
  fp2_sub(&m, &m, &p2);
  fp2_mul_xi(&x, &m);
  fp2_add(&out.a0, &p0, &x);
  // c1 = (a0+a1)(b0+b1) - p0 - p1 + xi p2
  fp2_add(&s1, &a->a0, &a->a1);
  fp2_add(&s2, &b->a0, &b->a1);
  fp2_mul(&m, &s1, &s2);
  fp2_sub(&m, &m, &p0);
  fp2_sub(&m, &m, &p1);
  fp2_mul_xi(&x, &p2);
  fp2_add(&out.a1, &m, &x);
  // c2 = (a0+a2)(b0+b2) - p0 - p2 + p1
  fp2_add(&s1, &a->a0, &a->a2);
  fp2_add(&s2, &b->a0, &b->a2);
  fp2_mul(&m, &s1, &s2);
  fp2_sub(&m, &m, &p0);
  fp2_sub(&m, &m, &p2);
  fp2_add(&out.a2, &m, &p1);
  *r = out;
}
static void fp6_sqr(fp6 *r, const fp6 *a) { fp6_mul(r, a, a); }
static void fp6_mul_by_v(fp6 *r, const fp6 *a) {
  fp6 out;
  fp2_mul_xi(&out.a0, &a->a2);
  out.a1 = a->a0;
  out.a2 = a->a1;
  *r = out;
}
static void fp6_mul_fp2(fp6 *r, const fp6 *a, const fp2 *s) {
  fp2_mul(&r->a0, &a->a0, s);
  fp2_mul(&r->a1, &a->a1, s);
  fp2_mul(&r->a2, &a->a2, s);
}
static void fp6_inv(fp6 *r, const fp6 *a) {
  fp2 t0, t1, t2, t3, t4, t5, c0, c1, c2, det, di, x;
  fp2_sqr(&t0, &a->a0);
  fp2_sqr(&t1, &a->a1);
  fp2_sqr(&t2, &a->a2);
  fp2_mul(&t3, &a->a0, &a->a1);
  fp2_mul(&t4, &a->a0, &a->a2);
  fp2_mul(&t5, &a->a1, &a->a2);
  fp2_mul_xi(&x, &t5);
  fp2_sub(&c0, &t0, &x);
  fp2_mul_xi(&x, &t2);
  fp2_sub(&c1, &x, &t3);
  fp2_sub(&c2, &t1, &t4);
  fp2 m1, m2, s;
  fp2_mul(&m1, &a->a2, &c1);
  fp2_mul(&m2, &a->a1, &c2);
  fp2_add(&s, &m1, &m2);
  fp2_mul_xi(&x, &s);
  fp2_mul(&m1, &a->a0, &c0);
  fp2_add(&det, &m1, &x);
  fp2_inv(&di, &det);
  fp2_mul(&r->a0, &c0, &di);
  fp2_mul(&r->a1, &c1, &di);
  fp2_mul(&r->a2, &c2, &di);
}

static void fp12_mul(fp12 *r, const fp12 *a, const fp12 *b) {
  fp6 t0, t1, s1, s2, m, v;
  fp6_mul(&t0, &a->b0, &b->b0);
  fp6_mul(&t1, &a->b1, &b->b1);
  fp12 out;
  fp6_mul_by_v(&v, &t1);
  fp6_add(&out.b0, &t0, &v);
  fp6_add(&s1, &a->b0, &a->b1);
  fp6_add(&s2, &b->b0, &b->b1);
  fp6_mul(&m, &s1, &s2);
  fp6_sub(&m, &m, &t0);
  fp6_sub(&out.b1, &m, &t1);
  *r = out;
}
static void fp12_sqr(fp12 *r, const fp12 *a) {
  fp6 t, s1, s2, m, v;
  fp6_mul(&t, &a->b0, &a->b1);
  fp6_add(&s1, &a->b0, &a->b1);
  fp6_mul_by_v(&v, &a->b1);
  fp6_add(&s2, &a->b0, &v);
  fp6_mul(&m, &s1, &s2);
  fp6_sub(&m, &m, &t);
  fp6_mul_by_v(&v, &t);
  fp12 out;
  fp6_sub(&out.b0, &m, &v);
  fp6_add(&out.b1, &t, &t);
  *r = out;
}
static void fp12_conj(fp12 *r, const fp12 *a) {
  r->b0 = a->b0;
  fp6_neg(&r->b1, &a->b1);
}
static void fp12_inv(fp12 *r, const fp12 *a) {
  fp6 s0, s1, det, di, v;
  fp6_sqr(&s0, &a->b0);
  fp6_sqr(&s1, &a->b1);
  fp6_mul_by_v(&v, &s1);
  fp6_sub(&det, &s0, &v);
  fp6_inv(&di, &det);
  fp6_mul(&r->b0, &a->b0, &di);
  fp6 m;
  fp6_mul(&m, &a->b1, &di);
  fp6_neg(&r->b1, &m);
}
static void fp6_frob(fp6 *r, const fp6 *a) {
  fp6 out;
  fp2_conj(&out.a0, &a->a0);
  fp2 c;
  fp2_conj(&c, &a->a1);
  fp2_mul(&out.a1, &c, &BLS_FROB_G2);
  fp2_conj(&c, &a->a2);
  fp2_mul(&out.a2, &c, &BLS_FROB_G4);
  *r = out;
}
static void fp12_frob(fp12 *r, const fp12 *a) {
  fp12 out;
  fp6_frob(&out.b0, &a->b0);
  fp6 f;
  fp6_frob(&f, &a->b1);
  fp6_mul_fp2(&out.b1, &f, &BLS_FROB_G1);
  *r = out;
}
static void fp12_frob_n(fp12 *r, const fp12 *a, int n) {
  fp12 t = *a;
  for (int i = 0; i < n; i++) fp12_frob(&t, &t);
  *r = t;
}

static void fp12_one(fp12 *r) {
  memset(r, 0, sizeof(*r));
  r->b0.a0.c0 = BLS_ONE_M;
}
static int fp12_is_one(const fp12 *a) {
  fp12 one;
  fp12_one(&one);
  return memcmp(a, &one, sizeof(one)) == 0;
}

// ---------------------------------------------------------------------------
// Curve points (Jacobian, a = 0), G1 over Fp and G2 over Fp2
// (mirrors golden curve.py; generic via macros over the field type)
// ---------------------------------------------------------------------------

typedef struct { fp x, y, z; } g1p;
typedef struct { fp2 x, y, z; } g2p;

#define DEF_POINT_OPS(NAME, PT, FE, F_ADD, F_SUB, F_NEG, F_MUL, F_SQR,        \
                      F_ISZ, F_EQ)                                            \
  static int NAME##_is_inf(const PT *p) { return F_ISZ(&p->z); }              \
  static void NAME##_dbl(PT *r, const PT *p) {                                \
    if (F_ISZ(&p->z)) { *r = *p; return; }                                    \
    FE a, b, c, d, e, f, t, x3, y3, z3, c8;                                   \
    F_SQR(&a, &p->x);                                                         \
    F_SQR(&b, &p->y);                                                         \
    F_SQR(&c, &b);                                                            \
    F_ADD(&t, &p->x, &b);                                                     \
    F_SQR(&d, &t);                                                            \
    F_SUB(&d, &d, &a);                                                        \
    F_SUB(&d, &d, &c);                                                        \
    F_ADD(&d, &d, &d);                                                        \
    F_ADD(&e, &a, &a);                                                        \
    F_ADD(&e, &e, &a);                                                        \
    F_SQR(&f, &e);                                                            \
    F_ADD(&t, &d, &d);                                                        \
    F_SUB(&x3, &f, &t);                                                       \
    F_ADD(&c8, &c, &c);                                                       \
    F_ADD(&c8, &c8, &c8);                                                     \
    F_ADD(&c8, &c8, &c8);                                                     \
    F_SUB(&t, &d, &x3);                                                       \
    F_MUL(&y3, &e, &t);                                                       \
    F_SUB(&y3, &y3, &c8);                                                     \
    F_MUL(&t, &p->y, &p->z);                                                  \
    F_ADD(&z3, &t, &t);                                                       \
    r->x = x3; r->y = y3; r->z = z3;                                          \
  }                                                                           \
  static void NAME##_add(PT *r, const PT *p1, const PT *p2) {                 \
    if (F_ISZ(&p1->z)) { *r = *p2; return; }                                  \
    if (F_ISZ(&p2->z)) { *r = *p1; return; }                                  \
    FE z1z1, z2z2, u1, u2, s1, s2, t, h, i, j, rr, v, x3, y3, z3;             \
    F_SQR(&z1z1, &p1->z);                                                     \
    F_SQR(&z2z2, &p2->z);                                                     \
    F_MUL(&u1, &p1->x, &z2z2);                                                \
    F_MUL(&u2, &p2->x, &z1z1);                                                \
    F_MUL(&t, &p1->y, &p2->z);                                                \
    F_MUL(&s1, &t, &z2z2);                                                    \
    F_MUL(&t, &p2->y, &p1->z);                                                \
    F_MUL(&s2, &t, &z1z1);                                                    \
    if (F_EQ(&u1, &u2)) {                                                     \
      if (F_EQ(&s1, &s2)) { NAME##_dbl(r, p1); return; }                      \
      memset(r, 0, sizeof(*r));                                               \
      return;                                                                 \
    }                                                                         \
    F_SUB(&h, &u2, &u1);                                                      \
    F_ADD(&t, &h, &h);                                                        \
    F_SQR(&i, &t);                                                            \
    F_MUL(&j, &h, &i);                                                        \
    F_SUB(&rr, &s2, &s1);                                                     \
    F_ADD(&rr, &rr, &rr);                                                     \
    F_MUL(&v, &u1, &i);                                                       \
    F_SQR(&x3, &rr);                                                          \
    F_SUB(&x3, &x3, &j);                                                      \
    F_ADD(&t, &v, &v);                                                        \
    F_SUB(&x3, &x3, &t);                                                      \
    F_SUB(&t, &v, &x3);                                                       \
    F_MUL(&y3, &rr, &t);                                                      \
    F_MUL(&t, &s1, &j);                                                       \
    F_ADD(&t, &t, &t);                                                        \
    F_SUB(&y3, &y3, &t);                                                      \
    F_ADD(&t, &p1->z, &p2->z);                                                \
    F_SQR(&t, &t);                                                            \
    F_SUB(&t, &t, &z1z1);                                                     \
    F_SUB(&t, &t, &z2z2);                                                     \
    F_MUL(&z3, &t, &h);                                                       \
    r->x = x3; r->y = y3; r->z = z3;                                          \
  }                                                                           \
  static void NAME##_mul_u64(PT *r, const PT *p, uint64_t k) {                \
    PT acc; memset(&acc, 0, sizeof(acc));                                     \
    PT base = *p;                                                             \
    while (k) {                                                               \
      if (k & 1) NAME##_add(&acc, &acc, &base);                               \
      NAME##_dbl(&base, &base);                                               \
      k >>= 1;                                                                \
    }                                                                         \
    *r = acc;                                                                 \
  }

DEF_POINT_OPS(g1, g1p, fp, fp_add, fp_sub, fp_neg, fp_mul, fp_sqr,
              fp_is_zero, fp_eq)
DEF_POINT_OPS(g2, g2p, fp2, fp2_add, fp2_sub, fp2_neg, fp2_mul, fp2_sqr,
              fp2_is_zero, fp2_eq)

static void g1_neg(g1p *r, const g1p *p) {
  r->x = p->x;
  fp_neg(&r->y, &p->y);
  r->z = p->z;
}
static void g2_neg(g2p *r, const g2p *p) {
  r->x = p->x;
  fp2_neg(&r->y, &p->y);
  r->z = p->z;
}

static int g1_to_affine(fp *x, fp *y, const g1p *p) {
  if (g1_is_inf(p)) return 0;
  fp zi, zi2, zi3;
  fp_inv(&zi, &p->z);
  fp_sqr(&zi2, &zi);
  fp_mul(&zi3, &zi2, &zi);
  fp_mul(x, &p->x, &zi2);
  fp_mul(y, &p->y, &zi3);
  return 1;
}
static int g2_to_affine(fp2 *x, fp2 *y, const g2p *p) {
  if (g2_is_inf(p)) return 0;
  fp2 zi, zi2, zi3;
  fp2_inv(&zi, &p->z);
  fp2_sqr(&zi2, &zi);
  fp2_mul(&zi3, &zi2, &zi);
  fp2_mul(x, &p->x, &zi2);
  fp2_mul(y, &p->y, &zi3);
  return 1;
}

// mul by 256-bit scalar (be bytes), variable base
static void g2_mul_be(g2p *r, const g2p *p, const uint8_t *be, int len) {
  g2p acc;
  memset(&acc, 0, sizeof(acc));
  for (int i = 0; i < len; i++) {
    for (int b = 7; b >= 0; b--) {
      g2_dbl(&acc, &acc);
      if ((be[i] >> b) & 1) g2_add(&acc, &acc, p);
    }
  }
  *r = acc;
}

// psi endomorphism (golden curve.py g2_psi)
static void g2_psi(g2p *r, const g2p *p) {
  fp2 cx, cy, cz;
  fp2_conj(&cx, &p->x);
  fp2_conj(&cy, &p->y);
  fp2_conj(&cz, &p->z);
  fp2_mul(&r->x, &cx, &BLS_PSI_X);
  fp2_mul(&r->y, &cy, &BLS_PSI_Y);
  r->z = cz;
}

static int g2_eq_points(const g2p *a, const g2p *b) {
  int ia = g2_is_inf(a), ib = g2_is_inf(b);
  if (ia || ib) return ia && ib;
  fp2 za2, zb2, t1, t2, za3, zb3;
  fp2_sqr(&za2, &a->z);
  fp2_sqr(&zb2, &b->z);
  fp2_mul(&t1, &a->x, &zb2);
  fp2_mul(&t2, &b->x, &za2);
  if (!fp2_eq(&t1, &t2)) return 0;
  fp2_mul(&za3, &za2, &a->z);
  fp2_mul(&zb3, &zb2, &b->z);
  fp2_mul(&t1, &a->y, &zb3);
  fp2_mul(&t2, &b->y, &za3);
  return fp2_eq(&t1, &t2);
}

// [k]P for 64-bit k with sign handling for the negative BLS parameter:
// returns [x]P where x = -|x|.
static void g2_mul_x(g2p *r, const g2p *p) {
  g2p t;
  g2_mul_u64(&t, p, BLS_X_ABS);
  g2_neg(r, &t);
}

static int g2_in_subgroup(const g2p *p) {  // psi(Q) == [x]Q
  if (g2_is_inf(p)) return 1;
  g2p lhs, rhs;
  g2_psi(&lhs, p);
  g2_mul_x(&rhs, p);
  return g2_eq_points(&lhs, &rhs);
}

// BP cofactor clearing (golden curve.py g2_clear_cofactor):
// [x^2-x-1]Q + [x-1]psi(Q) + psi^2(2Q)
static void g2_clear_cofactor(g2p *r, const g2p *q) {
  g2p xq, x2q, t, p1, p2, nq, nxq;
  g2_mul_x(&xq, q);
  g2_mul_x(&x2q, &xq);
  g2_neg(&nxq, &xq);
  g2_add(&t, &x2q, &nxq);      // [x^2 - x]Q
  g2_neg(&nq, q);
  g2_add(&t, &t, &nq);         // [x^2 - x - 1]Q
  g2_add(&p1, &xq, &nq);       // [x - 1]Q
  g2_psi(&p1, &p1);
  g2p dq;
  g2_dbl(&dq, q);
  g2_psi(&p2, &dq);
  g2_psi(&p2, &p2);
  g2_add(&t, &t, &p1);
  g2_add(r, &t, &p2);
}

// G1 effective cofactor (1 - x) = 1 + |x|
static void g1_clear_cofactor(g1p *r, const g1p *p) {
  g1p t;
  g1_mul_u64(&t, p, BLS_X_ABS);
  g1_add(r, &t, p);
}

// G1 subgroup check via GLV endomorphism phi(x,y) = (beta x, y):
// in-subgroup iff phi(P) == [lambda]P with lambda = x^2 - 1 (derived and
// convention-checked at init against [r]P == inf on the generator side).
static fp G1_BETA;        // cube root of unity (mont)
static int g1_endo_ready = 0;

static void g1_endo_init(void) {
  // beta = xi_fp^((p-1)/3)? Derive instead from x: beta is a nontrivial
  // cube root of 1: find via 2^((p-1)/3) style search on small bases.
  fp base = BLS_ONE_M;  // start from 2
  fp two;
  fp_add(&two, &BLS_ONE_M, &BLS_ONE_M);
  base = two;
  // exponent (p-1)/3
  uint64_t e[6];
  uint64_t pm1[6];
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)BLS_MOD.l[i] - ((i == 0) ? 1 : 0) - borrow;
    pm1[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  // divide pm1 by 3 (exact)
  u128 rem = 0;
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | pm1[i];
    e[i] = (uint64_t)(cur / 3);
    rem = cur % 3;
  }
  for (int tries = 0; tries < 40; tries++) {
    fp cand;
    fp_pow_limbs(&cand, &base, e);
    if (!fp_eq(&cand, &BLS_ONE_M)) {
      G1_BETA = cand;
      g1_endo_ready = 1;
      return;
    }
    fp_add(&base, &base, &BLS_ONE_M);
  }
}

static int g1_in_subgroup(const g1p *p) {
  if (g1_is_inf(p)) return 1;
  // phi(P) = (beta x, y); check phi(P) == [x^2-1]P  (lambda = x^2 - 1)
  // [x^2]P = [|x|]([|x|]P) since (-x)(-x) = x^2
  g1p xp, x2p, lam, phi;
  g1_mul_u64(&xp, p, BLS_X_ABS);
  g1_mul_u64(&x2p, &xp, BLS_X_ABS);
  g1p np;
  g1_neg(&np, p);
  g1_add(&lam, &x2p, &np);  // [x^2 - 1]P
  phi = *p;
  fp_mul(&phi.x, &phi.x, &G1_BETA);
  // compare
  int ia = g1_is_inf(&phi), ib = g1_is_inf(&lam);
  if (ia || ib) return ia && ib;
  fp za2, zb2, t1, t2, za3, zb3;
  fp_sqr(&za2, &phi.z);
  fp_sqr(&zb2, &lam.z);
  fp_mul(&t1, &phi.x, &zb2);
  fp_mul(&t2, &lam.x, &za2);
  if (!fp_eq(&t1, &t2)) {
    // beta has two nontrivial cube roots; the other one pairs with
    // lambda' = -x^2: check phi'(P) = (beta^2 x, y)
    g1p phi2 = *p;
    fp b2;
    fp_sqr(&b2, &G1_BETA);
    fp_mul(&phi2.x, &phi2.x, &b2);
    fp_sqr(&za2, &phi2.z);
    fp_mul(&t1, &phi2.x, &zb2);
    if (!fp_eq(&t1, &t2)) return 0;
    fp_mul(&za3, &za2, &phi2.z);
    fp_mul(&zb3, &zb2, &lam.z);
    fp_mul(&t1, &phi2.y, &zb3);
    fp_mul(&t2, &lam.y, &za3);
    return fp_eq(&t1, &t2);
  }
  fp_mul(&za3, &za2, &phi.z);
  fp_mul(&zb3, &zb2, &lam.z);
  fp_mul(&t1, &phi.y, &zb3);
  fp_mul(&t2, &lam.y, &za3);
  return fp_eq(&t1, &t2);
}

// ---------------------------------------------------------------------------
// Compressed deserialization (ZCash flags; golden curve.py:345-429)
// ---------------------------------------------------------------------------

static int g1_from_bytes(g1p *r, const uint8_t b[48]) {
  uint8_t flags = b[0];
  if (!(flags & 0x80)) return 0;
  if (flags & 0x40) { memset(r, 0, sizeof(*r)); return 1; }
  uint8_t xb[48];
  memcpy(xb, b, 48);
  xb[0] &= 0x1F;
  fp x;
  if (!fp_from_be48(&x, xb)) return 0;
  fp y2, t;
  fp_sqr(&t, &x);
  fp_mul(&y2, &t, &x);
  fp_add(&y2, &y2, &BLS_B_G1);
  fp y;
  if (!fp_sqrt(&y, &y2)) return 0;
  int big = fp_gt_half(&y);
  if (((flags >> 5) & 1) != big) fp_neg(&y, &y);
  r->x = x;
  r->y = y;
  r->z = BLS_ONE_M;
  return 1;
}

static int g2_from_bytes(g2p *r, const uint8_t b[96]) {
  uint8_t flags = b[0];
  if (!(flags & 0x80)) return 0;
  if (flags & 0x40) { memset(r, 0, sizeof(*r)); return 1; }
  uint8_t x1b[48];
  memcpy(x1b, b, 48);
  x1b[0] &= 0x1F;
  fp x1, x0;
  if (!fp_from_be48(&x1, x1b)) return 0;
  if (!fp_from_be48(&x0, b + 48)) return 0;
  fp2 x = {x0, x1};
  fp2 y2, t;
  fp2_sqr(&t, &x);
  fp2_mul(&y2, &t, &x);
  fp2_add(&y2, &y2, &BLS_B_G2);
  fp2 y;
  if (!fp2_sqrt(&y, &y2)) return 0;
  int big = fp2_gt_half(&y);
  if (((flags >> 5) & 1) != big) fp2_neg(&y, &y);
  r->x = x;
  r->y = y;
  r->z.c0 = BLS_ONE_M;
  r->z.c1 = BLS_ZERO;
  return 1;
}

static void g1_to_bytes(uint8_t out[48], const g1p *p) {
  if (g1_is_inf(p)) {
    memset(out, 0, 48);
    out[0] = 0xC0;
    return;
  }
  fp x, y;
  g1_to_affine(&x, &y, p);
  fp_to_be48(out, &x);
  out[0] |= 0x80;
  if (fp_gt_half(&y)) out[0] |= 0x20;
}

static void g2_to_bytes(uint8_t out[96], const g2p *p) {
  if (g2_is_inf(p)) {
    memset(out, 0, 96);
    out[0] = 0xC0;
    return;
  }
  fp2 x, y;
  g2_to_affine(&x, &y, p);
  fp_to_be48(out, &x.c1);
  fp_to_be48(out + 48, &x.c0);
  out[0] |= 0x80;
  if (fp2_gt_half(&y)) out[0] |= 0x20;
}

// ---------------------------------------------------------------------------
// SHA-256 (for expand_message_xmd + digesting)
// ---------------------------------------------------------------------------

typedef struct {
  uint32_t h[8];
  uint64_t len;
  uint8_t buf[64];
  int fill;
} sha256_ctx;

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t ror(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha_block(sha256_ctx *c, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
           ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = ror(w[i - 15], 7) ^ ror(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = ror(w[i - 2], 17) ^ ror(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = ror(e, 6) ^ ror(e, 11) ^ ror(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + SHA_K[i] + w[i];
    uint32_t S0 = ror(a, 2) ^ ror(a, 13) ^ ror(a, 22);
    uint32_t mj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + mj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void sha_init(sha256_ctx *c) {
  static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(c->h, iv, sizeof(iv));
  c->len = 0;
  c->fill = 0;
}
static void sha_update(sha256_ctx *c, const uint8_t *p, size_t n) {
  c->len += n;
  while (n) {
    size_t take = 64 - c->fill;
    if (take > n) take = n;
    memcpy(c->buf + c->fill, p, take);
    c->fill += (int)take;
    p += take;
    n -= take;
    if (c->fill == 64) {
      sha_block(c, c->buf);
      c->fill = 0;
    }
  }
}
static void sha_final(sha256_ctx *c, uint8_t out[32]) {
  uint64_t bits = c->len * 8;
  uint8_t pad = 0x80;
  sha_update(c, &pad, 1);
  uint8_t z = 0;
  while (c->fill != 56) sha_update(c, &z, 1);
  uint8_t lb[8];
  for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
  sha_update(c, lb, 8);
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 4; j++)
      out[4 * i + j] = (uint8_t)(c->h[i] >> (24 - 8 * j));
}

// ---------------------------------------------------------------------------
// expand_message_xmd + hash_to_field (RFC 9380; golden h2c.py)
// ---------------------------------------------------------------------------

static void expand_xmd(uint8_t *out, size_t len_out, const uint8_t *msg,
                       size_t msg_len, const uint8_t *dst, size_t dst_len) {
  uint8_t dstp[256];
  size_t dplen = dst_len;
  memcpy(dstp, dst, dst_len);
  dstp[dplen++] = (uint8_t)dst_len;
  int ell = (int)((len_out + 31) / 32);
  uint8_t b0[32], bi[32];
  sha256_ctx c;
  sha_init(&c);
  uint8_t zpad[64] = {0};
  sha_update(&c, zpad, 64);
  sha_update(&c, msg, msg_len);
  uint8_t lib[3] = {(uint8_t)(len_out >> 8), (uint8_t)len_out, 0};
  sha_update(&c, lib, 3);
  sha_update(&c, dstp, dplen);
  sha_final(&c, b0);
  sha_init(&c);
  sha_update(&c, b0, 32);
  uint8_t one = 1;
  sha_update(&c, &one, 1);
  sha_update(&c, dstp, dplen);
  sha_final(&c, bi);
  size_t off = 0;
  for (int i = 1;; i++) {
    size_t take = len_out - off;
    if (take > 32) take = 32;
    memcpy(out + off, bi, take);
    off += take;
    if (off >= len_out) break;
    uint8_t x[32];
    for (int j = 0; j < 32; j++) x[j] = b0[j] ^ bi[j];
    sha_init(&c);
    sha_update(&c, x, 32);
    uint8_t idx = (uint8_t)(i + 1);
    sha_update(&c, &idx, 1);
    sha_update(&c, dstp, dplen);
    sha_final(&c, bi);
  }
}

// 64-byte big-endian draw -> fp (Montgomery): value mod p
static void fp_from_be64_draw(fp *r, const uint8_t b[64]) {
  // split: hi = first 16 bytes, lo = last 48; value = hi*2^384 + lo
  // mont(value) = mont_mul(hi_plain, R3) + mont_mul(lo_plain, R2)
  // simpler: iterate bytes with r = r*256 + b (Horner) in plain domain via
  // Montgomery: keep acc in Montgomery, mul by 256_mont each step.
  fp acc = BLS_ZERO;
  fp mont256;
  fp v256 = {{256, 0, 0, 0, 0, 0}};
  fp_to_mont(&mont256, &v256);
  for (int i = 0; i < 64; i++) {
    fp_mul(&acc, &acc, &mont256);
    fp add = {{b[i], 0, 0, 0, 0, 0}};
    fp addm;
    fp_to_mont(&addm, &add);
    fp_add(&acc, &acc, &addm);
  }
  *r = acc;
}

// ---------------------------------------------------------------------------
// SSWU + isogenies (golden h2c.py)
// ---------------------------------------------------------------------------

static void sswu_fp2(fp2 *xo, fp2 *yo, const fp2 *u) {
  fp2 u2, zu2, tv1, tv2, x1, gx1, t, ai, bi;
  fp2_sqr(&u2, u);
  fp2_mul(&zu2, &SSWU2_Z, &u2);
  fp2_sqr(&tv1, &zu2);
  fp2_add(&tv2, &tv1, &zu2);
  if (fp2_is_zero(&tv2)) {
    fp2 za;
    fp2_mul(&za, &SSWU2_Z, &SSWU2_A);
    fp2_inv(&t, &za);
    fp2_mul(&x1, &SSWU2_B, &t);
  } else {
    fp2_inv(&ai, &SSWU2_A);
    fp2_mul(&bi, &SSWU2_B, &ai);
    fp2_neg(&bi, &bi);  // -B/A
    fp2 one = {BLS_ONE_M, BLS_ZERO};
    fp2_inv(&t, &tv2);
    fp2_add(&t, &t, &one);
    fp2_mul(&x1, &bi, &t);
  }
  fp2 x = x1;
  fp2_sqr(&t, &x);
  fp2_mul(&gx1, &t, &x);
  fp2_mul(&t, &SSWU2_A, &x);
  fp2_add(&gx1, &gx1, &t);
  fp2_add(&gx1, &gx1, &SSWU2_B);
  fp2 y;
  if (!fp2_sqrt(&y, &gx1)) {
    fp2_mul(&x, &zu2, &x1);
    fp2 gx2;
    fp2_sqr(&t, &x);
    fp2_mul(&gx2, &t, &x);
    fp2_mul(&t, &SSWU2_A, &x);
    fp2_add(&gx2, &gx2, &t);
    fp2_add(&gx2, &gx2, &SSWU2_B);
    fp2_sqrt(&y, &gx2);
  }
  if (fp2_sgn0(u) != fp2_sgn0(&y)) fp2_neg(&y, &y);
  *xo = x;
  *yo = y;
}

static void sswu_fp(fp *xo, fp *yo, const fp *u) {
  fp u2, zu2, tv1, tv2, x1, gx1, t, ai, bi;
  fp_sqr(&u2, u);
  fp_mul(&zu2, &SSWU1_Z, &u2);
  fp_sqr(&tv1, &zu2);
  fp_add(&tv2, &tv1, &zu2);
  if (fp_is_zero(&tv2)) {
    fp za;
    fp_mul(&za, &SSWU1_Z, &SSWU1_A);
    fp_inv(&t, &za);
    fp_mul(&x1, &SSWU1_B, &t);
  } else {
    fp_inv(&ai, &SSWU1_A);
    fp_mul(&bi, &SSWU1_B, &ai);
    fp_neg(&bi, &bi);
    fp_inv(&t, &tv2);
    fp_add(&t, &t, &BLS_ONE_M);
    fp_mul(&x1, &bi, &t);
  }
  fp x = x1;
  fp_sqr(&t, &x);
  fp_mul(&gx1, &t, &x);
  fp_mul(&t, &SSWU1_A, &x);
  fp_add(&gx1, &gx1, &t);
  fp_add(&gx1, &gx1, &SSWU1_B);
  fp y;
  if (!fp_sqrt(&y, &gx1)) {
    fp_mul(&x, &zu2, &x1);
    fp gx2;
    fp_sqr(&t, &x);
    fp_mul(&gx2, &t, &x);
    fp_mul(&t, &SSWU1_A, &x);
    fp_add(&gx2, &gx2, &t);
    fp_add(&gx2, &gx2, &SSWU1_B);
    fp_sqrt(&y, &gx2);
  }
  if (fp_sgn0(u) != fp_sgn0(&y)) fp_neg(&y, &y);
  *xo = x;
  *yo = y;
}

// affine addition on E': y^2 = x^3 + A x + B (general a)
static int aff_add_fp2(fp2 *xo, fp2 *yo, const fp2 *x1, const fp2 *y1,
                       const fp2 *x2, const fp2 *y2, const fp2 *a) {
  fp2 lam, t, d;
  if (fp2_eq(x1, x2)) {
    fp2 ys;
    fp2_add(&ys, y1, y2);
    if (fp2_is_zero(&ys)) return 0;  // infinity
    fp2_sqr(&t, x1);
    fp2_mul_small(&t, &t, 3);
    fp2_add(&t, &t, a);
    fp2_add(&d, y1, y1);
    fp2_inv(&d, &d);
    fp2_mul(&lam, &t, &d);
  } else {
    fp2_sub(&t, y2, y1);
    fp2_sub(&d, x2, x1);
    fp2_inv(&d, &d);
    fp2_mul(&lam, &t, &d);
  }
  fp2 x3, y3;
  fp2_sqr(&x3, &lam);
  fp2_sub(&x3, &x3, x1);
  fp2_sub(&x3, &x3, x2);
  fp2_sub(&t, x1, &x3);
  fp2_mul(&y3, &lam, &t);
  fp2_sub(&y3, &y3, y1);
  *xo = x3;
  *yo = y3;
  return 1;
}

static int aff_add_fp(fp *xo, fp *yo, const fp *x1, const fp *y1, const fp *x2,
                      const fp *y2, const fp *a) {
  fp lam, t, d;
  if (fp_eq(x1, x2)) {
    fp ys;
    fp_add(&ys, y1, y2);
    if (fp_is_zero(&ys)) return 0;
    fp_sqr(&t, x1);
    fp three;
    fp_add(&three, &t, &t);
    fp_add(&t, &three, &t);
    fp_add(&t, &t, a);
    fp_add(&d, y1, y1);
    fp_inv(&d, &d);
    fp_mul(&lam, &t, &d);
  } else {
    fp_sub(&t, y2, y1);
    fp_sub(&d, x2, x1);
    fp_inv(&d, &d);
    fp_mul(&lam, &t, &d);
  }
  fp x3, y3;
  fp_sqr(&x3, &lam);
  fp_sub(&x3, &x3, x1);
  fp_sub(&x3, &x3, x2);
  fp_sub(&t, x1, &x3);
  fp_mul(&y3, &lam, &t);
  fp_sub(&y3, &y3, y1);
  *xo = x3;
  *yo = y3;
  return 1;
}

static void iso3_map(g2p *r, const fp2 *x, const fp2 *y, int inf) {
  if (inf) { memset(r, 0, sizeof(*r)); return; }
  fp2 d, di, di2, di3, X, Yf, t;
  fp2_sub(&d, x, &ISO3_X0);
  if (fp2_is_zero(&d)) { memset(r, 0, sizeof(*r)); return; }
  fp2_inv(&di, &d);
  fp2_sqr(&di2, &di);
  fp2_mul(&di3, &di2, &di);
  fp2_mul(&t, &ISO3_V, &di);
  fp2_add(&X, x, &t);
  fp2_mul(&t, &ISO3_W, &di2);
  fp2_add(&X, &X, &t);
  fp2 one = {BLS_ONE_M, BLS_ZERO};
  fp2_mul(&t, &ISO3_V, &di2);
  fp2_sub(&Yf, &one, &t);
  fp2 w2;
  fp2_add(&w2, &ISO3_W, &ISO3_W);
  fp2_mul(&t, &w2, &di3);
  fp2_sub(&Yf, &Yf, &t);
  fp2 Y;
  fp2_mul(&Y, y, &Yf);
  fp2_mul(&r->x, &ISO3_S2, &X);
  fp2_mul(&r->y, &ISO3_S3, &Y);
  r->z.c0 = BLS_ONE_M;
  r->z.c1 = BLS_ZERO;
}

static void horner_fp(fp *r, const fp *tab, int n, const fp *x) {
  fp acc = tab[n - 1];
  for (int i = n - 2; i >= 0; i--) {
    fp_mul(&acc, &acc, x);
    fp_add(&acc, &acc, &tab[i]);
  }
  *r = acc;
}

static void iso1_map(g1p *r, const fp *x, const fp *y, int inf) {
  if (inf) { memset(r, 0, sizeof(*r)); return; }
  fp xn, xd, yn, yd, t;
  horner_fp(&xn, ISO1_XN, ISO1_XN_LEN, x);
  horner_fp(&xd, ISO1_XD, ISO1_XD_LEN, x);
  horner_fp(&yn, ISO1_YN, ISO1_YN_LEN, x);
  horner_fp(&yd, ISO1_YD, ISO1_YD_LEN, x);
  if (fp_is_zero(&xd) || fp_is_zero(&yd)) { memset(r, 0, sizeof(*r)); return; }
  fp xdi, ydi;
  fp_inv(&xdi, &xd);
  fp_inv(&ydi, &yd);
  fp_mul(&r->x, &xn, &xdi);
  fp_mul(&t, y, &yn);
  fp_mul(&r->y, &t, &ydi);
  r->z = BLS_ONE_M;
}

static void hash_to_g2(g2p *r, const uint8_t *msg, size_t msg_len,
                       const uint8_t *dst, size_t dst_len) {
  uint8_t buf[256];
  expand_xmd(buf, 256, msg, msg_len, dst, dst_len);
  fp2 u0, u1;
  fp_from_be64_draw(&u0.c0, buf);
  fp_from_be64_draw(&u0.c1, buf + 64);
  fp_from_be64_draw(&u1.c0, buf + 128);
  fp_from_be64_draw(&u1.c1, buf + 192);
  fp2 x0, y0, x1, y1, xs, ys;
  sswu_fp2(&x0, &y0, &u0);
  sswu_fp2(&x1, &y1, &u1);
  g2p e;
  int ok = aff_add_fp2(&xs, &ys, &x0, &y0, &x1, &y1, &SSWU2_A);
  iso3_map(&e, &xs, &ys, !ok);
  g2_clear_cofactor(r, &e);
}

static void hash_to_g1(g1p *r, const uint8_t *msg, size_t msg_len,
                       const uint8_t *dst, size_t dst_len) {
  uint8_t buf[128];
  expand_xmd(buf, 128, msg, msg_len, dst, dst_len);
  fp u0, u1;
  fp_from_be64_draw(&u0, buf);
  fp_from_be64_draw(&u1, buf + 64);
  fp x0, y0, x1, y1, xs, ys;
  sswu_fp(&x0, &y0, &u0);
  sswu_fp(&x1, &y1, &u1);
  g1p e;
  int ok = aff_add_fp(&xs, &ys, &x0, &y0, &x1, &y1, &SSWU1_A);
  iso1_map(&e, &xs, &ys, !ok);
  g1_clear_cofactor(r, &e);
}

// ---------------------------------------------------------------------------
// Pairing (golden pairing.py: e(P,Q)^3, affine Miller, x-chain hard part)
// ---------------------------------------------------------------------------

typedef struct { fp2 x, y; } g2aff;
typedef struct { fp x, y; } g1aff;

// f *= L for the SPARSE Miller line L = (A + B v) + (C v) w with
// A = lam*xt - yt, B = -lam*xp, C = (yp, 0) — the only nonzero slots the
// affine line evaluation produces (b0.a0, b0.a1, b1.a1).  Expanding
// (a + b w)(l + m w) = (a l + b m v) + (a m + b l) w over fp6 = fp2[v]
// with v^3 = xi gives each output coefficient as a THREE-TERM sum of
// fp2 products; the lazy double-width lane accumulates all three and
// reduces once per coefficient (xi twists folded into canonical
// operands: Bx = xi*B, and the (s,s) form of xi*C):
//   F0 = f0 A + f2 Bx + xi f4 C     F3 = f3 A + f5 Bx + xi f2 C
//   F1 = f1 A + f0 B  + xi f5 C     F4 = f4 A + f3 B  + f0 C
//   F2 = f2 A + f1 B  + f3 C        F5 = f5 A + f4 B  + f1 C
// Exactly fp12_mul(f, dense(L)) mod p, canonicalized — bit-identical —
// at ~57% of the dense multiply's 64x64-product count.
static void fp12_mul_line(fp12 *f, const fp2 *A, const fp2 *B,
                          const fp *yp) {
  fp2 Bx;
  fp2_mul_xi(&Bx, B);
  const fp2 *f0 = &f->b0.a0, *f1 = &f->b0.a1, *f2 = &f->b0.a2;
  const fp2 *f3 = &f->b1.a0, *f4 = &f->b1.a1, *f5 = &f->b1.a2;
  fp2w acc, t;
  fp12 out;
  fp2_mulw(&acc, f0, A);
  fp2_mulw(&t, f2, &Bx);
  fp2w_add(&acc, &t);
  fp2_mulw_fp_xi(&t, f4, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a0, &acc);
  fp2_mulw(&acc, f1, A);
  fp2_mulw(&t, f0, B);
  fp2w_add(&acc, &t);
  fp2_mulw_fp_xi(&t, f5, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a1, &acc);
  fp2_mulw(&acc, f2, A);
  fp2_mulw(&t, f1, B);
  fp2w_add(&acc, &t);
  fp2_mulw_fp(&t, f3, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a2, &acc);
  fp2_mulw(&acc, f3, A);
  fp2_mulw(&t, f5, &Bx);
  fp2w_add(&acc, &t);
  fp2_mulw_fp_xi(&t, f2, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a0, &acc);
  fp2_mulw(&acc, f4, A);
  fp2_mulw(&t, f3, B);
  fp2w_add(&acc, &t);
  fp2_mulw_fp(&t, f0, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a1, &acc);
  fp2_mulw(&acc, f5, A);
  fp2_mulw(&t, f4, B);
  fp2w_add(&acc, &t);
  fp2_mulw_fp(&t, f1, yp);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a2, &acc);
  *f = out;
}

// Fully general sparse line product: like fp12_mul_line but with the
// yp coefficient a full fp2 (the Jacobian ladder's lines carry a
// Z-dependent fp2 factor on every slot).  Same three-term lazy lanes,
// with the xi twist folded into canonical operands for the C terms too
// (Cx = xi*C); every product is (2,2)p^2 so each lane is <= 6p^2,
// within redc_wide's p*2^384 ~ 9.8p^2 budget.
static void fp12_mul_line_g(fp12 *f, const fp2 *A, const fp2 *B,
                            const fp2 *C) {
  fp2 Bx, Cx;
  fp2_mul_xi(&Bx, B);
  fp2_mul_xi(&Cx, C);
  const fp2 *f0 = &f->b0.a0, *f1 = &f->b0.a1, *f2 = &f->b0.a2;
  const fp2 *f3 = &f->b1.a0, *f4 = &f->b1.a1, *f5 = &f->b1.a2;
  fp2w acc, t;
  fp12 out;
  fp2_mulw(&acc, f0, A);
  fp2_mulw(&t, f2, &Bx);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f4, &Cx);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a0, &acc);
  fp2_mulw(&acc, f1, A);
  fp2_mulw(&t, f0, B);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f5, &Cx);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a1, &acc);
  fp2_mulw(&acc, f2, A);
  fp2_mulw(&t, f1, B);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f3, C);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b0.a2, &acc);
  fp2_mulw(&acc, f3, A);
  fp2_mulw(&t, f5, &Bx);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f2, &Cx);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a0, &acc);
  fp2_mulw(&acc, f4, A);
  fp2_mulw(&t, f3, B);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f0, C);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a1, &acc);
  fp2_mulw(&acc, f5, A);
  fp2_mulw(&t, f4, B);
  fp2w_add(&acc, &t);
  fp2_mulw(&t, f1, C);
  fp2w_add(&acc, &t);
  fp2w_redc(&out.b1.a2, &acc);
  *f = out;
}

// Per-step line coefficients (lam, pre-step T) — everything a line
// evaluation needs besides P's affine coordinates.  Recording them per
// fixed Q is the Miller-loop precomputation: for a group public key the
// whole G2 ladder (point arithmetic + per-step inversions) runs once per
// DistPublic instead of once per verify.
typedef struct { fp2 lam, xt, yt; } line_rec;

// steps with lambda precomputed (denominator already inverted); the
// _rec cores record (lam, pre-T) and advance T — shared verbatim by the
// live path and g2_prepare so both are bit-identical by construction.
static void dbl_step_rec(g2aff *t, line_rec *rec, const fp2 *dinv) {
  fp2 lam, num, x3, y3, s;
  fp2_sqr(&num, &t->x);
  fp2_mul_small(&num, &num, 3);
  fp2_mul(&lam, &num, dinv);
  fp2_sqr(&x3, &lam);
  fp2_add(&s, &t->x, &t->x);
  fp2_sub(&x3, &x3, &s);
  fp2_sub(&s, &t->x, &x3);
  fp2_mul(&y3, &lam, &s);
  fp2_sub(&y3, &y3, &t->y);
  rec->lam = lam;
  rec->xt = t->x;
  rec->yt = t->y;
  t->x = x3;
  t->y = y3;
}

static void add_step_rec(g2aff *t, const g2aff *q, line_rec *rec,
                         const fp2 *dinv) {
  fp2 lam, num, x3, y3, s;
  fp2_sub(&num, &t->y, &q->y);
  fp2_mul(&lam, &num, dinv);
  fp2_sqr(&x3, &lam);
  fp2_sub(&x3, &x3, &t->x);
  fp2_sub(&x3, &x3, &q->x);
  fp2_sub(&s, &t->x, &x3);
  fp2_mul(&y3, &lam, &s);
  fp2_sub(&y3, &y3, &t->y);
  rec->lam = lam;
  rec->xt = t->x;
  rec->yt = t->y;
  t->x = x3;
  t->y = y3;
}

// accumulate one recorded line into f: A = lam*xt - yt, B = -lam*xp,
// C = yp — then the lazy sparse product (fp12_mul_line)
static void miller_mul_line(fp12 *f, const line_rec *rec, const fp *xp,
                            const fp *yp) {
  fp2 A, B, t;
  fp2_mul(&t, &rec->lam, &rec->xt);
  fp2_sub(&A, &t, &rec->yt);
  fp2_neg(&B, &rec->lam);
  fp2_mul_fp(&B, &B, xp);
  fp12_mul_line(f, &A, &B, yp);
}

// 62 doublings + 5 additions for |x| = 0xd201000000010000
#define MILLER_STEPS 70

typedef struct {
  int n;
  line_rec steps[MILLER_STEPS];
} g2prep;

static void g2_prepare(g2prep *pre, const g2aff *q) {
  g2aff t = *q;
  int n = 0;
  int top = 63 - __builtin_clzll(BLS_X_ABS);
  for (int b = top - 1; b >= 0; b--) {
    fp2 den, dinv;
    fp2_add(&den, &t.y, &t.y);
    fp2_inv(&dinv, &den);
    dbl_step_rec(&t, &pre->steps[n++], &dinv);
    if ((BLS_X_ABS >> b) & 1) {
      fp2_sub(&den, &t.x, &q->x);
      fp2_inv(&dinv, &den);
      add_step_rec(&t, q, &pre->steps[n++], &dinv);
    }
  }
  pre->n = n;
}

// Inversion-free Miller steps on a Jacobian ladder (x = X/Z^2,
// y = Y/Z^3).  The affine tangent line at T evaluated at P is
//   l = (lam*xt - yt) - lam*xp + yp,   lam = 3*xt^2 / (2*yt);
// scaling l by the nonzero 2*Y*Z^3 clears every denominator:
//   l' = (3X^3 - 2Y^2) + (-3X^2*Z^2)*xp + (2Y*Z^3)*yp.
// A scalar c in Fp2* on a line only scales the final f by an Fp2
// element, and the final exponentiation kills it: c^(p^2-1) = 1, and
// (p^12-1)/r contains the factor p^6-1 = (p^2-1)(p^4+p^2+1), so
// c^((p^12-1)/r) = 1 and final_exp's output is bit-identical to the
// affine ladder's.  This trades the per-step Fermat-chain inversions
// (the dominant multi_miller cost) for a handful of fp2 muls.
// Point-update algebra is the same dbl-2009-l used by g2_dbl.
static void dbl_step_jac(g2p *t, fp2 *A, fp2 *B, fp2 *C) {
  fp2 a, b, c, d, e, f, s, zz, c8, x3, y3, z3;
  fp2_sqr(&a, &t->x);                    // X^2
  fp2_sqr(&b, &t->y);                    // Y^2
  fp2_sqr(&c, &b);                       // Y^4
  fp2_add(&s, &t->x, &b);
  fp2_sqr(&d, &s);
  fp2_sub(&d, &d, &a);
  fp2_sub(&d, &d, &c);
  fp2_add(&d, &d, &d);                   // 4*X*Y^2
  fp2_add(&e, &a, &a);
  fp2_add(&e, &e, &a);                   // 3*X^2
  fp2_sqr(&f, &e);
  fp2_add(&s, &d, &d);
  fp2_sub(&x3, &f, &s);                  // e^2 - 2d
  fp2_add(&c8, &c, &c);
  fp2_add(&c8, &c8, &c8);
  fp2_add(&c8, &c8, &c8);                // 8*Y^4
  fp2_sub(&s, &d, &x3);
  fp2_mul(&y3, &e, &s);
  fp2_sub(&y3, &y3, &c8);                // e*(d - x3) - 8*Y^4
  fp2_sqr(&zz, &t->z);                   // Z^2
  fp2_mul(&z3, &t->y, &t->z);
  fp2_add(&z3, &z3, &z3);                // 2*Y*Z
  fp2_mul(A, &e, &t->x);
  fp2_add(&s, &b, &b);
  fp2_sub(A, A, &s);                     // 3X^3 - 2Y^2
  fp2_mul(B, &e, &zz);
  fp2_neg(B, B);                         // -3X^2*Z^2   (coeff of xp)
  fp2_mul(C, &z3, &zz);                  // 2Y*Z^3      (coeff of yp)
  t->x = x3;
  t->y = y3;
  t->z = z3;
}

// Mixed addition T += Q (Q affine) with the chord line through T and Q
// scaled by Z_new = Z*h:  lam = rr/(Z*h) with rr = yq*Z^3 - Y and
// h = xq*Z^2 - X, so
//   l' = (rr*xq - Z_new*yq) + (-rr)*xp + Z_new*yp.
// Point-update algebra is madd (add-2007-bl with Z2 = 1).
static void add_step_jac(g2p *t, const g2aff *q, fp2 *A, fp2 *B, fp2 *C) {
  fp2 zz, zzz, u2, s2, h, rr, hh, hhh, v, s, x3, y3, z3;
  fp2_sqr(&zz, &t->z);
  fp2_mul(&zzz, &zz, &t->z);
  fp2_mul(&u2, &q->x, &zz);              // xq*Z^2
  fp2_mul(&s2, &q->y, &zzz);             // yq*Z^3
  fp2_sub(&h, &u2, &t->x);
  fp2_sub(&rr, &s2, &t->y);
  fp2_sqr(&hh, &h);
  fp2_mul(&hhh, &hh, &h);
  fp2_mul(&v, &t->x, &hh);
  fp2_sqr(&x3, &rr);
  fp2_sub(&x3, &x3, &hhh);
  fp2_sub(&x3, &x3, &v);
  fp2_sub(&x3, &x3, &v);                 // rr^2 - h^3 - 2*X*h^2
  fp2_sub(&s, &v, &x3);
  fp2_mul(&y3, &rr, &s);
  fp2_mul(&s, &t->y, &hhh);
  fp2_sub(&y3, &y3, &s);                 // rr*(v - x3) - Y*h^3
  fp2_mul(&z3, &t->z, &h);
  fp2_mul(A, &rr, &q->x);
  fp2_mul(&s, &z3, &q->y);
  fp2_sub(A, A, &s);                     // rr*xq - z3*yq
  fp2_neg(B, &rr);                       // -rr        (coeff of xp)
  *C = z3;                               // z3         (coeff of yp)
  t->x = x3;
  t->y = y3;
  t->z = z3;
}

// fold one Jacobian line into f: scale the xp/yp slots by P's affine
// coordinates, then the general lazy sparse product.
static void miller_mul_line_j(fp12 *f, const fp2 *A, const fp2 *B,
                              const fp2 *C, const fp *xp, const fp *yp) {
  fp2 Bs, Cs;
  fp2_mul_fp(&Bs, B, xp);
  fp2_mul_fp(&Cs, C, yp);
  fp12_mul_line_g(f, A, &Bs, &Cs);
}

static void multi_miller(fp12 *f_out, const g1aff *ps, const g2aff *qs,
                         int n) {
  g2p ts[4];
  for (int i = 0; i < n; i++) {
    ts[i].x = qs[i].x;
    ts[i].y = qs[i].y;
    memset(&ts[i].z, 0, sizeof(fp2));
    ts[i].z.c0 = BLS_ONE_M;
  }
  fp12 f;
  fp12_one(&f);
  fp2 A, B, C;
  // MSB-first over |x| bits, skipping the leading 1
  int top = 63 - __builtin_clzll(BLS_X_ABS);
  for (int b = top - 1; b >= 0; b--) {
    fp12_sqr(&f, &f);
    for (int i = 0; i < n; i++) {
      dbl_step_jac(&ts[i], &A, &B, &C);
      miller_mul_line_j(&f, &A, &B, &C, &ps[i].x, &ps[i].y);
    }
    if ((BLS_X_ABS >> b) & 1) {
      for (int i = 0; i < n; i++) {
        add_step_jac(&ts[i], &qs[i], &A, &B, &C);
        miller_mul_line_j(&f, &A, &B, &C, &ps[i].x, &ps[i].y);
      }
    }
  }
  fp12_conj(f_out, &f);  // x < 0
}

// multi_miller over PREPARED Q ladders: same pairing value (the
// recorded affine lines differ from the live Jacobian ones only by
// per-line Fp2* scalars, which final_exp kills — see dbl_step_jac),
// with zero G2 point arithmetic and zero inversions at verify time.
static void multi_miller_prepared(fp12 *f_out, const g1aff *ps,
                                  const g2prep *const *preps, int n) {
  fp12 f;
  fp12_one(&f);
  int idx[4] = {0, 0, 0, 0};
  int top = 63 - __builtin_clzll(BLS_X_ABS);
  for (int b = top - 1; b >= 0; b--) {
    fp12_sqr(&f, &f);
    for (int i = 0; i < n; i++)
      miller_mul_line(&f, &preps[i]->steps[idx[i]++], &ps[i].x, &ps[i].y);
    if ((BLS_X_ABS >> b) & 1) {
      for (int i = 0; i < n; i++)
        miller_mul_line(&f, &preps[i]->steps[idx[i]++], &ps[i].x, &ps[i].y);
    }
  }
  fp12_conj(f_out, &f);  // x < 0
}

// Granger-Scott cyclotomic squaring for UNITARY f (post-easy-part):
// three Fp4 squarings instead of a full fp12_sqr.  Slot/sign assignment
// verified against the golden model (see tools history) — pairs
// (c0,c4), (c3,c2), (c1,c5); even slots 3t-2c, odd slots 3t+2c, with
// xi on the c3 term.
static void fp4_sq(fp2 *A, fp2 *B, const fp2 *a, const fp2 *b) {
  fp2 a2, b2, s, x;
  fp2_sqr(&a2, a);
  fp2_sqr(&b2, b);
  fp2_mul_xi(&x, &b2);
  fp2_add(A, &a2, &x);
  fp2_add(&s, a, b);
  fp2_sqr(&s, &s);
  fp2_sub(&s, &s, &a2);
  fp2_sub(B, &s, &b2);  // 2ab
}

static void cyclo_sqr(fp12 *r, const fp12 *f) {
  const fp2 *c0 = &f->b0.a0, *c1 = &f->b0.a1, *c2 = &f->b0.a2;
  const fp2 *c3 = &f->b1.a0, *c4 = &f->b1.a1, *c5 = &f->b1.a2;
  fp2 t0, t1, t2, t3, t4, t5;
  fp4_sq(&t0, &t1, c0, c4);
  fp4_sq(&t2, &t3, c3, c2);
  fp4_sq(&t4, &t5, c1, c5);
  fp12 out;
#define THREE_M_TWO(dst, t, c)            \
  {                                       \
    fp2 th, tw;                           \
    fp2_add(&th, &(t), &(t));             \
    fp2_add(&th, &th, &(t));              \
    fp2_add(&tw, (c), (c));               \
    fp2_sub(&(dst), &th, &tw);            \
  }
#define THREE_P_TWO(dst, t, c)            \
  {                                       \
    fp2 th, tw;                           \
    fp2_add(&th, &(t), &(t));             \
    fp2_add(&th, &th, &(t));              \
    fp2_add(&tw, (c), (c));               \
    fp2_add(&(dst), &th, &tw);            \
  }
  THREE_M_TWO(out.b0.a0, t0, c0);
  THREE_M_TWO(out.b0.a1, t2, c1);
  THREE_M_TWO(out.b0.a2, t4, c2);
  fp2 xt5;
  fp2_mul_xi(&xt5, &t5);
  THREE_P_TWO(out.b1.a0, xt5, c3);
  THREE_P_TWO(out.b1.a1, t1, c4);
  THREE_P_TWO(out.b1.a2, t3, c5);
#undef THREE_M_TWO
#undef THREE_P_TWO
  *r = out;
}

static void pow_x(fp12 *r, const fp12 *f) {  // f^|x| then conj (unitary f)
  fp12 out;
  fp12_one(&out);
  int top = 63 - __builtin_clzll(BLS_X_ABS);
  for (int b = top; b >= 0; b--) {
    cyclo_sqr(&out, &out);
    if ((BLS_X_ABS >> b) & 1) fp12_mul(&out, &out, f);
  }
  fp12_conj(r, &out);
}

// Only called from poly_pow on the hard-part g[k], which are UNITARY
// (post-easy-part), so the squarings are cyclotomic (Granger-Scott) —
// same values as fp12_sqr on this domain, at a third of the cost.
static void pow_small(fp12 *r, const fp12 *f, int e) {
  int neg = e < 0;
  unsigned ue = (unsigned)(neg ? -e : e);
  fp12 out, base = *f;
  fp12_one(&out);
  while (ue) {
    if (ue & 1) fp12_mul(&out, &out, &base);
    if (ue >> 1) cyclo_sqr(&base, &base);
    ue >>= 1;
  }
  if (neg) fp12_conj(&out, &out);
  *r = out;
}

// hard-part coefficients (golden pairing.py _L0.._L3, high-first)
static const int HP_L0[6] = {1, -2, 0, 2, -1, 3};
static const int HP_L1[5] = {1, -2, 0, 2, -1};
static const int HP_L2[4] = {1, -2, 1, 0};
static const int HP_L3[3] = {1, -2, 1};

static void poly_pow(fp12 *r, const fp12 g[6], const int *coeffs, int n) {
  fp12 out;
  fp12_one(&out);
  int deg = n - 1;
  for (int i = 0; i < n; i++) {
    if (coeffs[i]) {
      fp12 t;
      pow_small(&t, &g[deg - i], coeffs[i]);
      fp12_mul(&out, &out, &t);
    }
  }
  *r = out;
}

static void final_exp(fp12 *r, const fp12 *f_in) {
  fp12 f, c, inv, t;
  // easy: f^(p^6-1) = conj(f) * f^-1; then f^(p^2+1)
  fp12_conj(&c, f_in);
  fp12_inv(&inv, f_in);
  fp12_mul(&f, &c, &inv);
  fp12_frob_n(&t, &f, 2);
  fp12_mul(&f, &t, &f);
  // hard part
  fp12 g[6];
  g[0] = f;
  for (int k = 1; k < 6; k++) pow_x(&g[k], &g[k - 1]);
  fp12 p0, p1, p2, p3;
  poly_pow(&p0, g, HP_L0, 6);
  poly_pow(&p1, g, HP_L1, 5);
  fp12_frob_n(&p1, &p1, 1);
  poly_pow(&p2, g, HP_L2, 4);
  fp12_frob_n(&p2, &p2, 2);
  poly_pow(&p3, g, HP_L3, 3);
  fp12_frob_n(&p3, &p3, 3);
  fp12_mul(&t, &p0, &p1);
  fp12 t2;
  fp12_mul(&t2, &p2, &p3);
  fp12_mul(r, &t, &t2);
}

// prod e(P_i, Q_i) == 1 ?
static int pairing_check(const g1p *ps, const g2p *qs, int n) {
  g1aff pa[4];
  g2aff qa[4];
  int live = 0;
  for (int i = 0; i < n; i++) {
    if (g1_is_inf(&ps[i]) || g2_is_inf(&qs[i])) continue;
    g1_to_affine(&pa[live].x, &pa[live].y, &ps[i]);
    g2_to_affine(&qa[live].x, &qa[live].y, &qs[i]);
    live++;
  }
  if (!live) return 1;
  fp12 f, e;
  multi_miller(&f, pa, qa, live);
  final_exp(&e, &f);
  return fp12_is_one(&e);
}

static int pairing_check_prepared(const g1p *ps,
                                  const g2prep *const *preps, int n) {
  g1aff pa[4];
  const g2prep *pl[4];
  int live = 0;
  for (int i = 0; i < n; i++) {
    if (g1_is_inf(&ps[i])) continue;
    g1_to_affine(&pa[live].x, &pa[live].y, &ps[i]);
    pl[live] = preps[i];
    live++;
  }
  if (!live) return 1;
  fp12 f, e;
  multi_miller_prepared(&f, pa, pl, live);
  final_exp(&e, &f);
  return fp12_is_one(&e);
}

// ---------------------------------------------------------------------------
// Public-key caches (ROADMAP item 5 down-payment, ISSUE 9 satellite).
//
// The group public key is fixed across rounds, so per-verify we cache:
//   - G2-scheme pk (48 B, G1 point): the decompression square root — a
//     full Fp Fermat chain per call otherwise;
//   - G1-scheme pk (96 B, G2 point): decompression (Fp2 sqrt chain) AND
//     the whole Miller-loop line precomputation (g2_prepare) — the G2
//     side of both pairings is fixed (generator + pk), so verify-time
//     pairing work drops to line evaluations at P plus the Fp12 ladder.
// Keyed by raw wire bytes; small LRU-ish ring, copy-out under a mutex so
// eviction never races a verify in another thread.  Results are
// bit-identical to the uncached path (unique decompression/inverses).
// ---------------------------------------------------------------------------

#include <mutex>

#define PK_G1_SLOTS 24  /* covers an n=16 group's evaluated signer keys */
#define PK_G2_SLOTS 8

static struct {
  int used;
  uint8_t key[48];
  g1p pk;
} g_pk_g1_cache[PK_G1_SLOTS];
static int g_pk_g1_next = 0;

static struct {
  int used;
  uint8_t key[96];
  g2prep prep;
} g_pk_g2_cache[PK_G2_SLOTS];
static int g_pk_g2_next = 0;

static std::mutex g_pk_mu;

// decompressed-G1 pk by wire bytes; returns 0 on invalid/infinity
static int g1_pk_cached(g1p *out, const uint8_t pk48[48]) {
  {
    std::lock_guard<std::mutex> lk(g_pk_mu);
    for (int i = 0; i < PK_G1_SLOTS; i++)
      if (g_pk_g1_cache[i].used &&
          !memcmp(g_pk_g1_cache[i].key, pk48, 48)) {
        *out = g_pk_g1_cache[i].pk;
        return 1;
      }
  }
  g1p pk;
  if (!g1_from_bytes(&pk, pk48) || g1_is_inf(&pk)) return 0;
  {
    std::lock_guard<std::mutex> lk(g_pk_mu);
    int s = g_pk_g1_next++ % PK_G1_SLOTS;
    memcpy(g_pk_g1_cache[s].key, pk48, 48);
    g_pk_g1_cache[s].pk = pk;
    g_pk_g1_cache[s].used = 1;
  }
  *out = pk;
  return 1;
}

// prepared-G2 pk (decompression + line precomputation) by wire bytes
static int g2_pk_prep_cached(g2prep *out, const uint8_t pk96[96]) {
  {
    std::lock_guard<std::mutex> lk(g_pk_mu);
    for (int i = 0; i < PK_G2_SLOTS; i++)
      if (g_pk_g2_cache[i].used &&
          !memcmp(g_pk_g2_cache[i].key, pk96, 96)) {
        *out = g_pk_g2_cache[i].prep;
        return 1;
      }
  }
  g2p pk;
  if (!g2_from_bytes(&pk, pk96) || g2_is_inf(&pk)) return 0;
  g2aff qa;
  g2_to_affine(&qa.x, &qa.y, &pk);
  g2prep prep;
  g2_prepare(&prep, &qa);
  {
    std::lock_guard<std::mutex> lk(g_pk_mu);
    int s = g_pk_g2_next++ % PK_G2_SLOTS;
    memcpy(g_pk_g2_cache[s].key, pk96, 96);
    g_pk_g2_cache[s].prep = prep;
    g_pk_g2_cache[s].used = 1;
  }
  *out = prep;
  return 1;
}

static g2prep g_gen_prep;
static int g_gen_prep_done = 0;  /* idempotent, ensure_init-style */
static const g2prep *gen_prep(void) {
  if (!g_gen_prep_done) {
    g2aff gen;
    gen.x = BLS_G2_X;
    gen.y = BLS_G2_Y;
    g2_prepare(&g_gen_prep, &gen);
    g_gen_prep_done = 1;
  }
  return &g_gen_prep;
}

// ---------------------------------------------------------------------------
// Public API
// ---------------------------------------------------------------------------

static int g_init_done = 0;
static void ensure_init(void) {
  if (!g_init_done) {
    exps_init();
    g1_endo_init();
    g_init_done = 1;
  }
}

extern "C" {

// returns 1 on valid signature
int drand_bls_verify_g2(const uint8_t pk48[48], const uint8_t *msg,
                        size_t msg_len, const uint8_t sig96[96],
                        const uint8_t *dst, size_t dst_len) {
  ensure_init();
  g1p pk;
  g2p sig;
  // pk decompression (an Fp sqrt chain) caches by wire bytes — the
  // group key is fixed across rounds
  if (!g1_pk_cached(&pk, pk48)) return 0;
  if (!g2_from_bytes(&sig, sig96) || g2_is_inf(&sig)) return 0;
  if (!g2_in_subgroup(&sig)) return 0;
  g2p h;
  hash_to_g2(&h, msg, msg_len, dst, dst_len);
  g1p gen = {BLS_G1_X, BLS_G1_Y, BLS_ONE_M}, ngen;
  g1_neg(&ngen, &gen);
  g1p ps[2] = {ngen, pk};
  g2p qs[2] = {sig, h};
  return pairing_check(ps, qs, 2);
}

int drand_bls_verify_g1(const uint8_t pk96[96], const uint8_t *msg,
                        size_t msg_len, const uint8_t sig48[48],
                        const uint8_t *dst, size_t dst_len) {
  ensure_init();
  // The short-sig scheme's pairings have FIXED G2 arguments (generator
  // and group key): both Miller ladders run fully precomputed — per
  // verify only line evaluations at P and the Fp12 accumulator remain
  // (bit-identical to the live ladder; see multi_miller_prepared).
  g2prep pkprep;
  if (!g2_pk_prep_cached(&pkprep, pk96)) return 0;
  g1p sig;
  if (!g1_from_bytes(&sig, sig48) || g1_is_inf(&sig)) return 0;
  if (!g1_in_subgroup(&sig)) return 0;
  g1p h;
  hash_to_g1(&h, msg, msg_len, dst, dst_len);
  g1p nsig;
  g1_neg(&nsig, &sig);
  g1p ps[2] = {nsig, h};
  const g2prep *preps[2] = {gen_prep(), &pkprep};
  return pairing_check_prepared(ps, preps, 2);
}

// tbls partial: commits = t compressed G1 points (48 B each); partial =
// 2-byte BE index || 96-byte sig; evaluates the public polynomial at
// index+1 (Horner in the exponent) and verifies.
int drand_tbls_verify_partial(const uint8_t *commits, int t,
                              const uint8_t *msg, size_t msg_len,
                              const uint8_t *partial, size_t partial_len,
                              const uint8_t *dst, size_t dst_len) {
  ensure_init();
  if (partial_len != 98) return 0;
  uint64_t xi = ((uint64_t)partial[0] << 8 | partial[1]) + 1;
  g1p acc;
  memset(&acc, 0, sizeof(acc));
  for (int i = t - 1; i >= 0; i--) {
    g1p cm;
    if (!g1_from_bytes(&cm, commits + 48 * i)) return 0;
    g1p scaled;
    g1_mul_u64(&scaled, &acc, xi);
    g1_add(&acc, &scaled, &cm);
  }
  if (g1_is_inf(&acc)) return 0;
  uint8_t pk48[48];
  g1_to_bytes(pk48, &acc);
  return drand_bls_verify_g2(pk48, msg, msg_len, partial + 2, dst, dst_len);
}

// Lagrange combination of t G2 partial signatures: out = sum scal_i *
// sig_i with 32-byte big-endian scalars (the Lagrange basis values mod
// r, computed host-side).  The threshold-recovery latency path
// (reference seam: `share.PubPoly.Recover` behind
// `chain/beacon/chain.go:158-165`): ~t * 3 ms on this host vs ~700 ms
// through the pure-python golden model.  Returns 1 on success; 0 on a
// malformed point or an infinity result (both mean bad partials).
int drand_g2_lincomb(const uint8_t *sigs96, const uint8_t *scalars32,
                     int t, uint8_t out96[96]) {
  ensure_init();
  g2p acc;
  memset(&acc, 0, sizeof(acc));  // z == 0: the group identity
  for (int i = 0; i < t; i++) {
    g2p s;
    if (!g2_from_bytes(&s, sigs96 + 96 * i) || g2_is_inf(&s)) return 0;
    g2p term;
    g2_mul_be(&term, &s, scalars32 + 32 * i, 32);
    g2_add(&acc, &acc, &term);
  }
  if (g2_is_inf(&acc)) return 0;
  g2_to_bytes(out96, &acc);
  return 1;
}

// test hooks
void drand_hash_to_g2_compressed(uint8_t out96[96], const uint8_t *msg,
                                 size_t msg_len, const uint8_t *dst,
                                 size_t dst_len) {
  ensure_init();
  g2p h;
  hash_to_g2(&h, msg, msg_len, dst, dst_len);
  g2_to_bytes(out96, &h);
}
void drand_hash_to_g1_compressed(uint8_t out48[48], const uint8_t *msg,
                                 size_t msg_len, const uint8_t *dst,
                                 size_t dst_len) {
  ensure_init();
  g1p h;
  hash_to_g1(&h, msg, msg_len, dst, dst_len);
  g1_to_bytes(out48, &h);
}
void drand_sha256(uint8_t out32[32], const uint8_t *msg, size_t len) {
  sha256_ctx c;
  sha_init(&c);
  sha_update(&c, msg, len);
  sha_final(&c, out32);
}

// Tower-arithmetic KAT surface (tests/test_native.py): byte-in/byte-out
// versions of the rebuilt hot ops so the Python golden model can pin
// them point-for-point.  Elements are concatenated 48-byte big-endian
// canonical Fp coefficients in golden tuple order (fp2 = c0||c1,
// fp6 = a0||a1||a2, fp12 = b0||b1).  Returns 1, or 0 on a
// non-canonical encoding (per-coefficient >= p) or unknown op.
//   op 0 fp_mul   1 fp_sqr    2 fp2_mul  3 fp2_sqr  4 fp6_mul
//      5 fp6_sqr  6 fp12_mul  7 fp12_sqr 8 cyclo_sqr (a must be
//      unitary — caller's contract, as in final_exp)
//      9 fp12_mul_line: a = fp12, b = A(96) || B(96) || yp(48)
int drand_test_tower_op(int op, const uint8_t *a, const uint8_t *b,
                        uint8_t *out) {
  ensure_init();
  static const int NFP[10] = {1, 1, 2, 2, 6, 6, 12, 12, 12, 12};
  if (op < 0 || op > 9) return 0;
  fp av[12], bv[12];
  for (int i = 0; i < NFP[op]; i++)
    if (!fp_from_be48(&av[i], a + 48 * i)) return 0;
  int nb = 0;  // b coefficient count per op (0 = unary)
  if (op == 0) nb = 1;
  else if (op == 2) nb = 2;
  else if (op == 4) nb = 6;
  else if (op == 6) nb = 12;
  else if (op == 9) nb = 5;  // A.c0, A.c1, B.c0, B.c1, yp
  for (int i = 0; i < nb; i++)
    if (!fp_from_be48(&bv[i], b + 48 * i)) return 0;
  fp rv[12];
  int nout = NFP[op];
  switch (op) {
    case 0: fp_mul(&rv[0], &av[0], &bv[0]); break;
    case 1: fp_sqr(&rv[0], &av[0]); break;
    case 2: {
      fp2 x = {av[0], av[1]}, y = {bv[0], bv[1]}, z;
      fp2_mul(&z, &x, &y);
      rv[0] = z.c0; rv[1] = z.c1;
      break;
    }
    case 3: {
      fp2 x = {av[0], av[1]}, z;
      fp2_sqr(&z, &x);
      rv[0] = z.c0; rv[1] = z.c1;
      break;
    }
    case 4: case 5: {
      fp6 x, y, z;
      memcpy(&x, av, sizeof(x));
      if (op == 4) { memcpy(&y, bv, sizeof(y)); fp6_mul(&z, &x, &y); }
      else fp6_sqr(&z, &x);
      memcpy(rv, &z, sizeof(z));
      break;
    }
    case 6: case 7: case 8: {
      fp12 x, y, z;
      memcpy(&x, av, sizeof(x));
      if (op == 6) { memcpy(&y, bv, sizeof(y)); fp12_mul(&z, &x, &y); }
      else if (op == 7) fp12_sqr(&z, &x);
      else cyclo_sqr(&z, &x);
      memcpy(rv, &z, sizeof(z));
      break;
    }
    case 9: {
      fp12 x;
      memcpy(&x, av, sizeof(x));
      fp2 A = {bv[0], bv[1]}, B = {bv[2], bv[3]};
      fp12_mul_line(&x, &A, &B, &bv[4]);
      memcpy(rv, &x, sizeof(x));
      break;
    }
  }
  for (int i = 0; i < nout; i++) fp_to_be48(out + 48 * i, &rv[i]);
  return 1;
}

}  // extern "C"
