"""Gossip mesh for the pubsub relay overlay.

Counterpart of the membership half of the reference's libp2p layer
(`lp2p/ctor.go` builds a GossipSub host with discovery; relays and
clients join the topic mesh and fan-out self-heals).  libp2p is not in
this image, so `relay/pubsub.py` carries rounds over gRPC streams and
this module supplies what GossipSub supplied around them:

- **peer discovery**: nodes bootstrap from any one known address and
  learn the rest through symmetric peer exchange (Gossip.Exchange pushes
  the caller's view and pulls the callee's — anti-entropy, so a new
  address reaches everyone in O(log n) heartbeats);
- **degree-D mesh**: each node keeps up to `degree` live stream
  subscriptions to random known peers (GossipSub's mesh degree), so the
  fan-out is a self-assembling graph instead of hand-wired relay
  chaining;
- **self-healing**: dead subscriptions and unreachable peers are dropped
  at the next heartbeat and replaced from the known set.

Every received round still passes the chain-info validator before being
republished (`PubSubClient._validate`, the reference's topic validator)
— a malicious mesh peer cannot inject beacons.
"""

from __future__ import annotations

import asyncio
import random

import grpc.aio

from drand_tpu import log as dlog
from drand_tpu.net.client import make_metadata
from drand_tpu.net.rpc import ServiceStub, service_handler
from drand_tpu.protogen import drand_pb2
from drand_tpu.relay.pubsub import PubSubClient, PubSubRelayNode, \
    pubsub_topic

log = dlog.get("relay")

DEFAULT_DEGREE = 3          # GossipSub's D
HEARTBEAT_S = 5.0           # mesh maintenance cadence
EXCHANGE_FANOUT = 2         # peers asked for their view per heartbeat
MAX_KNOWN = 256             # membership table bound (DoS hygiene)


def is_wildcard_listen(addr: str) -> bool:
    """True when `addr` binds a wildcard host (``''`` / ``0.0.0.0`` /
    ``::``) that mesh peers could not dial back.  Handles bracketed IPv6
    (``[::]:4454``), bare ``host:port``, and port-less forms — a naive
    ``addr.split(":")[0]`` yields ``"["`` for the canonical gRPC IPv6
    wildcard and misses it."""
    addr = addr.strip()
    if addr.startswith("["):                 # [v6]:port or [v6]
        host = addr[1:addr.index("]")] if "]" in addr else addr[1:]
    elif addr.count(":") == 1:               # host:port
        host = addr.split(":")[0]
    else:                                    # bare host (v6 has many colons)
        host = addr
    if host == "":
        return True
    import ipaddress
    try:
        # normalizes non-canonical spellings (::0, 0:0:0:0:0:0:0:0,
        # 0.0.0.0) that bind the wildcard just like '::'
        return ipaddress.ip_address(host).is_unspecified
    except ValueError:
        return False                         # hostname: dialable


class GossipRelayNode(PubSubRelayNode):
    """A pubsub relay that participates in a gossip mesh.

    `upstream` may be None: a pure mesh node learns every round from its
    mesh subscriptions (validated), exactly like a GossipSub relay with
    no direct drand connection.  With an upstream it acts as a root that
    injects rounds into the mesh.
    """

    def __init__(self, upstream, listen: str, chain_info,
                 bootstrap: list[str] | None = None,
                 degree: int = DEFAULT_DEGREE,
                 heartbeat_s: float = HEARTBEAT_S,
                 advertise: str | None = None):
        if upstream is None:
            upstream = _NullUpstream(chain_info)
        super().__init__(upstream, listen)
        self._chain_info = chain_info
        self.degree = degree
        self.heartbeat_s = heartbeat_s
        self.known: set[str] = set(bootstrap or [])
        # bootstrap peers survive failed exchanges (GossipSub retains
        # them for retry): discarding the only known address on one
        # failed dial would isolate a fresh node forever — nobody else
        # knows it exists yet
        self._bootstrap: set[str] = set(bootstrap or [])
        self._advertise = advertise
        if advertise is None and is_wildcard_listen(listen):
            log.warning("gossip relay bound to a wildcard address with no "
                        "advertise address: peers will learn an "
                        "undialable %s — pass advertise=<host:port>",
                        listen)
        self._mesh: dict[str, asyncio.Task] = {}    # addr -> pump task
        self._mesh_clients: dict[str, PubSubClient] = {}
        self._hb_task: asyncio.Task | None = None
        # mesh-peer liveness through the shared health tracker: the same
        # drand_group_connectivity{peer} gauge + state-change logging the
        # daemon watchdog uses for group members (drand_tpu/health)
        from drand_tpu.health import PeerStateTracker
        self.peer_states = PeerStateTracker(log, context="mesh peer")
        # anti-entropy freshness pull (below): validator for pulled
        # rounds + beats-without-progress counter that arms the pull
        from drand_tpu.chain.verify import ChainVerifier
        self._verifier = ChainVerifier(chain_info.scheme,
                                       chain_info.public_key)
        self._stalled_beats = 0
        self._last_seen_round = 0
        # membership rides its own service on the same server
        self.server.add_generic_rpc_handlers(
            (service_handler("Gossip", _GossipService(self)),))

    # -- lifecycle -----------------------------------------------------------

    async def start(self):
        await super().start()
        self._hb_task = asyncio.get_running_loop().create_task(self._heartbeat())

    async def stop(self):
        if self._hb_task is not None:
            self._hb_task.cancel()
        for task in self._mesh.values():
            task.cancel()
        for c in self._mesh_clients.values():
            try:
                await c.close()
            except Exception:
                pass
        self._mesh.clear()
        self._mesh_clients.clear()
        await super().stop()

    @property
    def advertise_addr(self) -> str:
        return self._advertise or self.address

    def topic(self) -> str:
        return pubsub_topic(self._chain_info.hash())

    # -- membership ----------------------------------------------------------

    def learn(self, addrs) -> None:
        for a in addrs:
            if a and a != self.advertise_addr and len(self.known) < MAX_KNOWN:
                self.known.add(a)

    async def _exchange_with(self, addr: str) -> None:
        from drand_tpu.chaos.failpoints import failpoint
        await failpoint("relay.exchange", src=self.advertise_addr, dst=addr)
        ch = grpc.aio.insecure_channel(addr)
        try:
            stub = ServiceStub(ch, "Gossip")
            resp = await stub.Exchange(
                drand_pb2.GossipPeersRequest(
                    topic=self.topic(), sender=self.advertise_addr,
                    known=sorted(self.known),
                    metadata=make_metadata(self._chain_info.beacon_id)),
                timeout=5.0)
            self.learn(resp.peers)
        finally:
            await ch.close()

    # -- mesh maintenance ----------------------------------------------------

    async def _heartbeat(self):
        while True:
            try:
                await self._heartbeat_once()
            except asyncio.CancelledError:
                return
            except Exception as exc:
                log.debug("gossip heartbeat: %s", exc)
            # fixed anti-entropy cadence (gossip protocol parameter),
            # not retry pacing: the exchange fans out to a random sample
            # each beat, so backoff semantics do not apply
            await asyncio.sleep(self.heartbeat_s)  # lint: disable=no-adhoc-retry

    async def _heartbeat_once(self):
        # 1. anti-entropy peer exchange with a few random known peers
        sample = random.sample(sorted(self.known),
                               min(EXCHANGE_FANOUT, len(self.known)))
        for addr in sample:
            try:
                await self._exchange_with(addr)
                self.peer_states.note(addr, True)
            except Exception:
                # unreachable: mark it down (watchdog semantics) and
                # forget it (re-learnable via exchange later) — except
                # bootstrap peers, which are retried forever
                self.peer_states.note(addr, False)
                if addr not in self._bootstrap:
                    self.known.discard(addr)
        # 2. prune dead mesh subscriptions (a dead pump = the peer fell
        # over mid-stream: mark it down until an exchange succeeds again)
        for addr, task in list(self._mesh.items()):
            if task.done():
                self._mesh.pop(addr)
                self.peer_states.note(addr, False)
                c = self._mesh_clients.pop(addr, None)
                if c is not None:
                    try:
                        await c.close()
                    except Exception:
                        pass
        # 3. graft up to degree subscriptions from the known set
        candidates = [a for a in self.known if a not in self._mesh]
        random.shuffle(candidates)
        while len(self._mesh) < self.degree and candidates:
            addr = candidates.pop()
            client = PubSubClient(addr, self._chain_info)
            self._mesh_clients[addr] = client
            self._mesh[addr] = asyncio.get_running_loop().create_task(
                self._pump(addr, client))
        # 4. anti-entropy freshness pull (GossipSub's IHAVE/IWANT
        # analog): when no mesh pump has delivered a new round for two
        # beats, ask one random known peer for its latest.  Heals
        # second-order starvation — a node whose pumps all point into a
        # dark/partitioned region converges again as long as ANY
        # reachable peer carries the round.  Pumps are streams: alive
        # but silent is indistinguishable from "nothing published"
        # without this probe.
        latest = self._latest.round if self._latest else 0
        if latest > self._last_seen_round:
            self._last_seen_round = latest
            self._stalled_beats = 0
        else:
            self._stalled_beats += 1
        if self._stalled_beats >= 2:
            await self._anti_entropy_pull()

    async def _anti_entropy_pull(self) -> None:
        """One light PublicRand(0) probe to a random known peer; a
        NEWER round than ours is validated and published like any mesh
        delivery (and passes the same ``relay.mesh_recv`` failpoint, so
        a partition rules this path too — a victim cannot pull around
        the dark link it is testing)."""
        if not self.known:
            return
        from drand_tpu.chain.beacon import Beacon
        from drand_tpu.chaos.failpoints import PacketDropped, failpoint
        from drand_tpu.client.base import RandomData
        addr = random.choice(sorted(self.known))
        ch = grpc.aio.insecure_channel(addr)
        try:
            stub = ServiceStub(ch, "Public")
            resp = await stub.PublicRand(
                drand_pb2.PublicRandRequest(
                    round=0,
                    metadata=make_metadata(self._chain_info.beacon_id)),
                timeout=3.0)
            if self._latest is not None and \
                    resp.round <= self._latest.round:
                return
            await failpoint("relay.mesh_recv", src=addr,
                            dst=self.advertise_addr, round=resp.round)
            beacon = Beacon(round=resp.round, signature=resp.signature,
                            previous_sig=resp.previous_signature)
            if not self._verifier.verify_beacon(beacon):
                log.warning("anti-entropy pull from %s failed "
                            "validation (round %d)", addr, resp.round)
                return
            self.publish(RandomData(
                round=resp.round, signature=resp.signature,
                previous_signature=resp.previous_signature))
        except PacketDropped:
            pass                     # the drop IS the modeled partition
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            log.debug("anti-entropy pull from %s: %s", addr, exc)
        finally:
            await ch.close()

    async def _pump(self, addr: str, client: PubSubClient):
        """Mesh subscription: validated rounds from a peer feed our own
        publish fan-out (publish() dedups by round, so a round arriving
        from several mesh peers is forwarded once).  The failpoint
        models a partitioned/lossy overlay link: a dropped delivery is
        suppressed WITHOUT killing the stream (the TCP session is fine;
        the path is dark), which is how asymmetric partitions present."""
        from drand_tpu.chaos.failpoints import PacketDropped, failpoint
        try:
            async for d in client.watch():
                try:
                    await failpoint("relay.mesh_recv", src=addr,
                                    dst=self.advertise_addr, round=d.round)
                except PacketDropped:
                    continue
                self.publish(d)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            log.debug("mesh subscription to %s ended: %s", addr, exc)


class _GossipService:
    def __init__(self, node: GossipRelayNode):
        self.node = node

    async def Exchange(self, request, context):
        if request.topic and request.topic != self.node.topic():
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"wrong topic {request.topic}")
        mine = sorted(self.node.known | {self.node.advertise_addr})
        self.node.learn([request.sender])
        self.node.learn(request.known)
        return drand_pb2.GossipPeersResponse(
            peers=mine,
            metadata=make_metadata(self.node._chain_info.beacon_id))


class _NullUpstream:
    """Upstream stand-in for pure mesh nodes: no rounds of its own."""

    def __init__(self, chain_info):
        self._info = chain_info

    async def info(self):
        return self._info

    async def watch(self):
        while True:             # never yields; mesh pumps feed the node
            await asyncio.sleep(3600)
        yield  # pragma: no cover

    async def close(self):
        pass
