"""Object-store relay: upload each round as JSON.

Counterpart of `cmd/relay-s3/main.go:40-50`.

.. deprecated:: PR 18
   This per-round JSON uploader is superseded by the objectsync tier
   (`drand_tpu/objectsync/`): sealed, content-addressed 16k-round
   segment objects plus one mutable manifest, published straight off
   the chain store and verifiable by any client against its own anchor.
   Per-round `{prefix}/{round}` JSON costs one object per round and is
   unverifiable without trusting the bucket; keep it only for consumers
   that scrape the legacy layout.  This module is now a thin shim on the
   objectsync `ObjectStore` seam — legacy sync backends (boto3 buckets,
   `FileStoreBackend`, any object with `put(key, body)`) keep working
   through `as_object_store`, and writes now go through the async seam
   instead of blocking the watch loop.
"""

from __future__ import annotations

import asyncio
import json

from drand_tpu import log as dlog
from drand_tpu.client.base import Client
from drand_tpu.objectsync.backends import (FilesystemBackend, ObjectStore,
                                           as_object_store)

log = dlog.get("relay")


class FileStoreBackend:
    """Local-filesystem stand-in for an S3 bucket (legacy sync seam).

    Kept for existing operator config; new code should use
    `drand_tpu.objectsync.FilesystemBackend` directly.  Delegates to it
    internally, so writes are now atomic (tmp + rename), which the old
    open/write version was not.
    """

    def __init__(self, root: str):
        self.root = root
        self._fs = FilesystemBackend(root)

    def put(self, key: str, body: bytes) -> None:
        self._fs.put_sync(key, body)

    def get(self, key: str) -> bytes:
        return self._fs.get_sync(key)


class S3Relay:
    def __init__(self, client: Client, backend, prefix: str = "public",
                 resilience=None):
        from drand_tpu.resilience import Resilience
        self.client = client
        self.backend = backend                      # as handed in (compat)
        self._store = as_object_store(backend)      # async seam used by _run
        # legacy sync backends get both per-round writes in ONE worker
        # call, preserving the old "round and latest land together"
        # behavior that sync puts gave callers
        self._sync_backend = None if isinstance(backend, ObjectStore) \
            else backend
        self.prefix = prefix
        self.resilience = resilience or Resilience()
        self._task: asyncio.Task | None = None

    async def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
        await self.client.close()

    async def _put_round(self, round_: int, body: bytes) -> None:
        k_round = f"{self.prefix}/{round_}"
        k_latest = f"{self.prefix}/latest"
        if self._sync_backend is not None:
            def both() -> None:
                self._sync_backend.put(k_round, body)
                self._sync_backend.put(k_latest, body)
            await asyncio.to_thread(both)
        else:
            await self._store.put(k_round, body)
            await self._store.put(k_latest, body)

    async def _run(self):
        # RetryPolicy-paced supervision (full jitter, reset on progress):
        # a fleet of relays uploading one chain must not retry a dead
        # upstream in lockstep (the old fixed 1 s sleep did exactly that)
        failures = 0
        while True:
            try:
                async for d in self.client.watch():
                    failures = 0
                    body = json.dumps({
                        "round": d.round,
                        "randomness": d.randomness.hex(),
                        "signature": d.signature.hex(),
                    }).encode()
                    await self._put_round(d.round, body)
            except asyncio.CancelledError:
                return
            except Exception as exc:
                failures += 1
                log.warning("s3 relay watch failed (%d consecutive), "
                            "backing off: %s", failures, exc)
            await self.resilience.retry.pace("relay.s3.watch", failures,
                                             key=self.prefix)
