"""Object-store relay: upload each round as JSON.

Counterpart of `cmd/relay-s3/main.go:40-50`.  The AWS SDK is not part of
this image, so the store backend is pluggable: any object with
`put(key: str, body: bytes)` works — boto3's Bucket adapts in one line,
and tests inject a filesystem store.
"""

from __future__ import annotations

import asyncio
import json
import os

from drand_tpu import log as dlog
from drand_tpu.client.base import Client

log = dlog.get("relay")


class FileStoreBackend:
    """Local-filesystem stand-in for an S3 bucket."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key: str, body: bytes) -> None:
        path = os.path.join(self.root, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(body)


class S3Relay:
    def __init__(self, client: Client, backend, prefix: str = "public",
                 resilience=None):
        from drand_tpu.resilience import Resilience
        self.client = client
        self.backend = backend
        self.prefix = prefix
        self.resilience = resilience or Resilience()
        self._task: asyncio.Task | None = None

    async def start(self):
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
        await self.client.close()

    async def _run(self):
        # RetryPolicy-paced supervision (full jitter, reset on progress):
        # a fleet of relays uploading one chain must not retry a dead
        # upstream in lockstep (the old fixed 1 s sleep did exactly that)
        failures = 0
        while True:
            try:
                async for d in self.client.watch():
                    failures = 0
                    body = json.dumps({
                        "round": d.round,
                        "randomness": d.randomness.hex(),
                        "signature": d.signature.hex(),
                    }).encode()
                    self.backend.put(f"{self.prefix}/{d.round}", body)
                    self.backend.put(f"{self.prefix}/latest", body)
            except asyncio.CancelledError:
                return
            except Exception as exc:
                failures += 1
                log.warning("s3 relay watch failed (%d consecutive), "
                            "backing off: %s", failures, exc)
            await self.resilience.retry.pace("relay.s3.watch", failures,
                                             key=self.prefix)
