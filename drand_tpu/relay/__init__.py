"""Relays: public distribution frontends over the client SDK.

Counterparts of the reference relay binaries:
  - `cmd/relay`        -> HTTPRelay (REST frontend over any client stack)
  - `cmd/relay-gossip` -> PubSubRelayNode + PubSubClient (push fan-out;
    the reference uses libp2p GossipSub — not available in this image, so
    the overlay here is gRPC PublicRandStream re-serving with the same
    topic/packet semantics) + GossipRelayNode (relay/gossip.py): the
    GossipSub membership half — bootstrap discovery, symmetric peer
    exchange, and a self-healing degree-D subscription mesh
  - `cmd/relay-s3`     -> S3Relay (object-store upload loop; the AWS
    client is pluggable so tests inject a local filesystem store).
    DEPRECATED since PR 18: per-round JSON objects can't feed catch-up.
    New deployments should publish content-addressed packed segments
    via `drand_tpu/objectsync/` instead; S3Relay now rides the same
    ObjectStore backend seam so existing config keeps working.
"""

from drand_tpu.relay.gossip import GossipRelayNode  # noqa: F401
from drand_tpu.relay.http_relay import HTTPRelay  # noqa: F401
from drand_tpu.relay.pubsub import PubSubClient, PubSubRelayNode  # noqa: F401
from drand_tpu.relay.s3 import S3Relay  # noqa: F401
