"""Push distribution: pubsub relay node and subscribing client.

Counterpart of the reference's libp2p gossip layer (`lp2p/relaynode.go`,
`lp2p/client/`): the relay node watches an upstream client and republishes
rounds on a topic; subscribers validate every message against pinned chain
info before accepting (the reference's topic validator,
`lp2p/client/validator.go`).

libp2p is not available in this image, so the overlay transport is the
Public gRPC service's PublicRandStream: a relay node IS a Public service
serving its validated feed, and relays can chain (subscribe to another
relay), giving the same tree-shaped fan-out GossipSub provides — with the
same topic naming `/drand/pubsub/v0.0.0/<chainhash>` carried in metadata.
"""

from __future__ import annotations

import asyncio

import grpc.aio

from drand_tpu import log as dlog
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.verify import ChainVerifier
from drand_tpu.client.base import Client, InfoBackedClient, RandomData
from drand_tpu.net.client import make_metadata
from drand_tpu.net.rpc import ServiceStub, service_handler
from drand_tpu.protogen import drand_pb2

log = dlog.get("relay")


def pubsub_topic(chain_hash: bytes) -> str:
    return f"/drand/pubsub/v0.0.0/{chain_hash.hex()}"


class PubSubRelayNode:
    """Watch an upstream client, republish to stream subscribers
    (lp2p/relaynode.go:48-179)."""

    def __init__(self, client: Client, listen: str, resilience=None):
        from drand_tpu.resilience import Resilience
        self.client = client
        self.listen = listen
        self.resilience = resilience or Resilience()
        self._subs: list[asyncio.Queue] = []
        self._latest: RandomData | None = None
        self._info = None
        self.server = grpc.aio.server()
        self.server.add_generic_rpc_handlers(
            (service_handler("Public", _RelayPublicService(self)),))
        self.port = self.server.add_insecure_port(listen)
        self._task: asyncio.Task | None = None

    @property
    def address(self) -> str:
        host = self.listen.rsplit(":", 1)[0]
        return f"{host}:{self.port}"

    async def start(self):
        self._info = await self.client.info()
        await self.server.start()
        self._task = asyncio.get_running_loop().create_task(self._watch())
        log.info("pubsub relay on %s topic %s", self.address,
                 pubsub_topic(self._info.hash()))

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
        await self.server.stop(0.5)
        await self.client.close()

    async def _watch(self):
        # Supervised watch loop paced by the shared RetryPolicy: the old
        # fixed 1 s sleep had no backoff and no jitter, so every relay
        # watching a dead upstream hammered it in lockstep.  Full-jitter
        # exponential backoff resets on the first republished round.
        failures = 0
        while True:
            try:
                async for d in self.client.watch():
                    failures = 0
                    self.publish(d)
            except asyncio.CancelledError:
                return
            except Exception as exc:
                failures += 1
                log.warning("relay watch failed (%d consecutive), "
                            "backing off: %s", failures, exc)
            await self.resilience.retry.pace("relay.pubsub.watch", failures,
                                             key=self.address)

    def publish(self, d: RandomData) -> None:
        if self._latest is not None and d.round <= self._latest.round:
            return
        self._latest = d
        for q in list(self._subs):
            try:
                q.put_nowait(d)
            except asyncio.QueueFull:
                # slow subscriber past its 32-round buffer: visible
                # shed, same contract as the HTTP watch fan-out
                try:
                    from drand_tpu import metrics as M
                    M.QUEUE_DROPPED.labels("pubsub_fanout").inc()
                except Exception:
                    pass

    def subscribe(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        self._subs.append(q)
        return q

    def unsubscribe(self, q) -> None:
        if q in self._subs:
            self._subs.remove(q)


class _RelayPublicService:
    """Minimal Public service over the relay's feed."""

    def __init__(self, node: PubSubRelayNode):
        self.node = node

    def _meta(self):
        info = self.node._info
        return make_metadata(info.beacon_id, info.hash())

    async def ChainInfo(self, request, context):
        from drand_tpu.core import convert
        return convert.info_to_proto(self.node._info)

    async def PublicRand(self, request, context):
        d = self.node._latest
        if d is None or (request.round and request.round != d.round):
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                "relay serves only the live round")
        return drand_pb2.PublicRandResponse(
            round=d.round, signature=d.signature,
            previous_signature=d.previous_signature,
            randomness=d.randomness, metadata=self._meta())

    async def PublicRandStream(self, request, context):
        q = self.node.subscribe()
        try:
            if self.node._latest is not None:
                d = self.node._latest
                yield drand_pb2.PublicRandResponse(
                    round=d.round, signature=d.signature,
                    previous_signature=d.previous_signature,
                    randomness=d.randomness, metadata=self._meta())
            while True:
                d = await q.get()
                yield drand_pb2.PublicRandResponse(
                    round=d.round, signature=d.signature,
                    previous_signature=d.previous_signature,
                    randomness=d.randomness, metadata=self._meta())
        finally:
            self.node.unsubscribe(q)


class PubSubClient(InfoBackedClient):
    """Subscribe to a relay with per-message validation
    (lp2p/client/client.go:50-193 + validator.go)."""

    def __init__(self, relay_addr: str, chain_info):
        self.relay_addr = relay_addr
        self._info = chain_info
        self._verifier = ChainVerifier(chain_info.scheme,
                                       chain_info.public_key)
        self._channel = grpc.aio.insecure_channel(relay_addr)
        self._stub = ServiceStub(self._channel, "Public")
        self._latest: RandomData | None = None

    def _validate(self, resp) -> RandomData | None:
        """The topic validator: drop anything that does not verify."""
        beacon = Beacon(round=resp.round, signature=resp.signature,
                        previous_sig=resp.previous_signature)
        if not self._verifier.verify_beacon(beacon):
            log.warning("relay message for round %d failed validation",
                        resp.round)
            return None
        return RandomData(round=resp.round, signature=resp.signature,
                          previous_signature=resp.previous_signature)

    async def watch(self):
        call = self._stub.PublicRandStream(
            drand_pb2.PublicRandRequest(
                metadata=make_metadata(self._info.beacon_id,
                                       self._info.hash())))
        async for resp in call:
            d = self._validate(resp)
            if d is not None:
                self._latest = d
                yield d

    async def get(self, round_: int = 0) -> RandomData:
        if round_ == 0 and self._latest is not None:
            return self._latest
        resp = await self._stub.PublicRand(
            drand_pb2.PublicRandRequest(
                round=round_, metadata=make_metadata(self._info.beacon_id)),
            timeout=5.0)
        d = self._validate(resp)
        if d is None:
            raise ValueError("relay returned an invalid beacon")
        return d

    async def close(self) -> None:
        await self._channel.close()
