"""HTTP relay: serve the public REST API from any client stack.

Counterpart of `cmd/relay/main.go:49-150`: a standalone web frontend that
follows upstream nodes through the client SDK (verified) and re-serves
/info, /public/{round}, /public/latest and /health — the piece operators
put behind a CDN.

The relay is the first hop a CDN retries against, so it carries the same
overload discipline as the node (drand_tpu/resilience/admission.py):
public routes run behind a bounded-concurrency/bounded-queue admission
stage and shed as 503 + ``Retry-After``; its own upstream fetches retry
under the round-derived deadline budget and HONOR an upstream node's
``Retry-After`` hint (a shedding upstream is telling us when it will
have room — hammering it sooner helps nobody on the edge).

Ingest validation (ISSUE 12): the relay re-signs nothing, so a beacon it
caches behind a CDN with an immutable Cache-Control header is the
upstream's word forever.  Every fetched beacon is therefore verified at
ingest against the chain's public key — through the native single-verify
tier (~3 ms warm), off the event loop — before it is re-served; an
invalid beacon is a 502, never a cacheable 200.  Validation is best
effort by construction: it arms itself from `client.info()`, so an
upstream that cannot provide chain info (or a chained beacon served
without its previous signature) passes through exactly as before.

Encode-once fast lane (ISSUE 14): the relay keeps its own
:class:`ResponseCache` of pre-encoded bodies.  Because the encoder and
ETag derivation are SHARED with the node (http/response_cache.py), the
relay re-serves byte-identical bodies under the node's exact ETag — a
CDN can revalidate against either end of the chain.  Fixed-round hits
never touch the upstream; concurrent cold-round misses coalesce onto
one upstream fetch; ``DRAND_TPU_SERVE_CACHE=0`` bypasses the lane.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from drand_tpu import log as dlog
from drand_tpu.beacon.clock import Clock, SystemClock
from drand_tpu.client.base import Client
from drand_tpu.http import response_cache as rc
from drand_tpu.resilience import Deadline, Resilience, RetryAfterError, \
    admission
from drand_tpu.resilience.admission import AdmissionShedError

log = dlog.get("relay")

# fallback upstream-fetch budget until the chain info (and so the group
# period) is known
DEFAULT_FETCH_BUDGET_S = 5.0


class _UpstreamError(Exception):
    """A failed upstream load, captured as plain data so N coalesced
    waiters can each build a FRESH error response (an aiohttp
    HTTPException is itself a Response — one instance cannot answer two
    requests)."""

    def __init__(self, status: int, text: str,
                 retry_after: "str | None" = None):
        super().__init__(text)
        self.status = status
        self.text = text
        self.retry_after = retry_after

    @classmethod
    def from_http(cls, exc: web.HTTPException) -> "_UpstreamError":
        return cls(exc.status, exc.text or "",
                   exc.headers.get("Retry-After"))

    def to_response(self) -> web.Response:
        headers = {}
        if self.retry_after is not None:
            headers["Retry-After"] = self.retry_after
        return web.Response(status=self.status, text=self.text,
                            headers=headers)


class HTTPRelay:
    def __init__(self, client: Client, listen: str,
                 clock: Clock | None = None, resilience=None,
                 admission_limits=None, verify_ingest: bool = True):
        self.client = client
        self.clock = clock or SystemClock()
        self.resilience = resilience or Resilience(clock=self.clock)
        self.admission = admission.AdmissionController(admission_limits)
        self.verify_ingest = verify_ingest
        self._ingest_verifier = None    # ChainVerifier, armed on first use
        # encode-once fast lane (ISSUE 14): None = bypass (A/B lever)
        self._cache = rc.ResponseCache() if rc.cache_enabled() else None
        host, _, port = listen.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)  # owner: relay start (rebound once to the bound port)
        self.app = web.Application()
        self.app.add_routes([
            web.get("/info", self.handle_info),
            web.get("/health", self.handle_health),
            web.get("/public/latest", self.handle_latest),
            web.get("/public/{round}", self.handle_round),
            web.get("/{chainhash}/info", self.handle_info),
            web.get("/{chainhash}/public/latest", self.handle_latest),
            web.get("/{chainhash}/public/{round}", self.handle_round),
        ])
        self._runner: web.AppRunner | None = None

    async def start(self):
        # same disconnect discipline as the node's public server: a
        # dropped edge connection frees its admission slot immediately
        self._runner = web.AppRunner(self.app, handler_cancellation=True)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        log.info("HTTP relay on %s:%d", self.host, self.port)

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()
        await self.client.close()

    @staticmethod
    def _shed(exc: AdmissionShedError) -> web.Response:
        from drand_tpu.http.server import shed_response
        return shed_response(exc)

    async def _check_chain(self, request):
        ch = request.match_info.get("chainhash")
        if ch:
            info = await self.client.info()
            if info.hash_hex() != ch:
                raise web.HTTPNotFound(text=f"unknown chain {ch}")

    async def _fetch(self, round_: int):
        """Upstream fetch under a deadline budget derived from round
        timing (drand_tpu/resilience/deadline.py): a CDN-fronted relay
        must answer or fail inside half a period, not hold the edge
        connection for a wedged upstream's full timeout.  Retries ride
        the shared RetryPolicy, so an upstream 429/503's Retry-After
        hint floors the backoff — capped at the budget (a hint past the
        budget means this request is not servable: give the edge its
        503 now)."""
        from drand_tpu.resilience import partial_broadcast_budget
        budget = DEFAULT_FETCH_BUDGET_S
        try:
            info = await self.client.info()     # cached by the SDK stack
            budget = min(partial_broadcast_budget(info.period),
                         DEFAULT_FETCH_BUDGET_S)
        except Exception:
            pass
        deadline = Deadline.after(self.clock, budget)

        async def attempt(_n):
            return await asyncio.wait_for(self.client.get(round_),
                                          deadline.timeout(budget))

        try:
            d = await self.resilience.retry.call(
                "relay.upstream_fetch", attempt, key=f"r{round_}",
                deadline=deadline)
            return await self._validate_ingest(d)
        except RetryAfterError as exc:
            # propagate the upstream's shed downstream: the edge gets a
            # 503 + Retry-After it can cache against, not a hung socket
            raise web.HTTPServiceUnavailable(
                text=f"upstream shedding: {exc}",
                headers={"Retry-After":
                         str(max(int(round(exc.retry_after_s)), 1))})
        except web.HTTPException:
            raise
        except (asyncio.TimeoutError, TimeoutError):
            # py3.10: asyncio.TimeoutError is not yet builtin TimeoutError;
            # DeadlineExceededError subclasses the builtin
            raise web.HTTPGatewayTimeout(
                text=f"upstream fetch exceeded {budget:.1f}s budget")

    async def _validate_ingest(self, d):
        """Verify a fetched beacon before re-serving: the native
        single-verify tier through ChainVerifier (~3 ms warm), in the
        crypto worker thread — never a pairing on the event loop.  Skips
        (serving as before) when chain info is unavailable or a chained
        beacon arrives without its previous signature; a failed check is
        a 502 — an invalid beacon must never earn a cacheable 200."""
        if not self.verify_ingest:
            return d
        if self._ingest_verifier is None:
            try:
                info = await self.client.info()
                from drand_tpu.chain.verify import ChainVerifier
                self._ingest_verifier = ChainVerifier(info.scheme,
                                                      info.public_key)
            except Exception:
                return d    # no chain info: nothing to verify against
        v = self._ingest_verifier
        if not v.scheme.decouple_prev_sig and not d.previous_signature:
            return d
        from drand_tpu.beacon.crypto_backend import run_in_crypto_thread
        from drand_tpu.chain.beacon import Beacon
        beacon = Beacon(round=d.round, signature=d.signature,
                        previous_sig=d.previous_signature)
        if not await run_in_crypto_thread(v.verify_beacon, beacon):
            log.warning("relay ingest: invalid beacon for round %d from "
                        "upstream", d.round)
            raise web.HTTPBadGateway(
                text=f"upstream served an invalid beacon for round {d.round}")
        return d

    @staticmethod
    def _rand_json(d) -> dict:
        # shared shape with the node's _beacon_json: same fields, same
        # order, same encoder — so the relay's bytes and ETag are the
        # node's bytes and ETag
        return rc.beacon_fields(d.round, d.randomness, d.signature,
                                d.previous_signature)

    @classmethod
    def _encode_rand(cls, d) -> rc.EncodedBody:
        return rc.EncodedBody(rc.encode_json(cls._rand_json(d)), d.round)

    async def handle_info(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "info"):
                await self._check_chain(request)
                info = await self.client.info()
                headers = {"Cache-Control": "max-age=604800"}
                if self._cache is None:
                    return rc.respond(request, rc.EncodedBody(
                        info.to_json()), headers, "info", "bypass")
                enc, event = self._cache.info_body(info.to_json)
                return rc.respond(request, enc, headers, "info", event)
        except AdmissionShedError as exc:
            return self._shed(exc)

    async def handle_round(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "round"):
                return await self._serve_round(request)
        except AdmissionShedError as exc:
            return self._shed(exc)

    async def _serve_round(self, request):
        await self._check_chain(request)
        try:
            round_ = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        if round_ < 1:
            # round 0 means "latest" to the client stack — routing it here
            # would stamp a mutable answer with the immutable cache header
            return await self._serve_latest(request)
        headers = {"Cache-Control": "public, max-age=31536000, immutable"}

        async def load() -> rc.EncodedBody:
            from drand_tpu import tracing
            with tracing.span("relay.fanout", round_=round_, route="round"):
                try:
                    d = await self._fetch(round_)
                except web.HTTPException as exc:
                    raise _UpstreamError.from_http(exc) from None
                except Exception as exc:
                    raise _UpstreamError(
                        404, f"round {round_}: {exc}") from None
            return self._encode_rand(d)

        try:
            if self._cache is None:
                return rc.respond(request, await load(), headers, "round",
                                  "bypass")
            # cached fixed rounds never touch the upstream again; cold
            # misses for the same round coalesce onto ONE fetch
            enc, event = await self._cache.get_or_load_round(round_, load)
        except _UpstreamError as exc:
            return exc.to_response()
        return rc.respond(request, enc, headers, "round", event)

    async def handle_latest(self, request):
        try:
            async with self.admission.slot(admission.PUBLIC, "latest"):
                return await self._serve_latest(request)
        except AdmissionShedError as exc:
            return self._shed(exc)

    async def _serve_latest(self, request):
        await self._check_chain(request)
        cache = self._cache
        if cache is not None:
            enc = cache.latest()
            if enc is not None:
                # freshness check against the upstream chain's round
                # schedule; no chain info yet means no fast lane (the
                # fetch below arms it)
                try:
                    expected = self.client.round_at(self.clock.now())
                except Exception:
                    expected = None
                if expected is not None and enc.round >= expected:
                    return rc.respond(request, enc,
                                      await self._latest_headers(enc.round),
                                      "latest", "hit")
        from drand_tpu import tracing
        with tracing.span("relay.fanout", route="latest") as sp:
            try:
                d = await self._fetch(0)
            except web.HTTPException:
                raise
            except Exception as exc:
                raise web.HTTPNotFound(text=f"latest: {exc}")
            sp.round = d.round
        enc = self._encode_rand(d)
        if cache is not None:
            cache.note_encoded(enc)
        return rc.respond(request, enc, await self._latest_headers(enc.round),
                          "latest", "miss" if cache is not None else "bypass")

    async def _latest_headers(self, round_: int) -> dict:
        info = await self.client.info()
        from drand_tpu.chain.time import time_of_round
        next_t = time_of_round(info.period, info.genesis_time, round_ + 1)
        max_age = max(int(next_t - self.clock.now()), 0)
        return {"Cache-Control": f"public, max-age={max_age}"}

    async def handle_health(self, request):
        """Probe lane (admission.PROBE): the relay's own health never
        queues behind the public traffic it is shedding."""
        try:
            async with self.admission.slot(admission.PROBE, "health"):
                try:
                    d = await self.client.get(0)
                    expected = self.client.round_at(self.clock.now())
                    status = 200 if expected - d.round <= 1 else 500
                    return web.json_response(
                        {"current": d.round, "expected": expected},
                        status=status)
                except Exception as exc:
                    return web.json_response({"error": str(exc)},
                                             status=500)
        except AdmissionShedError as exc:
            return self._shed(exc)
