"""HTTP relay: serve the public REST API from any client stack.

Counterpart of `cmd/relay/main.go:49-150`: a standalone web frontend that
follows upstream nodes through the client SDK (verified) and re-serves
/info, /public/{round}, /public/latest and /health — the piece operators
put behind a CDN.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from drand_tpu import log as dlog
from drand_tpu.beacon.clock import Clock, SystemClock
from drand_tpu.client.base import Client

log = dlog.get("relay")

# fallback upstream-fetch budget until the chain info (and so the group
# period) is known
DEFAULT_FETCH_BUDGET_S = 5.0


class HTTPRelay:
    def __init__(self, client: Client, listen: str,
                 clock: Clock | None = None):
        self.client = client
        self.clock = clock or SystemClock()
        host, _, port = listen.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self.app = web.Application()
        self.app.add_routes([
            web.get("/info", self.handle_info),
            web.get("/health", self.handle_health),
            web.get("/public/latest", self.handle_latest),
            web.get("/public/{round}", self.handle_round),
            web.get("/{chainhash}/info", self.handle_info),
            web.get("/{chainhash}/public/latest", self.handle_latest),
            web.get("/{chainhash}/public/{round}", self.handle_round),
        ])
        self._runner: web.AppRunner | None = None

    async def start(self):
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        log.info("HTTP relay on %s:%d", self.host, self.port)

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()
        await self.client.close()

    async def _check_chain(self, request):
        ch = request.match_info.get("chainhash")
        if ch:
            info = await self.client.info()
            if info.hash_hex() != ch:
                raise web.HTTPNotFound(text=f"unknown chain {ch}")

    async def _fetch(self, round_: int):
        """Upstream fetch under a deadline budget derived from round
        timing (drand_tpu/resilience/deadline.py): a CDN-fronted relay
        must answer or fail inside half a period, not hold the edge
        connection for a wedged upstream's full timeout."""
        from drand_tpu.resilience import partial_broadcast_budget
        budget = DEFAULT_FETCH_BUDGET_S
        try:
            info = await self.client.info()     # cached by the SDK stack
            budget = min(partial_broadcast_budget(info.period),
                         DEFAULT_FETCH_BUDGET_S)
        except Exception:
            pass
        try:
            return await asyncio.wait_for(self.client.get(round_), budget)
        except asyncio.TimeoutError:
            raise web.HTTPGatewayTimeout(
                text=f"upstream fetch exceeded {budget:.1f}s budget")

    @staticmethod
    def _rand_json(d) -> dict:
        out = {"round": d.round, "randomness": d.randomness.hex(),
               "signature": d.signature.hex()}
        if d.previous_signature:
            out["previous_signature"] = d.previous_signature.hex()
        return out

    async def handle_info(self, request):
        await self._check_chain(request)
        info = await self.client.info()
        return web.Response(body=info.to_json(),
                            content_type="application/json",
                            headers={"Cache-Control": "max-age=604800"})

    async def handle_round(self, request):
        await self._check_chain(request)
        try:
            round_ = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        if round_ < 1:
            # round 0 means "latest" to the client stack — routing it here
            # would stamp a mutable answer with the immutable cache header
            return await self.handle_latest(request)
        from drand_tpu import tracing
        with tracing.span("relay.fanout", round_=round_, route="round"):
            try:
                d = await self._fetch(round_)
            except web.HTTPException:
                raise
            except Exception as exc:
                raise web.HTTPNotFound(text=f"round {round_}: {exc}")
        return web.json_response(
            self._rand_json(d),
            headers={"Cache-Control": "public, max-age=31536000, immutable"})

    async def handle_latest(self, request):
        await self._check_chain(request)
        from drand_tpu import tracing
        with tracing.span("relay.fanout", route="latest") as sp:
            try:
                d = await self._fetch(0)
            except web.HTTPException:
                raise
            except Exception as exc:
                raise web.HTTPNotFound(text=f"latest: {exc}")
            sp.round = d.round
        info = await self.client.info()
        from drand_tpu.chain.time import time_of_round
        next_t = time_of_round(info.period, info.genesis_time, d.round + 1)
        max_age = max(int(next_t - self.clock.now()), 0)
        return web.json_response(
            self._rand_json(d),
            headers={"Cache-Control": f"public, max-age={max_age}"})

    async def handle_health(self, request):
        try:
            d = await self.client.get(0)
            expected = self.client.round_at(self.clock.now())
            status = 200 if expected - d.round <= 1 else 500
            return web.json_response({"current": d.round,
                                      "expected": expected}, status=status)
        except Exception as exc:
            return web.json_response({"error": str(exc)}, status=500)
