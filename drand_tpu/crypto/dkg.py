"""Pedersen distributed key generation with resharing.

Counterpart of the kyber `share/dkg` protocol driven by the reference at
`core/drand_beacon_control.go:351-422` (config at :355-366, phaser at
:915-926): a deal/response/justification state machine over an untrusted
broadcast channel, "fast sync" mode — phases advance as soon as all
expected bundles arrive, with clock timeouts as backstop.

Fresh DKG: every new node deals a random secret; the group key is the sum
of QUAL dealers' polynomials.  Resharing: old-group nodes deal their
existing share under a fresh degree-(t'-1) polynomial; new shares are
Lagrange-combined at the old indices, preserving the group public key.

Wire shapes match drand's dkg.proto (dealer/share indices, encrypted
shares, session id, schnorr bundle signatures).
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass, field

from drand_tpu import log as dlog
from drand_tpu.crypto import ecies
from drand_tpu.crypto import sign as S
from drand_tpu.crypto.bls12381 import curve as C
from drand_tpu.crypto.bls12381.constants import R
from drand_tpu.crypto.poly import (PriPoly, PriShare, PubPoly,
                                   _lagrange_basis_at_zero)

log = dlog.get("dkg")


@dataclass(frozen=True)
class DkgNode:
    index: int
    public: tuple      # G1 point
    address: str = ""


@dataclass
class DistKeyShare:
    """The DKG output (kyber dkg.DistKeyShare): public commitments + this
    node's private share."""
    commits: list        # G1 points, commits[0] = group public key
    pri_share: PriShare

    def public(self) -> PubPoly:
        return PubPoly(self.commits)


@dataclass
class DkgConfig:
    longterm: int                          # our long-term secret scalar
    new_nodes: list[DkgNode]
    threshold: int
    nonce: bytes                           # session id
    old_nodes: list[DkgNode] | None = None     # resharing only
    old_threshold: int = 0
    share: DistKeyShare | None = None          # our old share (reshare dealer)
    public_coeffs: list | None = None          # old group commits (reshare)
    entropy: object = None                     # callable n -> bytes, or None
    # (user entropy for the secret polynomial — the --source flag,
    # reference core/drand_beacon_control.go:1346+)

    @property
    def resharing(self) -> bool:
        return self.old_nodes is not None

    def dealers(self) -> list[DkgNode]:
        return self.old_nodes if self.resharing else self.new_nodes

    def our_new_index(self) -> int | None:
        pub = C.g1_mul(C.G1_GEN, self.longterm)
        for n in self.new_nodes:
            if C.g1_eq(n.public, pub):
                return n.index
        return None

    def our_dealer_index(self) -> int | None:
        pub = C.g1_mul(C.G1_GEN, self.longterm)
        for n in self.dealers():
            if C.g1_eq(n.public, pub):
                return n.index
        return None


# ---------------------------------------------------------------------------
# Bundles (in-memory mirror of dkg.proto)
# ---------------------------------------------------------------------------

@dataclass
class Deal:
    share_index: int
    encrypted_share: bytes


@dataclass
class DealBundle:
    dealer_index: int
    commits: list[bytes]          # compressed G1 commitments
    deals: list[Deal]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(b"deal")
        h.update(self.dealer_index.to_bytes(4, "big"))
        for c in self.commits:
            h.update(c)
        for d in sorted(self.deals, key=lambda d: d.share_index):
            h.update(d.share_index.to_bytes(4, "big"))
            h.update(d.encrypted_share)
        h.update(self.session_id)
        return h.digest()


@dataclass
class Response:
    dealer_index: int
    status: bool


@dataclass
class ResponseBundle:
    share_index: int
    responses: list[Response]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(b"response")
        h.update(self.share_index.to_bytes(4, "big"))
        for r in sorted(self.responses, key=lambda r: r.dealer_index):
            h.update(r.dealer_index.to_bytes(4, "big"))
            h.update(b"\x01" if r.status else b"\x00")
        h.update(self.session_id)
        return h.digest()


@dataclass
class Justification:
    share_index: int
    share: int          # revealed plaintext share (scalar)


@dataclass
class JustificationBundle:
    dealer_index: int
    justifications: list[Justification]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(b"justification")
        h.update(self.dealer_index.to_bytes(4, "big"))
        for j in sorted(self.justifications, key=lambda j: j.share_index):
            h.update(j.share_index.to_bytes(4, "big"))
            h.update(j.share.to_bytes(32, "big"))
        h.update(self.session_id)
        return h.digest()


class DkgError(Exception):
    pass


def _batch_enabled(rows: int) -> bool:
    """Gate for the device-batched commitment evaluations
    (ops/bls.pubpoly_eval_g1_stacked).  DRAND_TPU_DKG_BATCH=1/on forces
    the stacked kernel (the parity tests pin it at small shapes on the
    host backend), 0/off forces the scalar path; the default routes
    through the device only when a real accelerator backs jax AND the
    batch is large enough to amortize dispatch overhead."""
    import os
    v = os.environ.get("DRAND_TPU_DKG_BATCH", "").strip().lower()
    if v in ("1", "on", "force", "true"):
        return True
    if v in ("0", "off", "false"):
        return False
    if rows < 8:
        return False
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:        # jax absent/broken: host golden always works
        return False


# ---------------------------------------------------------------------------
# The state machine
# ---------------------------------------------------------------------------

class DkgProtocol:
    """Single-ceremony state machine.  The runner feeds verified bundles in
    and drives phase transitions; this class owns the crypto."""

    def __init__(self, conf: DkgConfig):
        self.conf = conf
        self.nidx = conf.our_new_index()
        self.didx = conf.our_dealer_index()
        self._poly: PriPoly | None = None
        self.deals: dict[int, DealBundle] = {}
        self.responses: dict[int, ResponseBundle] = {}
        self.justifs: dict[int, JustificationBundle] = {}
        # decrypted share from each dealer (for our new index)
        self._recv_shares: dict[int, int] = {}
        self._bad_dealers: set[int] = set()

    # -- phase 1: deals -----------------------------------------------------

    def make_deal_bundle(self) -> DealBundle | None:
        """Our deal, or None when we are not a dealer."""
        if self.didx is None:
            return None
        conf = self.conf
        if conf.resharing:
            if conf.share is None:
                return None
            secret = conf.share.pri_share.value
        else:
            secret = None
        self._poly = PriPoly.random(conf.threshold, secret=secret,
                                    rand=conf.entropy)
        commits = [C.g1_to_bytes(c) for c in self._poly.commit().commits]
        deals = []
        for node in conf.new_nodes:
            share = self._poly.eval(node.index)
            blob = ecies.seal(node.public, share.value.to_bytes(32, "big"))
            deals.append(Deal(share_index=node.index, encrypted_share=blob))
        bundle = DealBundle(dealer_index=self.didx, commits=commits,
                            deals=deals, session_id=conf.nonce)
        bundle.signature = S.schnorr_sign(conf.longterm, bundle.hash())
        return bundle

    def _dealer_pub(self, index: int):
        for n in self.conf.dealers():
            if n.index == index:
                return n.public
        return None

    def receive_deal_bundle(self, bundle: DealBundle) -> bool:
        """Verify signature + session, record.  Returns acceptance."""
        pub = self._dealer_pub(bundle.dealer_index)
        if pub is None or bundle.session_id != self.conf.nonce:
            return False
        if not S.schnorr_verify(pub, bundle.hash(), bundle.signature):
            return False
        if len(bundle.commits) != self.conf.threshold:
            self._bad_dealers.add(bundle.dealer_index)
            return False
        self.deals[bundle.dealer_index] = bundle
        return True

    # -- phase 2: responses -------------------------------------------------

    def make_response_bundle(self) -> ResponseBundle | None:
        """Decrypt and check every dealer's share for our index
        (None if we hold no new share)."""
        if self.nidx is None:
            return None
        checked = self._check_deals()
        responses = [Response(dealer_index=dealer.index,
                              status=checked.get(dealer.index, False))
                     for dealer in self.conf.dealers()]
        rb = ResponseBundle(share_index=self.nidx, responses=responses,
                            session_id=self.conf.nonce)
        rb.signature = S.schnorr_sign(self.conf.longterm, rb.hash())
        return rb

    def _check_deals(self) -> dict[int, bool]:
        """Verdicts for every dealer in one pass: the O(n·t) commitment
        evaluations route through the stacked device kernel when the
        batch gate is open, the host scalar path otherwise — both
        bit-identical (canonical affine comparison)."""
        out: dict[int, bool] = {}
        pre: dict[int, tuple[int, list]] = {}
        for dealer in self.conf.dealers():
            bundle = self.deals.get(dealer.index)
            if bundle is None:
                out[dealer.index] = False
                continue
            p = self._predecrypt(bundle)
            if p is None:
                out[dealer.index] = False
            else:
                pre[dealer.index] = p
        if not _batch_enabled(len(pre)):
            for di, (value, pts) in pre.items():
                out[di] = self._check_deal_host(self.deals[di], value, pts)
            return out
        out.update(self._check_deals_device(pre))
        return out

    def _check_deal(self, bundle: DealBundle) -> bool:
        p = self._predecrypt(bundle)
        if p is None:
            return False
        return self._check_deal_host(bundle, *p)

    def _predecrypt(self, bundle: DealBundle) -> tuple[int, list] | None:
        """Host half of a deal check: exactly one deal for our index,
        ECIES decryption, commitment decompression.  Returns
        (share value, commit points) or None on failure."""
        my = [d for d in bundle.deals if d.share_index == self.nidx]
        if len(my) != 1:
            return None
        try:
            plain = ecies.open_sealed(self.conf.longterm,
                                      my[0].encrypted_share)
            value = int.from_bytes(plain, "big") % R
        except Exception:
            return None
        try:
            pts = [C.g1_from_bytes(c) for c in bundle.commits]
        except Exception:
            return None
        return value, pts

    def _check_deal_host(self, bundle: DealBundle, value: int,
                         commit_pts: list) -> bool:
        """Scalar commitment check (golden model)."""
        commits = PubPoly(commit_pts)
        if not C.g1_eq(commits.eval(self.nidx), C.g1_mul(C.G1_GEN, value)):
            return False
        if self.conf.resharing:
            # dealer's constant term must commit to their old share:
            # old_pub_poly.eval(dealer) == commits[0]
            old = PubPoly(self.conf.public_coeffs)
            if not C.g1_eq(old.eval(bundle.dealer_index), commits.commits[0]):
                return False
        self._recv_shares[bundle.dealer_index] = value
        return True

    def _check_deals_device(self, pre: dict[int, tuple[int, list]]
                            ) -> dict[int, bool]:
        """Device-batched commitment checks: the dealers' per-node eval
        (and, for reshares, the old-poly constant-term check) stacked
        into one kernel dispatch each.  Dealers whose commitments
        contain the identity fall back to the host path row by row —
        the device Horner needs representable affine inputs, the same
        exposure `pubpoly_eval_g1` has."""
        import numpy as np

        from drand_tpu.ops import bls as OB
        out: dict[int, bool] = {}
        batch: list[int] = []
        for di, (value, pts) in pre.items():
            if any(C.point_is_inf(p, C.FP_OPS) for p in pts):
                out[di] = self._check_deal_host(self.deals[di], value, pts)
            else:
                batch.append(di)
        old_pts = None
        if self.conf.resharing:
            old_pts = list(self.conf.public_coeffs)
            if any(C.point_is_inf(p, C.FP_OPS) for p in old_pts):
                # degenerate old group poly: host path for everything
                for di in batch:
                    out[di] = self._check_deal_host(self.deals[di], *pre[di])
                return out
        if not batch:
            return out
        rows = len(batch)
        ctx, cty = [], []
        ex, ey, einf = [], [], []
        for di in batch:
            value, pts = pre[di]
            tx, ty, _ = OB.g1_rows_to_limbs(pts)
            ctx.append(tx)
            cty.append(ty)
            px, py, pinf = OB.g1_rows_to_limbs([C.g1_mul(C.G1_GEN, value)])
            ex.append(px[0])
            ey.append(py[0])
            einf.append(pinf[0])
        ok = OB.dkg_commit_checks(
            np.stack(ctx), np.stack(cty),
            np.asarray([self.nidx] * rows, dtype=np.int32),
            np.stack(ex), np.stack(ey), np.asarray(einf))
        if self.conf.resharing:
            # old_pub_poly.eval(dealer) == commits[0], one row per dealer
            otx, oty, _ = OB.g1_rows_to_limbs(old_pts)
            octx = np.broadcast_to(otx, (rows,) + otx.shape)
            octy = np.broadcast_to(oty, (rows,) + oty.shape)
            oex, oey, oeinf = [], [], []
            for di in batch:
                px, py, pinf = OB.g1_rows_to_limbs([pre[di][1][0]])
                oex.append(px[0])
                oey.append(py[0])
                oeinf.append(pinf[0])
            ok = ok & OB.dkg_commit_checks(
                octx, octy, np.asarray(batch, dtype=np.int32),
                np.stack(oex), np.stack(oey), np.asarray(oeinf))
        for di, good in zip(batch, ok):
            out[di] = bool(good)
            if good:
                self._recv_shares[di] = pre[di][0]
        return out

    def receive_response_bundle(self, rb: ResponseBundle) -> bool:
        holder = None
        for n in self.conf.new_nodes:
            if n.index == rb.share_index:
                holder = n
        if holder is None or rb.session_id != self.conf.nonce:
            return False
        if not S.schnorr_verify(holder.public, rb.hash(), rb.signature):
            return False
        self.responses[rb.share_index] = rb
        return True

    def complaints(self) -> dict[int, set[int]]:
        """dealer -> set of complaining share indices."""
        out: dict[int, set[int]] = {}
        for rb in self.responses.values():
            for r in rb.responses:
                if not r.status:
                    out.setdefault(r.dealer_index, set()).add(rb.share_index)
        return out

    # -- phase 3: justifications -------------------------------------------

    def make_justification_bundle(self) -> JustificationBundle | None:
        """Reveal plaintext shares answering complaints against us."""
        against = self.complaints().get(self.didx) if self.didx is not None \
            else None
        if not against or self._poly is None:
            return None
        justifs = [Justification(share_index=i,
                                 share=self._poly.eval(i).value)
                   for i in sorted(against)]
        jb = JustificationBundle(dealer_index=self.didx,
                                 justifications=justifs,
                                 session_id=self.conf.nonce)
        jb.signature = S.schnorr_sign(self.conf.longterm, jb.hash())
        return jb

    def receive_justification_bundle(self, jb: JustificationBundle) -> bool:
        pub = self._dealer_pub(jb.dealer_index)
        if pub is None or jb.session_id != self.conf.nonce:
            return False
        if not S.schnorr_verify(pub, jb.hash(), jb.signature):
            return False
        self.justifs[jb.dealer_index] = jb
        return True

    # -- finalization -------------------------------------------------------

    def qual(self) -> list[int]:
        """Qualified dealers: dealt, no unanswered valid complaint."""
        complaints = self.complaints()
        # dealers whose justification covers every accuser: their
        # revealed shares still need the commitment check (batchable)
        pending: dict[int, JustificationBundle] = {}
        for dealer in sorted(self.deals):
            if dealer in self._bad_dealers:
                continue
            accused = complaints.get(dealer, set())
            if not accused:
                continue
            jb = self.justifs.get(dealer)
            if jb is None:
                continue
            answered = {j.share_index for j in jb.justifications}
            if accused.issubset(answered):
                pending[dealer] = jb
        verified = self._verify_justifications(pending)
        out = []
        for dealer in sorted(self.deals):
            if dealer in self._bad_dealers:
                continue
            accused = complaints.get(dealer, set())
            if accused:
                if not verified.get(dealer, False):
                    continue
                # justified: pick up our share from the revealed values
                if self.nidx is not None and dealer not in self._recv_shares:
                    for j in self.justifs[dealer].justifications:
                        if j.share_index == self.nidx:
                            self._recv_shares[dealer] = j.share
            out.append(dealer)
        return out

    def _verify_justifications(self, pending: dict[int, JustificationBundle]
                               ) -> dict[int, bool]:
        """dealer -> every revealed share matches the dealer's
        commitments.  Batched through the stacked kernel when the gate
        is open (one row per justification), host scalar otherwise."""
        out: dict[int, bool] = {}
        host: dict[int, JustificationBundle] = {}
        n_rows = sum(len(jb.justifications) for jb in pending.values())
        if _batch_enabled(n_rows):
            import numpy as np

            from drand_tpu.ops import bls as OB
            rows: list[tuple[int, Justification, list]] = []
            for dealer, jb in pending.items():
                pts = [C.g1_from_bytes(c)
                       for c in self.deals[dealer].commits]
                if any(C.point_is_inf(p, C.FP_OPS) for p in pts):
                    host[dealer] = jb
                    continue
                for j in jb.justifications:
                    rows.append((dealer, j, pts))
            if rows:
                ctx, cty, idxs = [], [], []
                ex, ey, einf = [], [], []
                for dealer, j, pts in rows:
                    tx, ty, _ = OB.g1_rows_to_limbs(pts)
                    ctx.append(tx)
                    cty.append(ty)
                    idxs.append(j.share_index)
                    px, py, pinf = OB.g1_rows_to_limbs(
                        [C.g1_mul(C.G1_GEN, j.share)])
                    ex.append(px[0])
                    ey.append(py[0])
                    einf.append(pinf[0])
                ok = OB.dkg_commit_checks(
                    np.stack(ctx), np.stack(cty),
                    np.asarray(idxs, dtype=np.int32),
                    np.stack(ex), np.stack(ey), np.asarray(einf))
                for (dealer, _, _), good in zip(rows, ok):
                    out[dealer] = out.get(dealer, True) and bool(good)
        else:
            host = pending
        for dealer, jb in host.items():
            commits = PubPoly([C.g1_from_bytes(c)
                               for c in self.deals[dealer].commits])
            out[dealer] = all(C.g1_eq(commits.eval(j.share_index),
                                      C.g1_mul(C.G1_GEN, j.share))
                              for j in jb.justifications)
        return out

    def finalize(self) -> DistKeyShare | None:
        """Compute the distributed key share (None for leaving nodes)."""
        qual = self.qual()
        min_q = self.conf.old_threshold if self.conf.resharing \
            else self.conf.threshold
        if len(qual) < min_q:
            raise DkgError(f"too few qualified dealers: {len(qual)} < {min_q}")
        if self.nidx is None:
            return None
        missing = [d for d in qual if d not in self._recv_shares]
        if missing:
            raise DkgError(f"missing shares from qualified dealers {missing}")

        if not self.conf.resharing:
            value = 0
            commits = None
            for dealer in qual:
                value = (value + self._recv_shares[dealer]) % R
                poly = PubPoly([C.g1_from_bytes(c)
                                for c in self.deals[dealer].commits])
                commits = poly if commits is None else commits.add(poly)
            return DistKeyShare(commits=commits.commits,
                                pri_share=PriShare(self.nidx, value))

        # resharing: Lagrange-combine dealer contributions at old indices
        lam = _lagrange_basis_at_zero(qual)
        value = 0
        for dealer in qual:
            value = (value + lam[dealer] * self._recv_shares[dealer]) % R
        # commits: sum over dealers of lambda_d * dealer_poly coefficients
        commits = []
        for k in range(self.conf.threshold):
            acc = None
            for dealer in qual:
                c = C.g1_mul(C.g1_from_bytes(self.deals[dealer].commits[k]),
                             lam[dealer])
                acc = c if acc is None else C.g1_add(acc, c)
            commits.append(acc)
        return DistKeyShare(commits=commits,
                            pri_share=PriShare(self.nidx, value))


def new_nonce() -> bytes:
    return secrets.token_bytes(32)
