"""Threshold BLS (t-of-n) over BLS12-381: partial signatures on G2.

Counterpart of kyber's `tbls` scheme as used by the reference
(`key/curve.go:36`: `tbls.NewThresholdSchemeOnG2(Pairing)`), with the same
wire format for partials: 2-byte big-endian share index prefix followed by
the 96-byte compressed G2 signature (reference behavior at
`chain/beacon/node.go:119` IndexOf and `chain/beacon/crypto.go:55-59`).

The hot verification ops have batched device equivalents in
drand_tpu.ops.bls (`verify_partial_g2_sigs`, `pubpoly_eval_g1`), wired into
the live aggregation path by drand_tpu.beacon.chain.
"""

from __future__ import annotations

from .bls12381 import curve as C
from .bls12381 import h2c
from .bls12381 import pairing as PR
from .poly import PriShare, PubPoly, _lagrange_basis_at_zero, recover_commit_g2

INDEX_LEN = 2


def sign_partial(share: PriShare, msg: bytes) -> bytes:
    """Partial signature: BE16(index) || compressed(share.value * H2(msg))."""
    h = h2c.hash_to_g2(msg)
    sig = C.g2_to_bytes(C.g2_mul(h, share.value))
    return share.index.to_bytes(INDEX_LEN, "big") + sig


def index_of(partial: bytes) -> int:
    """Extract the signer index from a partial signature."""
    if len(partial) < INDEX_LEN:
        raise ValueError("partial too short")
    return int.from_bytes(partial[:INDEX_LEN], "big")


def sig_of(partial: bytes) -> bytes:
    return partial[INDEX_LEN:]


def verify_partial_at(pub_i, msg: bytes, partial: bytes) -> bool:
    """Verify one partial against an ALREADY-EVALUATED public point for
    its index (the seam the precomputed signer-key table feeds —
    `beacon/signer_table.py` caches `pub_poly.eval(i)` per group epoch
    instead of re-running the Horner ladder per partial)."""
    try:
        sigma = C.g2_from_bytes(sig_of(partial))
    except ValueError:
        return False
    if not C.g2_in_subgroup(sigma):
        return False
    h = h2c.hash_to_g2(msg)
    return PR.pairing_check([(C.g1_neg(C.G1_GEN), sigma), (pub_i, h)])


def verify_partial(pub_poly: PubPoly, msg: bytes, partial: bytes) -> bool:
    """Verify one partial against the public polynomial evaluated at its
    index (reference: `key.Scheme.VerifyPartial`, hot per-partial check at
    `chain/beacon/node.go:125`)."""
    try:
        idx = index_of(partial)
    except ValueError:
        return False
    return verify_partial_at(pub_poly.eval(idx), msg, partial)


def recover(pub_poly: PubPoly, msg: bytes, partials: list[bytes], threshold: int,
            n: int, verified: bool = False) -> bytes:
    """Lagrange-recover the full signature from >= t partials
    (reference: `key.Scheme.Recover` at `chain/beacon/chain.go:160`).

    When `verified` is False each partial is checked first (invalid ones are
    skipped), mirroring the safe default of the reference.
    """
    points: dict[int, tuple] = {}
    for partial in partials:
        try:
            idx = index_of(partial)
            sigma = C.g2_from_bytes(sig_of(partial))
        except ValueError:
            continue
        if idx >= n:
            continue
        if not verified and not verify_partial(pub_poly, msg, partial):
            continue
        points[idx] = sigma
        if len(points) >= threshold:
            break
    if len(points) < threshold:
        raise ValueError(f"not enough valid partials: {len(points)}/{threshold}")
    full = recover_commit_g2(points, threshold)
    return C.g2_to_bytes(full)


def verify_recovered(pub_key, msg: bytes, sig: bytes) -> bool:
    """Verify the recovered full signature against the distributed public key
    (reference: `key.Scheme.VerifyRecovered` at `chain/verify.go:44`)."""
    from .sign import bls_verify
    return bls_verify(pub_key, msg, sig)
