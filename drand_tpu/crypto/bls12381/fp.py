"""Field towers for BLS12-381: Fp, Fp2, Fp6, Fp12 (pure-Python golden model).

This is the oracle implementation the TPU (JAX/Pallas) kernels are validated
against.  Representation is deliberately plain for speed and unambiguity:

  Fp   : python int in [0, P)
  Fp2  : (c0, c1)           meaning c0 + c1*u,        u^2 = -1
  Fp6  : (a0, a1, a2)       each Fp2, meaning a0 + a1*v + a2*v^2,  v^3 = xi
  Fp12 : (b0, b1)           each Fp6, meaning b0 + b1*w,           w^2 = v

with xi = 1 + u (the standard BLS12-381 sextic-twist non-residue).

Counterpart of the reference's field tower in kilic/bls12-381 (dep of
`key/curve.go:24`); rebuilt from the mathematical definition, not ported.
"""

from .constants import P

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------

def fp_add(a, b):
    c = a + b
    return c - P if c >= P else c


def fp_sub(a, b):
    c = a - b
    return c + P if c < 0 else c


def fp_neg(a):
    return P - a if a else 0


def fp_mul(a, b):
    return a * b % P


def fp_sqr(a):
    return a * a % P


def fp_inv(a):
    if a == 0:
        raise ZeroDivisionError("fp inverse of 0")
    return pow(a, P - 2, P)


def fp_pow(a, e):
    return pow(a, e, P)


def fp_is_square(a):
    """Euler criterion; 0 counts as square."""
    return a == 0 or pow(a, (P - 1) // 2, P) == 1


def fp_sqrt(a):
    """Square root in Fp (p = 3 mod 4).  Returns None if not a square."""
    if a == 0:
        return 0
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a else None


def fp_sgn0(a):
    return a & 1


# ---------------------------------------------------------------------------
# Fp2 = Fp[u] / (u^2 + 1)
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
XI = (1, 1)  # the sextic non-residue 1 + u


def fp2(c0, c1=0):
    return (c0 % P, c1 % P)


def fp2_add(a, b):
    return (fp_add(a[0], b[0]), fp_add(a[1], b[1]))


def fp2_sub(a, b):
    return (fp_sub(a[0], b[0]), fp_sub(a[1], b[1]))


def fp2_neg(a):
    return (fp_neg(a[0]), fp_neg(a[1]))


def fp2_conj(a):
    return (a[0], fp_neg(a[1]))


def fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # (a0+a1)(b0+b1) - t0 - t1 = a0*b1 + a1*b0
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def fp2_sqr(a):
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_mul_fp(a, s):
    return (a[0] * s % P, a[1] * s % P)


def fp2_mul_xi(a):
    """Multiply by xi = 1 + u:  (c0 - c1) + (c0 + c1) u."""
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def fp2_inv(a):
    a0, a1 = a
    norm = (a0 * a0 + a1 * a1) % P
    ninv = fp_inv(norm)
    return (a0 * ninv % P, (P - a1) * ninv % P if a1 else 0)


def fp2_pow(a, e):
    result = FP2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_norm(a):
    """Norm map Fp2 -> Fp: a0^2 + a1^2."""
    return (a[0] * a[0] + a[1] * a[1]) % P


def fp2_is_square(a):
    """x in Fp2 is a square iff Norm(x) is a square in Fp."""
    return fp_is_square(fp2_norm(a))


def fp2_sqrt(a):
    """Square root in Fp2 via the complex method (p = 3 mod 4).

    Returns None when `a` is not a square.
    """
    if a == FP2_ZERO:
        return FP2_ZERO
    a0, a1 = a
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # a0 is a non-square in Fp, so sqrt is purely imaginary:
        # (t*u)^2 = -t^2 = a0  =>  t = sqrt(-a0)
        t = fp_sqrt(fp_neg(a0))
        if t is None:
            return None
        return (0, t)
    # alpha = norm(a) must be square in Fp
    alpha = fp_sqrt(fp2_norm(a))
    if alpha is None:
        return None
    # delta = (a0 + alpha)/2; if not square, use (a0 - alpha)/2
    inv2 = (P + 1) // 2
    delta = (a0 + alpha) * inv2 % P
    x0 = fp_sqrt(delta)
    if x0 is None:
        delta = (a0 - alpha) * inv2 % P
        x0 = fp_sqrt(delta)
        if x0 is None:
            return None
    x1 = a1 * inv2 % P * fp_inv(x0) % P
    cand = (x0, x1)
    return cand if fp2_sqr(cand) == a else None


def fp2_sgn0(a):
    """RFC 9380 sgn0 for m=2."""
    s0 = a[0] & 1
    z0 = a[0] == 0
    s1 = a[1] & 1
    return s0 | (z0 & s1)


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v] / (v^3 - xi)
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(t0, fp2_mul_xi(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)), fp2_mul_xi(t2))
    c2 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    """Multiply by v: (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_mul_fp2(a, s):
    return (fp2_mul(a[0], s), fp2_mul(a[1], s), fp2_mul(a[2], s))


def fp6_inv(a):
    a0, a1, a2 = a
    t0 = fp2_sqr(a0)
    t1 = fp2_sqr(a1)
    t2 = fp2_sqr(a2)
    t3 = fp2_mul(a0, a1)
    t4 = fp2_mul(a0, a2)
    t5 = fp2_mul(a1, a2)
    c0 = fp2_sub(t0, fp2_mul_xi(t5))
    c1 = fp2_sub(fp2_mul_xi(t2), t3)
    c2 = fp2_sub(t1, t4)
    # det = a0*c0 + xi*(a2*c1 + a1*c2)
    det = fp2_add(fp2_mul(a0, c0), fp2_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))))
    det_inv = fp2_inv(det)
    return (fp2_mul(c0, det_inv), fp2_mul(c1, det_inv), fp2_mul(c2, det_inv))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w] / (w^2 - v)
# ---------------------------------------------------------------------------

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_neg(a):
    return (fp6_neg(a[0]), fp6_neg(a[1]))


def fp12_conj(a):
    """Conjugate = Frobenius^6: a0 - a1*w."""
    return (a[0], fp6_neg(a[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    a0, a1 = a
    t = fp6_mul(a0, a1)
    c0 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1))), t), fp6_mul_by_v(t))
    c1 = fp6_add(t, t)
    return (c0, c1)


def fp12_inv(a):
    a0, a1 = a
    # 1/(a0 + a1 w) = (a0 - a1 w) / (a0^2 - v a1^2)
    det = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    det_inv = fp6_inv(det)
    return (fp6_mul(a0, det_inv), fp6_neg(fp6_mul(a1, det_inv)))


def fp12_pow(a, e):
    if e < 0:
        return fp12_pow(fp12_conj(a), -e)  # valid only for unitary elements
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


# ---------------------------------------------------------------------------
# Frobenius maps (coefficients computed at import, not hard-coded)
# ---------------------------------------------------------------------------

def _compute_frob_coeffs():
    """gamma_i = xi^(i*(p-1)/6) for i = 1..5, as Fp2 elements."""
    e = (P - 1) // 6
    g1 = fp2_pow(XI, e)
    gs = [FP2_ONE, g1]
    for _ in range(4):
        gs.append(fp2_mul(gs[-1], g1))
    return gs  # gs[i] = xi^(i(p-1)/6)


_FROB_GAMMA = _compute_frob_coeffs()


def fp2_frob(a):
    """a^p in Fp2 = conjugate (since p = 3 mod 4)."""
    return fp2_conj(a)


def fp6_frob(a):
    """(a0 + a1 v + a2 v^2)^p = a0^p + a1^p gamma2 v + a2^p gamma4 v^2."""
    return (
        fp2_conj(a[0]),
        fp2_mul(fp2_conj(a[1]), _FROB_GAMMA[2]),
        fp2_mul(fp2_conj(a[2]), _FROB_GAMMA[4]),
    )


def fp12_frob(a):
    """(b0 + b1 w)^p = b0^p + (b1^p * gamma1-spread) w."""
    a0, a1 = a
    b0 = fp6_frob(a0)
    b1 = fp6_frob(a1)
    # w^p = w * w^(p-1) = w * xi^((p-1)/6)
    b1 = fp6_mul_fp2(b1, _FROB_GAMMA[1])
    return (b0, b1)


def fp12_frob_n(a, n):
    for _ in range(n):
        a = fp12_frob(a)
    return a
