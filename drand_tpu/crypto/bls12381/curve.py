"""G1/G2 group operations for BLS12-381 (pure-Python golden model).

Points are Jacobian triples (X, Y, Z): affine (X/Z^2, Y/Z^3); Z == 0 is the
point at infinity.  G1 coordinates are Fp ints, G2 coordinates are Fp2 tuples.

Counterpart of the reference's kyber `Point` interface on bls12-381
(`key/curve.go:26-33`: keys on G1 48B, sigs on G2 96B); rebuilt from curve
math, not ported.  Serialization follows the ZCash BLS12-381 compressed
encoding used by drand's wire format.
"""

from . import fp as F
from .constants import (B_G1, B_G2, G1_GEN_X, G1_GEN_Y, G2_GEN_X, G2_GEN_Y,
                        H1, H2, P, R, X)

# ---------------------------------------------------------------------------
# Generic Jacobian arithmetic parameterized by the field (works for Fp / Fp2
# and, for the untwist self-check, Fp12).
# ---------------------------------------------------------------------------

class _Ops:
    """Field operation bundle so one set of curve formulas serves all fields."""

    def __init__(self, add, sub, neg, mul, sqr, inv, zero, one, eq=None):
        self.add, self.sub, self.neg, self.mul, self.sqr, self.inv = add, sub, neg, mul, sqr, inv
        self.zero, self.one = zero, one
        self.eq = eq or (lambda a, b: a == b)


FP_OPS = _Ops(F.fp_add, F.fp_sub, F.fp_neg, F.fp_mul, F.fp_sqr, F.fp_inv, 0, 1)
FP2_OPS = _Ops(F.fp2_add, F.fp2_sub, F.fp2_neg, F.fp2_mul, F.fp2_sqr, F.fp2_inv,
               F.FP2_ZERO, F.FP2_ONE)
FP12_OPS = _Ops(F.fp12_add, F.fp12_sub, F.fp12_neg, F.fp12_mul, F.fp12_sqr,
                F.fp12_inv, F.FP12_ZERO, F.FP12_ONE)


def point_is_inf(pt, ops):
    return ops.eq(pt[2], ops.zero)


def point_double(pt, ops):
    """Jacobian doubling for y^2 = x^3 + b (a = 0)."""
    x, y, z = pt
    if ops.eq(z, ops.zero):
        return pt
    a = ops.sqr(x)
    b = ops.sqr(y)
    c = ops.sqr(b)
    d = ops.sub(ops.sqr(ops.add(x, b)), ops.add(a, c))
    d = ops.add(d, d)
    e = ops.add(ops.add(a, a), a)
    f = ops.sqr(e)
    x3 = ops.sub(f, ops.add(d, d))
    c8 = ops.add(c, c)
    c8 = ops.add(c8, c8)
    c8 = ops.add(c8, c8)
    y3 = ops.sub(ops.mul(e, ops.sub(d, x3)), c8)
    yz = ops.mul(y, z)
    z3 = ops.add(yz, yz)
    return (x3, y3, z3)


def point_add(p1, p2, ops):
    """General Jacobian addition."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    if ops.eq(z1, ops.zero):
        return p2
    if ops.eq(z2, ops.zero):
        return p1
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    u1 = ops.mul(x1, z2z2)
    u2 = ops.mul(x2, z1z1)
    s1 = ops.mul(ops.mul(y1, z2), z2z2)
    s2 = ops.mul(ops.mul(y2, z1), z1z1)
    if ops.eq(u1, u2):
        if ops.eq(s1, s2):
            return point_double(p1, ops)
        return (ops.one, ops.one, ops.zero)  # P + (-P) = inf
    h = ops.sub(u2, u1)
    i = ops.sqr(ops.add(h, h))
    j = ops.mul(h, i)
    rr = ops.sub(s2, s1)
    rr = ops.add(rr, rr)
    v = ops.mul(u1, i)
    x3 = ops.sub(ops.sub(ops.sqr(rr), j), ops.add(v, v))
    s1j = ops.mul(s1, j)
    y3 = ops.sub(ops.mul(rr, ops.sub(v, x3)), ops.add(s1j, s1j))
    z3 = ops.mul(ops.sub(ops.sqr(ops.add(z1, z2)), ops.add(z1z1, z2z2)), h)
    return (x3, y3, z3)


def point_neg(pt, ops):
    return (pt[0], ops.neg(pt[1]), pt[2])


def point_mul(pt, k, ops):
    """Double-and-add scalar multiplication (golden model; not constant-time)."""
    if k < 0:
        return point_mul(point_neg(pt, ops), -k, ops)
    acc = (ops.one, ops.one, ops.zero)
    add_pt = pt
    while k > 0:
        if k & 1:
            acc = point_add(acc, add_pt, ops)
        add_pt = point_double(add_pt, ops)
        k >>= 1
    return acc


def point_to_affine(pt, ops):
    """Return (x, y) or None for infinity."""
    x, y, z = pt
    if ops.eq(z, ops.zero):
        return None
    zi = ops.inv(z)
    zi2 = ops.sqr(zi)
    return (ops.mul(x, zi2), ops.mul(y, ops.mul(zi, zi2)))


def point_eq(p1, p2, ops):
    """Projective equality."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    i1 = ops.eq(z1, ops.zero)
    i2 = ops.eq(z2, ops.zero)
    if i1 or i2:
        return i1 and i2
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    if not ops.eq(ops.mul(x1, z2z2), ops.mul(x2, z1z1)):
        return False
    return ops.eq(ops.mul(ops.mul(y1, z2), z2z2), ops.mul(ops.mul(y2, z1), z1z1))


# ---------------------------------------------------------------------------
# G1
# ---------------------------------------------------------------------------

G1_GEN = (G1_GEN_X, G1_GEN_Y, 1)
G1_INF = (1, 1, 0)


def g1_on_curve(pt):
    aff = point_to_affine(pt, FP_OPS)
    if aff is None:
        return True
    x, y = aff
    return F.fp_sqr(y) == F.fp_add(F.fp_mul(F.fp_sqr(x), x), B_G1)


def g1_double(pt):
    return point_double(pt, FP_OPS)


def g1_add(p1, p2):
    return point_add(p1, p2, FP_OPS)


def g1_neg(pt):
    return point_neg(pt, FP_OPS)


def g1_mul(pt, k):
    if pt is G1_GEN:
        # ceremony hot path: every schnorr verify, ECIES seal, and
        # polynomial commit multiplies the generator — route those
        # through the fixed-base window table (~6x over double-and-add)
        return g1_mul_gen(k)
    return point_mul(pt, k % R, FP_OPS)


def g1_mul_raw(pt, k):
    """Scalar mul WITHOUT reducing k mod r (for cofactor clearing)."""
    return point_mul(pt, k, FP_OPS)


# --- fixed-base generator multiplication -----------------------------------
# Window-4 precomputed table over G1_GEN: 64 windows x 15 non-zero digits.
# Built lazily on first use (~1k additions, a few ms) and amortized across
# the O(n^2) generator multiplications of a DKG ceremony.  The result is
# the same group element as point_mul(G1_GEN, k) — Jacobian coordinates may
# differ, but every consumer compares via g1_eq / affine / compressed bytes.

_GEN_WINDOW = 4
_GEN_TABLE: list[list[tuple]] | None = None


def _build_gen_table() -> list[list[tuple]]:
    windows = (R.bit_length() + _GEN_WINDOW - 1) // _GEN_WINDOW
    table = []
    base = G1_GEN
    for _ in range(windows):
        row = [G1_INF]
        acc = G1_INF
        for _ in range((1 << _GEN_WINDOW) - 1):
            acc = point_add(acc, base, FP_OPS)
            row.append(acc)
        table.append(row)
        # base <- 2^w * base for the next window
        for _ in range(_GEN_WINDOW):
            base = point_double(base, FP_OPS)
    return table


def g1_mul_gen(k):
    """k * G1_GEN via the fixed-base window table (canonicalizes k mod r)."""
    global _GEN_TABLE
    if _GEN_TABLE is None:
        _GEN_TABLE = _build_gen_table()
    k %= R
    acc = G1_INF
    w = 0
    while k:
        digit = k & ((1 << _GEN_WINDOW) - 1)
        if digit:
            acc = point_add(acc, _GEN_TABLE[w][digit], FP_OPS)
        k >>= _GEN_WINDOW
        w += 1
    return acc


def g1_affine(pt):
    return point_to_affine(pt, FP_OPS)


def g1_eq(p1, p2):
    return point_eq(p1, p2, FP_OPS)


def g1_in_subgroup(pt):
    if not g1_on_curve(pt):
        return False
    return point_is_inf(point_mul(pt, R, FP_OPS), FP_OPS)


def g1_clear_cofactor(pt):
    """RFC 9380 8.8.1 effective cofactor h_eff = 1 - x (NOT the full h1).

    Both land in G1, but only [1-x]P matches the standard suite's output
    point, so this must be (1-x) for wire interop with drand's kilic dep.
    """
    return g1_mul_raw(pt, 1 - X)


# ---------------------------------------------------------------------------
# G2
# ---------------------------------------------------------------------------

G2_GEN = (G2_GEN_X, G2_GEN_Y, F.FP2_ONE)
G2_INF = (F.FP2_ONE, F.FP2_ONE, F.FP2_ZERO)


def g2_on_curve(pt):
    aff = point_to_affine(pt, FP2_OPS)
    if aff is None:
        return True
    x, y = aff
    return F.fp2_sqr(y) == F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), B_G2)


def g2_double(pt):
    return point_double(pt, FP2_OPS)


def g2_add(p1, p2):
    return point_add(p1, p2, FP2_OPS)


def g2_neg(pt):
    return point_neg(pt, FP2_OPS)


def g2_mul(pt, k):
    return point_mul(pt, k % R, FP2_OPS)


def g2_mul_raw(pt, k):
    return point_mul(pt, k, FP2_OPS)


def g2_affine(pt):
    return point_to_affine(pt, FP2_OPS)


def g2_eq(p1, p2):
    return point_eq(p1, p2, FP2_OPS)


# --- untwist selection (runtime-verified, not memorized) -------------------
# The sextic twist satisfies E'(Fp2) -> E(Fp12) via (x, y) -> (x * w^a, y * w^b)
# for one of a small set of exponent conventions.  We pick the one that maps
# the G2 generator onto E: y^2 = x^3 + 4 over Fp12, at import time.

def _fp12_from_fp2(a):
    return ((a, F.FP2_ZERO, F.FP2_ZERO), F.FP6_ZERO)


def _select_untwist():
    """Find the curve isomorphism E' -> E: (x, y) -> (c^2 x, c^3 y).

    It needs c^6 * (4*xi) = 4, i.e. c^6 = xi^{-1}; since w^6 = xi, c = w^{-1}
    works.  We still *verify* by mapping the G2 generator onto
    y^2 = x^3 + 4 over Fp12 instead of trusting the algebra.
    """
    w = (F.FP6_ZERO, F.FP6_ONE)
    b12 = _fp12_from_fp2((4, 0))  # b = 4 in Fp12
    for c in (F.fp12_inv(w), w):
        wx = F.fp12_sqr(c)
        wy = F.fp12_mul(wx, c)
        ux = F.fp12_mul(_fp12_from_fp2(G2_GEN_X), wx)
        uy = F.fp12_mul(_fp12_from_fp2(G2_GEN_Y), wy)
        lhs = F.fp12_sqr(uy)
        rhs = F.fp12_add(F.fp12_mul(F.fp12_sqr(ux), ux), b12)
        if lhs == rhs:
            return wx, wy
    raise AssertionError("no valid untwist convention found")


_UNTWIST_WX, _UNTWIST_WY = _select_untwist()


def g2_untwist(pt):
    """Map an affine G2 point (Fp2 coords) to E(Fp12)."""
    aff = point_to_affine(pt, FP2_OPS)
    if aff is None:
        return None
    x, y = aff
    return (F.fp12_mul(_fp12_from_fp2(x), _UNTWIST_WX),
            F.fp12_mul(_fp12_from_fp2(y), _UNTWIST_WY))


# --- psi endomorphism ------------------------------------------------------
# psi = twist . Frobenius . untwist.  We derive the two Fp2 constants from
# that definition once at import (rather than hard-coding), then apply them
# cheaply: psi(x, y) = (conj(x) * PSI_X, conj(y) * PSI_Y).

def _derive_psi_constants():
    # untwist generator, frobenius, re-twist
    x12, y12 = g2_untwist(G2_GEN)
    fx = F.fp12_frob(x12)
    fy = F.fp12_frob(y12)
    # twist back: multiply by inverse w powers
    tx = F.fp12_mul(fx, F.fp12_inv(_UNTWIST_WX))
    ty = F.fp12_mul(fy, F.fp12_inv(_UNTWIST_WY))
    # results must be "scalar" Fp2 elements embedded in Fp12
    def _extract(a):
        c = a[0][0]
        assert a[1] == F.FP6_ZERO and a[0][1] == F.FP2_ZERO and a[0][2] == F.FP2_ZERO, \
            "psi derivation did not land in Fp2"
        return c
    px = _extract(tx)
    py = _extract(ty)
    # psi(gen) = (conj(gx)*cx, conj(gy)*cy): solve for cx, cy
    cx = F.fp2_mul(px, F.fp2_inv(F.fp2_conj(G2_GEN_X)))
    cy = F.fp2_mul(py, F.fp2_inv(F.fp2_conj(G2_GEN_Y)))
    return cx, cy


PSI_X, PSI_Y = _derive_psi_constants()


def g2_psi(pt):
    """The untwist-Frobenius-twist endomorphism on Jacobian G2 points."""
    x, y, z = pt
    # In Jacobian coords: x' = conj(x)*PSI_X, y' = conj(y)*PSI_Y, z' = conj(z)
    return (F.fp2_mul(F.fp2_conj(x), PSI_X),
            F.fp2_mul(F.fp2_conj(y), PSI_Y),
            F.fp2_conj(z))


def g2_in_subgroup(pt):
    """Fast subgroup check: psi(Q) == [x]Q  (Bowe's criterion for BLS12-381)."""
    if not g2_on_curve(pt):
        return False
    if point_is_inf(pt, FP2_OPS):
        return True
    return point_eq(g2_psi(pt), g2_mul_raw(pt, X), FP2_OPS)


def g2_clear_cofactor(pt):
    """Budroni-Pintore efficient cofactor clearing:
    h_eff(Q) = [x^2 - x - 1]Q + [x - 1]psi(Q) + psi^2([2]Q).
    Verified against plain [h2]Q multiplication in tests."""
    xq = g2_mul_raw(pt, X)          # [x]Q  (X negative handled by point_mul)
    x2q = g2_mul_raw(xq, X)         # [x^2]Q
    t = point_add(x2q, point_neg(xq, FP2_OPS), FP2_OPS)   # [x^2 - x]Q
    t = point_add(t, point_neg(pt, FP2_OPS), FP2_OPS)     # [x^2 - x - 1]Q
    p1 = point_add(xq, point_neg(pt, FP2_OPS), FP2_OPS)   # [x-1]Q
    p1 = g2_psi(p1)
    p2 = g2_psi(g2_psi(point_double(pt, FP2_OPS)))        # psi^2(2Q)
    return point_add(point_add(t, p1, FP2_OPS), p2, FP2_OPS)


# ---------------------------------------------------------------------------
# Serialization (ZCash compressed format, drand wire compatible)
# ---------------------------------------------------------------------------

_COMP_FLAG = 0x80
_INF_FLAG = 0x40
_SIGN_FLAG = 0x20
_HALF_P = (P - 1) // 2


def g1_to_bytes(pt):
    """48-byte compressed G1."""
    aff = g1_affine(pt)
    if aff is None:
        out = bytearray(48)
        out[0] = _COMP_FLAG | _INF_FLAG
        return bytes(out)
    x, y = aff
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= _COMP_FLAG
    if y > _HALF_P:
        out[0] |= _SIGN_FLAG
    return bytes(out)


def g1_from_bytes(data):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMP_FLAG:
        raise ValueError("only compressed encoding supported")
    if flags & _INF_FLAG:
        return G1_INF
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y2 = F.fp_add(F.fp_mul(F.fp_sqr(x), x), B_G1)
    y = F.fp_sqrt(y2)
    if y is None:
        raise ValueError("point not on curve")
    if bool(flags & _SIGN_FLAG) != (y > _HALF_P):
        y = F.fp_neg(y)
    pt = (x, y, 1)
    return pt


def _fp2_lex_gt_half(a):
    """ZCash sign rule for Fp2: lexicographic with c1 most significant."""
    c0, c1 = a
    if c1 != 0:
        return c1 > _HALF_P
    return c0 > _HALF_P


def g2_to_bytes(pt):
    """96-byte compressed G2 (c1 first, per ZCash convention)."""
    aff = g2_affine(pt)
    if aff is None:
        out = bytearray(96)
        out[0] = _COMP_FLAG | _INF_FLAG
        return bytes(out)
    (x0, x1), y = aff
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= _COMP_FLAG
    if _fp2_lex_gt_half(y):
        out[0] |= _SIGN_FLAG
    return bytes(out)


def g2_from_bytes(data):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMP_FLAG:
        raise ValueError("only compressed encoding supported")
    if flags & _INF_FLAG:
        return G2_INF
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("x out of range")
    x = (x0, x1)
    y2 = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), B_G2)
    y = F.fp2_sqrt(y2)
    if y is None:
        raise ValueError("point not on curve")
    if _fp2_lex_gt_half(y) != bool(flags & _SIGN_FLAG):
        y = F.fp2_neg(y)
    return (x[0:2], y, F.FP2_ONE)
