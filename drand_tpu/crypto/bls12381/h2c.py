"""Hash-to-curve for BLS12-381 G1/G2 (RFC 9380 structure, SVDW map).

Uses expand_message_xmd(SHA-256) + hash_to_field + the Shallue-van de
Woestijne map + cofactor clearing.  The SVDW map is used instead of the
SSWU+isogeny suite because every SVDW constant is derivable offline from the
curve equation alone (this build has no network access for the 11-isogeny
coefficient tables); the difference is only *which* RFC 9380 suite this is —
outputs are uniformly distributed subgroup points either way.  Wire-compat
with drand's SSWU suite (kilic/bls12-381's hash-to-curve, used via
`chain/verify.go:38-45`) is tracked as a follow-up.

All SVDW constants (Z, c1..c4) are computed at import from the curve
parameters, per the RFC's find_z_svdw procedure.
"""

import hashlib

from . import curve as C
from . import fp as F
from .constants import DST_G1, DST_G2, P

_L = 64  # bytes per field element draw (ceil((381 + 128)/8))


# ---------------------------------------------------------------------------
# expand_message_xmd (SHA-256)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    out = b""
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out += bi
    for i in range(2, ell + 1):
        bi = hashlib.sha256(bytes(a ^ b for a, b in zip(b0, bi)) + bytes([i]) + dst_prime).digest()
        out += bi
    return out[:len_in_bytes]


def hash_to_field_fp(msg: bytes, dst: bytes, count: int):
    data = expand_message_xmd(msg, dst, count * _L)
    return [int.from_bytes(data[i * _L:(i + 1) * _L], "big") % P for i in range(count)]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int):
    data = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        c0 = int.from_bytes(data[(2 * i) * _L:(2 * i + 1) * _L], "big") % P
        c1 = int.from_bytes(data[(2 * i + 1) * _L:(2 * i + 2) * _L], "big") % P
        out.append((c0, c1))
    return out


# ---------------------------------------------------------------------------
# SVDW map, generic over the field
# ---------------------------------------------------------------------------

class _SvdwField:
    """Field ops + derived SVDW constants for y^2 = x^3 + B (A = 0)."""

    def __init__(self, name, b, add, sub, neg, mul, sqr, inv, is_square, sqrt,
                 sgn0, from_int, zero, one):
        self.name = name
        self.b = b
        self.add, self.sub, self.neg, self.mul, self.sqr, self.inv = add, sub, neg, mul, sqr, inv
        self.is_square, self.sqrt, self.sgn0, self.from_int = is_square, sqrt, sgn0, from_int
        self.zero, self.one = zero, one
        self._derive_constants()

    def g(self, x):
        return self.add(self.mul(self.sqr(x), x), self.b)

    def inv0(self, x):
        return self.zero if x == self.zero else self.inv(x)

    def _derive_constants(self):
        # find_z_svdw (RFC 9380 appendix H.1), A = 0
        def cond(zi):
            z = self.from_int(zi)
            gz = self.g(z)
            if gz == self.zero:
                return None
            t = self.mul(self.from_int(3), self.sqr(z))  # 3Z^2 + 4A, A=0
            if t == self.zero:
                return None
            # -(3Z^2)/(4 g(Z)) must be a nonzero square
            ratio = self.neg(self.mul(t, self.inv(self.mul(self.from_int(4), gz))))
            if ratio == self.zero or not self.is_square(ratio):
                return None
            # at least one of g(Z), g(-Z/2) square
            half = self.inv(self.from_int(2))
            gz2 = self.g(self.neg(self.mul(z, half)))
            if not (self.is_square(gz) or self.is_square(gz2)):
                return None
            return z

        z = None
        for cand in [1, -1, 2, -2, 3, -3, 4, -4, 5, -5, 6, -6, 7, -7, 8, -8]:
            z = cond(cand)
            if z is not None:
                break
        assert z is not None, f"no SVDW Z found for {self.name}"
        self.Z = z
        gz = self.g(z)
        self.c1 = gz
        half = self.inv(self.from_int(2))
        self.c2 = self.neg(self.mul(z, half))
        t = self.mul(self.from_int(3), self.sqr(z))           # 3Z^2
        c3 = self.sqrt(self.neg(self.mul(gz, t)))
        assert c3 is not None, "SVDW c3 not a square — Z selection broken"
        if self.sgn0(c3) == 1:
            c3 = self.neg(c3)
        self.c3 = c3
        self.c4 = self.neg(self.mul(self.mul(self.from_int(4), gz), self.inv(t)))

    def map_to_curve(self, u):
        tv1 = self.mul(self.sqr(u), self.c1)
        tv2 = self.add(self.one, tv1)
        tv1 = self.sub(self.one, tv1)
        tv3 = self.inv0(self.mul(tv1, tv2))
        tv4 = self.mul(self.mul(self.mul(u, tv1), tv3), self.c3)
        x1 = self.sub(self.c2, tv4)
        gx1 = self.g(x1)
        e1 = self.is_square(gx1)
        x2 = self.add(self.c2, tv4)
        gx2 = self.g(x2)
        e2 = self.is_square(gx2) and not e1
        x3 = self.add(self.mul(self.sqr(self.mul(self.sqr(tv2), tv3)), self.c4), self.Z)
        x = x1 if e1 else (x2 if e2 else x3)
        gx = self.g(x)
        y = self.sqrt(gx)
        assert y is not None, "SVDW: no square g(x) among candidates"
        if self.sgn0(u) != self.sgn0(y):
            y = self.neg(y)
        return (x, y)


_FP_SVDW = _SvdwField(
    "Fp", 4,
    F.fp_add, F.fp_sub, F.fp_neg, F.fp_mul, F.fp_sqr, F.fp_inv,
    F.fp_is_square, F.fp_sqrt, F.fp_sgn0, lambda i: i % P, 0, 1,
)

_FP2_SVDW = _SvdwField(
    "Fp2", (4, 4),
    F.fp2_add, F.fp2_sub, F.fp2_neg, F.fp2_mul, F.fp2_sqr, F.fp2_inv,
    F.fp2_is_square, F.fp2_sqrt, F.fp2_sgn0, lambda i: (i % P, 0),
    F.FP2_ZERO, F.FP2_ONE,
)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """Hash arbitrary bytes to a G2 subgroup point (Jacobian)."""
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    q0 = _FP2_SVDW.map_to_curve(u0)
    q1 = _FP2_SVDW.map_to_curve(u1)
    r = C.point_add((q0[0], q0[1], F.FP2_ONE), (q1[0], q1[1], F.FP2_ONE), C.FP2_OPS)
    return C.g2_clear_cofactor(r)


def hash_to_g1(msg: bytes, dst: bytes = DST_G1):
    """Hash arbitrary bytes to a G1 subgroup point (Jacobian)."""
    u0, u1 = hash_to_field_fp(msg, dst, 2)
    q0 = _FP_SVDW.map_to_curve(u0)
    q1 = _FP_SVDW.map_to_curve(u1)
    r = C.point_add((q0[0], q0[1], 1), (q1[0], q1[1], 1), C.FP_OPS)
    return C.g1_clear_cofactor(r)
