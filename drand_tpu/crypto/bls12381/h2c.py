"""Hash-to-curve for BLS12-381 G1/G2: RFC 9380 SSWU suites (golden model).

Implements drand's exact wire suites:

  G2: BLS12381G2_XMD:SHA-256_SSWU_RO_  with DST
      BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_
  G1: BLS12381G1_XMD:SHA-256_SSWU_RO_  with DST
      BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_

matching the kilic/bls12-381 hash-to-curve drand calls through
`chain/verify.go:38-45` / `key/curve.go:24-43`.

The SSWU map targets an isogenous curve E'; the isogeny back to E was
RE-DERIVED offline with Velu's formulas (tools/derive_sswu_g2.py,
tools/derive_sswu_g1.py) because this build has zero network egress.  For G2
the derived rational map reproduces RFC 9380 Appendix E.3
coefficient-for-coefficient (pinned in tests/test_h2c_sswu.py); the G2
isogeny is applied in the compact Velu form

    X(x)   = s^2 * (x + v/(x-x0) + w/(x-x0)^2)
    Y(x,y) = s^3 * y * (1 - v/(x-x0)^2 - 2w/(x-x0)^3)

which is algebraically identical to the appendix's coefficient tables.
Points are mapped and ADDED on E' (an isogeny is a group homomorphism), so
the isogeny is evaluated once per hash, then the cofactor is cleared on E.
"""

import hashlib

from . import curve as C
from . import fp as F
from .constants import (DST_G1, DST_G2, ISO1_X_NUM, ISO1_X_DEN, ISO1_Y_NUM,
                        ISO1_Y_DEN, ISO3_S, ISO3_V, ISO3_W, ISO3_X0, P,
                        SSWU_G1_A, SSWU_G1_B, SSWU_G1_Z, SSWU_G2_A, SSWU_G2_B,
                        SSWU_G2_Z)

_L = 64  # bytes per field element draw (ceil((381 + 128)/8))


# ---------------------------------------------------------------------------
# expand_message_xmd (SHA-256)  -- RFC 9380 section 5.3.1
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    out = b""
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out += bi
    for i in range(2, ell + 1):
        bi = hashlib.sha256(bytes(a ^ b for a, b in zip(b0, bi)) + bytes([i]) + dst_prime).digest()
        out += bi
    return out[:len_in_bytes]


def hash_to_field_fp(msg: bytes, dst: bytes, count: int):
    data = expand_message_xmd(msg, dst, count * _L)
    return [int.from_bytes(data[i * _L:(i + 1) * _L], "big") % P for i in range(count)]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int):
    data = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        c0 = int.from_bytes(data[(2 * i) * _L:(2 * i + 1) * _L], "big") % P
        c1 = int.from_bytes(data[(2 * i + 1) * _L:(2 * i + 2) * _L], "big") % P
        out.append((c0, c1))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU map (RFC 9380 6.6.2) on the isogenous curves
# ---------------------------------------------------------------------------

def _sswu_fp2(u):
    """map_to_curve_simple_swu on E2': y^2 = x^3 + A'x + B' over Fp2."""
    a, b, z = SSWU_G2_A, SSWU_G2_B, SSWU_G2_Z
    u2 = F.fp2_sqr(u)
    zu2 = F.fp2_mul(z, u2)
    tv1 = F.fp2_add(F.fp2_sqr(zu2), zu2)            # Z^2 u^4 + Z u^2
    if tv1 == F.FP2_ZERO:
        x1 = F.fp2_mul(b, F.fp2_inv(F.fp2_mul(z, a)))
    else:
        x1 = F.fp2_mul(F.fp2_neg(F.fp2_mul(b, F.fp2_inv(a))),
                       F.fp2_add(F.FP2_ONE, F.fp2_inv(tv1)))
    gx1 = F.fp2_add(F.fp2_add(F.fp2_mul(F.fp2_sqr(x1), x1), F.fp2_mul(a, x1)), b)
    y1 = F.fp2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x = F.fp2_mul(zu2, x1)
        gx2 = F.fp2_add(F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), F.fp2_mul(a, x)), b)
        y = F.fp2_sqrt(gx2)
        assert y is not None, "SSWU: g(x2) must be square when g(x1) is not"
    if F.fp2_sgn0(u) != F.fp2_sgn0(y):
        y = F.fp2_neg(y)
    return (x, y)


def _sswu_fp(u):
    """map_to_curve_simple_swu on E1': y^2 = x^3 + A'x + B' over Fp."""
    a, b, z = SSWU_G1_A, SSWU_G1_B, SSWU_G1_Z
    u2 = F.fp_sqr(u)
    zu2 = F.fp_mul(z, u2)
    tv1 = F.fp_add(F.fp_sqr(zu2), zu2)
    if tv1 == 0:
        x1 = F.fp_mul(b, F.fp_inv(F.fp_mul(z, a)))
    else:
        x1 = F.fp_mul(F.fp_neg(F.fp_mul(b, F.fp_inv(a))),
                      F.fp_add(1, F.fp_inv(tv1)))
    gx1 = F.fp_add(F.fp_add(F.fp_mul(F.fp_sqr(x1), x1), F.fp_mul(a, x1)), b)
    y1 = F.fp_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x = F.fp_mul(zu2, x1)
        gx2 = F.fp_add(F.fp_add(F.fp_mul(F.fp_sqr(x), x), F.fp_mul(a, x)), b)
        y = F.fp_sqrt(gx2)
        assert y is not None, "SSWU: g(x2) must be square when g(x1) is not"
    if F.fp_sgn0(u) != F.fp_sgn0(y):
        y = F.fp_neg(y)
    return (x, y)


# ---------------------------------------------------------------------------
# Affine addition on a general short-Weierstrass curve (the isogenous curves
# have a != 0, so the production a=0 Jacobian formulas don't apply)
# ---------------------------------------------------------------------------

def _aff_add_fp2(p1, p2, a):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    (x1, y1), (x2, y2) = p1, p2
    if x1 == x2:
        if F.fp2_add(y1, y2) == F.FP2_ZERO:
            return None
        lam = F.fp2_mul(F.fp2_add(F.fp2_mul_fp(F.fp2_sqr(x1), 3), a),
                        F.fp2_inv(F.fp2_add(y1, y1)))
    else:
        lam = F.fp2_mul(F.fp2_sub(y2, y1), F.fp2_inv(F.fp2_sub(x2, x1)))
    x3 = F.fp2_sub(F.fp2_sub(F.fp2_sqr(lam), x1), x2)
    y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(x1, x3)), y1)
    return (x3, y3)


def _aff_add_fp(p1, p2, a):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    (x1, y1), (x2, y2) = p1, p2
    if x1 == x2:
        if F.fp_add(y1, y2) == 0:
            return None
        lam = F.fp_mul(F.fp_add(F.fp_mul(3, F.fp_sqr(x1)), a),
                       F.fp_inv(F.fp_add(y1, y1)))
    else:
        lam = F.fp_mul(F.fp_sub(y2, y1), F.fp_inv(F.fp_sub(x2, x1)))
    x3 = F.fp_sub(F.fp_sub(F.fp_sqr(lam), x1), x2)
    y3 = F.fp_sub(F.fp_mul(lam, F.fp_sub(x1, x3)), y1)
    return (x3, y3)


# ---------------------------------------------------------------------------
# Isogenies E' -> E
# ---------------------------------------------------------------------------

def iso3_map(pt):
    """3-isogeny E2' -> E2 in compact Velu form (equals RFC 9380 E.3)."""
    if pt is None:
        return None
    x, y = pt
    d = F.fp2_sub(x, ISO3_X0)
    if d == F.FP2_ZERO:
        return None  # kernel point maps to infinity
    di = F.fp2_inv(d)
    di2 = F.fp2_sqr(di)
    di3 = F.fp2_mul(di2, di)
    X = F.fp2_add(x, F.fp2_add(F.fp2_mul(ISO3_V, di), F.fp2_mul(ISO3_W, di2)))
    Yfac = F.fp2_sub(F.fp2_sub(F.FP2_ONE, F.fp2_mul(ISO3_V, di2)),
                     F.fp2_mul(F.fp2_add(ISO3_W, ISO3_W), di3))
    Y = F.fp2_mul(y, Yfac)
    s2 = F.fp2_sqr(ISO3_S)
    s3 = F.fp2_mul(s2, ISO3_S)
    return (F.fp2_mul(s2, X), F.fp2_mul(s3, Y))


def _eval_poly_fp(coeffs, x):
    """Horner evaluation, ascending coefficient order."""
    acc = 0
    for c in reversed(coeffs):
        acc = F.fp_add(F.fp_mul(acc, x), c)
    return acc


def iso1_map(pt):
    """11-isogeny E1' -> E1 via the derived rational-map coefficients."""
    if pt is None:
        return None
    x, y = pt
    xd = _eval_poly_fp(ISO1_X_DEN, x)
    yd = _eval_poly_fp(ISO1_Y_DEN, x)
    if xd == 0 or yd == 0:
        return None  # kernel point maps to infinity
    X = F.fp_mul(_eval_poly_fp(ISO1_X_NUM, x), F.fp_inv(xd))
    Y = F.fp_mul(y, F.fp_mul(_eval_poly_fp(ISO1_Y_NUM, x), F.fp_inv(yd)))
    return (X, Y)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """Hash arbitrary bytes to a G2 subgroup point (Jacobian)."""
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    q0 = _sswu_fp2(u0)
    q1 = _sswu_fp2(u1)
    s = _aff_add_fp2(q0, q1, SSWU_G2_A)   # add on E2'; isogeny is a hom.
    e = iso3_map(s)
    jac = C.G2_INF if e is None else (e[0], e[1], F.FP2_ONE)
    return C.g2_clear_cofactor(jac)


def hash_to_g1(msg: bytes, dst: bytes = DST_G1):
    """Hash arbitrary bytes to a G1 subgroup point (Jacobian)."""
    u0, u1 = hash_to_field_fp(msg, dst, 2)
    q0 = _sswu_fp(u0)
    q1 = _sswu_fp(u1)
    s = _aff_add_fp(q0, q1, SSWU_G1_A)
    e = iso1_map(s)
    jac = C.G1_INF if e is None else (e[0], e[1], 1)
    return C.g1_clear_cofactor(jac)
