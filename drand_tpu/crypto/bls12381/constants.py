"""BLS12-381 curve constants.

Role model: the reference drand's crypto dependency chain
(`key/curve.go:24-43` -> drand/kyber-bls12381 -> kilic/bls12-381).  We
re-derive every non-primary constant (cofactors, Frobenius coefficients,
twist order) programmatically from the primary parameters below, and
runtime-verify the derivations in tests, because this build runs with zero
network egress (no external test vectors).

Primary parameters (public knowledge of the BLS12-381 curve):
  - p: base field prime
  - r: scalar field prime (order of G1/G2)
  - x: the BLS parameter (p and r are polynomials in x)
"""

# Base field prime.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative).  p = (x-1)^2/3 * r + x,  r = x^4 - x^2 + 1.
X = -0xD201000000010000

# Curve: E/Fp : y^2 = x^3 + 4.  Twist: E'/Fp2 : y^2 = x^3 + 4*(1+u).
B_G1 = 4
B_G2 = (4, 4)  # 4*(1+u) as an Fp2 element (c0, c1)

# Generators (standard, from the BLS12-381 specification).
G1_GEN_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_GEN_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_GEN_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_GEN_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# Trace of Frobenius over Fp:  #E(Fp) = p + 1 - t,  t = x + 1 for BLS curves.
T_FROB = X + 1

# Group orders, derived.
N_E_FP = P + 1 - T_FROB           # #E(Fp)
H1 = N_E_FP // R                  # G1 cofactor
assert N_E_FP % R == 0

# #E(Fp2) = p^2 + 1 - t2 where t2 = t^2 - 2p.
T2 = T_FROB * T_FROB - 2 * P
N_E_FP2 = P * P + 1 - T2

# Sextic twist orders: t2^2 - 4 p^2 = -3 f^2; the two sextic twists have
# orders p^2 + 1 - (t2 + 3f)/2 and p^2 + 1 - (t2 - 3f)/2.  Exactly one is
# divisible by r; that one is E' (the twist used by BLS12-381 G2).
def _twist_order():
    d = 4 * P * P - T2 * T2
    assert d % 3 == 0
    f2 = d // 3
    f = _isqrt(f2)
    assert f * f == f2
    for cand in (P * P + 1 - (T2 + 3 * f) // 2, P * P + 1 - (T2 - 3 * f) // 2):
        if cand % R == 0:
            return cand
    raise AssertionError("no sextic twist order divisible by r")


def _isqrt(n: int) -> int:
    import math
    return math.isqrt(n)


N_TWIST = _twist_order()
H2 = N_TWIST // R                 # G2 cofactor

# Domain separation tags: drand's exact wire suites (the DSTs kilic/bls12-381
# applies behind `chain/verify.go:38-45` / `key/curve.go:24-43`).
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"
DST_G1 = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"

# --- RFC 9380 SSWU parameters ---------------------------------------------
#
# G2 suite BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380 8.8.2): map to the
# 3-isogenous curve E2': y^2 = x^3 + A2'x + B2' over Fp2, then apply the
# 3-isogeny back to E2.  The isogeny was RE-DERIVED offline with Velu's
# formulas (tools/derive_sswu_g2.py) rather than transcribed: the kernel is
# the unique Fp2-rational order-3 subgroup with j-invariant-0 quotient,
# x-coordinate x0 = -6+6u, and the normalizing isomorphism scale s below is
# the unique 6th root of (4+4u)/B_velu for which the expanded rational map
# reproduces RFC 9380 Appendix E.3 coefficient-for-coefficient (asserted in
# tests/test_h2c_sswu.py).
SSWU_G2_A = (0, 240)          # A' = 240*u
SSWU_G2_B = (1012, 1012)      # B' = 1012*(1+u)
SSWU_G2_Z = (P - 2, P - 1)    # Z  = -(2+u)
ISO3_X0 = (P - 6, 6)          # kernel x-coord: -6 + 6u
ISO3_V = (0, 48)              # Velu v = 2*(3*x0^2 + A')
ISO3_W = (16, 16)             # Velu w = 4*(x0^3 + A'x0 + B') = (2*y0)^2
ISO3_S = (0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38E, 0)

# G1 suite BLS12381G1_XMD:SHA-256_SSWU_RO_ (RFC 9380 8.8.1): 11-isogenous
# curve E1' over Fp.  Derivation: tools/derive_sswu_g1.py (same method,
# kernel polynomial of degree 5 from the 11-division polynomial).
SSWU_G1_A = 0x144698A3B8E9433D693A02C96D4982B0EA985383EE66A8D8E8981AEFD881AC98936F8DA0E0F97F5CF428082D584C1D
SSWU_G1_B = 0x12E2908D11688030018B12E8753EEE3B2016C1F0F24F4070A0B9C14FCEF35EF55A23215A316CEAA5D1CC48E98E172BE0
SSWU_G1_Z = 11

# 11-isogeny E1' -> E1 rational map coefficients (ascending powers of x),
# derived by tools/derive_sswu_g1.py.  Filled in below by that derivation.
ISO1_X_NUM = [
    0x11a05f2b1e833340b809101dd99815856b303e88a2d7005ff2627b56cdb4e2c85610c2d5f2e62d6eaeac1662734649b7,
    0x17294ed3e943ab2f0588bab22147a81c7c17e75b2f6a8417f565e33c70d1e86b4838f2a6f318c356e834eef1b3cb83bb,
    0xd54005db97678ec1d1048c5d10a9a1bce032473295983e56878e501ec68e25c958c3e3d2a09729fe0179f9dac9edcb0,
    0x1778e7166fcc6db74e0609d307e55412d7f5e4656a8dbf25f1b33289f1b330835336e25ce3107193c5b388641d9b6861,
    0xe99726a3199f4436642b4b3e4118e5499db995a1257fb3f086eeb65982fac18985a286f301e77c451154ce9ac8895d9,
    0x1630c3250d7313ff01d1201bf7a74ab5db3cb17dd952799b9ed3ab9097e68f90a0870d2dcae73d19cd13c1c66f652983,
    0xd6ed6553fe44d296a3726c38ae652bfb11586264f0f8ce19008e218f9c86b2a8da25128c1052ecaddd7f225a139ed84,
    0x17b81e7701abdbe2e8743884d1117e53356de5ab275b4db1a682c62ef0f2753339b7c8f8c8f475af9ccb5618e3f0c88e,
    0x80d3cf1f9a78fc47b90b33563be990dc43b756ce79f5574a2c596c928c5d1de4fa295f296b74e956d71986a8497e317,
    0x169b1f8e1bcfa7c42e0c37515d138f22dd2ecb803a0c5c99676314baf4bb1b7fa3190b2edc0327797f241067be390c9e,
    0x10321da079ce07e272d8ec09d2565b0dfa7dccdde6787f96d50af36003b14866f69b771f8c285decca67df3f1605fb7b,
    0x6e08c248e260e70bd1e962381edee3d31d79d7e22c837bc23c0bf1bc24c6b68c24b1b80b64d391fa9c8ba2e8ba2d229,
]
ISO1_X_DEN = [
    0x8ca8d548cff19ae18b2e62f4bd3fa6f01d5ef4ba35b48ba9c9588617fc8ac62b558d681be343df8993cf9fa40d21b1c,
    0x12561a5deb559c4348b4711298e536367041e8ca0cf0800c0126c2588c48bf5713daa8846cb026e9e5c8276ec82b3bff,
    0xb2962fe57a3225e8137e629bff2991f6f89416f5a718cd1fca64e00b11aceacd6a3d0967c94fedcfcc239ba5cb83e19,
    0x3425581a58ae2fec83aafef7c40eb545b08243f16b1655154cca8abc28d6fd04976d5243eecf5c4130de8938dc62cd8,
    0x13a8e162022914a80a6f1d5f43e7a07dffdfc759a12062bb8d6b44e833b306da9bd29ba81f35781d539d395b3532a21e,
    0xe7355f8e4e667b955390f7f0506c6e9395735e9ce9cad4d0a43bcef24b8982f7400d24bc4228f11c02df9a29f6304a5,
    0x772caacf16936190f3e0c63e0596721570f5799af53a1894e2e073062aede9cea73b3538f0de06cec2574496ee84a3a,
    0x14a7ac2a9d64a8b230b3f5b074cf01996e7f63c21bca68a81996e1cdf9822c580fa5b9489d11e2d311f7d99bbdcc5a5e,
    0xa10ecf6ada54f825e920b3dafc7a3cce07f8d1d7161366b74100da67f39883503826692abba43704776ec3a79a1d641,
    0x95fc13ab9e92ad4476d6e3eb3a56680f682b4ee96f7d03776df533978f31c1593174e4b4b7865002d6384d168ecdd0a,
    0x1,
]
ISO1_Y_NUM = [
    0x90d97c81ba24ee0259d1f094980dcfa11ad138e48a869522b52af6c956543d3cd0c7aee9b3ba3c2be9845719707bb33,
    0x134996a104ee5811d51036d776fb46831223e96c254f383d0f906343eb67ad34d6c56711962fa8bfe097e75a2e41c696,
    0xcc786baa966e66f4a384c86a3b49942552e2d658a31ce2c344be4b91400da7d26d521628b00523b8dfe240c72de1f6,
    0x1f86376e8981c217898751ad8746757d42aa7b90eeb791c09e4a3ec03251cf9de405aba9ec61deca6355c77b0e5f4cb,
    0x8cc03fdefe0ff135caf4fe2a21529c4195536fbe3ce50b879833fd221351adc2ee7f8dc099040a841b6daecf2e8fedb,
    0x16603fca40634b6a2211e11db8f0a6a074a7d0d4afadb7bd76505c3d3ad5544e203f6326c95a807299b23ab13633a5f0,
    0x4ab0b9bcfac1bbcb2c977d027796b3ce75bb8ca2be184cb5231413c4d634f3747a87ac2460f415ec961f8855fe9d6f2,
    0x987c8d5333ab86fde9926bd2ca6c674170a05bfe3bdd81ffd038da6c26c842642f64550fedfe935a15e4ca31870fb29,
    0x9fc4018bd96684be88c9e221e4da1bb8f3abd16679dc26c1e8b6e6a1f20cabe69d65201c78607a360370e577bdba587,
    0xe1bba7a1186bdb5223abde7ada14a23c42a0ca7915af6fe06985e7ed1e4d43b9b3f7055dd4eba6f2bafaaebca731c30,
    0x19713e47937cd1be0dfd0b8f1d43fb93cd2fcbcb6caf493fd1183e416389e61031bf3a5cce3fbafce813711ad011c132,
    0x18b46a908f36f6deb918c143fed2edcc523559b8aaf0c2462e6bfe7f911f643249d9cdf41b44d606ce07c8a4d0074d8e,
    0xb182cac101b9399d155096004f53f447aa7b12a3426b08ec02710e807b4633f06c851c1919211f20d4c04f00b971ef8,
    0x245a394ad1eca9b72fc00ae7be315dc757b3b080d4c158013e6632d3c40659cc6cf90ad1c232a6442d9d3f5db980133,
    0x5c129645e44cf1102a159f748c4a3fc5e673d81d7e86568d9ab0f5d396a7ce46ba1049b6579afb7866b1e715475224b,
    0x15e6be4e990f03ce4ea50b3b42df2eb5cb181d8f84965a3957add4fa95af01b2b665027efec01c7704b456be69c8b604,
]
ISO1_Y_DEN = [
    0x16112c4c3a9c98b252181140fad0eae9601a6de578980be6eec3232b5be72e7a07f3688ef60c206d01479253b03663c1,
    0x1962d75c2381201e1a0cbd6c43c348b885c84ff731c4d59ca4a10356f453e01f78a4260763529e3532f6102c2e49a03d,
    0x58df3306640da276faaae7d6e8eb15778c4855551ae7f310c35a5dd279cd2eca6757cd636f96f891e2538b53dbf67f2,
    0x16b7d288798e5395f20d23bf89edb4d1d115c5dbddbcd30e123da489e726af41727364f2c28297ada8d26d98445f5416,
    0xbe0e079545f43e4b00cc912f8228ddcc6d19c9f0f69bbb0542eda0fc9dec916a20b15dc0fd2ededda39142311a5001d,
    0x8d9e5297186db2d9fb266eaac783182b70152c65550d881c5ecd87b6f0f5a6449f38db9dfa9cce202c6477faaf9b7ac,
    0x166007c08a99db2fc3ba8734ace9824b5eecfdfa8d0cf8ef5dd365bc400a0051d5fa9c01a58b1fb93d1a1399126a775c,
    0x16a3ef08be3ea7ea03bcddfabba6ff6ee5a4375efa1f4fd7feb34fd206357132b920f5b00801dee460ee415a15812ed9,
    0x1866c8ed336c61231a1be54fd1d74cc4f9fb0ce4c6af5920abc5750c4bf39b4852cfe2f7bb9248836b233d9d55535d4a,
    0x167a55cda70a6e1cea820597d94a84903216f763e13d87bb5308592e7ea7d4fbc7385ea3d529b35e346ef48bb8913f55,
    0x4d2f259eea405bd48f010a01ad2911d9c6dd039bb61a6290e591b36e636a5c871a5c29f4f83060400f8b49cba8f6aa8,
    0xaccbb67481d033ff5852c1e48c50c477f94ff8aefce42d28c0f9a88cea7913516f968986f7ebbea9684b529e2561092,
    0xad6b9514c767fe3c3613144b45f1496543346d98adf02267d5ceef9a00d9b8693000763e3b90ac11e99b138573345cc,
    0x2660400eb2e4f3b628bdd0d53cd76f2bf565b94e72927c1cb748df27942480e420517bd8714cc80d1fadc1326ed06f7,
    0xe0fa1d816ddc03e6b24255e0d7819c171c40f65e273b853324efcd6356caa205ca2f570f13497804415473a1d634b8f,
    0x1,
]
