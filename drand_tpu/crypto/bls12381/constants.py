"""BLS12-381 curve constants.

Role model: the reference drand's crypto dependency chain
(`key/curve.go:24-43` -> drand/kyber-bls12381 -> kilic/bls12-381).  We
re-derive every non-primary constant (cofactors, Frobenius coefficients,
twist order) programmatically from the primary parameters below, and
runtime-verify the derivations in tests, because this build runs with zero
network egress (no external test vectors).

Primary parameters (public knowledge of the BLS12-381 curve):
  - p: base field prime
  - r: scalar field prime (order of G1/G2)
  - x: the BLS parameter (p and r are polynomials in x)
"""

# Base field prime.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative).  p = (x-1)^2/3 * r + x,  r = x^4 - x^2 + 1.
X = -0xD201000000010000

# Curve: E/Fp : y^2 = x^3 + 4.  Twist: E'/Fp2 : y^2 = x^3 + 4*(1+u).
B_G1 = 4
B_G2 = (4, 4)  # 4*(1+u) as an Fp2 element (c0, c1)

# Generators (standard, from the BLS12-381 specification).
G1_GEN_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_GEN_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_GEN_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_GEN_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# Trace of Frobenius over Fp:  #E(Fp) = p + 1 - t,  t = x + 1 for BLS curves.
T_FROB = X + 1

# Group orders, derived.
N_E_FP = P + 1 - T_FROB           # #E(Fp)
H1 = N_E_FP // R                  # G1 cofactor
assert N_E_FP % R == 0

# #E(Fp2) = p^2 + 1 - t2 where t2 = t^2 - 2p.
T2 = T_FROB * T_FROB - 2 * P
N_E_FP2 = P * P + 1 - T2

# Sextic twist orders: t2^2 - 4 p^2 = -3 f^2; the two sextic twists have
# orders p^2 + 1 - (t2 + 3f)/2 and p^2 + 1 - (t2 - 3f)/2.  Exactly one is
# divisible by r; that one is E' (the twist used by BLS12-381 G2).
def _twist_order():
    d = 4 * P * P - T2 * T2
    assert d % 3 == 0
    f2 = d // 3
    f = _isqrt(f2)
    assert f * f == f2
    for cand in (P * P + 1 - (T2 + 3 * f) // 2, P * P + 1 - (T2 - 3 * f) // 2):
        if cand % R == 0:
            return cand
    raise AssertionError("no sextic twist order divisible by r")


def _isqrt(n: int) -> int:
    import math
    return math.isqrt(n)


N_TWIST = _twist_order()
H2 = N_TWIST // R                 # G2 cofactor

# Domain separation tags.  NOTE: this build's hash-to-curve uses the RFC 9380
# Shallue-van-de-Woestijne (SVDW) map (fully self-derivable offline) rather
# than the SSWU+isogeny suite, so the suite IDs say SVDW.  The reference
# chain's exact SSWU suite (BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_,
# used via kilic/bls12-381) is a wire-compat gap tracked for a later round.
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SVDW_RO_NUL_"
DST_G1 = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SVDW_RO_NUL_"
