"""Optimal ate pairing on BLS12-381 (pure-Python golden model).

The pairing computed here is e(P, Q)^3 for the reduced optimal-ate e — the
cube comes from the denominators-cleared hard-part exponent 3*(p^4-p^2+1)/r.
Since gcd(3, r) = 1 this is itself a non-degenerate bilinear pairing, and all
sign/verify operations in this framework use it consistently on both sides.

Derivation notes (nothing here is taken on faith from memory):
  - The untwist convention is runtime-selected in curve.py by an on-curve
    check over Fp12.
  - Line evaluations are scaled by w^3 (an element of the Fp4 subfield, which
    the final exponentiation kills) so they become sparse Fp12 elements.
  - The hard-part base-p decomposition was derived symbolically
    (3*(p^4-p^2+1)/r = l0 + l1*p + l2*p^2 + l3*p^3) and is re-verified as an
    integer identity at import time below.

Reference counterpart: the pairing engine inside kilic/bls12-381 used via
`key.Pairing` (`key/curve.go:24`).
"""

from . import fp as F
from .constants import P, R, X

# ---------------------------------------------------------------------------
# Hard-part exponent decomposition: lambda_i coefficients (highest degree
# first) of 3*(p^4-p^2+1)/r in base p, as polynomials in the BLS parameter x.
# Derived with sympy; verified as exact integers here.
# ---------------------------------------------------------------------------

_L0 = [1, -2, 0, 2, -1, 3]      # x^5 - 2x^4 + 2x^2 - x + 3
_L1 = [1, -2, 0, 2, -1]         # x^4 - 2x^3 + 2x - 1
_L2 = [1, -2, 1, 0]             # x^3 - 2x^2 + x
_L3 = [1, -2, 1]                # x^2 - 2x + 1


def _poly_eval(coeffs, v):
    acc = 0
    for c in coeffs:
        acc = acc * v + c
    return acc


_E_HARD3 = 3 * (P**4 - P**2 + 1) // R
assert 3 * (P**4 - P**2 + 1) % R == 0
assert (_poly_eval(_L0, X) + _poly_eval(_L1, X) * P + _poly_eval(_L2, X) * P**2
        + _poly_eval(_L3, X) * P**3) == _E_HARD3, "hard-part decomposition broken"

_X_ABS = -X  # positive 64-bit loop counter
_X_BITS = bin(_X_ABS)[2:]


# ---------------------------------------------------------------------------
# Miller loop
# ---------------------------------------------------------------------------

def _line_sparse(lam, xt, yt, xp, yp):
    """Line through twisted point T=(xt,yt) slope lam (Fp2), evaluated at
    P=(xp,yp) in G1, pre-multiplied by w^3.  Result is a sparse Fp12 element
    with nonzero Fp2 slots c0[0], c0[1], c1[1]:
        (lam*xt - yt)  +  (-lam*xp) * w^2  +  yp * w^3.
    """
    a = F.fp2_sub(F.fp2_mul(lam, xt), yt)
    b = F.fp2_mul_fp(F.fp2_neg(lam), xp)
    c = (yp, 0)
    return ((a, b, F.FP2_ZERO), (F.FP2_ZERO, c, F.FP2_ZERO))


def _dbl_step(t, xp, yp):
    """Affine doubling of T (Fp2) + line eval.  Returns (2T, line)."""
    xt, yt = t
    lam = F.fp2_mul(F.fp2_mul_fp(F.fp2_sqr(xt), 3), F.fp2_inv(F.fp2_add(yt, yt)))
    x3 = F.fp2_sub(F.fp2_sqr(lam), F.fp2_add(xt, xt))
    y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(xt, x3)), yt)
    return (x3, y3), _line_sparse(lam, xt, yt, xp, yp)


def _add_step(t, q, xp, yp):
    """Affine addition T + Q + line eval.  Returns (T+Q, line)."""
    xt, yt = t
    xq, yq = q
    lam = F.fp2_mul(F.fp2_sub(yt, yq), F.fp2_inv(F.fp2_sub(xt, xq)))
    x3 = F.fp2_sub(F.fp2_sub(F.fp2_sqr(lam), xt), xq)
    y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(xt, x3)), yt)
    return (x3, y3), _line_sparse(lam, xt, yt, xp, yp)


def miller_loop(p_aff, q_aff):
    """f_{|x|, Q}(P) with lines scaled into sparse form.  Affine inputs:
    p_aff = (xp, yp) ints, q_aff = ((..),(..)) Fp2 pair.  Conjugated at the
    end because the BLS parameter x is negative."""
    xp, yp = p_aff
    t = q_aff
    f = F.FP12_ONE
    for bit in _X_BITS[1:]:
        t, line = _dbl_step(t, xp, yp)
        f = F.fp12_mul(F.fp12_sqr(f), line)
        if bit == "1":
            t, line = _add_step(t, q_aff, xp, yp)
            f = F.fp12_mul(f, line)
    return F.fp12_conj(f)  # x < 0


def multi_miller_loop(pairs):
    """Product of Miller loops over [(P_aff, Q_aff)] with shared squarings."""
    xs = [(p, q) for (p, q) in pairs]
    ts = [q for (_, q) in xs]
    f = F.FP12_ONE
    for bit in _X_BITS[1:]:
        f = F.fp12_sqr(f)
        for i, (pa, qa) in enumerate(xs):
            ts[i], line = _dbl_step(ts[i], pa[0], pa[1])
            f = F.fp12_mul(f, line)
        if bit == "1":
            for i, (pa, qa) in enumerate(xs):
                ts[i], line = _add_step(ts[i], qa, pa[0], pa[1])
                f = F.fp12_mul(f, line)
    return F.fp12_conj(f)


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------

def _pow_x(f):
    """f^|x| by square-and-multiply, then conjugate (x < 0).  Assumes f is
    unitary (true after the easy part), so inverse == conjugate."""
    out = F.FP12_ONE
    for bit in _X_BITS:
        out = F.fp12_sqr(out)
        if bit == "1":
            out = F.fp12_mul(out, f)
    return F.fp12_conj(out)


def _pow_small(f, e):
    """f^e for small |e|, unitary f."""
    if e < 0:
        return F.fp12_conj(_pow_small(f, -e))
    out = F.FP12_ONE
    base = f
    while e:
        if e & 1:
            out = F.fp12_mul(out, base)
        base = F.fp12_sqr(base)
        e >>= 1
    return out


def _poly_pow(powers, coeffs):
    """prod powers[k]^coeffs[deg-k]: powers[k] = f^(x^k), coeffs high-first."""
    out = F.FP12_ONE
    deg = len(coeffs) - 1
    for i, c in enumerate(coeffs):
        if c:
            out = F.fp12_mul(out, _pow_small(powers[deg - i], c))
    return out


def final_exp(f):
    """f^((p^6-1)(p^2+1)) then hard part f^(3(p^4-p^2+1)/r)."""
    # easy part
    f = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))       # f^(p^6-1), now unitary
    f = F.fp12_mul(F.fp12_frob_n(f, 2), f)              # f^(p^2+1)
    # hard part via x-power chain
    g = [f]
    for _ in range(5):
        g.append(_pow_x(g[-1]))                         # g[k] = f^(x^k)
    part0 = _poly_pow(g, _L0)
    part1 = F.fp12_frob_n(_poly_pow(g, _L1), 1)
    part2 = F.fp12_frob_n(_poly_pow(g, _L2), 2)
    part3 = F.fp12_frob_n(_poly_pow(g, _L3), 3)
    return F.fp12_mul(F.fp12_mul(part0, part1), F.fp12_mul(part2, part3))


def final_exp_plain(f):
    """Reference-slow final exponentiation with the same total exponent
    (easy * 3*(p^4-p^2+1)/r), used to cross-check final_exp in tests."""
    f = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))
    f = F.fp12_mul(F.fp12_frob_n(f, 2), f)
    return F.fp12_pow(f, _E_HARD3)


# ---------------------------------------------------------------------------
# Pairing API
# ---------------------------------------------------------------------------

def pairing(p_jac, q_jac):
    """e(P, Q)^3 for P in G1 (Jacobian, Fp), Q in G2 (Jacobian, Fp2)."""
    from .curve import FP2_OPS, FP_OPS, point_is_inf, point_to_affine
    if point_is_inf(p_jac, FP_OPS) or point_is_inf(q_jac, FP2_OPS):
        return F.FP12_ONE
    pa = point_to_affine(p_jac, FP_OPS)
    qa = point_to_affine(q_jac, FP2_OPS)
    return final_exp(miller_loop(pa, qa))


def pairing_check(pairs):
    """True iff prod e(P_i, Q_i) == 1.  One shared final exponentiation."""
    from .curve import FP2_OPS, FP_OPS, point_is_inf, point_to_affine
    live = []
    for p_jac, q_jac in pairs:
        if point_is_inf(p_jac, FP_OPS) or point_is_inf(q_jac, FP2_OPS):
            continue
        live.append((point_to_affine(p_jac, FP_OPS), point_to_affine(q_jac, FP2_OPS)))
    if not live:
        return True
    return final_exp(multi_miller_loop(live)) == F.FP12_ONE
