"""BLS and Schnorr signature schemes on BLS12-381 (host/golden path).

Counterparts of the reference's `key.AuthScheme` (BLS on G2,
`key/curve.go:39`) and `key.DKGAuthScheme` (Schnorr, `key/curve.go:43`).
Keys are G1 points (48 B compressed), BLS signatures are G2 points (96 B
compressed), matching drand's wire sizes.

The TPU path (drand_tpu.ops.bls via drand_tpu.verify) provides the batched
verify; this module is the single-item host implementation and its oracle.
"""

from __future__ import annotations

import hashlib
import secrets

from .bls12381 import curve as C
from .bls12381 import h2c
from .bls12381 import pairing as PR
from .bls12381.constants import R

# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------

def keygen(seed: bytes | None = None) -> tuple[int, tuple]:
    """Generate (secret scalar, G1 public key).  Deterministic if seed given."""
    if seed is None:
        sk = secrets.randbelow(R - 1) + 1
    else:
        sk = int.from_bytes(hashlib.sha512(b"drand-tpu-keygen" + seed).digest(), "big") % R
        sk = sk or 1
    return sk, C.g1_mul(C.G1_GEN, sk)


def public_key(sk: int) -> tuple:
    return C.g1_mul(C.G1_GEN, sk)


# ---------------------------------------------------------------------------
# Plain BLS (sign on G2, verify with 2 pairings)
# ---------------------------------------------------------------------------

def bls_sign(sk: int, msg: bytes) -> bytes:
    """sigma = sk * H2(msg); returns 96-byte compressed G2 signature."""
    h = h2c.hash_to_g2(msg)
    return C.g2_to_bytes(C.g2_mul(h, sk))


def bls_verify(pub, msg: bytes, sig: bytes) -> bool:
    """Check e(g1, sigma) == e(pub, H2(msg)), i.e.
    e(-g1, sigma) * e(pub, H2(msg)) == 1.  pub is a G1 Jacobian point."""
    try:
        sigma = C.g2_from_bytes(sig)
    except ValueError:
        return False
    if not C.g2_in_subgroup(sigma):
        return False
    h = h2c.hash_to_g2(msg)
    return PR.pairing_check([(C.g1_neg(C.G1_GEN), sigma), (pub, h)])


# --- G1-signature variant (short sigs, pk on G2): scheme
# bls-unchained-g1-rfc9380 in later upstream drand (BASELINE.md config 4). ---

def keygen_g2(seed: bytes | None = None) -> tuple[int, tuple]:
    if seed is None:
        sk = secrets.randbelow(R - 1) + 1
    else:
        sk = int.from_bytes(hashlib.sha512(b"drand-tpu-keygen-g2" + seed).digest(), "big") % R
        sk = sk or 1
    return sk, C.g2_mul(C.G2_GEN, sk)


def bls_sign_g1(sk: int, msg: bytes) -> bytes:
    """sigma = sk * H1(msg); returns 48-byte compressed G1 signature."""
    h = h2c.hash_to_g1(msg)
    return C.g1_to_bytes(C.g1_mul(h, sk))


def bls_verify_g1(pub_g2, msg: bytes, sig: bytes) -> bool:
    """Check e(sigma, g2) == e(H1(msg), pub):  pub is a G2 Jacobian point."""
    try:
        sigma = C.g1_from_bytes(sig)
    except ValueError:
        return False
    if not C.g1_in_subgroup(sigma):
        return False
    h = h2c.hash_to_g1(msg)
    return PR.pairing_check([(C.g1_neg(sigma), C.G2_GEN), (h, pub_g2)])


# ---------------------------------------------------------------------------
# Schnorr (DKG packet authentication)
# ---------------------------------------------------------------------------

def _schnorr_challenge(r_bytes: bytes, pub_bytes: bytes, msg: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"drand-tpu-schnorr" + r_bytes + pub_bytes + msg).digest(), "big") % R


def schnorr_sign(sk: int, msg: bytes) -> bytes:
    """sig = R_compressed(48B) || s(32B big-endian); s = k + sk*h mod r."""
    k = secrets.randbelow(R - 1) + 1
    r_pt = C.g1_mul(C.G1_GEN, k)
    r_bytes = C.g1_to_bytes(r_pt)
    pub_bytes = C.g1_to_bytes(C.g1_mul(C.G1_GEN, sk))
    h = _schnorr_challenge(r_bytes, pub_bytes, msg)
    s = (k + sk * h) % R
    return r_bytes + s.to_bytes(32, "big")


def schnorr_verify(pub, msg: bytes, sig: bytes) -> bool:
    """Check s*G == R + h*pub."""
    if len(sig) != 80:
        return False
    try:
        r_pt = C.g1_from_bytes(sig[:48])
    except ValueError:
        return False
    s = int.from_bytes(sig[48:], "big")
    if s >= R:
        return False
    h = _schnorr_challenge(sig[:48], C.g1_to_bytes(pub), msg)
    lhs = C.g1_mul(C.G1_GEN, s)
    rhs = C.g1_add(r_pt, C.g1_mul(pub, h))
    return C.g1_eq(lhs, rhs)
