"""ECIES over BLS12-381 G1: the private-randomness channel.

Counterpart of the reference's kyber ECIES used by `PrivateRand`
(`core/drand_beacon_public.go:135-160`): the client sends an ephemeral
public key, the node derives a shared secret via its long-term scalar,
and replies with AES-GCM-sealed random bytes.

Scheme: ephemeral keypair (e, E = e*G1); shared point S = e*PK (sender)
= sk*E (receiver); key = sha256(compressed(S)); AES-256-GCM with a zero
nonce (keys are single-use by construction — a fresh ephemeral per
request).
"""

from __future__ import annotations

import hashlib
import json
import secrets

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:
    # some images ship without the cryptography wheel; the pure-python
    # fallback is bit-compatible and these boxes are tens of bytes
    from drand_tpu.crypto.aesgcm_fallback import AESGCM

from drand_tpu.crypto import sign as S
from drand_tpu.crypto.bls12381 import curve as C
from drand_tpu.crypto.bls12381.constants import R

_NONCE = bytes(12)


def _kdf(shared_point) -> bytes:
    return hashlib.sha256(C.g1_to_bytes(shared_point)).digest()


def encode_request(node_public) -> tuple[bytes, int]:
    """Client side: returns (wire request, ephemeral secret)."""
    esk = secrets.randbelow(R - 1) + 1
    epub = C.g1_mul(C.G1_GEN, esk)
    return C.g1_to_bytes(epub), esk


def decode(request: bytes):
    """Node side: parse the ephemeral public key."""
    return C.g1_from_bytes(request)


def encrypt_reply(node_secret: int, ephemeral_pub, payload: bytes) -> bytes:
    shared = C.g1_mul(ephemeral_pub, node_secret)
    key = _kdf(shared)
    sealed = AESGCM(key).encrypt(_NONCE, payload, b"")
    return json.dumps({"box": sealed.hex()}).encode()


def seal(recipient_pub, payload: bytes) -> bytes:
    """One-shot ECIES seal to a G1 public key: ephemeral pub || AES-GCM box
    (the DKG deal encryption, kyber ecies equivalent)."""
    esk = secrets.randbelow(R - 1) + 1
    epub = C.g1_mul(C.G1_GEN, esk)
    shared = C.g1_mul(recipient_pub, esk)
    sealed = AESGCM(_kdf(shared)).encrypt(_NONCE, payload, b"")
    return C.g1_to_bytes(epub) + sealed


def open_sealed(secret: int, blob: bytes) -> bytes:
    epub = C.g1_from_bytes(blob[:48])
    shared = C.g1_mul(epub, secret)
    return AESGCM(_kdf(shared)).decrypt(_NONCE, blob[48:], b"")


def decrypt_reply(ephemeral_secret: int, node_public, reply: bytes) -> bytes:
    """Client side: open the sealed reply with the shared secret."""
    pk = C.g1_from_bytes(node_public) if isinstance(node_public, bytes) \
        else node_public
    shared = C.g1_mul(pk, ephemeral_secret)
    key = _kdf(shared)
    sealed = bytes.fromhex(json.loads(reply.decode())["box"])
    return AESGCM(key).decrypt(_NONCE, sealed, b"")
