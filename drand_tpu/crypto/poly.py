"""Shamir secret sharing polynomials over the BLS12-381 scalar field.

Counterpart of kyber's `share.PriPoly` / `share.PubPoly` / `share.PriShare`
used by the reference at `key/keys.go:239-252, 311-324` (shares and public
polynomial commitments).  Same conventions: share with index i is the
polynomial evaluated at x = i + 1; commitments live in G1 (the key group).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Sequence

from .bls12381 import curve as C
from .bls12381.constants import R


def rand_scalar() -> int:
    return secrets.randbelow(R - 1) + 1


@dataclass(frozen=True)
class PriShare:
    """A private share: polynomial evaluation at x = index + 1."""
    index: int
    value: int  # scalar mod R


class PriPoly:
    """Secret-sharing polynomial of degree threshold-1 over Z_r."""

    def __init__(self, coeffs: Sequence[int]):
        self.coeffs = [c % R for c in coeffs]

    @classmethod
    def random(cls, threshold: int, secret: int | None = None,
               rand=None) -> "PriPoly":
        """rand: optional callable n_bytes -> bytes supplying the entropy
        (the DKG's user entropy source, reference
        core/drand_beacon_control.go:1346+).  One streaming read covers
        every coefficient — 48 bytes per scalar keeps the mod-R bias
        below 2^-126.  Default: the OS CSPRNG."""
        if rand is None:
            coeffs = [rand_scalar() for _ in range(threshold)]
        else:
            buf = rand(48 * threshold)
            if len(buf) < 48 * threshold:
                raise ValueError("entropy source returned too few bytes")
            coeffs = [int.from_bytes(buf[i * 48:(i + 1) * 48], "big") % R
                      for i in range(threshold)]
        if secret is not None:
            coeffs[0] = secret % R
        return cls(coeffs)

    @property
    def threshold(self) -> int:
        return len(self.coeffs)

    def secret(self) -> int:
        return self.coeffs[0]

    def eval(self, index: int) -> PriShare:
        x = (index + 1) % R
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % R
        return PriShare(index, acc)

    def shares(self, n: int) -> list[PriShare]:
        return [self.eval(i) for i in range(n)]

    def commit(self) -> "PubPoly":
        return PubPoly([C.g1_mul(C.G1_GEN, c) for c in self.coeffs])

    def add(self, other: "PriPoly") -> "PriPoly":
        assert len(self.coeffs) == len(other.coeffs)
        return PriPoly([(a + b) % R for a, b in zip(self.coeffs, other.coeffs)])


class PubPoly:
    """Public commitments to a PriPoly: commits[j] = a_j * G1."""

    def __init__(self, commits: Sequence):
        self.commits = list(commits)

    @property
    def threshold(self) -> int:
        return len(self.commits)

    def key(self):
        """The distributed public key = commitment to the secret."""
        return self.commits[0]

    def eval(self, index: int):
        """Horner evaluation in the exponent at x = index + 1."""
        x = (index + 1) % R
        acc = C.G1_INF
        for commit in reversed(self.commits):
            acc = C.g1_add(C.g1_mul(acc, x), commit)
        return acc

    def add(self, other: "PubPoly") -> "PubPoly":
        assert self.threshold == other.threshold
        return PubPoly([C.g1_add(a, b) for a, b in zip(self.commits, other.commits)])

    def eq(self, other: "PubPoly") -> bool:
        return (self.threshold == other.threshold and
                all(C.g1_eq(a, b) for a, b in zip(self.commits, other.commits)))


def _lagrange_basis_at_zero(indices: Sequence[int]) -> dict[int, int]:
    """lambda_i for interpolation at 0, x-coords are index+1 (mod R)."""
    lambdas = {}
    for i in indices:
        xi = (i + 1) % R
        num, den = 1, 1
        for j in indices:
            if j == i:
                continue
            xj = (j + 1) % R
            num = num * xj % R
            den = den * ((xj - xi) % R) % R
        lambdas[i] = num * pow(den, R - 2, R) % R
    return lambdas


def recover_secret(shares: Sequence[PriShare], threshold: int) -> int:
    """Lagrange-interpolate the secret from >= threshold private shares."""
    if len(shares) < threshold:
        raise ValueError(f"need {threshold} shares, got {len(shares)}")
    subset = shares[:threshold]
    lambdas = _lagrange_basis_at_zero([s.index for s in subset])
    return sum(s.value * lambdas[s.index] for s in subset) % R


def recover_commit_g2(points: dict[int, tuple], threshold: int):
    """Lagrange interpolation at 0 over G2 points keyed by share index.

    This is the signature-recovery core (reference: tbls `Recover`, used at
    `chain/beacon/chain.go:160`)."""
    if len(points) < threshold:
        raise ValueError(f"need {threshold} points, got {len(points)}")
    indices = sorted(points)[:threshold]
    lambdas = _lagrange_basis_at_zero(indices)
    acc = C.G2_INF
    for i in indices:
        acc = C.g2_add(acc, C.g2_mul(points[i], lambdas[i]))
    return acc
