"""Pure-python AES-GCM, used only when the `cryptography` wheel is absent.

The ECIES channel (crypto/ecies.py) seals 32-byte DKG shares and
private-rand replies with AES-256-GCM.  Some deployment images ship
without the `cryptography` package (this container is one — see
CHANGES.md PR 1, where tomllib got the same treatment), which used to
kill every DKG at import time.  This is a dependency gate, not a
performance path: payloads are tens of bytes, so a table-based python
AES at ~µs/block is invisible next to the G1 scalar mul either side
of it.

Implements the subset ecies.py uses — `AESGCM(key).encrypt/decrypt`
with a 96-bit nonce — matching `cryptography`'s API shape and
ciphertext||tag layout bit-for-bit (tests/test_aesgcm_fallback.py pins
the NIST CAVP vector).
"""

from __future__ import annotations

import hmac


def _build_tables():
    # GF(2^8) exp/log over generator 3 -> S-box via inverse + affine map
    exp = [0] * 255
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x ^= ((x << 1) ^ (0x1B if x & 0x80 else 0)) & 0xFF
    sbox = [0] * 256
    for i in range(256):
        inv = 0 if i == 0 else exp[(255 - log[i]) % 255]
        b = inv
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            inv ^= b
        sbox[i] = inv ^ 0x63
    return exp, log, sbox


_EXP, _LOG, _SBOX = _build_tables()


def _xtime(a: int) -> int:
    return ((a << 1) ^ (0x1B if a & 0x80 else 0)) & 0xFF


def _expand_key(key: bytes) -> list[list[int]]:
    nk = len(key) // 4
    nr = nk + 6
    words = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    rcon = 1
    for i in range(nk, 4 * (nr + 1)):
        w = list(words[i - 1])
        if i % nk == 0:
            w = [_SBOX[b] for b in w[1:] + w[:1]]
            w[0] ^= rcon
            rcon = _xtime(rcon)
        elif nk > 6 and i % nk == 4:
            w = [_SBOX[b] for b in w]
        words.append([a ^ b for a, b in zip(words[i - nk], w)])
    # one flat 16-byte round key per round
    return [sum(words[4 * r:4 * r + 4], []) for r in range(nr + 1)]


def _encrypt_block(rk: list[list[int]], block: bytes) -> bytes:
    s = [b ^ k for b, k in zip(block, rk[0])]
    for rnd in range(1, len(rk)):
        s = [_SBOX[b] for b in s]
        # shift rows (column-major state layout: byte i is row i%4)
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if rnd != len(rk) - 1:
            mixed = []
            for c in range(0, 16, 4):
                a = s[c:c + 4]
                t = a[0] ^ a[1] ^ a[2] ^ a[3]
                mixed += [a[i] ^ t ^ _xtime(a[i] ^ a[(i + 1) % 4])
                          for i in range(4)]
            s = mixed
        s = [b ^ k for b, k in zip(s, rk[rnd])]
    return bytes(s)


_R_POLY = 0xE1 << 120


def _gmul(x: int, y: int) -> int:
    """GF(2^128) multiply, MSB-first bit order (NIST SP 800-38D §6.3)."""
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        v = (v >> 1) ^ _R_POLY if v & 1 else v >> 1
    return z


def _ghash(h: int, data: bytes) -> int:
    y = 0
    for i in range(0, len(data), 16):
        y = _gmul(y ^ int.from_bytes(data[i:i + 16], "big"), h)
    return y


def _pad16(b: bytes) -> bytes:
    return b + bytes(-len(b) % 16)


class AESGCM:
    """API-compatible subset of cryptography's AEAD AESGCM."""

    def __init__(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError("AESGCM key must be 128, 192, or 256 bits")
        self._rk = _expand_key(key)
        self._h = int.from_bytes(_encrypt_block(self._rk, bytes(16)), "big")

    def _ctr(self, j0: bytes, n_blocks: int) -> bytes:
        ctr = int.from_bytes(j0[12:], "big")
        out = bytearray()
        for i in range(n_blocks):
            cb = j0[:12] + ((ctr + 1 + i) & 0xFFFFFFFF).to_bytes(4, "big")
            out += _encrypt_block(self._rk, cb)
        return bytes(out)

    def _tag(self, j0: bytes, ct: bytes, aad: bytes) -> bytes:
        blob = _pad16(aad) + _pad16(ct) + \
            (8 * len(aad)).to_bytes(8, "big") + \
            (8 * len(ct)).to_bytes(8, "big")
        s = _ghash(self._h, blob).to_bytes(16, "big")
        return bytes(a ^ b for a, b in zip(s, _encrypt_block(self._rk, j0)))

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("only 96-bit nonces are supported")
        aad = aad or b""
        j0 = nonce + b"\x00\x00\x00\x01"
        ks = self._ctr(j0, (len(data) + 15) // 16)
        ct = bytes(p ^ k for p, k in zip(data, ks))
        return ct + self._tag(j0, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("only 96-bit nonces are supported")
        if len(data) < 16:
            raise ValueError("ciphertext shorter than the GCM tag")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        j0 = nonce + b"\x00\x00\x00\x01"
        if not hmac.compare_digest(self._tag(j0, ct, aad), tag):
            raise ValueError("GCM authentication tag mismatch")
        ks = self._ctr(j0, (len(ct) + 15) // 16)
        return bytes(c ^ k for c, k in zip(ct, ks))
