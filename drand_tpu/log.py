"""Structured logging setup.

Counterpart of `log/log.go:16-114` (zap-sugared logger with Named/With
hierarchy, console or JSON encoders): thin configuration over the stdlib
logging tree — `drand_tpu.<node-addr>.<beacon-id>` naming gives the same
hierarchical context the reference builds with Named()
(core/drand_beacon.go:130-131).
"""

from __future__ import annotations

import json
import logging
import sys
import time


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            # log timestamps are wall time by definition (operators
            # correlate them with external systems)
            "ts": round(time.time(), 3),  # lint: disable=no-wall-clock
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def configure(level: str = "info", json_output: bool = False,
              stream=None) -> None:
    """Configure the drand_tpu logger subtree (console or JSON encoder)."""
    root = logging.getLogger("drand_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.handlers.clear()
    h = logging.StreamHandler(stream or sys.stderr)
    if json_output:
        h.setFormatter(JSONFormatter())
    else:
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S"))
    root.addHandler(h)
    root.propagate = False


def named(base: logging.Logger, *parts: str) -> logging.Logger:
    """zap .Named() equivalent: child logger under dotted hierarchy."""
    name = ".".join([base.name, *[p.replace(".", "_") for p in parts if p]])
    return logging.getLogger(name)
