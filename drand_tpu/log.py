"""Structured logging setup.

Counterpart of `log/log.go:16-114` (zap-sugared logger with Named/With
hierarchy, console or JSON encoders): thin configuration over the stdlib
logging tree — `drand_tpu.<node-addr>.<beacon-id>` naming gives the same
hierarchical context the reference builds with Named()
(core/drand_beacon.go:130-131).

Two observability extensions beyond the reference (Dapper-style
trace<->log pivoting, Sigelman et al. 2010):

  - **trace correlation**: every record emitted inside an active
    tracing span (drand_tpu/tracing.py contextvars) carries that span's
    `trace_id`/`span_id` in both the JSON encoder output and the ring
    below, so one trace id pivots between `/debug/spans/{trace_id}` and
    its log lines.  Records may also set the fields explicitly via
    `extra={"trace_id": ...}` (the CLI watch path does).
  - **log ring**: a bounded in-process ring of recent structured
    records (`RING`), served at `/debug/logs?trace_id=...` on the
    metrics port (drand_tpu/metrics.py) — the log half of the pivot.

Module loggers MUST come from :func:`get` (or :func:`named` under a
`get` base) rather than `logging.getLogger(<literal>)` — the tools/lint
`log-hierarchy` rule enforces it — so every line lands under the
`drand_tpu` subtree where the correlating handlers are attached.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from collections import deque

ROOT_NAME = "drand_tpu"


def _trace_context(record: logging.LogRecord) -> tuple[str | None, str | None]:
    """(trace_id, span_id) for a record: explicit `extra` fields win,
    else the emitting task's current tracing span (contextvars)."""
    tid = getattr(record, "trace_id", None)
    sid = getattr(record, "span_id", None)
    if tid is not None:
        return tid, sid
    try:
        from drand_tpu import tracing
        sp = tracing.current()
        if sp is not None:
            return sp.trace_id, sp.span_id
    except Exception:
        pass
    return None, None


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            # log timestamps are wall time by definition (operators
            # correlate them with external systems)
            "ts": round(time.time(), 3),  # lint: disable=no-wall-clock
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        tid, sid = _trace_context(record)
        if tid:
            out["trace_id"] = tid
        if sid:
            out["span_id"] = sid
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


class LogRing:
    """Bounded ring of recent structured log records.

    Thread-safe (records come from the event loop, the crypto worker
    thread, and the store callback pool alike); like the span ring it is
    a debug surface sized in the low thousands, not a log store."""

    def __init__(self, maxlen: int = 4096):
        self._entries: deque[dict] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, entry: dict) -> None:
        with self._lock:
            self._entries.append(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entries(self, trace_id: str | None = None, level: str | None = None,
                limit: int = 200) -> dict:
        """Newest-last records with explicit truncation state, optionally
        filtered to one trace id and/or a minimum level name."""
        with self._lock:
            items = list(self._entries)
        if trace_id is not None:
            items = [e for e in items if e.get("trace_id") == trace_id]
        if level is not None:
            def lvl(name: str) -> int:
                v = logging.getLevelName(name.upper())
                return v if isinstance(v, int) else 0
            floor = lvl(level)
            if floor:
                items = [e for e in items
                         if lvl(e.get("level", "info")) >= floor]
        total = len(items)
        return {"logs": items[-limit:], "total": total,
                "truncated": total > limit}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


RING = LogRing()


class RingHandler(logging.Handler):
    """Feeds :data:`RING` with trace-correlated structured records."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                # wall stamp, same contract as JSONFormatter above
                "ts": round(time.time(), 3),  # lint: disable=no-wall-clock
                "level": record.levelname.lower(),
                "logger": record.name,
                "msg": record.getMessage(),
            }
            tid, sid = _trace_context(record)
            if tid:
                entry["trace_id"] = tid
            if sid:
                entry["span_id"] = sid
            RING.record(entry)
        except Exception:
            pass                # logging must never take the caller down


_ring_handler: RingHandler | None = None


def ensure_ring_handler() -> RingHandler:
    """Attach the ring handler to the drand_tpu subtree (idempotent).
    Called by configure() and by the daemon at start so `/debug/logs`
    works even when the operator skipped log configuration."""
    global _ring_handler
    root = logging.getLogger(ROOT_NAME)
    if _ring_handler is None:
        _ring_handler = RingHandler()
    if _ring_handler not in root.handlers:
        root.addHandler(_ring_handler)
    if root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    return _ring_handler


def configure(level: str = "info", json_output: bool = False,
              stream=None) -> None:
    """Configure the drand_tpu logger subtree (console or JSON encoder)."""
    root = logging.getLogger(ROOT_NAME)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.handlers.clear()
    h = logging.StreamHandler(stream or sys.stderr)
    if json_output:
        h.setFormatter(JSONFormatter())
    else:
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S"))
    root.addHandler(h)
    root.propagate = False
    ensure_ring_handler()


def named(base: logging.Logger, *parts: str) -> logging.Logger:
    """zap .Named() equivalent: child logger under dotted hierarchy."""
    name = ".".join([base.name, *[p.replace(".", "_") for p in parts if p]])
    return logging.getLogger(name)


def get(*parts: str) -> logging.Logger:
    """The project logger seam: a logger under the `drand_tpu` subtree.

    Modules use this instead of `logging.getLogger("drand_tpu.x")` so
    every line flows through the handlers attached above — the JSON
    encoder and the `/debug/logs` ring, both of which stamp the current
    tracing span's ids.  Enforced by the tools/lint `log-hierarchy`
    rule."""
    return named(logging.getLogger(ROOT_NAME), *parts)
