"""The multi-beacon daemon container.

Counterpart of `core/drand_daemon.go`: maps beaconID -> BeaconProcess and
chainHash -> beaconID (:23-44), boots the private gRPC gateway + localhost
control listener (:97-157), and loads beacons from the multibeacon folder
on disk (:248-275).
"""

from __future__ import annotations

import asyncio
import os

from drand_tpu import log as dlog
from drand_tpu.core.config import Config
from drand_tpu.core.process import BeaconProcess
from drand_tpu.core.services import ProtocolService, PublicService
from drand_tpu.key.store import FileStore
from drand_tpu.net.client import PeerClients
from drand_tpu.net.gateway import ControlListener, PrivateGateway

log = dlog.get("core")


class DrandDaemon:
    def __init__(self, config: Config | None = None):
        # fold {folder}/daemon.toml into unset fields (explicit Config
        # fields and CLI flags win; env vars win over both at use sites)
        self.config = (config or Config()).apply_daemon_toml()
        self.processes: dict[str, BeaconProcess] = {}
        self.chain_hashes: dict[str, str] = {}      # hex hash -> beaconID
        # bumped whenever chain_hashes changes: the HTTP server's cached
        # /chains body (ISSUE 14) keys its validity on this counter
        self.chains_version = 0
        self.peers = PeerClients(trust_pem=self._trust_pool(),
                                 timeout_s=60.0)
        # one resilience hub per daemon (like PeerClients): shared retry
        # policy + per-peer circuit breakers on the injected clock
        from drand_tpu.resilience import Resilience
        self.resilience = Resilience(clock=self.config.clock)
        self.protocol_service = ProtocolService(self)
        self.public_service = PublicService(self)
        self.private_gateway: PrivateGateway | None = None  # owner: daemon lifecycle
        self.control_listener: ControlListener | None = None  # owner: daemon lifecycle
        self.http_server = None      # owner: daemon lifecycle
        self.metrics_server = None   # owner: daemon lifecycle
        self.health = None                          # health.Watchdog
        self.consistency = None     # observatory.ConsistencyProber
        self._control_service = None

    def _trust_pool(self) -> bytes | None:
        """Concatenated trusted-peer PEMs for outbound TLS channels
        (net/certs.go CertManager fed from the --certs-dir flag).  None
        means gRPC's system roots — the right default for CA-issued group
        deployments; self-signed groups pass their cert folder.  Our own
        cert joins the pool so a node can dial its own TLS address."""
        cfg = self.config
        paths = list(cfg.trusted_certs)
        if not cfg.insecure and cfg.tls_cert:
            paths.append(cfg.tls_cert)
        if not paths:
            return None
        from drand_tpu.net.certs import CertManager
        cm = CertManager()
        for p in paths:
            if os.path.isdir(p):
                cm.add_folder(p)
            elif os.path.exists(p):
                cm.add(p)
            else:
                log.warning("trusted-certs path %s does not exist", p)
        pem = cm.pool_pem()
        log.info("TLS trust pool: %d certificate(s) from %s",
                 pem.count(b"BEGIN CERTIFICATE"), paths)
        return pem or None

    # -- boot (core/drand_daemon.go:47-157) ---------------------------------

    async def start(self) -> None:
        cfg = self.config
        from drand_tpu.chaos import failpoints as chaos
        if chaos.arm_from_env():
            # loud by design: an armed daemon is a test subject, never a
            # production beacon
            log.warning("chaos fault injection ARMED from DRAND_CHAOS "
                        "(%d rule(s), seed %d)",
                        len(chaos.active().rules), chaos.active().seed)
        from drand_tpu.metrics import MetricsRPC
        self.private_gateway = PrivateGateway(
            cfg.private_listen, self.protocol_service, self.public_service,
            tls_cert=None if cfg.insecure else cfg.tls_cert,
            tls_key=None if cfg.insecure else cfg.tls_key,
            metrics_impl=MetricsRPC(self))
        await self.private_gateway.start()
        from drand_tpu.core.control import ControlService
        self._control_service = ControlService(self)
        self.control_listener = ControlListener(self._control_service,
                                                cfg.control_port)
        await self.control_listener.start()
        if cfg.public_listen:
            from drand_tpu.http.server import PublicHTTPServer
            self.http_server = PublicHTTPServer(self, cfg.public_listen)
            await self.http_server.start()
        if cfg.metrics_port:
            from drand_tpu.metrics import MetricsServer
            self.metrics_server = MetricsServer(self, cfg.metrics_port)
            await self.metrics_server.start()
        # the health judge runs on every daemon (one task sleeping on the
        # injected clock); /debug/logs needs the ring attached even when
        # the operator skipped log configuration
        from drand_tpu import log as dlog
        dlog.ensure_ring_handler()
        from drand_tpu.health import Watchdog
        self.health = Watchdog(self)
        self.health.start()
        # the cross-node consistency prober runs beside the watchdog:
        # same injected clock, same cadence — tip skew, stale peers, and
        # fork detection over the cached node-to-node channels
        # (drand_tpu/observatory/consistency.py)
        from drand_tpu.observatory import ConsistencyProber
        self.consistency = ConsistencyProber(self)
        self.consistency.start()
        # breaker transitions feed the same peer-state surface the
        # connectivity pings do: a tripped breaker marks the peer down,
        # a closed one marks it back (drand_tpu/resilience/breaker.py)
        self.resilience.breakers.on_transition = self._note_breaker
        for bp in self.processes.values():   # instantiated pre-start
            bp.health_sink = self.health
        log.info("daemon up: private=%s control=%d",
                 self.private_addr(), self.control_listener.port)

    def _note_breaker(self, peer: str, state: int) -> None:
        from drand_tpu.resilience import breaker as brk
        health = self.health
        if health is None or state == brk.HALF_OPEN:
            return      # half-open is a probe window, not a verdict
        health.peer_states.note(peer, state == brk.CLOSED)

    def private_addr(self) -> str:
        host = self.config.private_listen.rsplit(":", 1)[0]
        return f"{host}:{self.private_gateway.port}"

    def find_group_node(self, address: str):
        """The group Node for `address` across all beacon processes, or
        None if it is not a member of any of this daemon's groups."""
        for bp in self.processes.values():
            if bp.group is not None:
                for n in bp.group.nodes:
                    if n.address == address:
                        return n
        return None

    async def fetch_peer_metrics(self, address: str) -> bytes:
        """Scrape a group member's Prometheus exposition over the private
        gRPC channel (reference metrics federation,
        net/client_grpc.go:336-371).  Only group members are scraped —
        same restriction as the reference's GroupHandler."""
        from drand_tpu.protogen import drand_pb2
        node = self.find_group_node(address)
        if node is None:
            raise KeyError(f"{address} is not a group member")
        stub = self.peers.metrics(address, tls=getattr(node, "tls", False))
        resp = await stub.Metrics(drand_pb2.MetricsRequest())
        return resp.payload

    async def stop(self) -> None:
        if getattr(self, "consistency", None) is not None:
            self.consistency.stop()
            self.consistency = None
        if self.health is not None:
            self.health.stop()
            self.health = None
        for bp in self.processes.values():
            bp.stop()
        if self.http_server is not None:
            await self.http_server.stop()
            self.http_server = None
        if getattr(self, "metrics_server", None) is not None:
            await self.metrics_server.stop()
            self.metrics_server = None
        if self.control_listener is not None:
            await self.control_listener.stop()
            self.control_listener = None
        if self.private_gateway is not None:
            await self.private_gateway.stop()
            self.private_gateway = None
        await self.peers.close()

    # -- beacon management (LoadBeaconsFromDisk, :248-275) -------------------

    def instantiate(self, beacon_id: str) -> BeaconProcess:
        ks = FileStore(self.config.folder, beacon_id)
        bp = BeaconProcess(beacon_id, self.config, ks, peers=self.peers,
                           resilience=self.resilience)
        # per-daemon SLO sample sink (NOT module-global: in-process
        # multi-node tests run several daemons side by side)
        bp.health_sink = self.health
        bp.on_group_transition = self.note_group_update
        self.processes[beacon_id] = bp
        return bp

    def note_group_update(self, bp: BeaconProcess) -> None:
        """A reshare transitioned `bp` to a new group.  The chain hash is
        UNCHANGED across a reshare (same genesis, same chain key), so
        register_chain_hash alone would never bump chains_version — bump
        it explicitly so anything caching per-version chain metadata
        (HTTP chains listing, relay indexes) refreshes its view of the
        resized group."""
        self.register_chain_hash(bp)
        self.chains_version += 1

    def register_chain_hash(self, bp: BeaconProcess) -> None:
        """Post-DKG: map the chain hash for hash-addressed RPC/HTTP
        (core/drand_daemon.go:216-232)."""
        try:
            h = bp.chain_info().hash().hex()
            if self.chain_hashes.get(h) != bp.beacon_id:
                self.chain_hashes[h] = bp.beacon_id
                self.chains_version += 1
        except Exception:
            pass

    async def load_beacons_from_disk(self) -> list[str]:
        loaded = []
        base = self.config.multibeacon_folder
        if not os.path.isdir(base):
            return loaded
        for beacon_id in sorted(os.listdir(base)):
            if not os.path.isdir(os.path.join(base, beacon_id)):
                continue
            bp = self.instantiate(beacon_id)
            if bp.load():
                self.register_chain_hash(bp)
                await bp.start(catchup=True)
                loaded.append(beacon_id)
            else:
                log.info("beacon %s: keypair only, waiting for DKG",
                         beacon_id)
        return loaded
