"""Core daemon orchestration.

Counterpart of the reference `core/` package: the multi-beacon
`DrandDaemon` (core/drand_daemon.go:23-44), per-chain `BeaconProcess`
(core/drand_beacon.go:28-77), the gRPC service facades that demux by
beacon id (core/drand_daemon_public.go:12-113), DKG setup/broadcast, and
the functional-options config (core/config.go:22-41).
"""

from drand_tpu.core.config import Config  # noqa: F401
from drand_tpu.core.daemon import DrandDaemon  # noqa: F401
from drand_tpu.core.process import BeaconProcess  # noqa: F401
