"""gRPC service facades: demux every RPC by beacon id to the right
BeaconProcess.

Counterpart of `core/drand_daemon_control.go:19-45`,
`core/drand_daemon_public.go:12-113` (daemon-level demux) and
`core/drand_beacon_public.go` / `core/drand_beacon_control.go`
(per-process implementations).
"""

from __future__ import annotations

import asyncio

import grpc

from drand_tpu import log as dlog
from drand_tpu.core import convert
from drand_tpu.net.client import make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("core")


def _meta_beacon_id(request) -> str:
    md = getattr(request, "metadata", None)
    return (md.beaconID if md and md.beaconID else "default")


class _Demux:
    def __init__(self, daemon):
        self.daemon = daemon

    async def _process(self, request, context=None):
        bid = _meta_beacon_id(request)
        bp = self.daemon.processes.get(bid)
        if bp is None:
            md = getattr(request, "metadata", None)
            if md is not None and md.chain_hash:
                bid2 = self.daemon.chain_hashes.get(md.chain_hash.hex())
                bp = self.daemon.processes.get(bid2) if bid2 else None
        if bp is None and context is not None:
            # grpc.aio abort is a coroutine and raises to end the RPC
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no beacon process for id {bid!r}")
        return bp


class ProtocolService(_Demux):
    """Node-to-node Protocol service (protocol.proto:17-36)."""

    async def GetIdentity(self, request, context):
        bp = await self._process(request, context)
        if bp.keypair is None:
            bp.load_keypair()
        ident = bp.keypair.public
        return drand_pb2.IdentityResponse(
            address=ident.address, key=ident.key, tls=ident.tls,
            signature=ident.signature,
            metadata=make_metadata(bp.beacon_id))

    async def PartialBeacon(self, request, context):
        bp = await self._process(request, context)
        # Deadline-budget honoring (drand_tpu/resilience/deadline.py):
        # the sender stamped the round-derived deadline into Metadata;
        # if it already passed in flight, the partial cannot aggregate
        # in time — shed it before it burns a verify slot.
        from drand_tpu import metrics as M
        from drand_tpu.resilience import DeadlineExceededError, deadline
        dl = deadline.from_metadata(getattr(request, "metadata", None),
                                    bp.config.clock)
        if dl is not None and dl.expired:
            M.DEADLINE_SHED.labels("PartialBeacon").inc()
            msg = (f"partial for round {request.round} shed: deadline "
                   f"passed {-dl.remaining():.3f}s ago")
            if context is None:
                raise DeadlineExceededError(msg)
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, msg)
        await bp.process_partial(request.round, request.previous_sig,
                                 request.partial_sig)
        return drand_pb2.Empty()

    async def SyncChain(self, request, context):
        bp = await self._process(request, context)
        # capability negotiation (ISSUE 13): chunk_size > 0 marks a
        # chunk-capable client (reference clients leave field 3 unset =
        # 0 and get the per-beacon stream unchanged); the server caps
        # the chunk at its own wire bound
        from drand_tpu.chain.segment import WIRE_CHUNK_DEFAULT
        chunk = min(int(getattr(request, "chunk_size", 0)),
                    WIRE_CHUNK_DEFAULT)
        async for item in bp.sync_chain_source(request.from_round,
                                               chunk_size=chunk):
            yield convert.item_to_packet(item)

    async def Status(self, request, context):
        bp = await self._process(request, context)
        st = bp.status()
        resp = drand_pb2.StatusResponse()
        resp.beacon.is_running = st["is_running"]
        resp.beacon.is_serving = st["is_running"]
        resp.chain_store.is_empty = st["is_empty"]
        resp.chain_store.last_round = st["last_round"]
        resp.chain_store.length = st["length"]
        return resp

    async def SignalDKGParticipant(self, request, context):
        bp = await self._process(request, context)
        if bp.setup_manager is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no DKG setup in progress")
        await bp.setup_manager.on_signal(request)
        return drand_pb2.Empty()

    async def PushDKGInfo(self, request, context):
        bp = await self._process(request, context)
        if bp.setup_receiver is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "not expecting DKG info")
        await bp.setup_receiver.on_dkg_info(request)
        return drand_pb2.Empty()

    async def BroadcastDKG(self, request, context):
        bp = await self._process(request, context)
        if bp.dkg_board is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no DKG in progress")
        await bp.dkg_board.on_incoming(request.dkg)
        return drand_pb2.Empty()


class PublicService(_Demux):
    """End-user Public service (api.proto:16-33)."""

    async def PublicRand(self, request, context):
        bp = await self._process(request, context)
        store = bp._store
        try:
            beacon = store.get(request.round) if request.round else store.last()
        except Exception:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no beacon for round {request.round}")
        return self._rand_response(bp, beacon)

    @staticmethod
    def _rand_response(bp, beacon):
        return drand_pb2.PublicRandResponse(
            round=beacon.round, signature=beacon.signature,
            previous_signature=beacon.previous_sig,
            randomness=beacon.randomness(),
            metadata=make_metadata(bp.beacon_id,
                                   bp.chain_info().hash()))

    async def PublicRandStream(self, request, context):
        bp = await self._process(request, context)
        q = bp.subscribe_live()
        try:
            # serve backlog from the requested round first
            if request.round:
                for beacon in bp._store.iter_range(request.round):
                    yield self._rand_response(bp, beacon)
            while True:
                beacon = await q.get()
                yield self._rand_response(bp, beacon)
        finally:
            bp.unsubscribe_live(q)

    async def ChainInfo(self, request, context):
        bp = await self._process(request, context)
        return convert.info_to_proto(bp.chain_info())

    async def Home(self, request, context):
        return drand_pb2.HomeResponse(
            status="drand-tpu up and running",
            metadata=make_metadata(_meta_beacon_id(request)))

    async def PrivateRand(self, request, context):
        bp = await self._process(request, context)
        if not bp.config.enable_private_rand:
            # Opt-in only (reference core/drand_beacon_public.go:136-138).
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                "private randomness is disabled")
        from drand_tpu import entropy as ent
        from drand_tpu.crypto import ecies
        try:
            box = ecies.decode(request.request)
            reply = ecies.encrypt_reply(bp.keypair.secret, box,
                                        ent.get_random(None, 32))
        except Exception as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"bad private-rand request: {exc}")
        return drand_pb2.PrivateRandResponse(response=reply)
