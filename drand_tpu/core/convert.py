"""Wire <-> model converters (reference `key/group.go:359-469` proto
round-trip and `chain/convert.go`)."""

from __future__ import annotations

from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.info import Info
from drand_tpu.key.group import Group, Node
from drand_tpu.key.keys import DistPublic, Identity
from drand_tpu.protogen import drand_pb2


def identity_to_proto(ident: Identity) -> drand_pb2.Identity:
    return drand_pb2.Identity(address=ident.address, key=ident.key,
                              tls=ident.tls, signature=ident.signature)


def identity_from_proto(p) -> Identity:
    return Identity(key=p.key, address=p.address, tls=p.tls,
                    signature=p.signature)


def group_to_proto(group: Group) -> drand_pb2.GroupPacket:
    pkt = drand_pb2.GroupPacket(
        threshold=group.threshold,
        period=group.period,
        genesis_time=group.genesis_time,
        transition_time=group.transition_time,
        genesis_seed=group.genesis_seed,
        catchup_period=group.catchup_period,
        schemeID=group.scheme_id,
    )
    pkt.metadata.beaconID = group.beacon_id
    for n in sorted(group.nodes, key=lambda x: x.index):
        pkt.nodes.append(drand_pb2.Node(
            public=identity_to_proto(n), index=n.index))
    if group.public_key is not None:
        pkt.dist_key.extend(group.public_key.coefficients)
    return pkt


def group_from_proto(pkt: drand_pb2.GroupPacket) -> Group:
    nodes = [Node(key=n.public.key, address=n.public.address,
                  tls=n.public.tls, signature=n.public.signature,
                  index=n.index) for n in pkt.nodes]
    public = DistPublic(coefficients=list(pkt.dist_key)) \
        if pkt.dist_key else None
    return Group(
        threshold=pkt.threshold, period=pkt.period, nodes=nodes,
        genesis_time=pkt.genesis_time, genesis_seed=pkt.genesis_seed,
        transition_time=pkt.transition_time,
        catchup_period=pkt.catchup_period,
        scheme_id=pkt.schemeID or "pedersen-bls-chained",
        beacon_id=pkt.metadata.beaconID or "default",
        public_key=public)


def info_to_proto(info: Info) -> drand_pb2.ChainInfoPacket:
    pkt = drand_pb2.ChainInfoPacket(
        public_key=info.public_key, period=info.period,
        genesis_time=info.genesis_time, hash=info.hash(),
        groupHash=info.genesis_seed, schemeID=info.scheme_id)
    pkt.metadata.beaconID = info.beacon_id
    return pkt


def info_from_proto(pkt) -> Info:
    return Info(public_key=pkt.public_key, period=pkt.period,
                genesis_time=pkt.genesis_time, genesis_seed=pkt.groupHash,
                scheme_id=pkt.schemeID or "pedersen-bls-chained",
                beacon_id=pkt.metadata.beaconID or "default")


def beacon_to_packet(b: Beacon) -> drand_pb2.BeaconPacket:
    return drand_pb2.BeaconPacket(previous_sig=b.previous_sig,
                                  round=b.round, signature=b.signature)


def beacon_from_packet(p) -> Beacon:
    return Beacon(round=p.round, signature=p.signature,
                  previous_sig=p.previous_sig)


# -- batched sync wire (ISSUE 13) -----------------------------------------

def packed_to_packet(packed) -> drand_pb2.BeaconPacket:
    """chain.segment.PackedBeacons -> a BeaconPacket carrying a SyncChunk
    (field 5 — reference clients never request chunks so never see one).
    The signature matrix rides as ONE row-major bytes blob."""
    pkt = drand_pb2.BeaconPacket()
    pkt.chunk.start_round = packed.start_round
    pkt.chunk.count = len(packed)
    pkt.chunk.sig_len = packed.sig_len
    pkt.chunk.signatures = packed.sigs.tobytes()
    pkt.chunk.first_previous_sig = packed.first_prev
    pkt.chunk.chained = packed.chained
    return pkt


def item_to_packet(item) -> drand_pb2.BeaconPacket:
    """Serve-side: a sync stream item (Beacon or PackedBeacons) to its
    wire form."""
    from drand_tpu.chain.segment import PackedBeacons
    if isinstance(item, PackedBeacons):
        return packed_to_packet(item)
    return beacon_to_packet(item)


def packet_to_item(pkt):
    """Client-side: BeaconPacket -> Beacon, or PackedBeacons when the
    packet carries a chunk.  Rejects malformed chunk geometry (blob size
    must equal count x sig_len) before any reshape."""
    if pkt.HasField("chunk"):
        import numpy as np

        from drand_tpu.chain.segment import PackedBeacons
        c = pkt.chunk
        if c.count == 0 or c.sig_len == 0 or \
                len(c.signatures) != c.count * c.sig_len:
            raise ValueError(
                f"malformed sync chunk: count={c.count} sig_len={c.sig_len} "
                f"blob={len(c.signatures)}")
        sigs = np.frombuffer(c.signatures, dtype=np.uint8).reshape(
            c.count, c.sig_len)
        return PackedBeacons(start_round=c.start_round, sigs=sigs,
                             first_prev=c.first_previous_sig,
                             chained=c.chained)
    return beacon_from_packet(pkt)
