"""DKG group setup: leader-side collection, follower-side reception.

Counterpart of `core/group_setup.go`: the leader collects participant
identities via SignalDKGParticipant (secret-gated, :169-199), creates the
group once quorum is reached with genesis = now + 3*dkg_timeout + offset
rounded up to the period (:248-273), and pushes it via PushDKGInfo; the
follower fetches the leader key, signals, and waits for the group
(:315-399).  Secrets compare by sha256 (:412-418).
"""

from __future__ import annotations

import asyncio
import hashlib

from drand_tpu import log as dlog
from drand_tpu.core import convert
from drand_tpu.key.group import Group
from drand_tpu.key.keys import Identity
from drand_tpu.net.client import make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("dkg")


def hash_secret(secret: bytes) -> bytes:
    return hashlib.sha256(secret).digest()


def compute_genesis(now: float, period: int, dkg_timeout: float,
                    beacon_offset: int = 0) -> int:
    """Genesis time rule (group_setup.go:248-273): leave room for 3 DKG
    phase timeouts plus an operator offset, rounded UP to a period
    boundary so round times stay aligned."""
    t = int(now + 3 * dkg_timeout + beacon_offset) + 1
    rem = t % period
    if rem:
        t += period - rem
    return t


class SetupManager:
    """Leader side: collect identities, build the group."""

    def __init__(self, leader_identity: Identity, expected: int,
                 threshold: int, period: int, catchup_period: int,
                 scheme_id: str, beacon_id: str, secret: bytes,
                 dkg_timeout: float, clock, beacon_offset: int = 0,
                 previous_group: Group | None = None,
                 transition_time: int = 0):
        self.expected = expected
        self.threshold = threshold
        self.period = period
        self.catchup_period = catchup_period
        self.scheme_id = scheme_id
        self.beacon_id = beacon_id
        self.secret_hash = hash_secret(secret)
        self.dkg_timeout = dkg_timeout
        self.clock = clock
        self.beacon_offset = beacon_offset
        self.previous_group = previous_group
        self.transition_time = transition_time
        self.identities: dict[bytes, Identity] = {}
        self._quorum = asyncio.Event()
        self.add_identity(leader_identity)

    def add_identity(self, ident: Identity) -> None:
        self.identities[ident.key] = ident
        if len(self.identities) >= self.expected:
            try:
                self._quorum.set()
            except RuntimeError:
                pass

    async def on_signal(self, request) -> None:
        """SignalDKGParticipant handler (group_setup.go:169-199)."""
        if hashlib.sha256(request.secret_proof).digest() != self.secret_hash:
            raise ValueError("wrong setup secret")
        ident = convert.identity_from_proto(request.node)
        if not ident.is_valid_signature():
            raise ValueError("invalid identity self-signature")
        if self.previous_group is not None and request.previous_group_hash \
                and request.previous_group_hash != self.previous_group.hash():
            raise ValueError("participant built on wrong previous group")
        self.add_identity(ident)
        log.info("setup: %d/%d participants", len(self.identities),
                 self.expected)

    async def wait_group(self, timeout: float) -> Group:
        await asyncio.wait_for(self._quorum.wait(), timeout)
        return self.create_group()

    def create_group(self) -> Group:
        nodes = Group.sort_nodes(list(self.identities.values()))
        if self.previous_group is not None:
            # resharing keeps the chain: same genesis/seed, new transition
            group = Group(
                threshold=self.threshold, period=self.period, nodes=nodes,
                genesis_time=self.previous_group.genesis_time,
                genesis_seed=self.previous_group.get_genesis_seed(),
                transition_time=self.transition_time or compute_genesis(
                    self.clock.now(), self.period, self.dkg_timeout,
                    self.beacon_offset),
                catchup_period=self.catchup_period,
                scheme_id=self.scheme_id, beacon_id=self.beacon_id)
        else:
            group = Group(
                threshold=self.threshold, period=self.period, nodes=nodes,
                genesis_time=compute_genesis(self.clock.now(), self.period,
                                             self.dkg_timeout,
                                             self.beacon_offset),
                catchup_period=self.catchup_period,
                scheme_id=self.scheme_id, beacon_id=self.beacon_id)
            group.get_genesis_seed()
        return group


class SetupReceiver:
    """Follower side: wait for the leader's PushDKGInfo
    (group_setup.go:315-399)."""

    def __init__(self, secret: bytes, leader_key: bytes):
        self.secret_hash = hash_secret(secret)
        self.leader_key = leader_key
        self.group: Group | None = None
        self.dkg_timeout: float = 0
        self._got = asyncio.Event()

    async def on_dkg_info(self, request) -> None:
        from drand_tpu.crypto import sign as S
        from drand_tpu.crypto.bls12381 import curve as C
        if hashlib.sha256(request.secret_proof).digest() != self.secret_hash:
            raise ValueError("wrong setup secret in DKG info")
        group = convert.group_from_proto(request.new_group)
        # leader signature over the group hash proves provenance
        if request.signature:
            leader_point = C.g1_from_bytes(self.leader_key)
            if not S.bls_verify(leader_point, group.hash(),
                                request.signature):
                raise ValueError("bad leader signature on group")
        self.group = group
        self.dkg_timeout = float(request.dkg_timeout or 10)
        self._got.set()

    async def wait_group(self, timeout: float) -> tuple[Group, float]:
        await asyncio.wait_for(self._got.wait(), timeout)
        return self.group, self.dkg_timeout


async def push_dkg_info(peers, group: Group, leader_pair, secret: bytes,
                        dkg_timeout: float, own_address: str) -> None:
    """Leader: send the group to every participant
    (core/drand_beacon_control.go:955-1041)."""
    from drand_tpu.crypto import sign as S
    signature = S.bls_sign(leader_pair.secret, group.hash())
    pkt = drand_pb2.DKGInfoPacket(
        new_group=convert.group_to_proto(group), secret_proof=secret,
        dkg_timeout=int(dkg_timeout), signature=signature,
        metadata=make_metadata(group.beacon_id))
    sends = []
    for node in group.nodes:
        if node.address == own_address:
            continue

        async def _send(n=node):
            stub = peers.protocol(n.address, n.tls)
            await stub.PushDKGInfo(pkt, timeout=10.0)

        sends.append(_send())
    results = await asyncio.gather(*sends, return_exceptions=True)
    failed = [r for r in results if isinstance(r, Exception)]
    if failed:
        raise RuntimeError(f"PushDKGInfo failed for {len(failed)} nodes: "
                           f"{failed[0]}")
