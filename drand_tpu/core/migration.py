"""File-layout migration: pre-multibeacon folders -> multibeacon/<id>/.

Counterpart of `core/migration/migration.go:17-56`: old deployments kept
key/, groups/ and db/ directly under the base folder; the multibeacon
layout nests them under multibeacon/<beacon id>/.  Idempotent.
"""

from __future__ import annotations

import os
import shutil

from drand_tpu import log as dlog
from drand_tpu.common import DEFAULT_BEACON_ID, MULTIBEACON_FOLDER

log = dlog.get("core")

_OLD_DIRS = ("key", "groups", "db")


def migrate_old_folder_structure(base_folder: str) -> bool:
    """Move a legacy layout into multibeacon/default/.  Returns True when
    a migration happened."""
    old_present = [d for d in _OLD_DIRS
                   if os.path.isdir(os.path.join(base_folder, d))]
    if not old_present:
        return False
    target = os.path.join(base_folder, MULTIBEACON_FOLDER, DEFAULT_BEACON_ID)
    if os.path.isdir(target) and os.listdir(target):
        raise RuntimeError(
            f"both legacy folders ({old_present}) and a populated "
            f"{target} exist; refusing to guess")
    os.makedirs(target, mode=0o700, exist_ok=True)
    for d in old_present:
        src = os.path.join(base_folder, d)
        dst = os.path.join(target, d)
        log.info("migrating %s -> %s", src, dst)
        shutil.move(src, dst)
    return True
