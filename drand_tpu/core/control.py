"""Control service: the localhost operator plane behind the CLI.

Counterpart of `core/drand_beacon_control.go` routed through the daemon
demux (`core/drand_daemon_control.go:19-45`): DKG/reshare initiation,
share/key/group queries, follow/check chain streams, DB backup, shutdown.
"""

from __future__ import annotations

import asyncio

import grpc

from drand_tpu import log as dlog
from drand_tpu.core import convert
from drand_tpu.core.services import _Demux, _meta_beacon_id
from drand_tpu.net.client import make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("core")


class ControlService(_Demux):
    async def PingPong(self, request, context):
        return drand_pb2.Pong(metadata=make_metadata())

    async def ListSchemes(self, request, context):
        from drand_tpu.chain.scheme import list_schemes
        return drand_pb2.ListSchemesResponse(ids=list_schemes(),
                                             metadata=make_metadata())

    async def ListBeaconIDs(self, request, context):
        return drand_pb2.ListBeaconIDsResponse(
            ids=sorted(self.daemon.processes.keys()),
            metadata=make_metadata())

    async def Status(self, request, context):
        bp = await self._process(request, context)
        st = bp.status()
        resp = drand_pb2.StatusResponse()
        resp.beacon.is_running = st["is_running"]
        resp.beacon.is_serving = st["is_running"]
        resp.chain_store.is_empty = st["is_empty"]
        resp.chain_store.last_round = st["last_round"]
        resp.chain_store.length = st["length"]
        return resp

    async def Share(self, request, context):
        bp = await self._process(request, context)
        if bp.share is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "no share")
        return drand_pb2.ShareResponse(
            index=bp.share.share_index(),
            share=bp.share.public().key_bytes(),
            metadata=make_metadata(bp.beacon_id))

    async def PublicKey(self, request, context):
        bp = await self._process(request, context)
        if bp.keypair is None:
            bp.load_keypair()
        return drand_pb2.PublicKeyResponse(
            pubKey=bp.keypair.public.key,
            metadata=make_metadata(bp.beacon_id))

    async def PrivateKey(self, request, context):
        bp = await self._process(request, context)
        if bp.keypair is None:
            bp.load_keypair()
        return drand_pb2.PrivateKeyResponse(
            priKey=bp.keypair.secret.to_bytes(32, "big"),
            metadata=make_metadata(bp.beacon_id))

    async def ChainInfo(self, request, context):
        bp = await self._process(request, context)
        return convert.info_to_proto(bp.chain_info())

    async def GroupFile(self, request, context):
        bp = await self._process(request, context)
        if bp.group is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "no group")
        return convert.group_to_proto(bp.group)

    async def InitDKG(self, request, context):
        bp = await self._process(request, context)
        from drand_tpu.core.dkg_runner import run_init_dkg
        try:
            group = await run_init_dkg(self.daemon, bp, request)
        except Exception as exc:
            log.exception("InitDKG failed")
            if context is None:
                raise
            await context.abort(grpc.StatusCode.INTERNAL, f"dkg failed: {exc}")
        return convert.group_to_proto(group)

    async def InitReshare(self, request, context):
        bp = await self._process(request, context)
        from drand_tpu.core.dkg_runner import run_init_reshare
        try:
            group = await run_init_reshare(self.daemon, bp, request)
        except Exception as exc:
            log.exception("InitReshare failed")
            if context is None:
                raise
            await context.abort(grpc.StatusCode.INTERNAL, f"reshare failed: {exc}")
        return convert.group_to_proto(group)

    async def LoadBeacon(self, request, context):
        bid = _meta_beacon_id(request)
        bp = self.daemon.processes.get(bid) or self.daemon.instantiate(bid)
        if bp._started:
            # already serving (daemon start auto-loads from disk) —
            # re-building the engine under a live handler would wedge it
            return drand_pb2.LoadBeaconResponse(metadata=make_metadata(bid))
        if bp.load():
            self.daemon.register_chain_hash(bp)
            await bp.start(catchup=True)
        return drand_pb2.LoadBeaconResponse(metadata=make_metadata(bid))

    async def StartFollowChain(self, request, context):
        """Observer-mode sync from a list of peers
        (core/drand_beacon_control.go:1055-1165)."""
        from drand_tpu.core.follow import follow_chain
        async for current, target in follow_chain(self.daemon, request):
            yield drand_pb2.SyncProgress(current=current, target=target)

    async def StartCheckChain(self, request, context):
        """Validate + repair the local chain
        (core/drand_beacon_control.go:1168-1257)."""
        bp = await self._process(request, context)
        if bp.sync_manager is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "beacon not loaded")
        loop = asyncio.get_running_loop()
        up_to = request.up_to or None
        faulty = await loop.run_in_executor(
            None, lambda: bp.sync_manager.check_past_beacons(up_to))
        target = request.up_to or bp.status()["last_round"]
        yield drand_pb2.SyncProgress(current=0, target=target)
        if faulty:
            fixed = await bp.sync_manager.correct_past_beacons(faulty)
            log.info("check chain: %d faulty, %d fixed", len(faulty), fixed)
        yield drand_pb2.SyncProgress(current=target, target=target)

    async def BackupDatabase(self, request, context):
        bp = await self._process(request, context)
        if bp._store is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "beacon not loaded")
        bp._store.save_to(request.output_file)
        return drand_pb2.BackupDBResponse(metadata=make_metadata())

    async def RemoteStatus(self, request, context):
        resp = drand_pb2.RemoteStatusResponse()
        bid = _meta_beacon_id(request)
        for addr in request.addresses:
            try:
                stub = self.daemon.peers.protocol(addr.address, addr.tls)
                st = await stub.Status(
                    drand_pb2.StatusRequest(metadata=make_metadata(bid)),
                    timeout=5.0)
                resp.statuses[addr.address].CopyFrom(st)
            except Exception:
                resp.statuses[addr.address].CopyFrom(
                    drand_pb2.StatusResponse())
        return resp

    async def Shutdown(self, request, context):
        async def _stop():
            await asyncio.sleep(0.2)
            await self.daemon.stop()
        asyncio.get_running_loop().create_task(_stop())
        return drand_pb2.ShutdownResponse(metadata=make_metadata())
