"""Daemon configuration (reference `core/config.go:22-41,129-271`
functional options, collapsed into a dataclass — Python's keyword
arguments make the option-function pattern redundant)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from drand_tpu.beacon.clock import Clock, SystemClock

DEFAULT_CONTROL_PORT = 8888
DEFAULT_DKG_TIMEOUT_S = 10.0

# optional daemon-level config file under `folder` (ISSUE 19): the
# reference reads its daemon options from disk; ours folds an
# [objectsync] table (and future daemon tables) into unset Config
# fields at daemon construction.  CLI flags / explicit fields win over
# the file; environment variables win over both (core/process.py).
DAEMON_TOML = "daemon.toml"


@dataclass
class Config:
    folder: str = os.path.expanduser("~/.drand")
    private_listen: str = "0.0.0.0:0"        # node-to-node gRPC bind
    public_listen: str = ""                  # REST bind ("" = disabled)
    control_port: int = DEFAULT_CONTROL_PORT
    tls_cert: str | None = None
    tls_key: str | None = None
    trusted_certs: list[str] = field(default_factory=list)
    dkg_timeout_s: float = DEFAULT_DKG_TIMEOUT_S
    clock: Clock = field(default_factory=SystemClock)
    insecure: bool = True                    # no TLS (tests, local nets)
    metrics_port: int = 0                    # 0 = disabled
    # health watchdog cadence (drand_tpu/health): sleeps on the injected
    # clock, so fake-clock tests drive ticks deterministically
    health_interval_s: float = 5.0
    # ECIES private randomness is opt-in, matching the reference's
    # WithPrivateRandomness (core/config.go:28,262): the RPC leaks node
    # liveness/entropy service by default otherwise.
    enable_private_rand: bool = False
    # opt-in objectsync publishing (ISSUE 18 residual): a non-empty dir
    # enables the per-beacon content-addressed segment publisher
    # (drand_tpu/objectsync) under {dir}/{beacon_id}/.  Settable here,
    # via [objectsync] in {folder}/daemon.toml, or overridden by the
    # DRAND_TPU_OBJECTSYNC_* env vars (strongest).
    objectsync_dir: str = ""
    objectsync_segment: int = 0              # 0 = format default (16384)
    # callbacks (core/config.go dkg/beacon callbacks)
    on_beacon: object = None                 # callable(beacon_id, Beacon)
    on_dkg_done: object = None               # callable(beacon_id, Group)

    @property
    def multibeacon_folder(self) -> str:
        return os.path.join(self.folder, "multibeacon")

    def apply_daemon_toml(self) -> "Config":
        """Fold `{folder}/daemon.toml` into UNSET fields (explicit
        field/CLI values keep precedence over the file).  Missing or
        malformed files are a quiet no-op — the file is an operator
        convenience, never a boot dependency."""
        path = os.path.join(self.folder, DAEMON_TOML)
        try:
            with open(path, encoding="utf-8") as f:
                from drand_tpu import toml_util
                doc = toml_util.loads(f.read())
        except FileNotFoundError:
            return self
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "ignoring unparseable %s", path)
            return self
        osync = doc.get("objectsync", {})
        if isinstance(osync, dict):
            if not self.objectsync_dir and osync.get("dir"):
                self.objectsync_dir = str(osync["dir"])
            if not self.objectsync_segment and osync.get("segment_rounds"):
                self.objectsync_segment = int(osync["segment_rounds"])
        return self
