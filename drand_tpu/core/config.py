"""Daemon configuration (reference `core/config.go:22-41,129-271`
functional options, collapsed into a dataclass — Python's keyword
arguments make the option-function pattern redundant)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from drand_tpu.beacon.clock import Clock, SystemClock

DEFAULT_CONTROL_PORT = 8888
DEFAULT_DKG_TIMEOUT_S = 10.0


@dataclass
class Config:
    folder: str = os.path.expanduser("~/.drand")
    private_listen: str = "0.0.0.0:0"        # node-to-node gRPC bind
    public_listen: str = ""                  # REST bind ("" = disabled)
    control_port: int = DEFAULT_CONTROL_PORT
    tls_cert: str | None = None
    tls_key: str | None = None
    trusted_certs: list[str] = field(default_factory=list)
    dkg_timeout_s: float = DEFAULT_DKG_TIMEOUT_S
    clock: Clock = field(default_factory=SystemClock)
    insecure: bool = True                    # no TLS (tests, local nets)
    metrics_port: int = 0                    # 0 = disabled
    # health watchdog cadence (drand_tpu/health): sleeps on the injected
    # clock, so fake-clock tests drive ticks deterministically
    health_interval_s: float = 5.0
    # ECIES private randomness is opt-in, matching the reference's
    # WithPrivateRandomness (core/config.go:28,262): the RPC leaks node
    # liveness/entropy service by default otherwise.
    enable_private_rand: bool = False
    # callbacks (core/config.go dkg/beacon callbacks)
    on_beacon: object = None                 # callable(beacon_id, Beacon)
    on_dkg_done: object = None               # callable(beacon_id, Group)

    @property
    def multibeacon_folder(self) -> str:
        return os.path.join(self.folder, "multibeacon")
