"""Per-beacon process: one chain's full state and engine.

Counterpart of `core/drand_beacon.go`: keypair + group + share loading
(`Load()`, :106-149), store/handler/sync wiring (`newBeacon`, :220-233,
292-335), DKG result harvesting (`WaitDKG`, :154-216) and reshare
transitions (`transition`, :243-279).
"""

from __future__ import annotations

import asyncio
import os

from drand_tpu import log as dlog
from drand_tpu.beacon.chain import ChainStore, PartialPacket
from drand_tpu.beacon.node import Handler, HandlerConfig
from drand_tpu.beacon.sync_manager import SyncManager, serve_sync_chain
from drand_tpu.chain.scheme import scheme_by_id
from drand_tpu.chain.store import new_chain_store
from drand_tpu.chain.verify import ChainVerifier
from drand_tpu.key.store import FileStore
from drand_tpu.net.client import GrpcBeaconNetwork, PeerClients

log = dlog.get("core")

# Startup integrity scan (ISSUE 15): "1" (default) = full scan, BLS
# through the batched verifier; "structural" = decode/contiguity/linkage
# only; "0"/"off" = skip entirely (bench stores, throwaway nets).
SCAN_ENV = "DRAND_TPU_STARTUP_SCAN"

# Opt-in objectsync publishing (ISSUE 18): a directory path enables a
# per-beacon ObjectPublisher writing content-addressed segment objects
# under {dir}/{beacon_id}/ (serve it statically / rsync it to a bucket).
# SEGMENT overrides the sealed-segment size (default 16384).
OBJECTSYNC_DIR_ENV = "DRAND_TPU_OBJECTSYNC_DIR"
OBJECTSYNC_SEGMENT_ENV = "DRAND_TPU_OBJECTSYNC_SEGMENT"


def objectsync_settings(config) -> tuple[str, int]:
    """Resolve the objectsync opt-in (publisher root dir, segment size).
    Precedence: env var > Config field (which itself folds in
    {folder}/daemon.toml via Config.apply_daemon_toml) > disabled.
    Both orders are pinned by tests/test_objectsync.py."""
    root = os.environ.get(OBJECTSYNC_DIR_ENV, "") or \
        str(getattr(config, "objectsync_dir", "") or "")
    seg = int(os.environ.get(OBJECTSYNC_SEGMENT_ENV, "0") or 0) or \
        int(getattr(config, "objectsync_segment", 0) or 0)
    return root, seg


class BeaconProcess:
    """One beacon chain inside the daemon (core/drand_beacon.go:28-77)."""

    def __init__(self, beacon_id: str, config, key_store: FileStore,
                 peers: PeerClients | None = None, network=None,
                 resilience=None):
        from drand_tpu.resilience import Resilience
        self.beacon_id = beacon_id
        self.config = config
        self.key_store = key_store
        self.peers = peers or PeerClients()
        # per-daemon resilience hub (retry policy + per-peer breakers on
        # the injected clock); standalone processes build their own
        self.resilience = resilience or Resilience(clock=config.clock)
        self.network = network or GrpcBeaconNetwork(
            self.peers, beacon_id, resilience=self.resilience)
        self.keypair = None
        self.group = None
        self.share = None
        self.verifier: ChainVerifier | None = None
        self.chain_store: ChainStore | None = None
        self.handler: Handler | None = None
        self.sync_manager: SyncManager | None = None
        self._store = None
        self.response_cache = None    # built with the engine (ISSUE 14)
        self.object_publisher = None  # owner: lifecycle (start/teardown caller); opt-in objectsync tier (ISSUE 18)
        self.health_sink = None       # daemon's health.Watchdog (SLO feed)
        self._live_queues: list[asyncio.Queue] = []
        self.integrity_report = None  # owner: startup task (last scan IntegrityReport)
        self._pending_repair = None   # (from_round, up_to) re-sync after heal
        self._started = False  # owner: lifecycle (start/stop/transition caller)
        self._engine_closed = False
        self._swap_task: asyncio.Task | None = None
        # DKG state (populated by core.dkg while a ceremony runs)
        self.setup_manager = None     # leader-side collector
        self.setup_receiver = None    # follower-side group waiter
        self.dkg_board = None         # echo-broadcast board
        self.dkg_status = None        # CeremonyStatus: outlives the board
                                      # for /debug/dkg post-mortems
        # fires (bp) after a reshare swapped group state in — the daemon
        # wires its chains_version bump here so hash-addressed routing
        # caches refresh even though the chain hash itself is unchanged
        self.on_group_transition = None

    # -- state loading (core/drand_beacon.go:106-149) -----------------------

    def load_keypair(self):
        self.keypair = self.key_store.load_key_pair()
        return self.keypair

    def load(self) -> bool:
        """Restore group + share from disk; returns True when this process
        can serve its chain."""
        self.load_keypair()
        if not self.key_store.has_group():
            return False
        self.group = self.key_store.load_group()
        if self.key_store.has_share():
            self.share = self.key_store.load_share()
        self._build_engine()
        return True

    def set_group(self, group, share) -> None:
        """Install a fresh DKG result (WaitDKG harvest, :154-216)."""
        self.group = group
        self.share = share
        self.key_store.save_group(group)
        if share is not None:
            self.key_store.save_share(share)
        self._build_engine()

    # -- engine wiring (newBeacon, :292-335) --------------------------------

    def db_path(self) -> str:
        folder = os.path.join(self.config.multibeacon_folder, self.beacon_id,
                              "db")
        os.makedirs(folder, mode=0o700, exist_ok=True)
        return os.path.join(folder, "drand.db")

    def _build_engine(self) -> None:
        self._engine_closed = False
        group = self.group
        self.verifier = ChainVerifier(scheme_by_id(group.scheme_id),
                                      group.public_key.key_bytes(),
                                      beacon_id=self.beacon_id)
        from drand_tpu import metrics as M
        own_addr = self.keypair.public.address if self.keypair else ""
        # chaos identity: the network's `src` and the store's `owner`
        # carry this node's address so seeded faults can target one node
        # of an in-process multi-node net
        self.network.local_addr = own_addr
        self._store = new_chain_store(
            self.db_path(), group, clock=self.config.clock.now,
            on_latency=self._note_latency,
            on_segment=lambda n: M.SYNC_ROUNDS_COMMITTED.labels(
                self.beacon_id).inc(n),
            beacon_id=self.beacon_id, owner=own_addr)
        # encode-once serve fast lane (ISSUE 14): the response cache
        # encodes each committed beacon ONCE, on the committing thread,
        # so the HTTP hot path serves memory bytes with zero store reads.
        # Registered FIRST among the tail callbacks: the cache must be
        # fresh before any watch wake-up marshals a long-poll back onto
        # the loop to read it.
        from drand_tpu.http.response_cache import ResponseCache
        self.response_cache = ResponseCache()
        if hasattr(self._store, "add_tail_callback"):
            self._store.add_tail_callback("serve-cache",
                                          self.response_cache.note_beacon)
        # seed genesis so sync/serve paths have an anchor from the start
        # (reference NewHandler inserts it, chain/beacon/node.go:63-96)
        from drand_tpu.chain.beacon import genesis_beacon
        from drand_tpu.chain.store import BeaconNotFound, StoreError
        try:
            self._store.last()
        except BeaconNotFound:
            self._store.put(genesis_beacon(group.get_genesis_seed()))
        except StoreError:
            # damaged tip row: the store is non-empty (no genesis to
            # seed) and the startup scan quarantines it right after this
            pass
        # warm the cache from the stored tip (restart path: the tail
        # callback only sees commits made after registration)
        try:
            self.response_cache.note_beacon(self._store.last())
        except Exception:
            pass
        self._store.add_callback("live-streams", self._fanout_live)
        self.chain_store = ChainStore(self._store, group, self.share,
                                      self.verifier,
                                      on_beacon=self._on_new_beacon)
        # reshare-in-place (update_group) invalidates the pre-encoded
        # bodies alongside the signer-table epoch bump
        self.chain_store.on_group_update = self.response_cache.invalidate
        conf = HandlerConfig(group=group, share=self.share,
                             public_identity=self.keypair.public,
                             clock=self.config.clock)
        self.handler = Handler(conf, self.chain_store, self.network,
                               self.verifier)
        others = [n for n in group.nodes
                  if n.address != self.keypair.public.address]
        self.sync_manager = SyncManager(
            self._store, group, self.verifier, self.network, others,
            self.config.clock,
            insecure_store=getattr(self._store, "insecure", None),
            resilience=self.resilience)
        self.handler.on_sync_needed = self.sync_manager.request_sync

    def _note_latency(self, round_: int, latency_ms: float) -> None:
        """Per-commit lateness: the shared gauges/histogram, plus this
        daemon's SLO tracker (health/slo.py) when a watchdog is wired."""
        from drand_tpu import metrics as M
        M.observe_beacon(self.beacon_id, round_, latency_ms)
        sink = self.health_sink
        if sink is not None:
            try:
                sink.note_round(self.beacon_id, round_, latency_ms,
                                self.group)
            except Exception:
                pass              # judging must never block committing

    def _on_new_beacon(self, beacon) -> None:
        if self.config.on_beacon is not None:
            from drand_tpu import tracing
            with tracing.span("beacon.fanout", beacon_id=self.beacon_id,
                              round_=beacon.round):
                try:
                    self.config.on_beacon(self.beacon_id, beacon)
                except Exception:
                    pass

    def _fanout_live(self, beacon) -> None:
        """Runs on the CallbackStore WORKER POOL thread: asyncio queues are
        not thread-safe, so the put must marshal onto each subscriber's
        event loop — a bare put_nowait from here appends to the deque but
        can fail to wake the loop-side `await q.get()`, silently starving
        live SyncChain/PublicRandStream watchers."""
        for q, loop in list(self._live_queues):
            try:
                loop.call_soon_threadsafe(self._offer, q, beacon)
            except RuntimeError:
                pass  # subscriber's loop already closed

    @staticmethod
    def _offer(q, beacon) -> None:
        try:
            q.put_nowait(beacon)
        except asyncio.QueueFull:
            pass

    def subscribe_live(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        self._live_queues.append((q, asyncio.get_running_loop()))
        return q

    def unsubscribe_live(self, q) -> None:
        self._live_queues = [(qq, l) for qq, l in self._live_queues
                             if qq is not q]

    # -- lifecycle (StartBeacon, :220-233) ----------------------------------

    async def start(self, catchup: bool = False) -> None:
        if self._started or self.handler is None:
            return
        if self._engine_closed:
            # a stopped engine closed its store/pool; rebuild like the
            # reference's restart path (Load + StartBeacon)
            self._build_engine()
        self._pending_repair = None
        await self._startup_integrity()
        self._started = True
        self.sync_manager.start()
        await self._start_object_publisher()
        if self._pending_repair is not None:
            # heal the rolled-back suffix from peers through the normal
            # chunked sync wire — repair IS a catch-up sync
            self.sync_manager.request_sync(*self._pending_repair)
        if catchup:
            await self.handler.catchup()
        else:
            await self.handler.start()

    async def _startup_integrity(self) -> None:
        """Boot-time store integrity scan + self-heal (ISSUE 15): stream
        the stored chain through the batched verifier before serving it.
        On damage: quarantine + roll back to the verified prefix, then
        REBUILD the engine — ChainStore cached the old (higher) tip at
        construction, and every cached view must re-read the repaired
        store — and queue a re-sync of the rolled-back range."""
        mode = os.environ.get(SCAN_ENV, "1").lower()
        if mode in ("0", "off", "no"):
            return
        base = getattr(self._store, "insecure", None)
        if base is None:
            return
        if await asyncio.to_thread(len, base) <= 1:
            return                  # empty / genesis-only: nothing to judge
        from drand_tpu.chain import recovery
        verifier = None if mode == "structural" else self.verifier
        report, summary = await recovery.startup_recovery(
            base, verifier, beacon_id=self.beacon_id)
        self.integrity_report = report
        if summary is None:
            return
        old_tip = report.tip_round
        self._teardown_engine()
        self._build_engine()
        self._pending_repair = (report.verified_tip + 1, old_tip)

    async def transition(self, new_group, new_share) -> None:
        """Reshare transition (core/drand_beacon.go:243-279): the OLD
        engine keeps producing (and validating old-group partials) until
        the transition round; the engine swap happens just before the
        boundary (the reference swaps the share via a store callback at
        that round, chain/beacon/node.go:228-247)."""
        import asyncio

        from drand_tpu.chain.time import current_round, time_of_round
        t_round = current_round(new_group.transition_time, new_group.period,
                                new_group.genesis_time)
        t_time = time_of_round(new_group.period, new_group.genesis_time,
                               t_round)
        if self.handler is not None and self._started:
            old_handler = self.handler
            old_sync = self.sync_manager
            old_handler.stop_at(t_round - 1)
            # persist the new state now; swap engines at the boundary
            self.key_store.save_group(new_group)
            if new_share is not None:
                self.key_store.save_share(new_share)

            async def swap():
                await self.config.clock.sleep_until(
                    t_time - new_group.period / 2)
                # old-engine teardown is best-effort: a failing close must
                # not prevent the swap below (a dead swap leaves the node on
                # the old group forever, rejecting every new-group partial).
                # keep_chain: the store, ChainStore, and response cache
                # survive into the new engine — a public read racing the
                # swap must never see a closed store (zero-blip, ISSUE 20)
                try:
                    old_handler.stop(keep_chain=True)
                    if old_sync is not None:
                        old_sync.stop()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("%s: old-engine teardown failed",
                                  self.beacon_id)
                # zero-blip path: swap key material + topology in place
                try:
                    self._swap_group_in_place(new_group, new_share)
                    self.sync_manager.start()
                    await self.handler.transition(None)
                    self._note_group_transition()
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception(
                        "%s: in-place reshare swap failed; rebuilding",
                        self.beacon_id)
                # fallback: full engine rebuild, retried once with the
                # half-built engine torn down first
                for attempt in (0, 1):
                    try:
                        self._teardown_engine()
                        self.set_group(new_group, new_share)
                        self.sync_manager.start()
                        await self.handler.transition(None)
                        self._note_group_transition()
                        return
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        log.exception(
                            "%s: reshare engine swap failed (attempt %d)",
                            self.beacon_id, attempt)

            # hold a strong reference: the event loop only weakly references
            # pending tasks, and a GC'd swap wedges the node on the old group
            self._swap_task = asyncio.get_running_loop().create_task(swap())
            return
        # fresh joiner: build now; the handler's wait-round gate holds
        # production until the transition while sync fetches the history
        self.set_group(new_group, new_share)
        self.sync_manager.start()
        self.sync_manager.request_sync(1)
        await self.handler.transition(None)
        self._started = True

    def _swap_group_in_place(self, new_group, new_share) -> None:
        """Zero-blip reshare swap (ISSUE 20): the chain continues across
        the transition, so everything chain-scoped survives — the store
        connection, the pre-encoded ResponseCache, and the ChainStore
        with its live aggregation task.  Only key material and the
        group-topology-derived parts (Handler, SyncManager) rebuild.
        The epoch seams fire together inside `chain_store.update_group`:
        the signer-table epoch bump (backend.update_group) and the serve
        cache invalidation (on_group_update); the daemon's
        chains_version bump rides `_note_group_transition` after the new
        handler is live."""
        self.group = new_group
        self.share = new_share
        self.verifier = ChainVerifier(scheme_by_id(new_group.scheme_id),
                                      new_group.public_key.key_bytes(),
                                      beacon_id=self.beacon_id)
        cs = self.chain_store
        cs.share = new_share
        cs.verifier = self.verifier
        cs.update_group(new_group)
        conf = HandlerConfig(group=new_group, share=new_share,
                             public_identity=self.keypair.public,
                             clock=self.config.clock)
        self.handler = Handler(conf, cs, self.network, self.verifier)
        others = [n for n in new_group.nodes
                  if n.address != self.keypair.public.address]
        self.sync_manager = SyncManager(
            self._store, new_group, self.verifier, self.network, others,
            self.config.clock,
            insecure_store=getattr(self._store, "insecure", None),
            resilience=self.resilience)
        self.handler.on_sync_needed = self.sync_manager.request_sync

    def _note_group_transition(self) -> None:
        """Tell the daemon a reshare landed (chains_version bump for
        hash-addressed routing caches); never fails the swap."""
        hook = self.on_group_transition
        if hook is not None:
            try:
                hook(self)
            except Exception:
                log.exception("%s: group-transition hook failed",
                              self.beacon_id)

    async def _start_object_publisher(self) -> None:
        """Opt-in objectsync tier (ISSUE 18): when the daemon config (or
        the OBJECTSYNC_DIR_ENV override) names a directory, publish this
        chain as content-addressed segment objects under
        {dir}/{beacon_id}/.  Failure to start is logged, never fatal —
        publishing is an export path, not part of the protocol engine."""
        root, seg = objectsync_settings(self.config)
        if not root or self.object_publisher is not None:
            return
        from drand_tpu.objectsync import (FilesystemBackend, ObjectPublisher,
                                          format as ofmt)
        info = self.group.chain_info()
        pub = ObjectPublisher(
            self._store,
            FilesystemBackend(os.path.join(root, self.beacon_id)),
            chain_hash=info.hash(), scheme_id=self.group.scheme_id,
            segment_rounds=seg or ofmt.DEFAULT_SEGMENT_ROUNDS,
            beacon_id=self.beacon_id)
        try:
            await pub.start()
        except Exception:
            log.exception("%s: objectsync publisher failed to start",
                          self.beacon_id)
            return
        self.object_publisher = pub

    def _teardown_engine(self) -> None:
        """Best-effort stop of a (possibly half-built) engine: handler,
        sync manager, object publisher, store connection + callback
        worker pool."""
        pub, self.object_publisher = self.object_publisher, None
        if pub is not None:
            try:
                pub.cancel()
            except Exception:
                pass
        for part, closer in ((self.handler, "stop"),
                             (self.sync_manager, "stop"),
                             (self._store, "close")):
            if part is not None:
                try:
                    getattr(part, closer)()
                except Exception:
                    pass

    def stop(self) -> None:
        if getattr(self, "_swap_task", None) is not None:
            self._swap_task.cancel()
            self._swap_task = None
        self._teardown_engine()
        self._started = False
        self._engine_closed = True

    # -- service entry points ------------------------------------------------

    async def process_partial(self, round_: int, previous_sig: bytes,
                              partial_sig: bytes) -> None:
        if self.handler is None:
            raise RuntimeError("beacon not running")
        from drand_tpu import tracing
        with tracing.span("partial.receive", beacon_id=self.beacon_id,
                          round_=round_):
            await self.handler.process_partial(PartialPacket(
                round=round_, previous_signature=previous_sig,
                partial_sig=partial_sig, beacon_id=self.beacon_id))

    def sync_chain_source(self, from_round: int, follow: bool = True,
                          chunk_size: int = 0):
        """Async generator serving SyncChain (server side).  chunk_size
        > 0 serves the stored backlog as packed chunks (ISSUE 13); the
        live tail is always per-beacon."""
        live = self.subscribe_live() if follow else None
        return serve_sync_chain(self._store, from_round, live_queue=live,
                                chunk_size=chunk_size)

    def chain_info(self):
        if self.group is None:
            raise RuntimeError("no group")
        return self.group.chain_info()

    def status(self) -> dict:
        st = {"is_running": self._started, "last_round": 0, "length": 0,
              "is_empty": True}
        if self._store is not None:
            try:
                last = self._store.last()
                st.update(last_round=last.round, length=len(self._store),
                          is_empty=False)
            except Exception:
                pass
        return st
