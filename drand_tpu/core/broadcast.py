"""Echo broadcast for DKG packets.

Counterpart of `core/broadcast.go`: a best-effort reliable broadcast — on
first sight of a valid packet, re-broadcast it once to every peer (hash-set
dedup, `:29-62,215-237`); packet signatures are verified before acceptance
(`:114-143`); per-peer sends run on bounded queues (`:241-333`).

The board bridges three worlds: the DkgProtocol state machine (in-memory
bundles), the dkg.proto wire form, and the Protocol.BroadcastDKG RPC.
"""

from __future__ import annotations

import asyncio
import hashlib

from drand_tpu import log as dlog
from drand_tpu.crypto import dkg as dkgm
from drand_tpu.net.client import make_metadata
from drand_tpu.protogen import dkg_pb2, drand_pb2

log = dlog.get("dkg")


# -- wire conversion --------------------------------------------------------

def bundle_to_proto(b) -> dkg_pb2.Packet:
    pkt = dkg_pb2.Packet()
    if isinstance(b, dkgm.DealBundle):
        d = pkt.deal
        d.dealer_index = b.dealer_index
        d.commits.extend(b.commits)
        for deal in b.deals:
            d.deals.append(dkg_pb2.Deal(share_index=deal.share_index,
                                        encrypted_share=deal.encrypted_share))
        d.session_id = b.session_id
        d.signature = b.signature
    elif isinstance(b, dkgm.ResponseBundle):
        r = pkt.response
        r.share_index = b.share_index
        for resp in b.responses:
            r.responses.append(dkg_pb2.Response(dealer_index=resp.dealer_index,
                                                status=resp.status))
        r.session_id = b.session_id
        r.signature = b.signature
    elif isinstance(b, dkgm.JustificationBundle):
        j = pkt.justification
        j.dealer_index = b.dealer_index
        for ju in b.justifications:
            j.justifications.append(dkg_pb2.Justification(
                share_index=ju.share_index,
                share=ju.share.to_bytes(32, "big")))
        j.session_id = b.session_id
        j.signature = b.signature
    else:
        raise TypeError(type(b))
    return pkt


def bundle_from_proto(pkt: dkg_pb2.Packet):
    kind = pkt.WhichOneof("Bundle")
    if kind == "deal":
        d = pkt.deal
        return dkgm.DealBundle(
            dealer_index=d.dealer_index, commits=list(d.commits),
            deals=[dkgm.Deal(share_index=x.share_index,
                             encrypted_share=x.encrypted_share)
                   for x in d.deals],
            session_id=d.session_id, signature=d.signature)
    if kind == "response":
        r = pkt.response
        return dkgm.ResponseBundle(
            share_index=r.share_index,
            responses=[dkgm.Response(dealer_index=x.dealer_index,
                                     status=x.status) for x in r.responses],
            session_id=r.session_id, signature=r.signature)
    if kind == "justification":
        j = pkt.justification
        return dkgm.JustificationBundle(
            dealer_index=j.dealer_index,
            justifications=[dkgm.Justification(
                share_index=x.share_index,
                share=int.from_bytes(x.share, "big")) for x in j.justifications],
            session_id=j.session_id, signature=j.signature)
    raise ValueError("empty dkg packet")


class EchoBroadcast:
    """The dkg.Board implementation (core/broadcast.go:72-85).

    Fan-out runs on bounded per-peer queues drained by one sender task
    each (broadcast.go:241-333): at n=128 every accepted packet echoes
    to 127 peers, and the unbounded-gather shape this replaces spawned
    O(n²) concurrent sends per phase.  A full queue DROPS the packet
    for that peer (counted on `drand_queue_dropped_total{queue=
    "dkg_fanout"}` and `self.drops`) — the echo overlay re-delivers
    through other peers, and the phaser's timeout bounds the damage."""

    # one echo send's deadline budget: an echo that has not landed in
    # 10 s is outrun by the protocol's own timeout phase anyway
    SEND_BUDGET_S = 10.0
    # per-peer outbound queue depth: a ceremony phase produces at most
    # n bundles, each echoed once — n=128 fits with headroom; a slower
    # peer sheds echoes rather than ballooning memory
    QUEUE_CAP = 256

    def __init__(self, protocol: "dkgm.DkgProtocol", peers, nodes,
                 own_address: str, beacon_id: str = "default",
                 resilience=None):
        """peers: net.PeerClients; nodes: group identities to fan out to;
        resilience: the daemon's hub — per-peer sends retry with seeded
        backoff inside SEND_BUDGET_S, gated by the peer's breaker."""
        from drand_tpu.resilience import Resilience
        self.protocol = protocol
        self.peers = peers
        self.own_address = own_address
        self.nodes = [n for n in nodes if n.address != own_address]
        self.beacon_id = beacon_id
        self.resilience = resilience or Resilience()
        self._seen: set[bytes] = set()
        self.fresh = asyncio.Event()     # pulses when a new bundle lands
        self._queues: dict[str, asyncio.Queue] = {}
        self._senders: dict[str, asyncio.Task] = {}
        self.drops = 0        # packets shed on full per-peer queues
        self._closed = False

    async def broadcast(self, bundle) -> None:
        """Send our own bundle to every peer (and accept it locally)."""
        self._accept(bundle)
        await self._fanout(bundle_to_proto(bundle))

    async def on_incoming(self, pkt: dkg_pb2.Packet,
                          digest: bytes | None = None) -> None:
        """RPC entry: verify, dedup, deliver, echo once (broadcast.go:29-62).
        `digest` lets an in-process loopback pass the sender-side hash
        instead of re-serializing the packet per receiver."""
        if digest is None:
            digest = hashlib.sha256(pkt.SerializeToString(deterministic=True)
                                    ).digest()
        if digest in self._seen:
            return
        self._seen.add(digest)
        try:
            bundle = bundle_from_proto(pkt)
        except Exception:
            return
        if not self._accept(bundle):
            return
        await self._fanout(pkt)

    def _accept(self, bundle) -> bool:
        p = self.protocol
        if isinstance(bundle, dkgm.DealBundle):
            ok = p.receive_deal_bundle(bundle)
        elif isinstance(bundle, dkgm.ResponseBundle):
            ok = p.receive_response_bundle(bundle)
        else:
            ok = p.receive_justification_bundle(bundle)
        if ok:
            self.fresh.set()
        return ok

    async def _fanout(self, pkt: dkg_pb2.Packet) -> None:
        req = drand_pb2.DKGPacket(dkg=pkt,
                                  metadata=make_metadata(self.beacon_id))
        for node in self.nodes:
            self._enqueue(node, req)

    def _enqueue(self, node, req) -> None:
        if self._closed:
            return
        q = self._queues.get(node.address)
        if q is None:
            q = asyncio.Queue(maxsize=self.QUEUE_CAP)
            self._queues[node.address] = q
            self._senders[node.address] = \
                asyncio.get_running_loop().create_task(self._sender(node, q))
        try:
            q.put_nowait(req)
        except asyncio.QueueFull:
            self.drops += 1
            from drand_tpu import metrics as M
            M.QUEUE_DROPPED.labels("dkg_fanout").inc()
            log.debug("dkg fanout queue to %s full, packet dropped",
                      node.address)

    async def _sender(self, node, q: asyncio.Queue) -> None:
        """Drain one peer's queue; per-peer ordering is preserved and a
        slow peer never blocks the others or the broadcasting task."""
        while True:
            req = await q.get()
            await self._send_one(node, req)

    def close(self) -> None:
        """Stop the per-peer sender tasks; idempotent.  Called when the
        ceremony ends — in-flight echoes the phaser no longer waits on
        are abandoned, same budget the SEND_BUDGET_S deadline enforced."""
        self._closed = True
        for t in self._senders.values():
            t.cancel()
        self._senders.clear()
        self._queues.clear()

    def snapshot(self) -> dict:
        """Operator view for /debug/dkg."""
        return {"peers": len(self.nodes), "seen": len(self._seen),
                "drops": self.drops,
                "queued": {a: q.qsize() for a, q in self._queues.items()
                           if q.qsize()}}

    async def _send_one(self, node, req) -> None:
        from drand_tpu.chaos import failpoints as chaos
        from drand_tpu.resilience import Deadline
        res = self.resilience
        dl = Deadline.after(res.clock, self.SEND_BUDGET_S)
        breaker = res.breakers.get(node.address)

        async def attempt(_n):
            await chaos.failpoint("dkg.fanout", src=self.own_address,
                                  dst=node.address)
            stub = self.peers.protocol(node.address,
                                       getattr(node, "tls", False))
            await stub.BroadcastDKG(req, timeout=dl.timeout())

        try:
            await res.retry.call("dkg.fanout", attempt, peer=node.address,
                                 deadline=dl, breaker=breaker)
        except Exception as exc:
            log.debug("dkg fanout to %s failed: %s", node.address, exc)
