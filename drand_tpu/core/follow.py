"""Observer-mode chain following (StartFollowChain).

Counterpart of `core/drand_beacon_control.go:1055-1165`: fetch + verify the
chain info from the given peers (hash check against metadata when
provided), build a store for the beacon id, and drive the sync manager
against those peers, streaming progress back to the CLI.
"""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu.beacon.sync_manager import SyncManager, SyncRequest
from drand_tpu.chain.scheme import scheme_by_id
from drand_tpu.chain.store import new_chain_store
from drand_tpu.chain.verify import ChainVerifier
from drand_tpu.core import convert
from drand_tpu.key.group import Node
from drand_tpu.net.client import GrpcBeaconNetwork, make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("core")


async def chain_info_from_peers(peers, addresses, tls, beacon_id,
                                expected_hash: bytes | None = None):
    """Query peers for chain info until one answers with a matching hash
    (core/drand_beacon_control.go:1259-1287)."""
    last_exc = None
    for addr in addresses:
        try:
            stub = peers.public(addr, tls)
            pkt = await stub.ChainInfo(
                drand_pb2.ChainInfoRequest(metadata=make_metadata(beacon_id)),
                timeout=10.0)
            info = convert.info_from_proto(pkt)
            if expected_hash and info.hash() != expected_hash:
                raise ValueError(
                    f"chain info hash mismatch from {addr}")
            return info
        except Exception as exc:
            last_exc = exc
    raise RuntimeError(f"no peer returned usable chain info: {last_exc}")


async def follow_chain(daemon, request):
    """Async generator of (current, target) progress pairs."""
    md = request.metadata
    beacon_id = md.beaconID or "default"
    expected = md.chain_hash or None
    addresses = list(request.nodes)
    if not addresses:
        raise RuntimeError("StartFollowChain needs at least one peer")

    info = await chain_info_from_peers(daemon.peers, addresses,
                                       request.is_tls, beacon_id, expected)

    # observer store under multibeacon/<id>/db, like a real process
    bp = daemon.processes.get(beacon_id) or daemon.instantiate(beacon_id)
    import os
    folder = os.path.join(daemon.config.multibeacon_folder, beacon_id, "db")
    os.makedirs(folder, mode=0o700, exist_ok=True)

    class _FollowGroup:
        period = info.period
        genesis_time = info.genesis_time
        scheme_id = info.scheme_id
        threshold = 0

    store = new_chain_store(os.path.join(folder, "drand.db"), _FollowGroup,
                            clock=daemon.config.clock.now,
                            beacon_id=beacon_id)
    verifier = ChainVerifier(scheme_by_id(info.scheme_id), info.public_key,
                             beacon_id=beacon_id)
    nodes = [Node(key=b"", address=a, tls=request.is_tls, index=i)
             for i, a in enumerate(addresses)]
    network = GrpcBeaconNetwork(daemon.peers, beacon_id,
                                resilience=daemon.resilience)
    sm = SyncManager(store, _FollowGroup, verifier, network, nodes,
                     daemon.config.clock,
                     insecure_store=getattr(store, "insecure", None),
                     resilience=daemon.resilience)

    from drand_tpu.chain.time import current_round
    target = request.up_to or current_round(
        daemon.config.clock.now(), info.period, info.genesis_time)

    q: asyncio.Queue = asyncio.Queue(maxsize=64)
    sm.on_progress = lambda cur, tgt: q.put_nowait((cur, target))
    # begin/end (not `with`): the span brackets an async generator's
    # whole life, which ends in the finally below, not a lexical scope
    from drand_tpu import tracing
    sp = tracing.begin_span("sync.follow", beacon_id=beacon_id,
                            target=int(target), peers=len(addresses))
    try:
        # seed genesis so the append chain has an anchor
        from drand_tpu.chain.beacon import genesis_beacon
        from drand_tpu.chain.store import BeaconNotFound
        try:
            store.last()
        except BeaconNotFound:
            store.put(genesis_beacon(info.genesis_seed))
        yield 0, target
        task = asyncio.ensure_future(
            sm.sync(SyncRequest(from_round=1, up_to=request.up_to)))
        while not task.done():
            try:
                yield await asyncio.wait_for(q.get(), 0.5)
            except asyncio.TimeoutError:
                continue
        while not q.empty():
            yield q.get_nowait()
        ok = task.result()
        last = store.last()
        yield last.round, target
        if not ok and last.round < target:
            sp.set(stalled_at=last.round)
            raise RuntimeError(
                f"follow stalled at round {last.round}/{target}")
    except BaseException:
        sp.status = "error"
        raise
    finally:
        sp.end()
        store.close()
