"""DKG orchestration: InitDKG / InitReshare end to end.

Counterpart of `core/drand_beacon_control.go:42-201` (control entry),
`leaderRunSetup`/`setupAutomaticDKG` (:292-347, :546-633), `runDKG`
(:351-422) with the fast-sync phaser (:915-926), and `WaitDKG`
(core/drand_beacon.go:154-216): harvest the result, save share + group,
start the beacon at genesis (or transition for reshares).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass, field

from drand_tpu import log as dlog
from drand_tpu import tracing
from drand_tpu.core import convert
from drand_tpu.core.broadcast import EchoBroadcast
from drand_tpu.core.group_setup import (SetupManager, SetupReceiver,
                                        push_dkg_info)
from drand_tpu.crypto import dkg as dkgm
from drand_tpu.crypto.bls12381 import curve as C
from drand_tpu.key.group import Group
from drand_tpu.key.keys import Share
from drand_tpu.net.client import make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("dkg")


def session_nonce(group: Group) -> bytes:
    """All participants derive the DKG session id from the group they were
    handed, so bundles can't replay across ceremonies."""
    return hashlib.sha256(b"drand-dkg-session" + group.hash()).digest()


def _dkg_nodes(group: Group) -> list[dkgm.DkgNode]:
    return [dkgm.DkgNode(index=n.index, public=C.g1_from_bytes(n.key),
                         address=n.address)
            for n in sorted(group.nodes, key=lambda x: x.index)]


@dataclass
class PhaseOutcome:
    """One ceremony phase's terminal verdict — the phaser's return value
    (was a silent None: a timeout and a complete phase were
    indistinguishable to callers, logs, and metrics)."""
    phase: str           # deal | response | justification
    outcome: str         # complete | timeout
    have: int            # bundles in hand when the phase closed
    want: int            # bundles the fast-sync path was waiting for
    duration_s: float

    def to_dict(self) -> dict:
        return {"phase": self.phase, "outcome": self.outcome,
                "have": self.have, "want": self.want,
                "duration_s": round(self.duration_s, 6)}


@dataclass
class CeremonyStatus:
    """Live + post-mortem view of one ceremony, kept on the
    BeaconProcess (`bp.dkg_status`) for the /debug/dkg route.  States
    mirror the reference's DKG metric values: waiting=1, in_progress=2,
    done=3, failed=4 (metrics.go:20-40); `left` is the reshare exit
    where this node is not in the new group (reported as done)."""
    kind: str            # dkg | reshare
    beacon_id: str
    n_nodes: int = 0
    threshold: int = 0
    state: str = "in_progress"   # in_progress | done | failed | left
    phases: list[PhaseOutcome] = field(default_factory=list)
    qual: list[int] = field(default_factory=list)
    error: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "beacon_id": self.beacon_id,
                "n_nodes": self.n_nodes, "threshold": self.threshold,
                "state": self.state,
                "phases": [p.to_dict() for p in self.phases],
                "qual": list(self.qual), "error": self.error}


async def _wait_phase(board, phase: str, have, want: int, timeout: float,
                      beacon_id: str = "default") -> PhaseOutcome:
    """Fast-sync phaser: advance as soon as all expected bundles arrive,
    else at the phase timeout (drand_beacon_control.go:915-926).  Every
    phase closes with a typed PhaseOutcome and feeds the per-phase
    duration/outcome metrics."""
    from drand_tpu import metrics as M
    loop = asyncio.get_running_loop()
    start = loop.time()
    deadline = start + timeout
    outcome = "complete"
    while have() < want:
        remaining = deadline - loop.time()
        if remaining <= 0:
            outcome = "timeout"
            break
        board.fresh.clear()
        if have() >= want:      # landed between the check and the clear
            break
        try:
            await asyncio.wait_for(board.fresh.wait(), remaining)
        except asyncio.TimeoutError:
            outcome = "timeout"
            break
    res = PhaseOutcome(phase=phase, outcome=outcome, have=have(),
                       want=want, duration_s=loop.time() - start)
    M.DKG_PHASE_SECONDS.labels(beacon_id, phase).observe(res.duration_s)
    M.DKG_PHASE_OUTCOMES.labels(beacon_id, phase, outcome).inc()
    if outcome == "timeout":
        log.warning("dkg %s phase timed out with %d/%d bundles",
                    phase, res.have, res.want)
    return res


def extract_entropy(request):
    """EntropyInfo -> callable n -> bytes, or None (the reference's
    extractEntropy, core/drand_beacon_control.go:1346-1353): the user
    script's output is XOR-mixed with the OS CSPRNG unless userOnly."""
    ei = getattr(request, "entropy", None)
    if ei is None or not ei.script:
        return None
    from drand_tpu import entropy as ent
    reader = ent.ScriptReader(ei.script)
    user_only = bool(ei.userOnly)
    return lambda n: ent.get_random(reader, n, user_only)


async def run_ceremony(bp, group: Group, dkg_timeout: float,
                       old_group: Group | None = None,
                       old_share: Share | None = None, entropy=None):
    """Run one DKG/reshare ceremony over the echo-broadcast overlay.
    Returns the resulting key.Share (None when this node leaves)."""
    nonce = session_nonce(group)
    new_nodes = _dkg_nodes(group)
    if old_group is None:
        conf = dkgm.DkgConfig(longterm=bp.keypair.secret,
                              new_nodes=new_nodes,
                              threshold=group.threshold, nonce=nonce,
                              entropy=entropy)
        n_dealers = len(new_nodes)
    else:
        old_nodes = _dkg_nodes(old_group)
        old_dist = old_group.public_key
        conf = dkgm.DkgConfig(
            longterm=bp.keypair.secret, new_nodes=new_nodes,
            threshold=group.threshold, nonce=nonce,
            old_nodes=old_nodes, old_threshold=old_group.threshold,
            share=dkgm.DistKeyShare(
                commits=[C.g1_from_bytes(c)
                         for c in old_dist.coefficients],
                pri_share=old_share.pri_share) if old_share else None,
            public_coeffs=[C.g1_from_bytes(c)
                           for c in old_dist.coefficients],
            entropy=entropy)
        n_dealers = len(old_nodes)

    from drand_tpu import metrics as M
    kind = "dkg" if old_group is None else "reshare"
    gauge = M.DKG_STATE if old_group is None else M.RESHARE_STATE
    status = CeremonyStatus(kind=kind, beacon_id=bp.beacon_id,
                            n_nodes=len(new_nodes),
                            threshold=group.threshold)
    bp.dkg_status = status
    gauge.labels(bp.beacon_id).set(2)       # in progress

    protocol = dkgm.DkgProtocol(conf)
    board = EchoBroadcast(protocol, bp.peers, group.nodes,
                          bp.keypair.public.address, bp.beacon_id,
                          resilience=bp.resilience)
    if old_group is not None:
        # reshare bundles also fan out to the old group's members
        extra = [n for n in old_group.nodes
                 if all(n.address != m.address for m in board.nodes)
                 and n.address != bp.keypair.public.address]
        board.nodes = board.nodes + extra
    bp.dkg_board = board
    try:
        with tracing.span("dkg.ceremony", beacon_id=bp.beacon_id,
                          kind=kind, n=len(new_nodes),
                          t=group.threshold):
            # phase 1: deals
            with tracing.span("dkg.deal", beacon_id=bp.beacon_id):
                deal = protocol.make_deal_bundle()
                if deal is not None:
                    await board.broadcast(deal)
                status.phases.append(await _wait_phase(
                    board, "deal", lambda: len(protocol.deals), n_dealers,
                    dkg_timeout, bp.beacon_id))
            # phase 2: responses
            with tracing.span("dkg.response", beacon_id=bp.beacon_id):
                resp = protocol.make_response_bundle()
                if resp is not None:
                    await board.broadcast(resp)
                n_holders = len(new_nodes)
                status.phases.append(await _wait_phase(
                    board, "response", lambda: len(protocol.responses),
                    n_holders, dkg_timeout, bp.beacon_id))
            # phase 3: justifications, only when someone complained.
            # Wait ONLY for accused dealers that actually dealt: a dealer
            # that went dark before phase 1 can never justify, and a
            # complaint against it must not cost a full phase timeout —
            # the phase short-circuits once every live accused dealer's
            # justification is in, then qual() renders the verdict.
            complaints = protocol.complaints()
            if complaints:
                with tracing.span("dkg.justification",
                                  beacon_id=bp.beacon_id):
                    jb = protocol.make_justification_bundle()
                    if jb is not None:
                        await board.broadcast(jb)
                    accused_live = {d for d in complaints
                                    if d in protocol.deals}
                    status.phases.append(await _wait_phase(
                        board, "justification",
                        lambda: sum(1 for d in accused_live
                                    if d in protocol.justifs),
                        len(accused_live), dkg_timeout, bp.beacon_id))
            with tracing.span("dkg.finalize", beacon_id=bp.beacon_id):
                status.qual = protocol.qual()
                result = protocol.finalize()
    except BaseException as exc:
        status.state = "failed"
        status.error = repr(exc)
        gauge.labels(bp.beacon_id).set(4)   # failed
        raise
    finally:
        board.close()
        bp.dkg_board = None

    if result is None:
        if old_group is not None and bp.keypair.public.address not in \
                {n.address for n in group.nodes}:
            # leaving the group is a successful reshare outcome
            status.state = "left"
            gauge.labels(bp.beacon_id).set(3)
        else:
            status.state = "failed"
            status.error = "below threshold: qual=%r" % (status.qual,)
            gauge.labels(bp.beacon_id).set(4)
        return None
    status.state = "done"
    gauge.labels(bp.beacon_id).set(3)       # done
    return Share(commits=[C.g1_to_bytes(c) for c in result.commits],
                 pri_share=result.pri_share)


def _harvest(bp, group: Group, share: Share | None) -> Group:
    """WaitDKG tail (core/drand_beacon.go:154-216): attach the distributed
    key to the group, persist, index the chain hash."""
    from drand_tpu.key.keys import DistPublic
    if share is not None:
        group.public_key = DistPublic(list(share.commits))
    bp.set_group(group, share)
    return group


async def run_init_dkg(daemon, bp, request) -> Group:
    """Control InitDKG: leader or follower path picked by request.info."""
    info = request.info
    bp.load_keypair()
    secret = info.secret
    period = request.beacon_period or 30
    scheme_id = request.schemeID or "pedersen-bls-chained"
    timeout = float(info.timeout or daemon.config.dkg_timeout_s)
    from drand_tpu import metrics as M
    M.DKG_STATE.labels(bp.beacon_id).set(1)     # waiting for the group

    if info.leader:
        manager = SetupManager(
            leader_identity=bp.keypair.public, expected=info.nodes,
            threshold=info.threshold, period=period,
            catchup_period=request.catchup_period,
            scheme_id=scheme_id, beacon_id=bp.beacon_id, secret=secret,
            dkg_timeout=timeout, clock=daemon.config.clock,
            beacon_offset=info.beacon_offset)
        bp.setup_manager = manager
        try:
            group = await manager.wait_group(timeout * 6 + 60)
            await push_dkg_info(bp.peers, group, bp.keypair, secret,
                                timeout, bp.keypair.public.address)
        finally:
            bp.setup_manager = None
    else:
        # follower: fetch leader identity, signal, wait for the group
        leader_stub = bp.peers.protocol(info.leader_address, info.leader_tls)
        leader = await leader_stub.GetIdentity(
            drand_pb2.IdentityRequest(metadata=make_metadata(bp.beacon_id)),
            timeout=10.0)
        receiver = SetupReceiver(secret, leader.key)
        bp.setup_receiver = receiver
        try:
            await leader_stub.SignalDKGParticipant(
                drand_pb2.SignalDKGPacket(
                    node=convert.identity_to_proto(bp.keypair.public),
                    secret_proof=secret,
                    metadata=make_metadata(bp.beacon_id)),
                timeout=10.0)
            group, timeout = await receiver.wait_group(timeout * 6 + 60)
        finally:
            bp.setup_receiver = None

    share = await run_ceremony(bp, group, timeout,
                               entropy=extract_entropy(request))
    group = _harvest(bp, group, share)
    daemon.register_chain_hash(bp)
    await bp.start(catchup=False)
    return group


async def run_init_reshare(daemon, bp, request) -> Group:
    """Control InitReshare: same shape, but dealers are the old group and
    the chain continues across the transition."""
    info = request.info
    bp.load_keypair()
    secret = info.secret
    old_group = bp.group
    if old_group is None and request.old.path:
        import pathlib
        old_group = Group.from_toml(await asyncio.to_thread(
            pathlib.Path(request.old.path).read_text))
    if old_group is None:
        raise RuntimeError("reshare needs the previous group")
    timeout = float(info.timeout or daemon.config.dkg_timeout_s)
    from drand_tpu import metrics as M
    M.RESHARE_STATE.labels(bp.beacon_id).set(1)  # waiting for the group

    if info.leader:
        manager = SetupManager(
            leader_identity=bp.keypair.public, expected=info.nodes,
            threshold=info.threshold, period=old_group.period,
            catchup_period=request.catchup_period or
            old_group.catchup_period,
            scheme_id=old_group.scheme_id, beacon_id=bp.beacon_id,
            secret=secret, dkg_timeout=timeout, clock=daemon.config.clock,
            beacon_offset=info.beacon_offset, previous_group=old_group)
        bp.setup_manager = manager
        try:
            group = await manager.wait_group(timeout * 6 + 60)
            group.public_key = old_group.public_key  # same chain key
            await push_dkg_info(bp.peers, group, bp.keypair, secret,
                                timeout, bp.keypair.public.address)
        finally:
            bp.setup_manager = None
    else:
        leader_stub = bp.peers.protocol(info.leader_address, info.leader_tls)
        leader = await leader_stub.GetIdentity(
            drand_pb2.IdentityRequest(metadata=make_metadata(bp.beacon_id)),
            timeout=10.0)
        receiver = SetupReceiver(secret, leader.key)
        bp.setup_receiver = receiver
        try:
            await leader_stub.SignalDKGParticipant(
                drand_pb2.SignalDKGPacket(
                    node=convert.identity_to_proto(bp.keypair.public),
                    secret_proof=secret,
                    previous_group_hash=old_group.hash(),
                    metadata=make_metadata(bp.beacon_id)),
                timeout=10.0)
            group, timeout = await receiver.wait_group(timeout * 6 + 60)
        finally:
            bp.setup_receiver = None

    share = await run_ceremony(bp, group, timeout, old_group=old_group,
                               old_share=bp.share)
    if share is None:
        # we left the group: stop producing after the transition round
        if bp.handler is not None:
            from drand_tpu.chain.time import current_round
            bp.handler.stop_at(current_round(
                group.transition_time, group.period, group.genesis_time) - 1)
        group.public_key = old_group.public_key
        return group
    from drand_tpu.key.keys import DistPublic
    group.public_key = DistPublic(list(share.commits))
    await bp.transition(group, share)   # persists group+share, swaps handler
    daemon.register_chain_hash(bp)
    return group
