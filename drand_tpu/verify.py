"""Chain verification: the batched TPU seam (`chain/verify.go` equivalent).

The reference funnels every beacon check through `chain.Verifier.VerifyBeacon`
(`chain/verify.go:38-45`) — one sha256 digest + one 2-pairing BLS verify per
round, serially (`chain/beacon/sync_manager.go:397-399`,
`client/verify.go:149-169`).  This module provides the batched primitive the
reference lacks: `Verifier.verify_batch(rounds, prev_sigs, sigs) -> bool[B]`,
which digests, hashes-to-curve, and pairing-checks B rounds in one device
call, padded to a small set of static batch shapes so XLA compiles a handful
of programs total.

Digest rules (reference `chain/verify.go:24-32`):
  chained   : msg = sha256(prev_sig || be64(round))
  unchained : msg = sha256(be64(round))
Signature randomness = sha256(sig) (`chain/beacon.go:51-54`).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from drand_tpu.crypto.bls12381.constants import DST_G1, DST_G2
from drand_tpu.ops import bls as BLS
from drand_tpu.ops.sha256 import sha256

# Batch buckets: requests are padded up to the nearest size so only a few
# XLA programs are ever compiled per scheme.  Overridable for tests/small
# deployments where each bucket's compile matters more than padding waste.
import os as _os

_BUCKETS = tuple(
    int(x) for x in _os.environ.get("DRAND_TPU_BUCKETS", "").split(",")
    if x.strip()) or (8, 64, 512, 4096, 16384)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


def rounds_be8(rounds: np.ndarray) -> np.ndarray:
    """uint64 rounds -> [B, 8] big-endian bytes (vectorized)."""
    r = np.asarray(rounds, dtype=">u8")
    return r.view(np.uint8).reshape(-1, 8)


@dataclass(frozen=True)
class SchemeShape:
    """Static wire shape of a scheme (see drand_tpu.chain.scheme registry)."""
    chained: bool          # prev_sig part of the digest
    sig_on_g1: bool        # short-sig variant (pk on G2)
    dst: bytes

    @property
    def sig_len(self):
        return 48 if self.sig_on_g1 else 96


SHAPE_CHAINED = SchemeShape(chained=True, sig_on_g1=False, dst=DST_G2)
SHAPE_UNCHAINED = SchemeShape(chained=False, sig_on_g1=False, dst=DST_G2)
SHAPE_UNCHAINED_G1 = SchemeShape(chained=False, sig_on_g1=True, dst=DST_G1)


class Verifier:
    """Batched beacon verifier for one chain (public key + scheme shape)."""

    def __init__(self, public_key, shape: SchemeShape):
        """public_key: golden-model Jacobian point — G1 for G2-signature
        schemes, G2 for the short-sig scheme."""
        self.shape = shape
        self._pk_golden = public_key
        if shape.sig_on_g1:
            self._pk = BLS._const_g2_affine(public_key)
        else:
            self._pk = BLS._const_g1_affine(public_key)
        self._kernels = {}

    # -- digest construction (host, vectorized numpy) -----------------------

    def messages(self, rounds: np.ndarray, prev_sigs: np.ndarray | None) -> np.ndarray:
        be = rounds_be8(rounds)
        if self.shape.chained:
            assert prev_sigs is not None, "chained scheme needs previous signatures"
            return np.concatenate([prev_sigs, be], axis=1)
        return be

    # -- device kernel, cached per batch size -------------------------------

    def _aot_name(self, n: int) -> str:
        import hashlib

        # The public key is a runtime argument, not a baked constant: one
        # executable per (scheme shape, batch) serves every chain.
        kind = "g1sig" if self.shape.sig_on_g1 else "g2sig"
        link = "ch" if self.shape.chained else "un"
        dst_h = hashlib.sha256(self.shape.dst).hexdigest()[:8]
        return f"verify-{kind}-{link}-{dst_h}-anykey-b{n}"

    def _pk_struct(self):
        """ShapeDtypeStruct pytree matching self._pk (affine limb arrays)."""
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._pk)

    def _msg_len(self) -> int:
        # unchained: 8-byte big-endian round; chained: prev_sig || round
        return self.shape.sig_len + 8 if self.shape.chained else 8

    def _run_fn(self):
        """The pure (msgs, sigs, pk) -> bool[B] verify body.  Exposed so
        the multi-device path (parallel/sharded.py) compiles the SAME
        body with mesh shardings instead of duplicating it."""
        shape = self.shape

        def run(msgs_u8, sig_u8, pk):
            digest = sha256(msgs_u8)
            if shape.sig_on_g1:
                return BLS.verify_g1_sigs(digest, sig_u8, pk, shape.dst)
            return BLS.verify_g2_sigs(digest, sig_u8, pk, shape.dst)

        return run

    def _kernel(self, n: int):
        if n not in self._kernels:
            run = self._run_fn()

            # The full verify graph costs hours of XLA compile per process
            # on this backend (persistent-cache executable reload is
            # unsupported for TPU) — load a serialized AOT executable when
            # one matches this exact program, else jit as usual.  See
            # drand_tpu/aot.py.
            from drand_tpu import aot
            name = self._aot_name(n)
            fn = aot.load(name)
            if fn is None:
                if aot.warming():
                    fn = aot.compile_and_save(
                        name, run,
                        jax.ShapeDtypeStruct((n, self._msg_len()), jnp.uint8),
                        jax.ShapeDtypeStruct((n, self.shape.sig_len),
                                             jnp.uint8),
                        self._pk_struct())
                else:
                    fn = self._compile_miss(name, run, n)
            self._kernels[n] = fn
        return self._kernels[n]

    def _compile_miss(self, name: str, run, n: int):
        """AOT miss outside a warm run: compile eagerly and, when the
        compile was expensive enough to matter (the multi-hour TPU verify
        program — not the small CPU test buckets), persist it so an
        accidental cold run doubles as the warm run."""
        import time as _time

        t0 = _time.monotonic()
        compiled = jax.jit(run).lower(
            jax.ShapeDtypeStruct((n, self._msg_len()), jnp.uint8),
            jax.ShapeDtypeStruct((n, self.shape.sig_len), jnp.uint8),
            self._pk_struct()).compile()
        if _time.monotonic() - t0 > 300.0:
            try:
                from drand_tpu import aot
                aot.save(name, compiled)
            except Exception as e:
                import sys
                print(f"drand_tpu.aot: save after cold compile failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
        return compiled

    def verify_batch_async(self, rounds, sigs: np.ndarray,
                           prev_sigs: np.ndarray | None = None):
        """Dispatch a batched verify WITHOUT blocking on the result.

        Returns a zero-arg callable that blocks and yields bool[B].  The
        host->device transfer and the device program are queued
        asynchronously, so a caller that streams segments (catch-up sync,
        the throughput bench) can overlap segment i+1's transfer with
        segment i's compute — on this backend the per-call dispatch and
        tunnel-transfer overhead is ~0.1-0.2 s, a measurable slice of each
        batch (the reference's serial loop at
        `chain/beacon/sync_manager.go:397-399` has the same hiding
        opportunity and does not use it)."""
        rounds = np.asarray(rounds, dtype=np.uint64)
        n = rounds.shape[0]
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        msgs = self.messages(rounds, prev_sigs)
        m = _bucket(n)
        if m != n:
            pad = m - n
            msgs = np.concatenate([msgs, np.repeat(msgs[-1:], pad, axis=0)])
            sigs = np.concatenate([sigs, np.repeat(sigs[-1:], pad, axis=0)])
        import time as _time
        t0 = _time.perf_counter()
        ok = self._kernel(m)(jnp.asarray(msgs, dtype=jnp.uint8),
                             jnp.asarray(sigs, dtype=jnp.uint8),
                             self._pk)
        dispatch_s = _time.perf_counter() - t0
        done = [False]    # split dispatch/resolve: record exactly once

        def resolve():
            t1 = _time.perf_counter()
            out = np.asarray(ok)[:n]
            if not done[0]:
                done[0] = True
                from drand_tpu.profiling import record_dispatch
                # device wall = async dispatch + the blocking resolve
                # (queue-wait is the gap the CALLER leaves before
                # resolving — that overlap is the pipelining win, not
                # waste, so it is not charged here)
                record_dispatch("verify", n, m,
                                dispatch_s + (_time.perf_counter() - t1))
            return out
        return resolve

    def verify_batch(self, rounds, sigs: np.ndarray,
                     prev_sigs: np.ndarray | None = None) -> np.ndarray:
        """rounds: int array [B]; sigs: [B, sig_len] uint8;
        prev_sigs: [B, 96] uint8 for chained schemes.  Returns bool[B]."""
        return self.verify_batch_async(rounds, sigs, prev_sigs)()

    def verify_chain_segment_async(self, start_round: int, sigs: np.ndarray,
                                   anchor_prev_sig: np.ndarray):
        """Async-dispatch form of verify_chain_segment: returns a zero-arg
        resolver yielding bool[B], with the device program already queued
        — the packed catch-up path resolves it from a worker thread while
        the event loop fetches the next chunk."""
        b = sigs.shape[0]
        anchor_prev_sig = np.asarray(anchor_prev_sig, dtype=np.uint8)
        if b and anchor_prev_sig.shape[0] != sigs.shape[1]:
            # irregular anchor (round 1 links to the 32-byte genesis
            # seed): host-check the first element, batch the rest
            first_ok = self._verify_single_host(
                start_round, bytes(sigs[0]), bytes(anchor_prev_sig))
            rest = self.verify_chain_segment_async(
                start_round + 1, sigs[1:], sigs[0]) if b > 1 else \
                (lambda: np.zeros(0, dtype=bool))
            return lambda: np.concatenate(
                [[first_ok], rest()]).astype(bool)
        rounds = np.arange(start_round, start_round + b, dtype=np.uint64)
        prev = np.concatenate([anchor_prev_sig[None], sigs[:-1]], axis=0)
        return self.verify_batch_async(rounds, sigs, prev)

    def verify_chain_segment(self, start_round: int, sigs: np.ndarray,
                             anchor_prev_sig: np.ndarray) -> np.ndarray:
        """Verify a contiguous chained segment [start_round, start_round+B):
        prev_sig of element i is sigs[i-1] (data, not computation — the
        round dimension is embarrassingly parallel, SURVEY.md §5.7).

        The anchor may have a different length than a signature (round 1
        links to the 32-byte genesis seed); that first element is checked
        on the host golden model and the rest batches on device with
        uniform shapes."""
        return self.verify_chain_segment_async(start_round, sigs,
                                               anchor_prev_sig)()

    def _verify_single_host(self, round_: int, sig: bytes,
                            prev_sig: bytes) -> bool:
        """Golden-model scalar check (used for shape-irregular elements)."""
        import hashlib

        from drand_tpu.crypto import sign as S
        h = hashlib.sha256()
        if self.shape.chained:
            h.update(prev_sig)
        h.update(np.uint64(round_).byteswap().tobytes())
        msg = h.digest()
        try:
            if self.shape.sig_on_g1:
                return S.bls_verify_g1(self._pk_golden, msg, sig)
            return S.bls_verify(self._pk_golden, msg, sig)
        except Exception:
            return False


# jit once at module scope: re-wrapping `jax.jit(sha256)` per call made
# every call a fresh jit object, so the trace cache never hit and each
# invocation re-traced (and on shape change re-compiled) the hash graph
_randomness_jit = jax.jit(sha256)


def randomness(sigs: np.ndarray) -> np.ndarray:
    """Batched beacon randomness: sha256 of each signature."""
    out = _randomness_jit(jnp.asarray(sigs, dtype=jnp.uint8))
    return np.asarray(out)
