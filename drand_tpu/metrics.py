"""Metrics and observability.

Counterpart of `metrics/metrics.go`: beacon gauges (discrepancy latency,
last round, group size/threshold, `:80-91`), DKG/reshare state-machine
gauges (`:20-40`), and an HTTP exposition endpoint.  The reference's four
separate registries collapse into per-metric label dimensions
(beacon_id), which Prometheus handles natively.
"""

from __future__ import annotations


from aiohttp import web
from prometheus_client import (CollectorRegistry, Counter, Gauge, Histogram,
                               generate_latest)

from drand_tpu import log as dlog
log = dlog.get("metrics")

REGISTRY = CollectorRegistry()

# Naming conventions (enforced by tests/test_hygiene.py):
#   - every collector is `drand_`-prefixed
#   - histograms are native-seconds and end in `_seconds`
#   - point-in-time latency/duration gauges end in `_ms`

# beacon metrics (metrics.go:80-91)
BEACON_DISCREPANCY_LATENCY = Gauge(
    "drand_beacon_discrepancy_latency_ms",
    "Difference between a beacon's creation and expected round time (ms)",
    ["beacon_id"], registry=REGISTRY)
LAST_BEACON_ROUND = Gauge(
    "drand_last_beacon_round", "Last locally stored beacon round",
    ["beacon_id"], registry=REGISTRY)
GROUP_SIZE = Gauge("drand_group_size", "Number of group members",
                   ["beacon_id"], registry=REGISTRY)
GROUP_THRESHOLD = Gauge("drand_group_threshold", "Group threshold",
                        ["beacon_id"], registry=REGISTRY)
# DKG state machine (metrics.go:20-40): 0=not started, 1=waiting, 2=in
# progress, 3=done, 4=failed
DKG_STATE = Gauge("drand_dkg_state", "DKG state machine",
                  ["beacon_id"], registry=REGISTRY)
RESHARE_STATE = Gauge("drand_reshare_state", "Reshare state machine",
                      ["beacon_id"], registry=REGISTRY)
# ceremony phase observability (ISSUE 20): the fast-sync phaser closes
# every deal/response/justification phase with a typed outcome —
# duration distribution plus complete-vs-timeout counts per phase.
# Buckets bracket the sub-second in-process ceremonies through the
# multi-minute n=128 phase timeouts.
DKG_PHASE_SECONDS = Histogram(
    "drand_dkg_phase_seconds",
    "Wall duration of one DKG/reshare ceremony phase "
    "(deal/response/justification), from phase open to its typed close",
    ["beacon_id", "phase"], registry=REGISTRY,
    buckets=(.05, .1, .25, .5, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0,
             900.0))
DKG_PHASE_OUTCOMES = Counter(
    "drand_dkg_phase_outcomes_total",
    "Typed ceremony phase closes per phase (complete = every awaited "
    "bundle arrived; timeout = the phaser advanced on the deadline "
    "with bundles missing)",
    ["beacon_id", "phase", "outcome"], registry=REGISTRY)
# verification throughput (TPU path)
VERIFIED_BEACONS = Counter(
    "drand_verified_beacons_total",
    "Beacons verified through the batched device path",
    ["beacon_id"], registry=REGISTRY)
PARTIALS_RECEIVED = Counter(
    "drand_partials_received_total", "Partial signatures accepted",
    ["beacon_id"], registry=REGISTRY)
SYNC_ROUNDS_COMMITTED = Counter(
    "drand_sync_rounds_committed_total",
    "Rounds committed via batched catch-up segments (put_many) — the "
    "latency gauge emits one sample per SEGMENT on this path, so rate "
    "consumers should count rounds here",
    ["beacon_id"], registry=REGISTRY)
# batched sync wire (ISSUE 13): rounds RECEIVED per wire shape ("chunk"
# = packed SyncChunk messages, "single" = per-beacon BeaconPackets — the
# reference-compat fallback), vs rounds COMMITTED above; a chunk-capable
# client talking to a reference peer shows up as wire="single" here.
SYNC_ROUNDS = Counter(
    "drand_sync_rounds_total",
    "Rounds received on the catch-up sync wire, by wire shape",
    ["beacon_id", "wire"], registry=REGISTRY)
SYNC_SEGMENT_SECONDS = Histogram(
    "drand_sync_segment_seconds",
    "Host seconds per catch-up pipeline stage per segment "
    "(fetch/pack/verify/commit)",
    ["stage"], registry=REGISTRY,
    buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5, 5.0, 15.0, 60.0))
# client-side instrumentation (reference client/metric.go +
# client/http/http.go:146-177 instrumented transports): per-source
# request counters/latency and the watch's actual-vs-expected lag
CLIENT_REQUESTS = Counter(
    "drand_client_requests_total",
    "Client SDK requests by source, operation, and outcome",
    ["source", "op", "outcome"], registry=REGISTRY)
CLIENT_REQUEST_LATENCY = Gauge(
    "drand_client_request_latency_ms",
    "Latest client SDK request latency per source and operation (ms)",
    ["source", "op"], registry=REGISTRY)
CLIENT_WATCH_LATENCY = Gauge(
    "drand_client_watch_latency_ms",
    "Delay between a watched round's expected time and its arrival (ms)",
    ["source"], registry=REGISTRY)
# per-stage round-lifecycle latency distributions, fed by every ended
# tracing.Span (drand_tpu/tracing.py).  Buckets span the sub-ms host
# stages (store commit, partial verify) through multi-second deep-sync
# segment verifies.
STAGE_DURATION = Histogram(
    "drand_stage_duration_seconds",
    "Duration of one traced round-lifecycle stage",
    ["stage", "beacon_id"], registry=REGISTRY,
    buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
# health / SLO surface (drand_tpu/health): the judgments layer over the
# raw gauges above — how far behind the clock is this node, how late do
# rounds land, which peers answer pings (reference metrics/metrics.go
# GroupConnectivity + the /health handler's expected-vs-actual check).
BEACON_LAG_ROUNDS = Gauge(
    "drand_beacon_lag_rounds",
    "Rounds the stored chain tip lags the clock-expected round",
    ["beacon_id"], registry=REGISTRY)
ROUND_LATENESS = Histogram(
    "drand_round_lateness_seconds",
    "How late each committed round landed relative to its scheduled time",
    ["beacon_id"], registry=REGISTRY,
    buckets=(.05, .1, .25, .5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0,
             60.0, 120.0))
GROUP_CONNECTIVITY = Gauge(
    "drand_group_connectivity",
    "1 when the peer answered the last health ping, else 0",
    ["peer"], registry=REGISTRY)
PEER_PARTIAL_LAG = Gauge(
    "drand_peer_partial_lag_rounds",
    "Rounds since a valid partial signature was last seen from a peer",
    ["beacon_id", "peer"], registry=REGISTRY)
SLO_ATTAINMENT = Gauge(
    "drand_slo_attainment_ratio",
    "Fraction of windowed rounds published within the SLO threshold",
    ["beacon_id", "window"], registry=REGISTRY)
SLO_BURN_RATE = Gauge(
    "drand_slo_error_budget_burn",
    "Error-budget burn rate over the window (1.0 = spending the budget "
    "exactly as fast as the SLO allows)",
    ["beacon_id", "window"], registry=REGISTRY)
SCRAPE_ERRORS = Counter(
    "drand_metrics_scrape_errors_total",
    "Gauge-refresh failures swallowed during /metrics exposition",
    ["beacon_id"], registry=REGISTRY)
CHAOS_INJECTED = Counter(
    "drand_chaos_injected_total",
    "Faults injected by an armed chaos schedule (drand_tpu/chaos)",
    ["site", "kind"], registry=REGISTRY)
# resilience layer (drand_tpu/resilience): retries, breakers, hedges,
# and server-side deadline shedding — the policies every remote-call
# site now routes through
RETRY_ATTEMPTS = Counter(
    "drand_retry_attempts_total",
    "Retry-policy attempt outcomes per call site "
    "(success/retry/exhausted/fatal/deadline/breaker_open)",
    ["site", "outcome"], registry=REGISTRY)
BREAKER_STATE = Gauge(
    "drand_breaker_state",
    "Per-peer circuit breaker state: 0=closed, 1=open, 2=half-open",
    ["peer"], registry=REGISTRY)
HEDGE_REQUESTS = Counter(
    "drand_hedge_requests_total",
    "Hedged-request launches and wins per call site "
    "(primary/hedged/win)",
    ["site", "outcome"], registry=REGISTRY)
DEADLINE_SHED = Counter(
    "drand_deadline_shed_total",
    "RPCs shed server-side because the caller's deadline budget had "
    "already expired on arrival",
    ["rpc"], registry=REGISTRY)
# serving surface (drand_tpu/resilience/admission.py): the overload-
# protection stage in front of the public HTTP API and the relay
# frontend — inflight per priority class, sheds (503 + Retry-After),
# and the end-to-end handler latency distribution the load harness
# (tools/bench_serve.py) asserts over
SERVE_INFLIGHT = Gauge(
    "drand_serve_inflight",
    "Requests currently inside an admission-guarded handler, per "
    "priority class",
    ["cls"], registry=REGISTRY)
SERVE_SHED = Counter(
    "drand_serve_shed_total",
    "Requests shed by the admission stage (503 + Retry-After) per "
    "route, priority class, and reason (queue_full/queue_timeout)",
    ["route", "cls", "reason"], registry=REGISTRY)
SERVE_LATENCY = Histogram(
    "drand_serve_latency_seconds",
    "Admission-to-response latency of public-surface handlers",
    ["route", "cls"], registry=REGISTRY,
    buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5, 5.0, 10.0, 30.0))
# encode-once serve fast lane (drand_tpu/http/response_cache.py,
# ISSUE 14): whether each public response came from the pre-encoded
# memory body (hit — includes requests coalesced behind an in-flight
# cold load), required the one stampede-guarded store read (miss), or
# skipped the cache entirely (bypass: DRAND_TPU_SERVE_CACHE=0 or a
# process without a cache) — plus the store reads the fast lane exists
# to eliminate, which the serve smoke asserts stay at ZERO for the hot
# latest path under burst
SERVE_CACHE = Counter(
    "drand_serve_cache_total",
    "Serve fast-lane outcomes per route: hit (pre-encoded memory body), "
    "miss (one stampede-guarded store read), bypass (cache disabled or "
    "absent)",
    ["route", "event"], registry=REGISTRY)
SERVE_STORE_READS = Counter(
    "drand_serve_store_reads_total",
    "Store reads performed by public serve handlers — the cost the "
    "encode-once fast lane eliminates (0 per request on the hot latest "
    "path at steady state)",
    ["route"], registry=REGISTRY)
# aggregation hot loop (beacon/crypto_backend + beacon/signer_table):
# the live-wiring visibility the partials bench trajectory is tracked
# against — batch sizes reaching the device path and the signer-key
# table's group epoch (a reshare MUST bump it; a frozen epoch across a
# group transition means stale key material on the verify path)
AGGREGATE_BATCH_SIZE = Gauge(
    "drand_aggregate_batch_size",
    "Partials per backend verify call (the aggregation path's batching "
    "efficiency — 1 means the micro-batcher is not coalescing)",
    registry=REGISTRY)
SIGNER_TABLE_EPOCH = Gauge(
    "drand_signer_table_epoch",
    "Group epoch of the precomputed signer-key table (bumps on "
    "reshare/group transition; stale = wrong-key verification risk)",
    registry=REGISTRY)
LAYOUT_CONVERSIONS = Counter(
    "drand_layout_conversions_total",
    "Trace-time crossings of the device tile-layout boundary "
    "(TileForm.wrap/unwrap in ops/pallas_field.py).  The tile-residency "
    "invariant (ISSUE 9) keeps hot dispatches at entry+exit only; a "
    "growing per-trace count means per-call relayout churn regressed",
    ["kind"], registry=REGISTRY)
QUEUE_DROPPED = Counter(
    "drand_queue_dropped_total",
    "Items dropped because a bounded internal queue was full — visible "
    "shed instead of silent backlog growth (queue = partial_verify / "
    "sync_requests / watch_fanout / dkg_fanout)",
    ["queue"], registry=REGISTRY)
# warm-pipeline orchestrator (drand_tpu/warm): the resumable warm/
# measure chains that replaced the hand-run stage() shell scripts —
# per-stage outcomes (success/skipped/fatal/exhausted + the classify
# verdicts) and wall durations, plus the AOT executable cache's
# compile-vs-load economics the whole subsystem exists to manage
# (fresh-process load must beat the <60 s bar; a compile is the
# hours-long event the checkpoints protect)
WARM_STAGE = Counter(
    "drand_warm_stage_total",
    "Warm-pipeline stage outcomes per pipeline and stage "
    "(success/skipped/transient/fatal/exhausted)",
    ["pipeline", "stage", "outcome"], registry=REGISTRY)
WARM_STAGE_DURATION = Histogram(
    "drand_warm_stage_duration_seconds",
    "Wall duration of one successful warm-pipeline stage subprocess",
    ["pipeline", "stage"], registry=REGISTRY,
    buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 1800.0, 3600.0,
             7200.0, 14400.0))
AOT_COMPILE_SECONDS = Gauge(
    "drand_aot_compile_seconds",
    "Seconds the last XLA compile of this AOT cache entry took "
    "(the cost a warm cache entry avoids)",
    ["name"], registry=REGISTRY)
AOT_LOAD_SECONDS = Gauge(
    "drand_aot_load_seconds",
    "Seconds the last deserialize-and-load of this AOT cache entry "
    "took (must stay far under the <60 s fresh-process bar)",
    ["name"], registry=REGISTRY)
AOT_CACHE = Counter(
    "drand_aot_cache_total",
    "AOT executable-cache events per entry name "
    "(hit/miss/compile/stale/load_error)",
    ["name", "event"], registry=REGISTRY)
# native (C++) host-verify tier (drand_tpu/native, ISSUE 12): the
# single-verify latency axis the rebuilt Montgomery arithmetic targets —
# per-scheme distributions from every wrapped verify call, plus the
# availability gauge the golden-model fallback routing is visible
# through.  Buckets bracket the warm ≤3/≤5 ms targets and the ~175 ms
# golden fallback.
NATIVE_VERIFY = Histogram(
    "drand_native_verify_seconds",
    "Latency of one native-tier BLS verification, by scheme "
    "(g2/g1/partial)",
    ["scheme"], registry=REGISTRY,
    buckets=(.0005, .001, .002, .003, .005, .0075, .01, .025, .05,
             .1, .25))
NATIVE_AVAILABLE = Gauge(
    "drand_native_available",
    "1 when the native C++ BLS tier built and loaded, else 0",
    registry=REGISTRY)
# crash-safe chain storage (drand_tpu/chain/recovery.py, ISSUE 15): the
# startup integrity scan's verdict per beacon and the forensic-quarantine
# volume — the pair the chaos crash-recover / torn-write-heal scenarios
# counter-assert (a clean kill -9 must leave integrity=1 and move ZERO
# rows; injected corruption must move exactly the damaged suffix)
STORE_INTEGRITY = Gauge(
    "drand_store_integrity",
    "Last startup integrity-scan verdict for this beacon's chain store "
    "(1 = clean, 0 = damage found and repair engaged)",
    ["beacon_id"], registry=REGISTRY)
STORE_QUARANTINED = Counter(
    "drand_store_quarantined_total",
    "Rows moved from the live chain to the quarantine sidecar table "
    "(damaged rows + rolled-back suffixes; forensics, never deleted)",
    registry=REGISTRY)
# object sync tier (drand_tpu/objectsync, ISSUE 18): sealed-segment
# publishing progress and how far the published tip trails the chain —
# a stalled publisher (backend down, damaged local row) shows up as a
# growing lag long before any client notices a stale manifest
OBJECTSYNC_PUBLISHED = Counter(
    "drand_objectsync_published_total",
    "Sealed segment objects published to the object-store backend",
    ["beacon_id"], registry=REGISTRY)
OBJECTSYNC_LAG = Gauge(
    "drand_objectsync_lag_rounds",
    "Committed rounds not yet covered by a published segment object",
    ["beacon_id"], registry=REGISTRY)
# dispatch flight recorder (drand_tpu/profiling/dispatch.py, ISSUE 17):
# every batched seam pads work up to a bucket — these are the axes a
# chronically under-filled device shows up on.  Ratio gauges end in
# `_ratio` (unitless 0..1), same contract as the SLO attainment gauge.
DISPATCH_SECONDS = Histogram(
    "drand_dispatch_seconds",
    "Device-wall seconds of one batched dispatch, by seam and padded "
    "bucket size",
    ["seam", "bucket"], registry=REGISTRY,
    buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5, 5.0, 15.0))
DISPATCH_FILL_RATIO = Gauge(
    "drand_dispatch_fill_ratio",
    "Requested-n over chosen-bucket of the LAST dispatch per seam "
    "(1.0 = no padding waste; chronically low = wrong bucket table)",
    ["seam"], registry=REGISTRY)
DISPATCH_PADDING = Counter(
    "drand_dispatch_padding_rounds_total",
    "Padding rounds dispatched to fill buckets — device work spent "
    "verifying repeated filler rows, by seam",
    ["seam"], registry=REGISTRY)
# round-journey timelines (drand_tpu/profiling/journey.py, ISSUE 17):
# per-hop seconds-since-tick of each round's life, collated from the
# tracing spans (tick -> broadcast -> partials -> aggregate -> commit ->
# first served byte)
JOURNEY_SECONDS = Histogram(
    "drand_round_journey_seconds",
    "Seconds from a round's tick to the completion of each journey hop",
    ["hop"], registry=REGISTRY,
    buckets=(.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0,
             15.0, 60.0))
# fleet observatory (drand_tpu/observatory, ISSUE 19): the group-wide
# signer-health plane.  Participation/margin come from the ledger fed by
# the Handler accept seam + the aggregator's recovery hook; the fleet_*
# families come from the cross-node consistency prober.
SIGNER_PARTICIPATION = Gauge(
    "drand_signer_participation_ratio",
    "Fraction of the rolling finalized-round window this signer "
    "contributed a partial to (on-time or late)",
    ["beacon_id", "signer"], registry=REGISTRY)
THRESHOLD_MARGIN = Gauge(
    "drand_threshold_margin",
    "Distinct contributors minus threshold for the newest finalized "
    "round — 0 means one more silent signer halts the chain",
    ["beacon_id"], registry=REGISTRY)
TIME_TO_THRESHOLD = Histogram(
    "drand_time_to_threshold_seconds",
    "Seconds from a round's scheduled time to its threshold recovery",
    ["beacon_id"], registry=REGISTRY,
    buckets=(.05, .1, .25, .5, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0))
FLEET_TIP_SKEW = Gauge(
    "drand_fleet_tip_skew_rounds",
    "Sampled peer chain tip minus local tip (negative = peer behind)",
    ["beacon_id", "peer"], registry=REGISTRY)
FLEET_FORK_DETECTED = Counter(
    "drand_fleet_fork_detected_total",
    "Fork/equivocation detections: a peer served a different signature "
    "for a round this node committed (one count per peer+round)",
    registry=REGISTRY)


def observe_beacon(beacon_id: str, round_: int,
                   latency_ms: float | None = None) -> None:
    LAST_BEACON_ROUND.labels(beacon_id).set(round_)
    if latency_ms is not None:
        BEACON_DISCREPANCY_LATENCY.labels(beacon_id).set(latency_ms)
        # same sample, as a distribution: the point-in-time gauge answers
        # "how late is it NOW", the histogram answers "how late are
        # rounds usually" (the SLO tracker's raw material)
        ROUND_LATENESS.labels(beacon_id).observe(max(latency_ms, 0.0) / 1000.0)


def observe_group(beacon_id: str, size: int, threshold: int) -> None:
    GROUP_SIZE.labels(beacon_id).set(size)
    GROUP_THRESHOLD.labels(beacon_id).set(threshold)


def exposition(daemon) -> bytes:
    """Refresh gauges from live processes, return Prometheus text format."""
    for bid, bp in daemon.processes.items():
        try:
            st = bp.status()
            if not st["is_empty"]:
                LAST_BEACON_ROUND.labels(bid).set(st["last_round"])
            if bp.group is not None:
                observe_group(bid, bp.group.size, bp.group.threshold)
        except Exception as exc:
            # a scrape must still answer with whatever refreshed, but
            # never silently: count it so a flapping process shows up on
            # the dashboard that is hiding it
            SCRAPE_ERRORS.labels(bid).inc()
            log.debug("gauge refresh failed for beacon %s: %s", bid, exc)
    return generate_latest(REGISTRY)


class MetricsRPC:
    """MetricsService gRPC impl on the private gateway: lets any group
    member scrape this node through the authenticated node-to-node channel
    (reference: metrics federation via httpgrpc tunnel,
    net/client_grpc.go:336-371, handler registration at
    core/drand_daemon.go:263-272)."""

    def __init__(self, daemon):
        self.daemon = daemon

    async def Metrics(self, request, context):
        from drand_tpu.protogen import drand_pb2
        return drand_pb2.MetricsResponse(payload=exposition(self.daemon))


# bound on one peer scrape through the gRPC metrics channel: shared by
# the /peers/{addr}/metrics proxy and the /debug/fleet fan-out — a hung
# peer must cost a timeout, never a wedged handler
PEER_SCRAPE_TIMEOUT_S = 10.0


class MetricsServer:
    """Exposition endpoint + pprof-style debug routes on the metrics port
    (metrics.Start + metrics/pprof, reference core/drand_daemon.go:271).
    `/peers/{addr}/metrics` proxies a group member's exposition over the
    node-to-node gRPC channel (the reference's GroupHandler)."""

    def __init__(self, daemon, port: int, host: str = "127.0.0.1"):
        self.daemon = daemon
        self.host = host
        self.port = port  # owner: server start (rebound once to the bound port)
        self.app = web.Application()
        self.app.add_routes([
            web.get("/metrics", self.handle_metrics),
            web.get("/peers/{addr}/metrics", self.handle_peer_metrics),
            web.get("/debug/gc", self.handle_gc),
            web.get("/debug/tasks", self.handle_tasks),
            web.get("/debug/jax-profile", self.handle_jax_profile),
            web.get("/debug/dispatch", self.handle_dispatch),
            web.get("/debug/journey", self.handle_journey),
            web.get("/debug/spans", self.handle_spans),
            web.get("/debug/spans/{trace_id}", self.handle_trace),
            web.get("/debug/logs", self.handle_logs),
            web.get("/debug/slo", self.handle_slo),
            web.get("/debug/health", self.handle_health_snapshot),
            web.get("/debug/resilience", self.handle_resilience),
            web.get("/debug/serve", self.handle_serve),
            web.get("/debug/sync", self.handle_sync),
            web.get("/debug/dkg", self.handle_dkg),
            web.get("/debug/objectsync", self.handle_objectsync),
            web.get("/debug/participation", self.handle_participation),
            web.get("/debug/consistency", self.handle_consistency),
            web.get("/debug/fleet", self.handle_fleet),
            web.get("/debug/store", self.handle_store),
            web.get("/debug/chaos", self.handle_chaos),
            web.post("/debug/chaos/arm", self.handle_chaos_arm),
            web.post("/debug/chaos/disarm", self.handle_chaos_disarm),
        ])
        self._runner: web.AppRunner | None = None

    async def start(self):
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        log.info("metrics on %s:%d", self.host, self.port)

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()

    async def handle_metrics(self, request):
        return web.Response(body=exposition(self.daemon),
                            content_type="text/plain")

    async def handle_peer_metrics(self, request):
        """Scrape a group member through the private gRPC channel.  The
        peer must be a member of one of this daemon's groups (same
        restriction as the reference's GroupHandler).  The scrape is
        deadline-bounded: a hung peer costs the caller a 504, never a
        stuck handler holding an admission slot."""
        import asyncio
        addr = request.match_info["addr"]
        try:
            payload = await asyncio.wait_for(
                self.daemon.fetch_peer_metrics(addr), PEER_SCRAPE_TIMEOUT_S)
        except KeyError:
            return web.Response(status=404, text="unknown peer")
        except asyncio.TimeoutError:
            return web.Response(status=504, text="peer scrape timed out")
        except Exception as exc:
            return web.Response(status=502, text=f"peer scrape failed: {exc}")
        return web.Response(body=payload, content_type="text/plain")

    async def handle_gc(self, request):
        import gc
        return web.json_response({"collected": gc.collect()})

    async def handle_jax_profile(self, request):
        """On-demand JAX profiler capture (the reference's pprof-on-metrics
        pattern, metrics/pprof/pprof.go; ours records an XLA device trace
        instead of Go stacks)."""
        import asyncio
        seconds = min(float(request.query.get("seconds", "2")), 30.0)
        # output path is server-generated: the reference pprof pattern
        # never takes a filesystem path from the request
        out = f"/tmp/drand_tpu_trace_{int(self._now())}"
        from drand_tpu import profiling
        try:
            await asyncio.to_thread(profiling.capture, out, seconds)
        except Exception as exc:
            return web.Response(status=500, text=f"profile failed: {exc}")
        # full manifest, not just the path: the operator pulling a trace
        # wants to know whether the capture actually wrote device data
        # (an empty dir means the profiler found nothing to record)
        man = profiling.manifest(out)
        man["seconds"] = seconds
        try:
            import jax
            man["device_platform"] = jax.default_backend()
        except Exception:
            man["device_platform"] = None
        return web.json_response(man)

    @staticmethod
    def _now():
        import time
        # wall-clock stamp in the trace dir name, so operators can match
        # a capture to their incident timeline
        return time.time()  # lint: disable=no-wall-clock

    async def handle_tasks(self, request):
        import asyncio
        tasks = [str(t.get_coro()) for t in asyncio.all_tasks()]
        return web.json_response({"count": len(tasks), "tasks": tasks[:100],
                                  "truncated": len(tasks) > 100})

    # -- perf-observability routes (drand_tpu/profiling) ------------------

    async def handle_dispatch(self, request):
        """Dispatch flight recorder snapshot: per-seam fill/padding/
        amortized-cost totals plus the recent per-dispatch ring
        (drand_tpu/profiling/dispatch.py)."""
        from drand_tpu.profiling import dispatch
        try:
            limit = int(request.query.get("limit", "50"))
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        if not (1 <= limit <= 500):
            return web.Response(status=400, text="limit must be 1..500")
        return web.json_response(dispatch.DISPATCH.snapshot(limit=limit))

    async def handle_journey(self, request):
        """Round-journey snapshot: recent per-round hop timelines plus
        rolling p50/p99/p999 per hop (drand_tpu/profiling/journey.py)."""
        from drand_tpu.profiling import journey
        try:
            limit = int(request.query.get("limit", "20"))
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        if not (1 <= limit <= 200):
            return web.Response(status=400, text="limit must be 1..200")
        return web.json_response(journey.JOURNEY.snapshot(limit=limit))

    # -- span routes (drand_tpu/tracing.py ring buffer) ------------------

    async def handle_spans(self, request):
        """Newest-first trace summaries with bounded pagination."""
        from drand_tpu import tracing
        try:
            limit = int(request.query.get("limit", "50"))
            offset = int(request.query.get("offset", "0"))
        except ValueError:
            return web.Response(status=400,
                                text="limit/offset must be integers")
        if not (1 <= limit <= 500) or offset < 0:
            return web.Response(
                status=400, text="limit must be 1..500, offset >= 0")
        return web.json_response(tracing.RECORDER.traces(limit, offset))

    async def handle_trace(self, request):
        from drand_tpu import tracing
        trace_id = request.match_info["trace_id"]
        spans = tracing.RECORDER.trace(trace_id)
        if not spans:
            return web.Response(status=404,
                                text=f"no spans for trace {trace_id}")
        return web.json_response({
            "trace_id": trace_id,
            "spans": [s.to_dict() for s in spans]})

    # -- health / SLO / log-pivot routes (drand_tpu/health, drand_tpu/log) --

    async def handle_logs(self, request):
        """Recent structured log records from the in-process ring
        (drand_tpu/log.py).  `?trace_id=<hex>` pivots one trace between
        `/debug/spans/{trace_id}` and its log lines; `?level=` filters
        by minimum level, `?limit=` bounds the page (1..1000)."""
        from drand_tpu import log as dlog
        try:
            limit = int(request.query.get("limit", "200"))
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        if not (1 <= limit <= 1000):
            return web.Response(status=400, text="limit must be 1..1000")
        return web.json_response(dlog.RING.entries(
            trace_id=request.query.get("trace_id"),
            level=request.query.get("level"), limit=limit))

    async def handle_slo(self, request):
        """Rolling-window SLO attainment and error-budget burn per
        beacon (health/slo.py), fed by the daemon's watchdog."""
        health = getattr(self.daemon, "health", None)
        if health is None:
            return web.Response(status=404,
                                text="health watchdog not running")
        return web.json_response(health.slo_snapshot())

    async def handle_health_snapshot(self, request):
        """The watchdog's full operator view: per-beacon verdicts,
        stall flags, peer connectivity, SLO windows."""
        health = getattr(self.daemon, "health", None)
        if health is None:
            return web.Response(status=404,
                                text="health watchdog not running")
        return web.json_response(health.snapshot())

    async def handle_resilience(self, request):
        """The resilience hub's operator view: per-peer breaker states
        plus the tail of the retry/breaker decision log
        (drand_tpu/resilience)."""
        hub = getattr(self.daemon, "resilience", None)
        if hub is None:
            return web.Response(status=404,
                                text="resilience hub not wired")
        return web.json_response(hub.snapshot())

    async def handle_serve(self, request):
        """The public HTTP server's admission-stage snapshot: per-class
        inflight/waiting/shed counters (drand_tpu/resilience/admission)."""
        http = getattr(self.daemon, "http_server", None)
        adm = getattr(http, "admission", None)
        if adm is None:
            return web.Response(status=404,
                                text="public HTTP server not running")
        return web.json_response(adm.snapshot())

    async def handle_sync(self, request):
        """Catch-up sync operator view (ISSUE 13): per-beacon pipeline
        snapshot — current peer, adaptive chunk target, pipeline depth,
        backlog estimate, cumulative per-stage host seconds."""
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        out = {}
        for beacon_id, bp in processes.items():
            sm = getattr(bp, "sync_manager", None)
            if sm is not None:
                out[beacon_id] = sm.snapshot()
        return web.json_response(out)

    async def handle_dkg(self, request):
        """Ceremony operator view (ISSUE 20): per-beacon CeremonyStatus
        (live phases + post-mortem of the last ceremony) plus, while a
        ceremony runs, the echo-broadcast board's queue/drop snapshot
        (core/dkg_runner.CeremonyStatus, core/broadcast.EchoBroadcast)."""
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        out = {}
        for beacon_id, bp in processes.items():
            st = getattr(bp, "dkg_status", None)
            entry = {"status": st.to_dict() if st is not None else None}
            board = getattr(bp, "dkg_board", None)
            if board is not None:
                entry["board"] = board.snapshot()
            out[beacon_id] = entry
        return web.json_response(out)

    async def handle_objectsync(self, request):
        """Object-sync publisher operator view (ISSUE 18): per-beacon
        publisher snapshot — backend, published tip vs store tip, lag,
        last error (drand_tpu/objectsync/publisher.py)."""
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        out = {}
        for beacon_id, bp in processes.items():
            pub = getattr(bp, "object_publisher", None)
            if pub is not None:
                out[beacon_id] = pub.snapshot()
        return web.json_response(out)

    # -- fleet observatory routes (drand_tpu/observatory, ISSUE 19) --------

    async def handle_participation(self, request):
        """Signer participation ledger operator view: per-beacon rolling
        contributor bitmaps, threshold margins, time-to-threshold, and
        per-signer participation rates
        (drand_tpu/observatory/participation.py)."""
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        try:
            limit = int(request.query.get("limit", "32"))
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        if not (1 <= limit <= 512):
            return web.Response(status=400, text="limit must be 1..512")
        out = {}
        for beacon_id, bp in processes.items():
            ledger = getattr(getattr(bp, "handler", None), "ledger", None)
            if ledger is not None:
                out[beacon_id] = ledger.snapshot(limit=limit)
        return web.json_response(out)

    async def handle_consistency(self, request):
        """Cross-node consistency prober operator view: per-peer tip
        skew, stale flags, and the typed fork-report ring
        (drand_tpu/observatory/consistency.py)."""
        prober = getattr(self.daemon, "consistency", None)
        if prober is None:
            return web.Response(status=404,
                                text="consistency prober not running")
        return web.json_response(prober.snapshot())

    async def handle_fleet(self, request):
        """Group-wide metric federation: every peer's exposition scraped
        through the gRPC metrics channel and folded into one typed
        FleetSnapshot (drand_tpu/observatory/fleet.py)."""
        from drand_tpu.observatory import fleet
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        snap = await fleet.collect_fleet(self.daemon,
                                         timeout_s=PEER_SCRAPE_TIMEOUT_S)
        return web.json_response(snap.to_dict())

    async def handle_store(self, request):
        """Chain-store durability operator view (ISSUE 15): per-beacon
        db path, tip, quarantine volume, and the last startup
        integrity-scan report (drand_tpu/chain/recovery.py)."""
        import asyncio
        processes = getattr(self.daemon, "processes", None)
        if not processes:
            return web.Response(status=404, text="no beacon processes")
        out = {}
        for beacon_id, bp in processes.items():
            entry = {"db_path": bp.db_path(), "tip": -1, "rows": 0,
                     "quarantined": 0, "integrity_report": None}
            base = getattr(bp._store, "insecure", None) \
                if bp._store is not None else None
            if base is not None:
                def snap(b=base):
                    try:
                        tip = b.last().round
                    except Exception:
                        tip = -1
                    return tip, len(b), len(b.quarantined())
                try:
                    entry["tip"], entry["rows"], entry["quarantined"] = \
                        await asyncio.to_thread(snap)
                except Exception:
                    pass
            rep = getattr(bp, "integrity_report", None)
            if rep is not None:
                entry["integrity_report"] = rep.to_dict()
            out[beacon_id] = entry
        return web.json_response(out)

    # -- chaos control routes (drand_tpu/chaos/failpoints.py) -------------
    # The metrics server binds 127.0.0.1 by default: these are the
    # localhost control seam for arming/inspecting fault injection on a
    # live (test) daemon — the reference's gofail HTTP endpoint analog.

    async def handle_chaos(self, request):
        from drand_tpu.chaos import failpoints as chaos
        sched = chaos.active()
        out = {"armed": sched is not None,
               "sites": dict(chaos.SITES)}
        if sched is not None:
            out["schedule"] = sched.to_spec()
            out["injections"] = sched.injection_log()[-200:]
        return web.json_response(out)

    async def handle_chaos_arm(self, request):
        from drand_tpu.chaos import failpoints as chaos
        try:
            spec = await request.json()
            chaos.arm(chaos.Schedule.from_spec(spec))
        except Exception as exc:
            return web.Response(status=400, text=f"bad chaos spec: {exc}")
        log.warning("chaos fault injection ARMED via /debug/chaos/arm")
        return web.json_response({"armed": True,
                                  "rules": len(chaos.active().rules)})

    async def handle_chaos_disarm(self, request):
        from drand_tpu.chaos import failpoints as chaos
        chaos.disarm()
        return web.json_response({"armed": False})
