"""Tracing / profiling hooks (SURVEY §5.1).

The reference mounts net/http/pprof on its metrics mux
(`metrics/pprof/pprof.go:12-23`, wired at `core/drand_daemon.go:271`).
The TPU-native equivalent is the JAX profiler: XLA device traces (op
timelines, HBM usage, fusion boundaries) captured on demand, plus the
same "debug handler on the metrics port" pattern (drand_tpu.metrics
mounts `/debug/jax-profile`).

Usage:
  - programmatic: `with profiling.trace("/tmp/trace"): run_kernels()`
  - one-shot:     `profiling.capture("/tmp/trace", seconds=2.0)`
  - daemon:       GET /debug/jax-profile?seconds=2  on the metrics port
  - perf work:    `python -m drand_tpu.profiling out_dir -- cmd ...` is
                  not provided; use tools/profile_verify.py instead.

Traces are TensorBoard-compatible (`xplane.pb` under the out dir); on the
axon backend only device traces are trustworthy — host-side wall times
include the remote tunnel (~120 ms/call).
"""

from __future__ import annotations

import contextlib
import os
import time


@contextlib.contextmanager
def trace(out_dir: str):
    """Capture a JAX profiler trace around a block."""
    import jax
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)
    try:
        yield out_dir
    finally:
        jax.profiler.stop_trace()


def capture(out_dir: str, seconds: float = 2.0) -> str:
    """Record whatever device activity happens in the next `seconds`."""
    with trace(out_dir):
        time.sleep(seconds)
    return out_dir


def annotate(name: str):
    """Named span visible in the trace timeline (TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
