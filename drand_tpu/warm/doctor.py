"""`warm doctor`: environment preflight before spending hours.

Round 7's chain ran its whole measurement protocol against a container
with **no reachable TPU** — jax's backend init silently fell back to
CPU after a ~60 s stall, and every "device" number was quietly a CPU
number (STATUS.md round-7 deviation).  The doctor makes that class of
failure cost seconds, not hours: each check prints a one-line verdict,
any FAIL exits non-zero, and `warm run`/`warm resume` refuse to start a
chain until the doctor passes (override: --no-doctor).

Checks:

  - **backend** — a *subprocess* imports jax and reports
    platform/device count/init seconds.  Run in a subprocess because
    the pathological case is exactly an import that stalls for 60 s (or
    hangs): the orchestrator itself must never pay it.  Verdicts: FAIL
    when the env asks for a device platform but init fell back to CPU;
    FAIL when init exceeds the fallback threshold; FAIL on
    timeout/import error.
  - **aot-dir** — the AOT executable cache directory exists/is
    writable, plus an entry count (an empty cache before a measure run
    means hours of compiles: say so up front).
  - **workdir** — the pipeline workdir (warm_logs) is writable; the
    checkpoint file must be able to land.
  - **fixtures** — the files a bench stage needs exist in this
    checkout (bench.py, __graft_entry__.py, the fixtures module).
  - **compile-cache** — the persistent XLA compilation-cache probe,
    folded in from the former ``tools/cache_probe.py``: two fresh
    subprocesses jit the same small program against the configured
    cache dir; the second must find a populated cache.  Skipped by
    ``fast=True`` (it costs two interpreter+jax starts).

Every probe subprocess is bounded by a timeout — a doctor that hangs
is a doctor that failed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass

from drand_tpu.warm.spec import repo_root

BACKEND_TIMEOUT_S = 150.0       # hard bound on the backend probe
FALLBACK_THRESHOLD_S = 45.0     # init slower than this = the 60 s
#                                 no-reachable-TPU fallback pattern
CACHE_PROBE_TIMEOUT_S = 120.0

# the probe subprocess: report init time + platform as one JSON line
_BACKEND_PROBE = (
    "import json,time\n"
    "t0=time.perf_counter()\n"
    "import jax\n"
    "ds=jax.devices()\n"
    "print(json.dumps({'init_s': round(time.perf_counter()-t0,2),"
    " 'platform': ds[0].platform, 'devices': len(ds),"
    " 'jax': jax.__version__}))\n")

# the compile-cache probe (the former tools/cache_probe.py, shrunk to
# doctor budget): odd shapes dodge unrelated cache hits; min compile
# time 0 so even this small program persists
_CACHE_PROBE = (
    "import json,time\n"
    "t0=time.perf_counter()\n"
    "import jax, jax.numpy as jnp\n"
    "def step(x, w):\n"
    "    def body(c, _):\n"
    "        return jnp.tanh(c @ w) + 0.03125 * c, ()\n"
    "    out, _ = jax.lax.scan(body, x, None, length=37)\n"
    "    return out.sum()\n"
    "x = jnp.ones((8, 131), jnp.float32)\n"
    "w = jnp.ones((131, 131), jnp.float32)\n"
    "t1 = time.perf_counter()\n"
    "jax.jit(step)(x, w).block_until_ready()\n"
    "print(json.dumps({'import_s': round(t1-t0,2),"
    " 'first_call_s': round(time.perf_counter()-t1,2)}))\n")


@dataclass
class CheckResult:
    name: str
    ok: bool
    verdict: str                  # the one-line operator explanation

    def line(self) -> str:
        mark = "ok  " if self.ok else "FAIL"
        return f"doctor: {self.name:14s} {mark}  {self.verdict}"


def _run_probe(code: str, env: dict, timeout_s: float) -> dict:
    """Run `code` in a fresh interpreter, parse its one JSON stdout
    line.  Raises on timeout/crash with the stderr tail attached."""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout_s, env=env, cwd=repo_root())
    if proc.returncode != 0:
        raise RuntimeError(
            f"probe rc={proc.returncode}: {proc.stderr.strip()[-400:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def check_backend(probe=None) -> CheckResult:
    """Is the configured JAX backend actually reachable, and how long
    does a fresh process pay for it?  `probe` is injectable for tests
    (a callable returning the probe dict or raising)."""
    requested = os.environ.get("JAX_PLATFORMS", "")
    expects_device = bool(requested) and "cpu" not in requested.lower()
    try:
        t0 = time.perf_counter()
        info = (probe or (lambda: _run_probe(
            _BACKEND_PROBE, dict(os.environ), BACKEND_TIMEOUT_S)))()
        wall = time.perf_counter() - t0
    except subprocess.TimeoutExpired:
        return CheckResult(
            "backend", False,
            f"backend init did not answer within {BACKEND_TIMEOUT_S:.0f}s "
            f"(JAX_PLATFORMS={requested or 'unset'}) — unreachable device "
            "or hung tunnel")
    except Exception as exc:
        return CheckResult("backend", False, f"backend probe failed: {exc}")
    init_s = float(info.get("init_s", wall))
    platform = str(info.get("platform", "?"))
    detail = (f"platform={platform} devices={info.get('devices', '?')} "
              f"init={init_s:.1f}s (JAX_PLATFORMS={requested or 'unset'})")
    if expects_device and platform == "cpu":
        return CheckResult(
            "backend", False,
            f"{detail} — requested a device platform but init FELL BACK "
            "TO CPU: no reachable TPU.  Every 'device' number this chain "
            "takes would silently be a CPU number (the round-7 trap)")
    if init_s > FALLBACK_THRESHOLD_S:
        return CheckResult(
            "backend", False,
            f"{detail} — init slower than {FALLBACK_THRESHOLD_S:.0f}s: "
            "the no-reachable-backend fallback stall pattern")
    return CheckResult("backend", True, detail)


def check_aot_dir() -> CheckResult:
    from drand_tpu import aot
    d = aot.aot_dir()
    try:
        os.makedirs(d, exist_ok=True)
        probe = os.path.join(d, ".doctor_probe")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as exc:
        return CheckResult("aot-dir", False, f"{d} not writable: {exc}")
    entries = [fn for fn in os.listdir(d) if fn.endswith(".aotx")]
    note = "" if entries else " — EMPTY: expect cold compiles"
    return CheckResult("aot-dir", True,
                       f"{d} writable, {len(entries)} entries{note}")


def check_workdir(workdir: str) -> CheckResult:
    try:
        os.makedirs(workdir, exist_ok=True)
        probe = os.path.join(workdir, ".doctor_probe")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as exc:
        return CheckResult("workdir", False,
                           f"{workdir} not writable: {exc}")
    return CheckResult("workdir", True, f"{workdir} writable")


def check_fixtures() -> CheckResult:
    root = repo_root()
    missing = [rel for rel in ("bench.py", "__graft_entry__.py",
                               "drand_tpu/fixtures.py")
               if not os.path.exists(os.path.join(root, rel))]
    if missing:
        return CheckResult("fixtures", False,
                           f"missing from checkout: {missing}")
    return CheckResult("fixtures", True, "bench/entry/fixtures present")


def check_compile_cache(probe=None) -> CheckResult:
    """The folded cache_probe: does the persistent compilation cache
    survive across processes on this backend?  Two fresh subprocesses
    compile the same program; the cache dir must be populated after the
    first and the second's first-call must come in under the <60 s
    fresh-process bar."""
    from drand_tpu import aot
    cache_dir = aot.persistent_cache_dir()
    env = dict(os.environ)
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    run = probe or (lambda: _run_probe(_CACHE_PROBE, env,
                                       CACHE_PROBE_TIMEOUT_S))
    try:
        cold = run()
        n_files = sum(len(fs) for _, _, fs in os.walk(cache_dir)) \
            if os.path.isdir(cache_dir) else 0
        warm = run()
    except Exception as exc:
        return CheckResult("compile-cache", False, f"probe failed: {exc}")
    detail = (f"{cache_dir}: {n_files} files, cold first-call "
              f"{cold.get('first_call_s', '?')}s, warm "
              f"{warm.get('first_call_s', '?')}s")
    if n_files == 0:
        return CheckResult(
            "compile-cache", False,
            f"{detail} — nothing persisted: fresh processes will pay "
            "full compiles (cache dir misconfigured or backend refuses "
            "serialization)")
    if float(warm.get("first_call_s", 0.0)) >= 60.0:
        return CheckResult(
            "compile-cache", False,
            f"{detail} — warm reload missed the <60s fresh-process bar")
    return CheckResult("compile-cache", True, detail)


def run_doctor(workdir: str, fast: bool = False,
               backend_probe=None, cache_probe=None) -> list[CheckResult]:
    """All checks, in cheapest-first order (a broken workdir should
    fail before a 2-minute backend probe is paid)."""
    results = [
        check_workdir(workdir),
        check_aot_dir(),
        check_fixtures(),
        check_backend(probe=backend_probe),
    ]
    if not fast:
        results.append(check_compile_cache(probe=cache_probe))
    return results


def print_results(results: list[CheckResult], say=None) -> bool:
    say = say or (lambda m: print(m, file=sys.stderr, flush=True))
    for r in results:
        say(r.line())
    ok = all(r.ok for r in results)
    if not ok:
        say("doctor: preflight FAILED — fix the environment (or pass "
            "--no-doctor to proceed anyway, eyes open)")
    return ok


def cache_probe_main() -> int:
    """Back-compat entry for `python tools/cache_probe.py`: run just the
    compile-cache check and exit 0/1 on its verdict."""
    result = check_compile_cache()
    print(result.line(), file=sys.stderr, flush=True)
    return 0 if result.ok else 1
