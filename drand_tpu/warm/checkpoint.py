"""Byte-stable pipeline checkpoints: `<workdir>/state.json`.

The old chains' only state was an append-only chain.log; resuming was a
human re-reading it.  The checkpoint file is the machine form: written
atomically (tmp + rename) after **every** stage transition, in a
canonical serialization (sorted keys, fixed separators, trailing
newline) so that serializing the same logical state always produces
identical bytes — `load(path).dumps() == open(path).read()` is a tested
invariant, which keeps resume decisions reproducible and diffs honest.

Wall-clock stamps come from the runner's injected Clock at stage
completion (never at save time), so re-saving an unchanged state is a
byte-identical no-op.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

STATE_VERSION = 1

# stage status values, in lifecycle order
PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


@dataclass
class StageState:
    """Everything resume needs to know about one stage's last run."""

    status: str = PENDING
    attempts: int = 0                 # subprocess attempts so far
    rc: int | None = None             # last exit code
    duration_s: float | None = None   # last attempt's wall duration
    completed_wall: float | None = None   # clock.now() at success
    def_hash: str = ""                # spec.def_hash() at success
    code_hash: str = ""               # aot.code_hash() at success (if
    #                                   aot_sensitive — kernel-edit dirty)
    artifacts: list[str] = field(default_factory=list)
    error: str = ""                   # last classified failure reason

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "StageState":
        known = {k: d[k] for k in cls.__dataclass_fields__ if k in d}
        return cls(**known)


@dataclass
class PipelineState:
    """The whole pipeline's durable state."""

    pipeline: str
    stages: dict[str, StageState] = field(default_factory=dict)
    version: int = STATE_VERSION

    def stage(self, name: str) -> StageState:
        if name not in self.stages:
            self.stages[name] = StageState()
        return self.stages[name]

    def to_dict(self) -> dict:
        return {"version": self.version, "pipeline": self.pipeline,
                "stages": {k: v.to_dict()
                           for k, v in sorted(self.stages.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineState":
        st = cls(pipeline=d.get("pipeline", ""),
                 version=int(d.get("version", STATE_VERSION)))
        for name, sd in (d.get("stages") or {}).items():
            st.stages[name] = StageState.from_dict(sd)
        return st

    # -- canonical serialization ------------------------------------------

    def dumps(self) -> str:
        """Canonical bytes: sorted keys, 2-space indent, trailing
        newline.  The byte-stability contract — same logical state,
        same bytes, every time."""
        return json.dumps(self.to_dict(), sort_keys=True, indent=2,
                          separators=(",", ": ")) + "\n"

    def save(self, path: str) -> None:
        """Atomic write: a kill -9 mid-checkpoint leaves either the old
        complete state or the new complete state, never a torn file."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.dumps())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "PipelineState":
        with open(path) as f:
            return cls.from_dict(json.load(f))
