"""Warm-pipeline orchestrator: resumable, retrying, checkpointed
AOT warm/measure chains (ROADMAP item 4 — the velocity unlock).

Every kernel or shape experiment used to price at a ~96-minute
hand-shepherded warm cycle run as a `stage()`-shell-function chain
(scripts/warm_r5.sh / warm_r7.sh): a stage that died 76 minutes in to a
tunnel drop was re-run by hand, environment resets were survived only
by human relaunching, and the only record was an append-only
`chain.log`.  This package replaces that with a declarative pipeline:

  - :mod:`spec` — a pipeline is data: named stages with argv/env,
    dependencies, a **required** timeout and **required** expected
    artifacts (the hygiene gate rejects specs without either), plus the
    AOT-cache sensitivity that drives done-detection.
  - :mod:`runner` — supervised subprocess execution with per-stage
    auto-retry through the resilience layer's replay-deterministic
    :class:`~drand_tpu.resilience.RetryPolicy`, per-stage tracing spans
    and ``drand_warm_stage_*`` metrics, heartbeat progress lines, and a
    checkpoint to ``<workdir>/state.json`` after every stage so a
    killed or reset chain resumes at the first incomplete stage.
  - :mod:`classify` — transient failures (tunnel drop, backend-init
    timeout, rc from a killed process) are retried; real benchmark
    failures (tracebacks, assertion failures, SIGSEGV/SIGILL) stop the
    chain loudly.
  - :mod:`checkpoint` — byte-stable canonical-JSON pipeline state with
    atomic writes; done-detection = recorded success + artifacts exist
    + the AOT cache key still hits, so a kernel edit correctly
    re-dirties downstream stages.
  - :mod:`doctor` — environment preflight (TPU reachable?  backend-init
    CPU fallback?  aot/ writable?  fixtures present?  persistent
    compilation cache live?) with one-line verdicts and a non-zero
    exit, run automatically before any chain — the no-reachable-TPU
    60 s fallback that silently degraded round 7 now fails in seconds,
    not hours.
  - :mod:`specs` — the registry: ``warm_r8`` re-expresses the full
    round-7 measurement protocol; ``smoke3`` is the tiny CPU spec the
    check.sh warm-smoke stage kills and resumes end-to-end.

CLI: ``drand-tpu warm run|resume|status|doctor|list`` (cli/main.py).
"""

from __future__ import annotations

from drand_tpu.warm.checkpoint import PipelineState, StageState
from drand_tpu.warm.classify import FATAL, TRANSIENT, classify_stage
from drand_tpu.warm.runner import (FatalStageError, PipelineRunner,
                                   StageFailure, TransientStageError)
from drand_tpu.warm.spec import PipelineSpec, SpecError, StageSpec

__all__ = ["PipelineSpec", "StageSpec", "SpecError", "PipelineRunner",
           "PipelineState", "StageState", "StageFailure",
           "TransientStageError", "FatalStageError",
           "classify_stage", "TRANSIENT", "FATAL"]
