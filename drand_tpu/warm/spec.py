"""Pipeline specs: a warm/measure chain as data, not shell functions.

The `stage()` shell chains (scripts/warm_r5.sh / warm_r7.sh) encoded
each stage as "run this argv, redirect stdout to warm_logs/<name>.json"
— with no declared timeout, no expected artifacts, no dependencies, and
therefore nothing a runner could retry, resume, or preflight.  A
:class:`StageSpec` makes all of that explicit and **mandatory**:
`timeout_s` and `artifacts` are validation-required on every stage
(tests/test_hygiene.py gates every registered spec), because a stage
without a timeout is a stage that can silently eat a night, and a stage
without declared artifacts is a stage whose success cannot be detected
on resume.

Substitution: argv and env values may reference ``{python}`` (the
current interpreter), ``{workdir}`` (the pipeline working directory),
``{repo}`` (the checkout root), and ``{jax_cache}`` (the persistent
XLA compilation cache dir, drand_tpu/aot.py) — resolved by the runner
at spawn time so specs stay machine-independent data.

This module is deliberately jax-free and grpc-free: the orchestrator
process must start in milliseconds and must never pay (or hang on) a
backend init — that is exactly the failure mode `warm doctor` exists
to probe *in a subprocess*.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field


class SpecError(ValueError):
    """A pipeline spec that fails validation (the hygiene contract:
    every stage declares timeout + expected artifacts)."""


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclass(frozen=True)
class StageSpec:
    """One supervised stage of a warm/measure chain.

    `argv`/`env` values go through runner substitution ({python},
    {workdir}, {repo}, {jax_cache}).  `artifacts` are paths relative to
    the pipeline workdir (absolute paths allowed) that MUST exist and
    be non-empty after a successful run — they are half of resume
    done-detection.  `aot_names` are AOT cache name stems
    (drand_tpu/aot.py cache entries) the stage is expected to leave
    behind; `aot_sensitive` stages additionally record
    `aot.code_hash()` at completion, so a kernel edit re-dirties them
    (and everything downstream) on resume."""

    name: str
    argv: tuple[str, ...]
    timeout_s: float
    artifacts: tuple[str, ...]
    env: tuple[tuple[str, str], ...] = ()
    deps: tuple[str, ...] = ()
    doc: str = ""
    stdout_artifact: bool = True      # capture stdout to workdir/<name>.json
    aot_names: tuple[str, ...] = ()
    aot_sensitive: bool = True
    max_attempts: int = 3

    def validate(self) -> None:
        if not self.name or "/" in self.name or self.name.startswith("."):
            raise SpecError(f"bad stage name {self.name!r}")
        if not self.argv:
            raise SpecError(f"stage {self.name}: empty argv")
        try:
            timeout = float(self.timeout_s)
        except (TypeError, ValueError):
            timeout = 0.0
        if not timeout > 0:
            raise SpecError(
                f"stage {self.name}: timeout_s is required and must be > 0 "
                "(a stage without a timeout can silently eat a night)")
        if not self.artifacts:
            raise SpecError(
                f"stage {self.name}: expected artifacts are required "
                "(without them success cannot be detected on resume)")
        if self.max_attempts < 1:
            raise SpecError(f"stage {self.name}: max_attempts must be >= 1")

    def def_hash(self) -> str:
        """Hash of everything that defines this stage's WORK.  A changed
        definition re-dirties the stage on resume even if its artifacts
        survived — resumed state must never vouch for a different
        command than the one that produced it."""
        blob = json.dumps({
            "argv": list(self.argv), "env": sorted(self.env),
            "artifacts": sorted(self.artifacts),
            "aot_names": sorted(self.aot_names),
            "aot_sensitive": self.aot_sensitive,
        }, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass(frozen=True)
class PipelineSpec:
    """A named DAG of stages, executed serially in dependency order
    (warm chains contend for one device — parallel stages would corrupt
    each other's measurements)."""

    name: str
    stages: tuple[StageSpec, ...]
    doc: str = ""
    workdir: str = "warm_logs"        # default, relative to the repo root
    slow: bool = field(default=True, compare=False)   # hours, not seconds

    def validate(self) -> None:
        if not self.name:
            raise SpecError("pipeline needs a name")
        if not self.stages:
            raise SpecError(f"pipeline {self.name}: no stages")
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            raise SpecError(f"pipeline {self.name}: duplicate stage names")
        known = set(names)
        for s in self.stages:
            s.validate()
            unknown = set(s.deps) - known
            if unknown:
                raise SpecError(f"stage {s.name}: unknown deps "
                                f"{sorted(unknown)}")
        self.order()                   # raises on cycles

    def stage(self, name: str) -> StageSpec:
        for s in self.stages:
            if s.name == name:
                return s
        raise KeyError(name)

    def order(self) -> list[StageSpec]:
        """Topological order, stable in declaration order among ready
        stages — so a linear chain executes exactly as written."""
        done: set[str] = set()
        out: list[StageSpec] = []
        pending = list(self.stages)
        while pending:
            progressed = False
            for s in list(pending):
                if set(s.deps) <= done:
                    out.append(s)
                    done.add(s.name)
                    pending.remove(s)
                    progressed = True
            if not progressed:
                raise SpecError(
                    f"pipeline {self.name}: dependency cycle among "
                    f"{sorted(s.name for s in pending)}")
        return out

    def dependents(self, name: str) -> set[str]:
        """Transitive closure of stages depending on `name` — the set a
        dirty stage drags with it on resume."""
        out: set[str] = set()
        changed = True
        while changed:
            changed = False
            for s in self.stages:
                if s.name in out:
                    continue
                if name in s.deps or out & set(s.deps):
                    out.add(s.name)
                    changed = True
        return out
