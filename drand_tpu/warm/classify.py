"""Transient-vs-real stage-failure classification.

The hand-run chains could not tell a tunnel drop from a failed
benchmark — both left a dead stage in chain.log and a human decided
what to re-run.  This module encodes that judgment:

  **transient** (auto-retried through the RetryPolicy):
    - the process was *killed* — SIGKILL/SIGTERM/SIGHUP/SIGINT/SIGPIPE,
      as a negative returncode or the shell's 128+N form.  That is the
      round-5 tunnel-drop / environment-reset signature: something
      outside the benchmark ended it.
    - the stage hit its declared timeout (the 60 s backend-init
      fallback stalls and remote-compile hangs present as this).
    - the stderr tail carries a known transport/backend marker
      (connection reset, tunnel, backend init, DEADLINE_EXCEEDED, ...).

  **fatal** (stops the chain loudly):
    - crash signals — SIGSEGV/SIGABRT/SIGILL/SIGFPE/SIGBUS.  SIGILL in
      particular is the AOT machine-feature hazard (drand_tpu/aot.py):
      re-running cannot fix it, rebuilding the executable can.
    - any other non-zero exit: a Python traceback, a failed assertion,
      a bad config — a REAL benchmark failure a retry would only
      repeat (and whose repetition would corrupt the measurement
      ledger with a silently re-run stage).

The classifier is a pure function of (returncode, stderr tail,
timed-out flag) so the matrix is unit-testable without subprocesses
(tests/test_warm.py).
"""

from __future__ import annotations

import signal

TRANSIENT = "transient"
FATAL = "fatal"

# signals that mean "something outside the stage ended it" — retryable
_KILLED_SIGNALS = frozenset({
    signal.SIGKILL, signal.SIGTERM, signal.SIGHUP, signal.SIGINT,
    signal.SIGPIPE,
})
# signals that mean "the stage itself crashed" — a retry repeats it
_CRASH_SIGNALS = frozenset({
    signal.SIGSEGV, signal.SIGABRT, signal.SIGILL, signal.SIGFPE,
    signal.SIGBUS,
})

# lowercase substrings in the stderr tail that mark a transient
# transport/backend condition even when the stage exited non-zero on
# its own (e.g. a grpc UNAVAILABLE surfacing as a Python exception)
_TRANSIENT_MARKERS = (
    "connection reset", "connection refused", "connection closed",
    "broken pipe", "tunnel", "socket closed", "socket hang up",
    "temporarily unavailable", "timed out", "timeout exceeded",
    "deadline_exceeded", "deadline exceeded", "unavailable",
    "failed to initialize backend", "unable to initialize backend",
    "backend init", "backend_init", "plugin disconnected",
    "transport failure", "rpc failed", "os error 104",
)


def _signal_name(num: int) -> str:
    try:
        return signal.Signals(num).name
    except ValueError:
        return f"signal {num}"


def classify_stage(returncode: int | None, stderr_tail: str = "",
                   timed_out: bool = False) -> tuple[str, str]:
    """Classify one failed stage attempt.  Returns (verdict, reason)
    where verdict is :data:`TRANSIENT` or :data:`FATAL` and reason is
    the one-line operator explanation recorded in the checkpoint and
    the decision log."""
    if timed_out:
        return TRANSIENT, "stage hit its declared timeout (killed)"
    rc = returncode if returncode is not None else -1
    sig = None
    if rc < 0:
        sig = -rc
    elif rc > 128 and rc <= 128 + 64:        # the shell's 128+N encoding
        sig = rc - 128
    if sig is not None:
        if sig in {int(s) for s in _CRASH_SIGNALS}:
            return FATAL, (f"stage crashed with {_signal_name(sig)} — a "
                           "retry would repeat it (SIGILL: rebuild the "
                           "AOT entry on this machine)")
        if sig in {int(s) for s in _KILLED_SIGNALS}:
            return TRANSIENT, (f"process killed by {_signal_name(sig)} "
                               "(tunnel drop / environment reset pattern)")
        return TRANSIENT, f"process ended by {_signal_name(sig)}"
    tail = (stderr_tail or "").lower()
    for marker in _TRANSIENT_MARKERS:
        if marker in tail:
            return TRANSIENT, (f"rc={rc} with transient marker "
                               f"{marker!r} in stderr")
    return FATAL, (f"rc={rc} with no transient signature — a real "
                   "benchmark failure; fix it, then `warm resume`")
