"""The pipeline registry.

``warm_r8`` re-expresses the full round-7 measurement protocol
(scripts/warm_r7.sh) as the first real spec: same stages, same argv/env,
same artifact layout under warm_logs/ — but now resumable, retried, and
preflighted.  ``scripts/warm_r8.sh`` is a thin wrapper that just invokes
``drand-tpu warm run warm_r8``.

``smoke3`` is the tiny CPU-only 3-stage spec the check.sh warm-smoke
stage (scripts/warm_smoke.py) and tests/test_warm.py drive end-to-end:
one injected transient failure (exit 137 on s2's first attempt) that
the runner must retry, and a hang knob (WARM_SMOKE_HANG_S) that holds
s2 open long enough to SIGKILL the whole orchestrator and prove
resume.

Every spec registered here is validated by the hygiene gate
(tests/test_hygiene.py): a stage without a declared timeout or without
expected artifacts does not ship.
"""

from __future__ import annotations

from drand_tpu.warm.spec import PipelineSpec, StageSpec

_BENCH_HOUR = 3600.0

# the r7/r8 measurement protocol, one stage per bench config; linear
# dependency chain — warm stages contend for one device, and a kernel
# edit invalidating stage k must re-dirty everything measured after it
_R8_STAGES = (
    StageSpec(
        name="catchup",
        doc="strict round-4-comparable catch-up (reps=3) — the "
            "accounting VERDICT weak #1 asks for alongside reps-10",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "3")),
        timeout_s=6 * _BENCH_HOUR,      # round-5 contended recompile: 7448 s
        artifacts=("catchup.json",),
    ),
    StageSpec(
        name="catchup10",
        doc="reps=10 (the BASELINE.md round-5 headline protocol)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "10")),
        deps=("catchup",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("catchup10.json",),
    ),
    StageSpec(
        name="chained",
        doc="pedersen-bls-chained at b16384 — the LoE mainnet default, "
            "first throughput-scale run (VERDICT weak #3)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "chained")),
        deps=("catchup10",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("chained.json",),
    ),
    StageSpec(
        name="partials",
        doc="the rebuilt aggregation path (shared-message hash, "
            "signer-key table, 1024x16 rounds-major batches, "
            "rounds-batched recovery MSM) -> BENCH_partials.json; "
            "targets >= 15k partials/s, >= 1k recoveries/s",
        argv=("{python}", "bench.py", "--json",
              "{repo}/BENCH_partials.json"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "partials")),
        deps=("chained",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("partials.json", "{repo}/BENCH_partials.json"),
    ),
    StageSpec(
        name="partials-old-shape",
        doc="BENCH_PARTIAL_ROUNDS=64 on the new path: the "
            "shape-for-shape comparison against warm_logs/partials.json "
            "(5,732/s, 117 rec/s)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "partials"),
             ("BENCH_PARTIAL_ROUNDS", "64")),
        deps=("partials",),
        timeout_s=2 * _BENCH_HOUR,
        artifacts=("partials-old-shape.json",),
    ),
    StageSpec(
        name="dryrun",
        doc="the driver's CPU multichip artifact (parity-asserts the "
            "tabled path vs the legacy kernels, warms both sharded "
            "executables); rides the persistent XLA:CPU compilation "
            "cache so fresh processes reload instead of recompiling",
        argv=("{python}", "-c",
              "import __graft_entry__ as g; g.dryrun_multichip(8)"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("JAX_PLATFORMS", "cpu"),
             ("XLA_FLAGS", "--xla_cpu_max_isa=AVX2"),
             ("JAX_COMPILATION_CACHE_DIR", "{jax_cache}"),
             ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")),
        deps=("partials-old-shape",),
        timeout_s=2 * _BENCH_HOUR,
        artifacts=("dryrun.json",),
    ),
    StageSpec(
        name="g1",
        doc="short-sig scheme (sigs on G1) — keeps BASELINE complete",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "g1")),
        deps=("dryrun",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("g1.json",),
    ),
    StageSpec(
        name="single",
        doc="single-round chained verify (latency path)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "single")),
        deps=("g1",),
        timeout_s=2 * _BENCH_HOUR,
        artifacts=("single.json",),
    ),
    StageSpec(
        name="multichain",
        doc="concurrent verification across independent chains at "
            "b32768",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "multichain"),
             ("BENCH_BATCH", "32768")),
        deps=("single",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("multichain.json",),
    ),
)

WARM_R8 = PipelineSpec(
    name="warm_r8",
    doc="the full round-7/8 warm/measure protocol (ISSUE 7 staging, "
        "ISSUE 8 orchestration): catchup strict+reps10, chained b16384, "
        "partials new-path + old-shape, dryrun parity, g1/single/"
        "multichain — run on a TPU-attached host",
    stages=_R8_STAGES,
    workdir="warm_logs",
    slow=True,
)

_SMOKE_STAGES = (
    StageSpec(
        name="s1",
        doc="writes its artifact immediately",
        argv=("{python}", "-m", "drand_tpu.warm._smoke_stage", "s1",
              "{workdir}"),
        timeout_s=60.0,
        artifacts=("s1.json",),
        aot_sensitive=False,
    ),
    StageSpec(
        name="s2",
        doc="fails transiently (exit 137) on its first-ever attempt, "
            "then succeeds; WARM_SMOKE_HANG_S holds it open for the "
            "kill -9 / resume proof",
        argv=("{python}", "-m", "drand_tpu.warm._smoke_stage", "s2",
              "{workdir}"),
        deps=("s1",),
        timeout_s=300.0,
        artifacts=("s2.json",),
        aot_sensitive=False,
    ),
    StageSpec(
        name="s3",
        doc="proves the chain continues past a retried stage",
        argv=("{python}", "-m", "drand_tpu.warm._smoke_stage", "s3",
              "{workdir}"),
        deps=("s2",),
        timeout_s=60.0,
        artifacts=("s3.json",),
        aot_sensitive=False,
    ),
)

SMOKE3 = PipelineSpec(
    name="smoke3",
    doc="tiny CPU-only 3-stage spec for the check.sh warm-smoke stage: "
        "one injected transient retry, kill -9 + resume end-to-end",
    stages=_SMOKE_STAGES,
    workdir="warm_logs/smoke3",
    slow=False,
)

# the round-9 kernel-lever measurement protocol (ISSUE 9): the merged
# Miller-iteration kernel + sparse line merge + tile residency land as
# env-gated paths (DRAND_TPU_MILLER_MERGED / DRAND_TPU_LINE_MERGE, both
# default-on; AOT-keyed so the A/B executables coexist), so the chain
# measures the trio baseline at THIS revision first, then each lever,
# then the full protocol on the winner — plus the configs round 8 left
# staged (chained b16384 = the LoE mainnet default, partials new-path,
# dryrun parity gate).
_R9_STAGES = (
    StageSpec(
        name="catchup-trio",
        doc="strict reps-3 catch-up with the merged kernels OFF — the "
            "same-revision trio baseline every lever below is judged "
            "against (kernel A/B needs a same-code control, not the "
            "round-5 number)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "3"), ("DRAND_TPU_MILLER_MERGED", "0")),
        timeout_s=6 * _BENCH_HOUR,
        artifacts=("catchup-trio.json",),
    ),
    StageSpec(
        name="catchup",
        doc="strict reps-3 catch-up, merged Miller kernel + sparse line "
            "merge (the default path) — the round-9 headline lever "
            "under the STRICT protocol (VERDICT weak #1)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "3")),
        deps=("catchup-trio",),
        timeout_s=6 * _BENCH_HOUR,
        artifacts=("catchup.json",),
    ),
    StageSpec(
        name="catchup-nolinemerge",
        doc="strict reps-3, merged kernel WITHOUT the sparse line merge "
            "(DRAND_TPU_LINE_MERGE=0) — isolates lever 3's sign; the "
            "op-count arithmetic says +36 sparse convs vs one fewer "
            "full-f accumulator pass, only the device decides",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "3"), ("DRAND_TPU_LINE_MERGE", "0")),
        deps=("catchup",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("catchup-nolinemerge.json",),
    ),
    StageSpec(
        name="catchup10",
        doc="reps=10 on the default merged path (the BASELINE.md "
            "round-5 headline protocol, for series continuity)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "10")),
        deps=("catchup-nolinemerge",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("catchup10.json",),
    ),
    StageSpec(
        name="chained",
        doc="pedersen-bls-chained at b16384 — the LoE mainnet default, "
            "still never run at throughput scale (VERDICT weak #3)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "chained")),
        deps=("catchup10",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("chained.json",),
    ),
    StageSpec(
        name="partials",
        doc="the ISSUE-7 aggregation path on the round-9 kernels -> "
            "BENCH_partials.json; targets >= 15k partials/s",
        argv=("{python}", "bench.py", "--json",
              "{repo}/BENCH_partials.json"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "partials")),
        deps=("chained",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("partials.json", "{repo}/BENCH_partials.json"),
    ),
    StageSpec(
        name="dryrun",
        doc="the CPU multichip parity gate (new-vs-legacy partials "
            "asserted inside the driver artifact; also exercises the "
            "multichip sharded executables at the r9 kernel revision)",
        argv=("{python}", "-c",
              "import __graft_entry__ as g; g.dryrun_multichip(8)"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("JAX_PLATFORMS", "cpu"),
             ("XLA_FLAGS", "--xla_cpu_max_isa=AVX2"),
             ("JAX_COMPILATION_CACHE_DIR", "{jax_cache}"),
             ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")),
        deps=("partials",),
        timeout_s=2 * _BENCH_HOUR,
        artifacts=("dryrun.json",),
    ),
    StageSpec(
        name="g1",
        doc="short-sig scheme (sigs on G1) at the r9 kernels",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "g1")),
        deps=("dryrun",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("g1.json",),
    ),
    StageSpec(
        name="single",
        doc="single-round chained verify (latency path; also reports "
            "the native prepared-pk delta)",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "single")),
        deps=("g1",),
        timeout_s=2 * _BENCH_HOUR,
        artifacts=("single.json",),
    ),
    StageSpec(
        name="multichain",
        doc="concurrent chains at b32768 on the winner path",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "multichain"),
             ("BENCH_BATCH", "32768")),
        deps=("single",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("multichain.json",),
    ),
)

WARM_R9 = PipelineSpec(
    name="warm_r9",
    doc="the round-9 kernel-lever protocol (ISSUE 9): trio baseline vs "
        "merged Miller kernel vs no-line-merge A/B under the strict "
        "reps-3 protocol, then reps-10, chained b16384, partials, "
        "dryrun parity, g1/single/multichain — run on a TPU-attached "
        "host (scripts/warm_r9.sh)",
    stages=_R9_STAGES,
    workdir="warm_logs",
    slow=True,
)

# the round-13 catch-up measurement protocol (ISSUE 13): the sync path
# was rebuilt end to end (SyncChunk wire, binary store codec, off-loop
# fetch/pack/commit pipeline), and the CPU harness already proves the
# host-side win with verify stubbed — this chain stages the TPU-attached
# proof, where REAL batched verification overlaps the host stages.
_R13_STAGES = (
    StageSpec(
        name="catchup",
        doc="strict reps-3 catch-up bench first: warms the b512 and "
            "b16384 verify executables the sync pipeline dispatches to, "
            "and refreshes the raw-kernel headline the end-to-end "
            "number is judged against",
        argv=("{python}", "bench.py"),
        env=(("DRAND_TPU_AOT_WARM", "1"), ("BENCH_CONFIG", "catchup"),
             ("BENCH_REPS", "3")),
        timeout_s=6 * _BENCH_HOUR,
        artifacts=("catchup.json",),
    ),
    StageSpec(
        name="sync-e2e",
        doc="tools/bench_sync.py --mode=real: two in-process nodes over "
            "real gRPC, 64k-round native-signed backlog, chunked vs "
            "fallback vs legacy passes with the REAL ChainVerifier -> "
            "BENCH_sync.json (per-stage breakdown + the >=5x non-verify "
            "acceptance ratio)",
        argv=("{python}", "tools/bench_sync.py", "--mode", "real",
              "--out", "{repo}/BENCH_sync.json"),
        env=(("DRAND_TPU_AOT_WARM", "1"),),
        deps=("catchup",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("{repo}/BENCH_sync.json",),
    ),
    StageSpec(
        name="sync-e2e-depth1",
        doc="same harness with the hand-off queues throttled to depth 1 "
            "(DRAND_TPU_SYNC_PIPELINE_DEPTH=1) — isolates how much of "
            "the end-to-end win is stage overlap vs wire/codec",
        argv=("{python}", "tools/bench_sync.py", "--mode", "real",
              "--out", "{workdir}/sync-depth1.json"),
        env=(("DRAND_TPU_AOT_WARM", "1"),
             ("DRAND_TPU_SYNC_PIPELINE_DEPTH", "1")),
        deps=("sync-e2e",),
        timeout_s=4 * _BENCH_HOUR,
        artifacts=("sync-depth1.json",),
    ),
)

WARM_R13 = PipelineSpec(
    name="warm_r13",
    doc="the round-13 catch-up protocol (ISSUE 13): raw-kernel catchup "
        "warm/baseline, then the two-node real-gRPC sync harness with "
        "real verification (chunked/fallback/legacy A/B -> "
        "BENCH_sync.json), then the depth-1 pipeline lever — run on a "
        "TPU-attached host (scripts/warm_r13.sh)",
    stages=_R13_STAGES,
    workdir="warm_logs",
    slow=True,
)

SPECS: dict[str, PipelineSpec] = {
    WARM_R8.name: WARM_R8,
    WARM_R9.name: WARM_R9,
    WARM_R13.name: WARM_R13,
    SMOKE3.name: SMOKE3,
}


def get(name: str) -> PipelineSpec:
    try:
        return SPECS[name]
    except KeyError:
        raise SystemExit(
            f"unknown warm pipeline {name!r} (known: {sorted(SPECS)}; "
            "see `drand-tpu warm list`)") from None
