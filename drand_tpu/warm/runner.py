"""The pipeline runner: supervised, retrying, checkpointed stages.

Replaces the `stage()` shell chains (scripts/warm_r7.sh) with an
orchestrator that owns the whole lifecycle:

  - each stage runs as a **subprocess in its own session** (so a stage
    timeout can kill the entire process group, not just the leader),
    stdout captured to its declared artifact, stderr to
    ``<workdir>/<stage>.err``;
  - failures are classified (warm/classify.py): transient ones retry
    through the resilience layer's replay-deterministic
    :class:`~drand_tpu.resilience.RetryPolicy` (same full-jitter
    hash-derived backoff, same decision log the chaos subsystem
    prints), real ones stop the chain loudly with the `warm resume`
    command in the error;
  - state checkpoints to ``<workdir>/state.json`` after **every**
    transition (warm/checkpoint.py, atomic + byte-stable), so kill -9
    at any point resumes at the first incomplete stage;
  - done-detection on resume = recorded success + declared artifacts
    exist + the stage definition hash matches + (for AOT-sensitive
    stages) ``drand_tpu.aot.code_hash()`` still matches and every
    declared AOT name still has a cache entry — a kernel edit
    re-dirties the stage and, transitively, everything downstream;
  - per-stage ``warm.stage`` tracing spans (visible at /debug/spans
    when a metrics server is up), ``drand_warm_stage_*`` metrics, and
    heartbeat progress lines on the injected clock replace the
    append-only chain.log.

The module is jax-free: stages pay backend init in their own
subprocesses; the orchestrator must survive precisely the environments
where that init hangs.
"""

from __future__ import annotations

import asyncio
import os
import signal
import sys
import time

from drand_tpu import log as dlog
from drand_tpu import tracing
from drand_tpu.beacon.clock import Clock, SystemClock
from drand_tpu.chaos.failpoints import FaultInjectedError, failpoint
from drand_tpu.resilience.policy import RetryPolicy
from drand_tpu.warm import checkpoint as ckpt
from drand_tpu.warm.classify import TRANSIENT, classify_stage
from drand_tpu.warm.spec import PipelineSpec, StageSpec, repo_root

log = dlog.get("warm", "runner")

STDERR_TAIL_BYTES = 4096        # classification window into a stage's stderr
DEFAULT_HEARTBEAT_S = 30.0


class StageFailure(RuntimeError):
    """A stage attempt that did not succeed."""

    def __init__(self, message: str, *, stage: str = "",
                 rc: int | None = None, reason: str = ""):
        super().__init__(message)
        self.stage = stage
        self.rc = rc
        self.reason = reason or message


class TransientStageError(StageFailure):
    """Classified transient (tunnel drop / kill / timeout): retried by
    the stage's RetryPolicy.  Also the exception type the
    ``warm.stage_exec`` chaos failpoint raises, so injected faults
    exercise the real retry path."""


class FatalStageError(StageFailure):
    """Classified real: stops the chain loudly."""


def _default_code_hash() -> str:
    try:
        from drand_tpu import aot
        return aot.code_hash()
    except Exception:
        return ""


def _default_aot_entries(name: str) -> list[str]:
    try:
        from drand_tpu import aot
        return aot.entries_for(name)
    except Exception:
        return []


def _stderr_say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class PipelineRunner:
    """Drives one :class:`PipelineSpec` to completion."""

    def __init__(self, spec: PipelineSpec, workdir: str | None = None, *,
                 clock: Clock | None = None, seed: int = 0,
                 heartbeat_s: float = DEFAULT_HEARTBEAT_S,
                 env: dict | None = None, say=None,
                 code_hash_fn=None, aot_entries_fn=None):
        spec.validate()
        self.spec = spec
        self.repo = repo_root()
        self.workdir = os.path.abspath(workdir or
                                       os.path.join(self.repo, spec.workdir))
        self.state_path = os.path.join(self.workdir, "state.json")
        self.clock = clock or SystemClock()
        self.seed = seed
        self.heartbeat_s = heartbeat_s
        self.extra_env = dict(env or {})
        self._say = say or _stderr_say
        self._code_hash = code_hash_fn or _default_code_hash
        self._aot_entries = aot_entries_fn or _default_aot_entries

    # -- substitution ------------------------------------------------------

    def _subst(self, s: str) -> str:
        from drand_tpu import aot
        return (s.replace("{python}", sys.executable)
                 .replace("{workdir}", self.workdir)
                 .replace("{repo}", self.repo)
                 .replace("{jax_cache}", aot.persistent_cache_dir()))

    def _artifact_path(self, rel: str) -> str:
        rel = self._subst(rel)
        return rel if os.path.isabs(rel) else os.path.join(self.workdir, rel)

    # -- done-detection / planning ----------------------------------------

    def _not_done(self, stage: StageSpec,
                  state: ckpt.PipelineState) -> str:
        """'' when the stage's recorded success still holds; else the
        one-line reason it must re-run."""
        ss = state.stages.get(stage.name)
        if ss is None or ss.status != ckpt.DONE:
            return "not completed"
        if ss.def_hash != stage.def_hash():
            return "stage definition changed"
        for rel in stage.artifacts:
            path = self._artifact_path(rel)
            if not os.path.exists(path) or os.path.getsize(path) == 0:
                return f"artifact {rel} missing or empty"
        if stage.aot_sensitive:
            current = self._code_hash()
            if current and ss.code_hash and ss.code_hash != current:
                return ("kernel sources changed since this stage ran "
                        "(AOT cache key miss)")
        for name in stage.aot_names:
            if not self._aot_entries(name):
                return f"AOT cache entry {name!r} missing"
        return ""

    def plan(self, state: ckpt.PipelineState) -> dict[str, str]:
        """stage name -> reason it will run; stages absent from the map
        are done and will be skipped.  Dirtiness propagates through
        dependencies: a re-running stage drags every dependent with it
        (its outputs — AOT entries, fixtures — feed them)."""
        dirty: dict[str, str] = {}
        for stage in self.spec.order():
            why = self._not_done(stage, state)
            if not why:
                dirty_deps = [d for d in stage.deps if d in dirty]
                if dirty_deps:
                    why = f"dependency {dirty_deps[0]} re-runs"
            if why:
                dirty[stage.name] = why
        return dirty

    # -- state I/O ---------------------------------------------------------

    def load_state(self) -> ckpt.PipelineState | None:
        if not os.path.exists(self.state_path):
            return None
        state = ckpt.PipelineState.load(self.state_path)
        if state.pipeline and state.pipeline != self.spec.name:
            raise FatalStageError(
                f"{self.state_path} belongs to pipeline "
                f"{state.pipeline!r}, not {self.spec.name!r} — pass a "
                "different --workdir", stage="", reason="state mismatch")
        return state

    async def _checkpoint(self, state: ckpt.PipelineState) -> None:
        await asyncio.to_thread(state.save, self.state_path)

    # -- status (CLI `warm status`) ---------------------------------------

    def status(self) -> dict:
        state = self.load_state() or ckpt.PipelineState(
            pipeline=self.spec.name)
        dirty = self.plan(state)
        stages = []
        for stage in self.spec.order():
            ss = state.stages.get(stage.name) or ckpt.StageState()
            stages.append({
                "stage": stage.name, "status": ss.status,
                "attempts": ss.attempts, "rc": ss.rc,
                "duration_s": ss.duration_s, "error": ss.error,
                "next": ("run" if stage.name in dirty else "skip"),
                "why": dirty.get(stage.name, "done"),
            })
        complete = not dirty and all(
            state.stages.get(s.name) is not None
            and state.stages[s.name].status == ckpt.DONE
            for s in self.spec.stages)
        return {"pipeline": self.spec.name, "workdir": self.workdir,
                "state_file": self.state_path, "complete": complete,
                "stages": stages}

    # -- execution ---------------------------------------------------------

    async def run(self, resume: bool = False) -> ckpt.PipelineState:
        """Execute the pipeline.  ``resume=True`` loads the checkpoint
        and skips stages whose recorded success still holds; a fresh
        run starts from an empty state (done-detection then sees every
        stage as dirty)."""
        await asyncio.to_thread(os.makedirs, self.workdir, exist_ok=True)
        state = (self.load_state() if resume else None) \
            or ckpt.PipelineState(pipeline=self.spec.name)
        dirty = self.plan(state)
        order = self.spec.order()
        todo = [s for s in order if s.name in dirty]
        self._say(f"warm {self.spec.name}: {len(order)} stages, "
                  f"{len(order) - len(todo)} already done, "
                  f"{len(todo)} to run (workdir {self.workdir})")
        with tracing.span("warm.pipeline", pipeline=self.spec.name,
                          stages=len(order), to_run=len(todo)):
            for stage in order:
                if stage.name not in dirty:
                    self._count(stage.name, "skipped")
                    self._say(f"warm {self.spec.name}: stage "
                              f"{stage.name}: done — skipping")
                    continue
                self._say(f"warm {self.spec.name}: stage {stage.name}: "
                          f"starting ({dirty[stage.name]})")
                await self._run_stage(stage, state)
        return state

    async def _run_stage(self, stage: StageSpec,
                         state: ckpt.PipelineState) -> None:
        policy = RetryPolicy(max_attempts=stage.max_attempts,
                             clock=self.clock, seed=self.seed)
        site = f"warm.{self.spec.name}.{stage.name}"
        ss = state.stage(stage.name)
        ss.status = ckpt.RUNNING
        ss.error = ""
        ss.rc = None
        ss.completed_wall = None

        async def attempt(i: int):
            ss.attempts += 1
            await self._checkpoint(state)
            with tracing.span("warm.stage", pipeline=self.spec.name,
                              stage=stage.name, attempt=i) as sp:
                # the chaos seam: an armed schedule can kill this
                # attempt exactly like a tunnel drop would, and the
                # retry below must recover deterministically
                await failpoint("warm.stage_exec", exc=TransientStageError,
                                pipeline=self.spec.name, stage=stage.name,
                                attempt=i)
                rc, dur, timed_out, err_tail = await self._spawn(stage)
                sp.set(rc=rc, duration_s=round(dur, 3),
                       timed_out=timed_out)
                if rc == 0:
                    missing = [rel for rel in stage.artifacts
                               if not os.path.exists(
                                   self._artifact_path(rel))
                               or os.path.getsize(
                                   self._artifact_path(rel)) == 0]
                    if missing:
                        sp.set(missing_artifacts=missing)
                        raise FatalStageError(
                            f"stage {stage.name} exited 0 but expected "
                            f"artifacts are missing/empty: {missing}",
                            stage=stage.name, rc=0,
                            reason="declared artifact missing after "
                                   "success — spec or stage bug")
                    return rc, dur
                verdict, reason = classify_stage(rc, err_tail, timed_out)
                sp.set(verdict=verdict, reason=reason)
                exc_cls = TransientStageError if verdict == TRANSIENT \
                    else FatalStageError
                raise exc_cls(
                    f"stage {stage.name} failed (rc={rc}): {reason}",
                    stage=stage.name, rc=rc, reason=reason)

        def _retryable(exc: BaseException) -> bool:
            return isinstance(exc, (TransientStageError,
                                    FaultInjectedError))

        t0 = time.perf_counter()
        try:
            _, dur = await policy.call(site, attempt, key=stage.name,
                                       classify=_retryable)
        except Exception as exc:
            ss.status = ckpt.FAILED
            ss.rc = getattr(exc, "rc", ss.rc)
            ss.error = getattr(exc, "reason", "") or str(exc)
            await self._checkpoint(state)
            fatal = isinstance(exc, FatalStageError)
            self._count(stage.name, "fatal" if fatal else "exhausted")
            self._say(f"warm {self.spec.name}: stage {stage.name}: "
                      f"{'FAILED' if fatal else 'retries exhausted'} — "
                      f"{ss.error}\n  fix, then: drand-tpu warm resume "
                      f"{self.spec.name}")
            log.error("stage %s failed after %d attempt(s): %s",
                      stage.name, ss.attempts, ss.error)
            raise
        ss.status = ckpt.DONE
        ss.rc = 0
        ss.duration_s = round(dur, 3)
        ss.completed_wall = round(self.clock.now(), 3)
        ss.def_hash = stage.def_hash()
        ss.code_hash = self._code_hash() if stage.aot_sensitive else ""
        ss.artifacts = sorted(stage.artifacts)
        ss.error = ""
        await self._checkpoint(state)
        self._count(stage.name, "success")
        self._observe(stage.name, dur)
        retried = f" (attempt {ss.attempts})" if ss.attempts > 1 else ""
        self._say(f"warm {self.spec.name}: stage {stage.name}: ok in "
                  f"{dur:.1f}s{retried}")
        log.info("stage %s ok in %.1fs attempts=%d total=%.1fs",
                 stage.name, dur, ss.attempts, time.perf_counter() - t0)

    async def _spawn(self, stage: StageSpec):
        """One supervised subprocess attempt: (rc, duration_s,
        timed_out, stderr_tail)."""
        argv = [self._subst(a) for a in stage.argv]
        env = dict(os.environ)
        env.update({k: self._subst(v) for k, v in stage.env})
        env.update(self.extra_env)
        out_path = (self._artifact_path(stage.artifacts[0])
                    if stage.stdout_artifact
                    else os.path.join(self.workdir, stage.name + ".out"))
        err_path = os.path.join(self.workdir, stage.name + ".err")

        def _open_streams():
            os.makedirs(os.path.dirname(out_path), exist_ok=True)
            return open(out_path, "wb"), open(err_path, "wb")

        out_f, err_f = await asyncio.to_thread(_open_streams)
        t0 = time.perf_counter()
        timed_out = False
        try:
            proc = await asyncio.create_subprocess_exec(
                *argv, stdout=out_f, stderr=err_f, cwd=self.repo,
                env=env, start_new_session=True)
            hb = asyncio.create_task(self._heartbeat(stage, proc.pid, t0))
            try:
                await asyncio.wait_for(proc.wait(),
                                       timeout=stage.timeout_s)
            except asyncio.TimeoutError:
                timed_out = True
                self._kill_group(proc)
                await proc.wait()
            finally:
                hb.cancel()
                try:
                    await hb
                except asyncio.CancelledError:
                    pass
        finally:
            await asyncio.to_thread(self._close_streams, out_f, err_f)
        dur = time.perf_counter() - t0
        tail = await asyncio.to_thread(self._tail, err_path)
        return proc.returncode, dur, timed_out, tail

    @staticmethod
    def _close_streams(*fs) -> None:
        for f in fs:
            try:
                f.close()
            except OSError:
                pass

    @staticmethod
    def _kill_group(proc) -> None:
        """SIGKILL the stage's whole session: a timed-out bench may have
        device-tunnel children the leader's death would orphan."""
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass

    @staticmethod
    def _tail(path: str, nbytes: int = STDERR_TAIL_BYTES) -> str:
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    async def _heartbeat(self, stage: StageSpec, pid: int,
                         t0: float) -> None:
        """Progress lines while a stage runs — the liveness signal the
        hand-run chains never had (a wedged stage looked identical to a
        long one).  Rides the injected clock so fake-clock tests can
        drive it."""
        while True:
            await self.clock.sleep(self.heartbeat_s)
            elapsed = int(time.perf_counter() - t0)
            self._say(f"warm {self.spec.name}: stage {stage.name}: "
                      f"running {elapsed}s / timeout "
                      f"{int(stage.timeout_s)}s (pid {pid})")

    # -- metrics (never fail the chain) -----------------------------------

    def _count(self, stage: str, outcome: str) -> None:
        try:
            from drand_tpu import metrics as M
            M.WARM_STAGE.labels(self.spec.name, stage, outcome).inc()
        except Exception:
            pass

    def _observe(self, stage: str, dur: float) -> None:
        try:
            from drand_tpu import metrics as M
            M.WARM_STAGE_DURATION.labels(self.spec.name, stage) \
                .observe(dur)
        except Exception:
            pass
