"""Stage bodies for the `smoke3` pipeline (warm/specs.py): tiny,
jax-free, deterministic — the subprocess side of the orchestrator's
kill/resume/retry proofs (tests/test_warm.py, scripts/warm_smoke.py).

    python -m drand_tpu.warm._smoke_stage <stage> <workdir>

Stages:
  s1   writes its artifact immediately.
  s2   the interesting one:
         - if WARM_SMOKE_HANG_S is set (>0), sleeps that long before
           doing anything — the window in which the smoke kills the
           whole orchestrator with SIGKILL;
         - on its first-ever attempt (no ``s2.attempted`` sentinel in
           the workdir) it records the sentinel and exits 137 — the
           shell's SIGKILL encoding, classified TRANSIENT, so the
           runner's RetryPolicy must retry it;
         - on any later attempt it writes its artifact and succeeds.
  s3   writes its artifact immediately (depends on s2 in the spec, so
       it proves the chain continues past a retried stage).
"""

from __future__ import annotations

import json
import os
import sys
import time


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print("usage: _smoke_stage <stage> <workdir>", file=sys.stderr)
        return 2
    stage, workdir = argv
    os.makedirs(workdir, exist_ok=True)
    if stage == "s2":
        hang_s = float(os.environ.get("WARM_SMOKE_HANG_S", "0") or 0)
        if hang_s > 0:
            time.sleep(hang_s)
        sentinel = os.path.join(workdir, "s2.attempted")
        if not os.path.exists(sentinel):
            with open(sentinel, "w") as f:
                f.write("first attempt\n")
            print("smoke s2: injected transient failure (exit 137)",
                  file=sys.stderr)
            return 137
    print(json.dumps({"stage": stage, "ok": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
