"""Mesh-sharded batched verification.

`ShardedVerifier` wraps `drand_tpu.verify.Verifier` with a 1-D device
mesh over the round axis: inputs are placed shard-by-shard, every device
verifies its slice of the chain segment, and the boolean results gather
back.  On a multi-chip host this is the throughput path for catch-up
sync and the check-chain audit; on one chip it degrades to the plain
verifier.

The signer dimension of t-of-n partial verification shards the same way
(`verify_partials`): rounds x signers lays out on a 2-D mesh so both the
catch-up and the aggregation workloads scale with chips.
"""

from __future__ import annotations

import numpy as np


def _pad2(arr: np.ndarray, rp: int, sp: int) -> np.ndarray:
    """Edge-pad the two leading (rounds, signers) axes up to (rp, sp)."""
    r, s = arr.shape[:2]
    widths = [(0, rp - r), (0, sp - s)] + [(0, 0)] * (arr.ndim - 2)
    return np.pad(arr, widths, mode="edge")


class ShardedVerifier:
    def __init__(self, verifier, devices=None, axis: str = "rounds"):
        import jax
        from jax.sharding import Mesh

        self.verifier = verifier
        devs = list(devices if devices is not None else jax.devices())
        self.n_dev = len(devs)
        self.axis = axis
        self.mesh = Mesh(np.array(devs), (axis,))

    def _shard(self, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P(self.axis)))

    def _run_fn(self):
        """The verifier's pure (msgs, sigs, pk) -> bool[B] body
        (`Verifier._run_fn`; stubs provide the same hook)."""
        return self.verifier._run_fn()

    def _sharded_kernel(self, m: int):
        """The verify body compiled with explicit mesh in/out shardings.

        Verifier._kernel's executables (AOT-loaded or compiled fresh) are
        lowered from sharding-less single-device ShapeDtypeStructs: a
        `Compiled` does not re-specialize, so calling one with
        NamedSharding multi-device inputs either fails or (through the
        AOT path's committed-input wrapper) silently device_puts the
        shards back to one device, de-sharding the throughput path.  The
        multi-device path therefore compiles its own kernels, keyed by
        batch size (mesh/axis are fixed per ShardedVerifier), and
        persists them through the same serialized-executable cache as the
        single-device path so a node restart loads instead of recompiling
        (the mesh shape is part of the cache name; aot's env tag already
        pins platform + device count)."""
        cache = getattr(self, "_skernels", None)
        if cache is None:
            cache = self._skernels = {}
        if m not in cache:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from drand_tpu import aot

            name = (f"sharded-{self.axis}{self.n_dev}-"
                    f"{self.verifier._aot_name(m)}")
            fn = aot.load(name)
            if fn is None:
                shard_in = NamedSharding(self.mesh, P(self.axis, None))
                out_sh = NamedSharding(self.mesh, P(self.axis))
                repl = NamedSharding(self.mesh, P())
                pk_sh = jax.tree_util.tree_map(lambda _: repl,
                                               self.verifier._pk)
                fn = jax.jit(
                    self._run_fn(),
                    in_shardings=(shard_in, shard_in, pk_sh),
                    out_shardings=out_sh,
                ).lower(
                    jax.ShapeDtypeStruct((m, self.verifier._msg_len()),
                                         "uint8"),
                    jax.ShapeDtypeStruct((m, self.verifier.shape.sig_len),
                                         "uint8"),
                    self.verifier._pk_struct()).compile()
                try:
                    aot.save(name, fn)
                except Exception as e:
                    import sys
                    print(f"drand_tpu.aot: sharded kernel save failed "
                          f"({type(e).__name__}: {e}); continuing without "
                          "persistence", file=sys.stderr)
            cache[m] = fn
        return cache[m]

    def verify_batch_async(self, rounds, sigs, prev_sigs=None):
        """Dispatch a sharded batch verify without blocking; returns a
        zero-arg callable yielding bool[B] (same contract as
        Verifier.verify_batch_async, so the sync manager's one-in-flight
        pipeline overlaps transfer with compute on multi-device hosts
        too).

        Pads the batch to a multiple of the mesh size so every device
        holds an equal slice (the kernel is branchless — padded lanes
        just redo the last element's work)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        rounds = np.asarray(rounds, dtype=np.uint64)
        n = rounds.shape[0]
        if n == 0 or self.n_dev == 1:
            return self.verifier.verify_batch_async(rounds, sigs, prev_sigs)
        v = self.verifier
        msgs = v.messages(rounds, prev_sigs)
        # pad to devices * bucket granularity
        per_dev = -(-n // self.n_dev)
        from drand_tpu.verify import _bucket
        per_dev = _bucket(per_dev)
        m = per_dev * self.n_dev
        if m != n:
            pad = m - n
            msgs = np.concatenate([msgs, np.repeat(msgs[-1:], pad, 0)])
            sigs = np.concatenate([sigs, np.repeat(sigs[-1:], pad, 0)])
        kern = self._sharded_kernel(m)
        # pk is a replicated runtime argument (verify.py batch-3 design);
        # only the round axis shards
        repl = NamedSharding(self.mesh, P())
        pk = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl),
                                    v._pk)
        import time as _time
        t0 = _time.perf_counter()
        ok = kern(self._shard(jnp.asarray(msgs, jnp.uint8)),
                  self._shard(jnp.asarray(sigs, jnp.uint8)),
                  pk)
        dispatch_s = _time.perf_counter() - t0
        done = [False]

        def resolve():
            t1 = _time.perf_counter()
            out = np.asarray(ok)[:n]
            if not done[0]:
                done[0] = True
                from drand_tpu.profiling import record_dispatch
                record_dispatch("sharded", n, m,
                                dispatch_s + (_time.perf_counter() - t1),
                                devices=self.n_dev, per_dev=per_dev)
            return out
        return resolve

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        """Same contract as Verifier.verify_batch, sharded over rounds."""
        return self.verify_batch_async(rounds, sigs, prev_sigs)()

    # -- t-of-n partial verification on a 2-D rounds x signers mesh ----------

    def verify_partials(self, msgs, sigs, indices, commits, dst):
        """Batched tbls partial verification sharded on a 2-D mesh.

        msgs [R, S, L] uint8 digests, sigs [R, S, 96] uint8 (index prefix
        stripped), indices [R, S] int32, commits = golden G1 commitment
        points (the group's public polynomial), dst = G2 hash suite DST.
        Returns bool [R, S].

        The device mesh factors as (rounds, signers): the signer axis gets
        the largest factor of n_dev that fits S, rounds take the rest —
        both catch-up audits (R large) and live aggregation (S large)
        shard fully.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        msgs = np.asarray(msgs, dtype=np.uint8)
        sigs = np.asarray(sigs, dtype=np.uint8)
        indices = np.asarray(indices, dtype=np.int32)
        R, S = indices.shape
        if self.n_dev == 1:
            return np.asarray(self._partials_kernel(
                commits, dst, (R, S), None, msgs.shape[2])(
                jnp.asarray(msgs), jnp.asarray(sigs), jnp.asarray(indices),
                self._dev_commits(commits)))[:R, :S]
        ds = next(d for d in range(min(self.n_dev, S), 0, -1)
                  if self.n_dev % d == 0)
        dr = self.n_dev // ds
        Rp = -(-R // dr) * dr
        Sp = -(-S // ds) * ds
        if (Rp, Sp) != (R, S):
            msgs = _pad2(msgs, Rp, Sp)
            sigs = _pad2(sigs, Rp, Sp)
            indices = _pad2(indices, Rp, Sp)
        devs = np.array(jax.devices()[:self.n_dev]).reshape(dr, ds)
        mesh = Mesh(devs, ("rounds", "signers"))
        sh3 = NamedSharding(mesh, P("rounds", "signers", None))
        sh2 = NamedSharding(mesh, P("rounds", "signers"))
        kern = self._partials_kernel(commits, dst, (Rp, Sp), (sh3, sh2),
                                     msgs.shape[2])
        repl = NamedSharding(mesh, P())
        dev_commits = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), self._dev_commits(commits))
        ok = kern(jax.device_put(jnp.asarray(msgs), sh3),
                  jax.device_put(jnp.asarray(sigs), sh3),
                  jax.device_put(jnp.asarray(indices), sh2),
                  dev_commits)
        return np.asarray(ok)[:R, :S]

    def verify_partials_shared(self, round_msgs, sigs, indices, table, dst):
        """Rounds-major tabled partial verification on the 2-D mesh: one
        digest per round hashes ONCE (sharded on the rounds axis) and
        broadcasts across the signer axis in-kernel; signer public keys
        gather from the precomputed per-signer table instead of riding
        the Horner eval in-batch.

        round_msgs [R, L] uint8 (one digest per round), sigs [R, S, 96],
        indices [R, S] int32, table = (tx, ty, tinf) signer-key arrays
        (drand_tpu/beacon/signer_table.py), dst = G2 hash suite DST.
        Returns bool [R, S] — bit-identical verdicts to verify_partials
        on the equivalent per-partial batch.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        round_msgs = np.asarray(round_msgs, dtype=np.uint8)
        sigs = np.asarray(sigs, dtype=np.uint8)
        indices = np.asarray(indices, dtype=np.int32)
        R, S = indices.shape
        tx, ty, tinf = (np.asarray(a) for a in table)
        if self.n_dev == 1:
            kern = self._shared_kernel(tx.shape[0], dst, (R, S), None,
                                       round_msgs.shape[1])
            return np.asarray(kern(
                jnp.asarray(round_msgs), jnp.asarray(sigs),
                jnp.asarray(indices), jnp.asarray(tx), jnp.asarray(ty),
                jnp.asarray(tinf)))[:R, :S]
        ds = next(d for d in range(min(self.n_dev, S), 0, -1)
                  if self.n_dev % d == 0)
        dr = self.n_dev // ds
        Rp = -(-R // dr) * dr
        Sp = -(-S // ds) * ds
        if (Rp, Sp) != (R, S):
            sigs = _pad2(sigs, Rp, Sp)
            indices = _pad2(indices, Rp, Sp)
            if Rp != R:
                round_msgs = np.pad(round_msgs, [(0, Rp - R), (0, 0)],
                                    mode="edge")
        devs = np.array(jax.devices()[:self.n_dev]).reshape(dr, ds)
        mesh = Mesh(devs, ("rounds", "signers"))
        shm = NamedSharding(mesh, P("rounds", None))
        sh3 = NamedSharding(mesh, P("rounds", "signers", None))
        sh2 = NamedSharding(mesh, P("rounds", "signers"))
        repl = NamedSharding(mesh, P())
        kern = self._shared_kernel(tx.shape[0], dst, (Rp, Sp),
                                   (shm, sh3, sh2, repl),
                                   round_msgs.shape[1])
        ok = kern(jax.device_put(jnp.asarray(round_msgs), shm),
                  jax.device_put(jnp.asarray(sigs), sh3),
                  jax.device_put(jnp.asarray(indices), sh2),
                  jax.device_put(jnp.asarray(tx), repl),
                  jax.device_put(jnp.asarray(ty), repl),
                  jax.device_put(jnp.asarray(tinf), repl))
        return np.asarray(ok)[:R, :S]

    @staticmethod
    def shared_partials_name(Rp: int, Sp: int, n: int, dst: bytes,
                             msg_len: int = 32) -> str:
        """AOT cache name for a sharded SHARED-HASH tabled partials
        executable at the padded (Rp, Sp) shape (n = table size)."""
        import hashlib as _hl
        dst_h = _hl.sha256(dst).hexdigest()[:8]
        return (f"sharded-partials-shared-{Rp}x{Sp}-n{n}-{dst_h}"
                f"-m{msg_len}")

    def _shared_kernel(self, n: int, dst, shape, shardings,
                       msg_len: int = 32):
        """Shared-hash tabled partial-verify kernel.  The signer-key
        table is a RUNTIME argument (one executable serves every group
        and epoch — same design as the runtime commitments of
        _partials_kernel), so the cache key is shapes only."""
        import jax

        from drand_tpu.ops import bls as BLS

        key = ("shared", n, dst, shape, shardings is not None, msg_len)
        cache = getattr(self, "_pkernels", None)
        if cache is None:
            cache = self._pkernels = {}
        if key not in cache:
            def run(rm, s, i, tx, ty, tinf):
                return BLS.verify_partial_g2_sigs_shared(
                    rm, s, i, (tx, ty, tinf), dst)

            if shardings is None:
                cache[key] = jax.jit(run)
            else:
                import jax.numpy as jnp

                from drand_tpu import aot
                shm, sh3, sh2, repl = shardings
                R, S = shape
                name = self.shared_partials_name(R, S, n, dst, msg_len)
                fn = aot.load(name)
                if fn is None:
                    fn = jax.jit(
                        run,
                        in_shardings=(shm, sh3, sh2, repl, repl, repl),
                        out_shardings=sh2,
                    ).lower(
                        jax.ShapeDtypeStruct((R, msg_len), jnp.uint8),
                        jax.ShapeDtypeStruct((R, S, 96), jnp.uint8),
                        jax.ShapeDtypeStruct((R, S), jnp.int32),
                        jax.ShapeDtypeStruct((n, 32), jnp.int32),
                        jax.ShapeDtypeStruct((n, 32), jnp.int32),
                        jax.ShapeDtypeStruct((n,), jnp.bool_)).compile()
                    try:
                        aot.save(name, fn)
                    except Exception as e:
                        import sys
                        print(f"drand_tpu.aot: sharded shared-partials "
                              f"save failed ({type(e).__name__}: {e}); "
                              "continuing without persistence",
                              file=sys.stderr)
                cache[key] = fn
        return cache[key]

    @staticmethod
    def partials_name(Rp: int, Sp: int, t: int, dst: bytes,
                      msg_len: int = 32) -> str:
        """AOT cache name for a sharded partials executable at the PADDED
        shape (Rp, Sp).  Single source of truth — the warm-persistence
        gate in __graft_entry__ queries this instead of duplicating the
        formula (ADVICE r4)."""
        import hashlib as _hl
        dst_h = _hl.sha256(dst).hexdigest()[:8]
        return f"sharded-partials-{Rp}x{Sp}-t{t}-{dst_h}-m{msg_len}"

    @classmethod
    def partials_artifact_name(cls, n_dev: int, R: int, S: int, t: int,
                               dst: bytes, msg_len: int = 32) -> str:
        """Name for the executable `verify_partials` on an n_dev-device
        host would build for a logical (R, S) batch — applies the same
        mesh factorization + padding as verify_partials."""
        ds = next(d for d in range(min(n_dev, S), 0, -1) if n_dev % d == 0)
        dr = n_dev // ds
        return cls.partials_name(-(-R // dr) * dr, -(-S // ds) * ds,
                                 t, dst, msg_len)

    def _dev_commits(self, commits):
        """Golden commitment points -> device affine pytree (cached by
        wire bytes; conversion is host bignum math)."""
        from drand_tpu.crypto.bls12381 import curve as GC
        from drand_tpu.ops import bls as BLS
        key = tuple(GC.g1_to_bytes(c) for c in commits)
        cache = getattr(self, "_pcommits", None)
        if cache is None:
            cache = self._pcommits = {}
        if key not in cache:
            cache[key] = tuple(BLS._const_g1_affine(c) for c in commits)
        return cache[key]

    def _partials_kernel(self, commits, dst, shape, shardings,
                         msg_len: int = 32):
        """Partial-verify kernel: commitments are RUNTIME arguments (one
        executable serves every group — same design as the runtime public
        key), so the cache key is shapes + threshold only and the
        mesh-sharded form persists through the AOT cache."""
        import jax

        from drand_tpu.ops import bls as BLS

        key = ("partials", len(commits), dst, shape,
               shardings is not None, msg_len)
        cache = getattr(self, "_pkernels", None)
        if cache is None:
            cache = self._pkernels = {}
        if key not in cache:
            def run(m, s, i, dev_commits):
                return BLS.verify_partial_g2_sigs(m, s, i,
                                                  list(dev_commits), dst)

            dev_commits = self._dev_commits(commits)
            cstruct = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                dev_commits)
            if shardings is None:
                cache[key] = jax.jit(run)
            else:
                from drand_tpu import aot
                sh3, sh2 = shardings
                repl = jax.sharding.NamedSharding(
                    sh2.mesh, jax.sharding.PartitionSpec())
                csh = jax.tree_util.tree_map(lambda _: repl, dev_commits)
                R, S = shape
                name = self.partials_name(R, S, len(commits), dst, msg_len)
                fn = aot.load(name)
                if fn is None:
                    import jax.numpy as jnp
                    fn = jax.jit(
                        run, in_shardings=(sh3, sh3, sh2, csh),
                        out_shardings=sh2,
                    ).lower(
                        jax.ShapeDtypeStruct((R, S, msg_len), jnp.uint8),
                        jax.ShapeDtypeStruct((R, S, 96), jnp.uint8),
                        jax.ShapeDtypeStruct((R, S), jnp.int32),
                        cstruct).compile()
                    try:
                        aot.save(name, fn)
                    except Exception as e:
                        import sys
                        print(f"drand_tpu.aot: sharded partials save "
                              f"failed ({type(e).__name__}: {e}); "
                              "continuing without persistence",
                              file=sys.stderr)
                cache[key] = fn
        return cache[key]

    def _verify_single_host(self, round_, sig, prev_sig):
        return self.verifier._verify_single_host(round_, sig, prev_sig)

    def verify_chain_segment(self, start_round: int, sigs, anchor_prev_sig):
        """Same anchor/recursion semantics as the single-device verifier —
        reused directly so the irregular-anchor handling lives once; only
        verify_batch (sharded here) differs."""
        from drand_tpu.verify import Verifier
        return Verifier.verify_chain_segment(
            self, start_round, np.asarray(sigs), anchor_prev_sig)

    def verify_chain_segment_async(self, start_round: int, sigs,
                                   anchor_prev_sig):
        from drand_tpu.verify import Verifier
        return Verifier.verify_chain_segment_async(
            self, start_round, np.asarray(sigs), anchor_prev_sig)
