"""Mesh-sharded batched verification.

`ShardedVerifier` wraps `drand_tpu.verify.Verifier` with a 1-D device
mesh over the round axis: inputs are placed shard-by-shard, every device
verifies its slice of the chain segment, and the boolean results gather
back.  On a multi-chip host this is the throughput path for catch-up
sync and the check-chain audit; on one chip it degrades to the plain
verifier.

The signer dimension of t-of-n partial verification shards the same way
(`verify_partials`): rounds x signers lays out on a 2-D mesh so both the
catch-up and the aggregation workloads scale with chips.
"""

from __future__ import annotations

import numpy as np


class ShardedVerifier:
    def __init__(self, verifier, devices=None, axis: str = "rounds"):
        import jax
        from jax.sharding import Mesh

        self.verifier = verifier
        devs = list(devices if devices is not None else jax.devices())
        self.n_dev = len(devs)
        self.axis = axis
        self.mesh = Mesh(np.array(devs), (axis,))

    def _shard(self, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P(self.axis)))

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        """Same contract as Verifier.verify_batch, sharded over rounds.

        Pads the batch to a multiple of the mesh size so every device
        holds an equal slice (the kernel is branchless — padded lanes
        just redo the last element's work)."""
        import jax.numpy as jnp

        rounds = np.asarray(rounds, dtype=np.uint64)
        n = rounds.shape[0]
        if n == 0 or self.n_dev == 1:
            return self.verifier.verify_batch(rounds, sigs, prev_sigs)
        v = self.verifier
        msgs = v.messages(rounds, prev_sigs)
        # pad to devices * bucket granularity
        per_dev = -(-n // self.n_dev)
        from drand_tpu.verify import _bucket
        per_dev = _bucket(per_dev)
        m = per_dev * self.n_dev
        if m != n:
            pad = m - n
            msgs = np.concatenate([msgs, np.repeat(msgs[-1:], pad, 0)])
            sigs = np.concatenate([sigs, np.repeat(sigs[-1:], pad, 0)])
        kern = v._kernel(m)
        ok = kern(self._shard(jnp.asarray(msgs, jnp.uint8)),
                  self._shard(jnp.asarray(sigs, jnp.uint8)))
        return np.asarray(ok)[:n]

    def _verify_single_host(self, round_, sig, prev_sig):
        return self.verifier._verify_single_host(round_, sig, prev_sig)

    def verify_chain_segment(self, start_round: int, sigs, anchor_prev_sig):
        """Same anchor/recursion semantics as the single-device verifier —
        reused directly so the irregular-anchor handling lives once; only
        verify_batch (sharded here) differs."""
        from drand_tpu.verify import Verifier
        return Verifier.verify_chain_segment(
            self, start_round, np.asarray(sigs), anchor_prev_sig)
