"""Multi-device parallelism for batched verification.

drand's protocol parallelism is t-of-n signing over the WAN (SURVEY.md
§2.3); this package is the DEVICE-side counterpart: the round dimension of
chain verification is embarrassingly parallel (verify(round_i) depends
only on sig_{i-1}, which is data), so a catch-up batch shards across a
`jax.sharding.Mesh` with one `psum` for the verdict — data parallelism
over ICI, the TPU-native replacement for "more verifier threads".
"""

from drand_tpu.parallel.sharded import ShardedVerifier  # noqa: F401
