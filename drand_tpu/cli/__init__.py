"""Operator CLI (reference `cmd/drand-cli/cli.go:302-530`).

    python -m drand_tpu.cli <command> ...

Commands mirror the reference daemon CLI: start, stop, share, load, sync,
generate-keypair, get {public,chain-info}, show {share,group,chain-info,
public,private}, util {status,ping,list-schemes,list-ids,check,backup,
self-sign,reset,del-beacon}.  All non-`start` commands drive the localhost
control port (net/control.go) exactly like the reference.
"""

from drand_tpu.cli.main import main  # noqa: F401
