"""CLI entry point and command implementations.

Counterpart of `cmd/drand-cli/cli.go` (flags/commands, :62-530) and
`control.go` (command impls over `net.ControlClient`, :101-833).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from drand_tpu.net.client import ControlClient, make_metadata
from drand_tpu.protogen import drand_pb2

DEFAULT_FOLDER = os.path.expanduser("~/.drand")
DEFAULT_CONTROL = 8888


def _base_flags(p: argparse.ArgumentParser):
    p.add_argument("--folder", default=DEFAULT_FOLDER,
                   help="drand state folder")
    p.add_argument("--control", type=int, default=DEFAULT_CONTROL,
                   help="control port")
    p.add_argument("--id", default="default", dest="beacon_id",
                   help="beacon id")


def _secret(args) -> bytes:
    """DKG secret: --secret-file or DRAND_SHARE_SECRET
    (cmd/drand-cli/control.go:44-62)."""
    if getattr(args, "secret_file", None):
        with open(args.secret_file, "rb") as f:
            return f.read().strip()
    env = os.environ.get("DRAND_SHARE_SECRET", "")
    if not env:
        raise SystemExit(
            "missing DKG secret: pass --secret-file or set "
            "DRAND_SHARE_SECRET")
    return env.encode()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="drand-tpu",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="run the daemon")
    _base_flags(sp)
    sp.add_argument("--private-listen", default="0.0.0.0:4444")
    sp.add_argument("--public-listen", default="")
    sp.add_argument("--metrics", type=int, default=0)
    # TLS is the default transport posture (cmd/drand-cli/cli.go:62-119):
    # an operator must either supply a cert/key pair or EXPLICITLY opt out
    # with --tls-disable (--insecure is the historical alias).  cmd_start
    # enforces the either/or.
    sp.add_argument("--tls-cert", help="PEM certificate for the private "
                    "gRPC listener")
    sp.add_argument("--tls-key", help="PEM key for --tls-cert")
    sp.add_argument("--tls-disable", "--insecure", dest="tls_disable",
                    action="store_true", default=False,
                    help="run without TLS (tests, local nets)")
    sp.add_argument("--certs-dir", default="",
                    help="folder of trusted peer certificate PEMs "
                    "(self-signed group deployments); system roots are "
                    "used when empty")
    sp.add_argument("--private-rand", action="store_true", default=False,
                    help="serve ECIES private randomness (opt-in)")

    sp = sub.add_parser("stop", help="stop the daemon")
    _base_flags(sp)

    sp = sub.add_parser("generate-keypair",
                        help="create the longterm keypair")
    _base_flags(sp)
    sp.add_argument("address", help="public address host:port")
    sp.add_argument("--tls", action="store_true")
    sp.add_argument("--source", default="",
                    help="executable whose stdout seeds the keypair, "
                    "XOR-mixed with the OS CSPRNG")
    sp.add_argument("--user-source-only", action="store_true", default=False)

    sp = sub.add_parser("share", help="run DKG / reshare")
    _base_flags(sp)
    sp.add_argument("--leader", action="store_true")
    sp.add_argument("--connect", default="", help="leader address")
    sp.add_argument("--tls-disable", "--insecure", dest="tls_disable",
                    action="store_true", default=False,
                    help="dial the leader without TLS (must match the "
                    "network's transport posture)")
    sp.add_argument("--nodes", type=int, default=0)
    sp.add_argument("--threshold", type=int, default=0)
    sp.add_argument("--period", type=int, default=30)
    sp.add_argument("--catchup-period", type=int, default=0)
    sp.add_argument("--scheme", default="pedersen-bls-chained")
    sp.add_argument("--timeout", type=int, default=10)
    sp.add_argument("--secret-file")
    sp.add_argument("--source", default="",
                    help="executable whose stdout supplies DKG entropy, "
                    "XOR-mixed with the OS CSPRNG "
                    "(cmd/drand-cli/cli.go sourceFlag)")
    sp.add_argument("--user-source-only", action="store_true", default=False,
                    help="use ONLY --source entropy (no CSPRNG mixing)")
    sp.add_argument("--transition", action="store_true",
                    help="reshare from the existing group")
    sp.add_argument("--from", dest="old_group_path", default="",
                    help="previous group TOML (joining a reshare)")

    sp = sub.add_parser("load", help="load a beacon from disk")
    _base_flags(sp)

    sp = sub.add_parser("sync", help="follow/sync a chain from peers")
    _base_flags(sp)
    sp.add_argument("--sync-nodes", required=True,
                    help="comma-separated peer addresses")
    sp.add_argument("--up-to", type=int, default=0)
    sp.add_argument("--follow", action="store_true")
    sp.add_argument("--chain-hash", default="")

    sp = sub.add_parser("get", help="fetch randomness / chain info")
    _base_flags(sp)
    sp.add_argument("what", choices=["public", "private", "chain-info"])
    sp.add_argument("round", nargs="?", type=int, default=0)
    sp.add_argument("--url", action="append", default=[],
                    help="HTTP API endpoints")
    sp.add_argument("--watch", action="store_true", default=False,
                    help="get public: stream rounds as they land "
                    "(failover via the optimizing client stack); each "
                    "emitted round logs with its per-round trace id")
    sp.add_argument("--chain-hash", default="")
    sp.add_argument("--group", default="",
                    help="group TOML (get private: node picked from it)")
    sp.add_argument("--certs-dir", default="",
                    help="trusted peer certificate PEMs for TLS group "
                    "members (self-signed deployments)")

    sp = sub.add_parser("show", help="print local state")
    _base_flags(sp)
    sp.add_argument("what", choices=["share", "group", "chain-info",
                                     "public", "private"])

    sp = sub.add_parser("util", help="operator utilities")
    _base_flags(sp)
    sp.add_argument("what", choices=["status", "ping", "list-schemes",
                                     "list-ids", "check", "backup",
                                     "self-sign", "reset", "del-beacon",
                                     "remote-status", "migrate", "health",
                                     "fsck", "journey", "fleet"])
    sp.add_argument("target", nargs="?", default="",
                    help="util health: the node's public HTTP address "
                    "(host:port or URL) to probe; util fsck: the chain "
                    "db path to scan; util journey: the round number "
                    "to reconstruct; util fleet: any group member's "
                    "metrics address (host:port) to pull /debug/fleet "
                    "from")
    sp.add_argument("--nodes", default="",
                    help="util journey: comma-separated metrics "
                    "addresses (host:port) to pull /debug/spans from")
    sp.add_argument("--repair", action="store_true",
                    help="util fsck: quarantine damaged rows and roll "
                    "the tip back to the verified prefix (forensic "
                    "sidecar, nothing deleted)")
    sp.add_argument("--json", action="store_true", dest="json_out",
                    help="util fsck: machine-readable report on stdout")

    sp = sub.add_parser("perf", help="perf trajectory utilities: gate "
                        "unified bench artifacts against the committed "
                        "baselines, show the gated history")
    sp.add_argument("action", choices=["gate", "history"])
    sp.add_argument("artifacts", nargs="*",
                    help="perf gate: unified bench artifact JSON paths")
    sp.add_argument("--baseline", default=None,
                    help="baselines file (default: committed "
                    "tools/perf/baselines.json)")
    sp.add_argument("--history", default=None,
                    help="history JSONL path (default: "
                    "BENCH_HISTORY.jsonl)")
    sp.add_argument("--no-history", action="store_true",
                    help="perf gate: do not append to the history")
    sp.add_argument("--metric", default=None,
                    help="perf history: filter to one <bench>/<metric> "
                    "key")
    sp.add_argument("--limit", type=int, default=20,
                    help="perf history: newest entries to show")

    sp = sub.add_parser("relay", help="run an HTTP relay over upstreams")
    sp.add_argument("--url", action="append", required=True,
                    help="upstream HTTP API endpoints")
    sp.add_argument("--chain-hash", required=True)
    sp.add_argument("--listen", default="0.0.0.0:8080")

    sp = sub.add_parser("relay-pubsub",
                        help="run a push-distribution relay node")
    sp.add_argument("--url", action="append", default=[],
                    help="upstream HTTP API endpoints (optional when "
                    "--bootstrap is given: a pure mesh node learns "
                    "rounds from its peers)")
    sp.add_argument("--chain-hash", required=True)
    sp.add_argument("--listen", default="0.0.0.0:4454")
    sp.add_argument("--bootstrap", default="",
                    help="comma-separated gossip peers; enables the "
                    "self-assembling mesh (peer exchange + degree-D "
                    "subscriptions) instead of a standalone relay")
    sp.add_argument("--degree", type=int, default=3,
                    help="gossip mesh degree (subscriptions kept live)")
    sp.add_argument("--advertise", default="",
                    help="address peers should dial back (defaults to "
                    "the bound listen address)")

    sp = sub.add_parser("lint", help="run the project linter "
                        "(tools/lint: async/clock/jit/secret hygiene)")
    sp.add_argument("paths", nargs="*",
                    help="files/dirs relative to the repo root "
                    "(default: drand_tpu demo tools)")
    sp.add_argument("--format", choices=["text", "json"], default="text",
                    dest="lint_format")
    sp.add_argument("--rule", action="append", default=None,
                    metavar="NAME", dest="lint_rules",
                    help="run only this rule (repeatable; --list-rules "
                    "shows names)")
    sp.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    sp.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline keeping surviving "
                    "justifications")
    sp.add_argument("--list-rules", action="store_true")

    sp = sub.add_parser("chaos", help="deterministic fault injection: "
                        "list failpoint sites/scenarios, run or replay "
                        "seeded multi-node chaos scenarios")
    sp.add_argument("action", choices=["list", "run", "replay"])
    sp.add_argument("scenario", nargs="?", default="",
                    help="scenario name (chaos list shows them)")
    sp.add_argument("--seed", type=int, default=1,
                    help="schedule seed: same seed, same injections — "
                    "replay a failing run by its seed")
    sp.add_argument("--nodes", type=int, default=3)
    sp.add_argument("--threshold", type=int, default=0,
                    help="0 = majority (n//2 + 1)")
    sp.add_argument("--scheme", default="pedersen-bls-unchained")
    sp.add_argument("--json", action="store_true", dest="chaos_json",
                    help="machine-readable report")
    sp.add_argument("--sanitize", action="store_true",
                    help="arm the runtime asyncio sanitizer across the "
                    "fault window (loop-blocking callbacks, unlocked / "
                    "cross-task mutations); also via "
                    "DRAND_TPU_ASYNC_SANITIZE=1")

    sp = sub.add_parser("warm", help="warm/measure pipeline orchestrator "
                        "(drand_tpu/warm): resumable, retrying, "
                        "checkpointed AOT warm chains with environment "
                        "preflight")
    sp.add_argument("action",
                    choices=["run", "resume", "status", "doctor", "list"])
    sp.add_argument("pipeline", nargs="?", default="",
                    help="pipeline name (warm list shows them)")
    sp.add_argument("--workdir", default="",
                    help="override the spec's working directory "
                    "(artifacts + state.json checkpoint)")
    sp.add_argument("--no-doctor", action="store_true",
                    help="skip the environment preflight before "
                    "run/resume (eyes open)")
    sp.add_argument("--fast-doctor", action="store_true",
                    help="preflight without the two-subprocess "
                    "compile-cache probe")
    sp.add_argument("--seed", type=int, default=0,
                    help="retry-backoff hash seed (replay a chain's "
                    "retry schedule byte-for-byte)")
    sp.add_argument("--heartbeat", type=float, default=30.0,
                    help="seconds between stage progress lines")
    sp.add_argument("--metrics", type=int, default=-1, dest="warm_metrics",
                    help="serve /metrics + /debug/spans on this port "
                    "while the chain runs (0 = ephemeral port; default "
                    "off)")
    sp.add_argument("--json", action="store_true", dest="warm_json",
                    help="machine-readable output (status/doctor)")

    sp = sub.add_parser("relay-s3", help="relay rounds into an object "
                        "store (cmd/relay-s3/main.go)")
    sp.add_argument("--url", action="append", required=True,
                    help="upstream HTTP API endpoints")
    sp.add_argument("--chain-hash", required=True)
    sp.add_argument("--bucket", required=True,
                    help="S3 bucket name, or a filesystem path when "
                    "boto3 is unavailable / --fs is set")
    sp.add_argument("--prefix", default="public",
                    help="object key prefix (default: public)")
    sp.add_argument("--fs", action="store_true",
                    help="force the filesystem backend (treat --bucket "
                    "as a directory)")

    sp = sub.add_parser("objectsync",
                        help="content-addressed segment objects over dumb "
                        "object storage (supersedes relay-s3's per-round "
                        "JSON; drand_tpu/objectsync/)")
    sp.add_argument("action", choices=["publish", "sync", "status"])
    sp.add_argument("--dir", default="",
                    help="filesystem object-store root (tests, rsync-to-"
                    "bucket deployments)")
    sp.add_argument("--url", default="",
                    help="HTTP object-store base URL (S3-compatible "
                    "endpoint or any static server / CDN)")
    sp.add_argument("--db", default="",
                    help="chain store sqlite path (publish: source; "
                    "sync: destination)")
    sp.add_argument("--chain-hash", default="",
                    help="hex chain hash pinned into objects / verified "
                    "against the manifest")
    sp.add_argument("--scheme", default="",
                    help="scheme id (default: pedersen-bls-chained)")
    sp.add_argument("--public-key", default="",
                    help="hex group public key (sync: BLS verification)")
    sp.add_argument("--segment-rounds", type=int, default=0,
                    help="rounds per segment object (default 16384; an "
                    "existing manifest's value always wins)")
    sp.add_argument("--up-to", type=int, default=0,
                    help="sync: stop after this round (0 = whole chain)")
    sp.add_argument("--genesis-seed", default="",
                    help="sync: hex genesis seed to anchor an EMPTY "
                    "store (round-0 row); existing stores ignore it")
    return p


# ---------------------------------------------------------------------------
# Command implementations
# ---------------------------------------------------------------------------

async def cmd_start(args):
    from drand_tpu import log as dlog
    dlog.configure(level=os.environ.get("DRAND_LOG_LEVEL", "info"),
                   json_output=bool(os.environ.get("DRAND_LOG_JSON")))
    from drand_tpu.core import Config, DrandDaemon
    if not args.tls_disable and not (args.tls_cert and args.tls_key):
        raise SystemExit(
            "TLS is the default: provide --tls-cert and --tls-key, or "
            "explicitly opt out with --tls-disable "
            "(cmd/drand-cli/cli.go:62-119 enforces the same either/or)")
    cfg = Config(folder=args.folder, private_listen=args.private_listen,
                 public_listen=args.public_listen,
                 control_port=args.control, tls_cert=args.tls_cert,
                 tls_key=args.tls_key, insecure=args.tls_disable,
                 trusted_certs=[args.certs_dir] if args.certs_dir else [],
                 metrics_port=args.metrics,
                 enable_private_rand=args.private_rand)
    daemon = DrandDaemon(cfg)
    await daemon.start()
    loaded = await daemon.load_beacons_from_disk()
    print(f"daemon running: private={daemon.private_addr()} "
          f"control={cfg.control_port} beacons={loaded}")
    try:
        while daemon.control_listener is not None:
            await asyncio.sleep(1)
    except (KeyboardInterrupt, asyncio.CancelledError):
        await daemon.stop()


async def cmd_stop(args):
    cc = ControlClient(args.control)
    await cc.stub.Shutdown(drand_pb2.ShutdownRequest(
        metadata=make_metadata(args.beacon_id)), timeout=10)
    print("daemon stopping")
    await cc.close()


async def cmd_generate_keypair(args):
    from drand_tpu.key.keys import Pair
    from drand_tpu.key.store import FileStore
    ks = FileStore(args.folder, args.beacon_id)
    seed = None
    if args.source:
        from drand_tpu import entropy as ent
        seed = ent.get_random(ent.ScriptReader(args.source), 32,
                              args.user_source_only)
    pair = Pair.generate(args.address, tls=args.tls, seed=seed)
    ks.save_key_pair(pair)
    print(json.dumps({"address": args.address,
                      "public_key": pair.public.key.hex(),
                      "folder": args.folder, "beacon": args.beacon_id}))


async def cmd_share(args):
    if (args.transition or args.old_group_path) and args.source:
        # The reshare wire packet carries no EntropyInfo (ours and the
        # reference's, protobuf/drand/control.proto InitResharePacket):
        # resharing polynomials anchor on the existing share, and the
        # reference CLI silently drops --source here — reject loudly
        # (and before any channel is opened) instead of letting the
        # operator believe their entropy was used.
        raise SystemExit(
            "--source only applies to a fresh DKG (share without "
            "--transition/--from): resharing re-deals the existing "
            "secret and takes no user entropy")
    cc = ControlClient(args.control, timeout_s=600.0)
    secret = _secret(args)
    info = drand_pb2.SetupInfoPacket(
        leader=args.leader, leader_address=args.connect,
        nodes=args.nodes, threshold=args.threshold,
        timeout=args.timeout, secret=secret,
        leader_tls=not args.tls_disable)
    if args.transition or args.old_group_path:
        req = drand_pb2.InitResharePacket(
            info=info, catchup_period=args.catchup_period,
            metadata=make_metadata(args.beacon_id))
        if args.old_group_path:
            req.old.path = args.old_group_path
        group = await cc.stub.InitReshare(req, timeout=600)
    else:
        req = drand_pb2.InitDKGPacket(
            info=info, beacon_period=args.period,
            catchup_period=args.catchup_period, schemeID=args.scheme,
            metadata=make_metadata(args.beacon_id))
        if args.source:
            req.entropy.script = args.source
            req.entropy.userOnly = args.user_source_only
        group = await cc.stub.InitDKG(req, timeout=600)
    from drand_tpu.core import convert
    g = convert.group_from_proto(group)
    print(g.to_toml())
    await cc.close()


async def cmd_load(args):
    cc = ControlClient(args.control)
    await cc.stub.LoadBeacon(drand_pb2.LoadBeaconRequest(
        metadata=make_metadata(args.beacon_id)), timeout=30)
    print(f"beacon {args.beacon_id} loaded")
    await cc.close()


async def cmd_sync(args):
    cc = ControlClient(args.control, timeout_s=0)
    req = drand_pb2.StartSyncRequest(
        nodes=args.sync_nodes.split(","), up_to=args.up_to,
        metadata=make_metadata(
            args.beacon_id,
            bytes.fromhex(args.chain_hash) if args.chain_hash else b""))
    rpc = cc.stub.StartFollowChain if args.follow \
        else cc.stub.StartCheckChain
    async for progress in rpc(req):
        print(f"\rsync {progress.current}/{progress.target}",
              end="", flush=True)
    print()
    await cc.close()


async def cmd_get(args):
    if args.what == "public":
        if not args.url:
            raise SystemExit("get public needs at least one --url")
        from drand_tpu.client import new_client
        chain_hash = bytes.fromhex(args.chain_hash) \
            if args.chain_hash else None
        cli = new_client(urls=args.url, chain_hash=chain_hash,
                         insecure=chain_hash is None,
                         speed_test_interval=0)
        try:
            if args.watch:
                await _watch_public(cli, args.beacon_id)
                return
            d = await cli.get(args.round)
            print(json.dumps({"round": d.round,
                              "randomness": d.randomness.hex(),
                              "signature": d.signature.hex()}))
        finally:
            await cli.close()
    elif args.what == "private":
        # ECIES round trip against a node from the group file
        # (reference: `drand get private group.toml`,
        # cmd/drand-cli/control.go private randomness path +
        # core/drand_beacon_public.go:135-160).
        if not args.group:
            raise SystemExit("get private needs --group <group.toml>")
        import random

        from drand_tpu.crypto import ecies
        from drand_tpu.crypto.bls12381 import curve as GC
        from drand_tpu.key.group import Group
        from drand_tpu.net.client import PeerClients
        import pathlib
        group = Group.from_toml(
            await asyncio.to_thread(pathlib.Path(args.group).read_text))
        if not group.nodes:
            raise SystemExit("group file has no nodes")
        # Shuffled first-success: private randomness is per-node opt-in,
        # so fall through members that refuse (the reference client's
        # peer-iteration discipline).
        candidates = list(group.nodes)
        random.shuffle(candidates)
        pool = None
        if getattr(args, "certs_dir", ""):
            from drand_tpu.net.certs import CertManager
            cm = CertManager()
            cm.add_folder(args.certs_dir)
            pool = cm.pool_pem() or None
        peers = PeerClients(trust_pem=pool)
        errors = []
        try:
            for node in candidates:
                req_bytes, esk = ecies.encode_request(None)
                try:
                    stub = peers.public(node.address, node.tls)
                    resp = await stub.PrivateRand(
                        drand_pb2.PrivateRandRequest(
                            request=req_bytes,
                            metadata=make_metadata(args.beacon_id)),
                        timeout=10)
                    rand = ecies.decrypt_reply(
                        esk, GC.g1_from_bytes(node.key), resp.response)
                    print(json.dumps({"node": node.address,
                                      "randomness": rand.hex()}))
                    return
                except Exception as exc:
                    errors.append(f"{node.address}: {exc}")
            raise SystemExit("no node served private randomness:\n  " +
                             "\n  ".join(errors))
        finally:
            await peers.close()
    else:  # chain-info
        cc = ControlClient(args.control)
        pkt = await cc.stub.ChainInfo(drand_pb2.ChainInfoRequest(
            metadata=make_metadata(args.beacon_id)), timeout=10)
        from drand_tpu.core import convert
        print(convert.info_from_proto(pkt).to_json().decode())
        await cc.close()


async def _watch_public(cli, beacon_id: str) -> None:
    """`get public --watch`: stream rounds through the client stack's
    failover watch (client/optimizing.py watchState — source demotion +
    resubscribe on stream death).  Each emitted round prints AND logs
    with its deterministic per-round trace id, so an operator can pivot
    from a watched round straight into `/debug/spans/{trace_id}` and
    `/debug/logs?trace_id=...` on any group member."""
    from drand_tpu import log as dlog
    from drand_tpu import tracing
    wlog = dlog.get("cli", "watch")
    async for d in cli.watch():
        tid = tracing.round_trace_id(beacon_id, d.round)
        wlog.info("watch round %d", d.round,
                  extra={"trace_id": tid, "span_id": None})
        print(json.dumps({"round": d.round,
                          "randomness": d.randomness.hex(),
                          "signature": d.signature.hex(),
                          "trace_id": tid}), flush=True)


async def cmd_show(args):
    cc = ControlClient(args.control)
    md = make_metadata(args.beacon_id)
    if args.what == "share":
        r = await cc.stub.Share(drand_pb2.ShareRequest(metadata=md),
                                timeout=10)
        print(json.dumps({"index": r.index, "public": r.share.hex()}))
    elif args.what == "group":
        r = await cc.stub.GroupFile(drand_pb2.GroupRequest(metadata=md),
                                    timeout=10)
        from drand_tpu.core import convert
        print(convert.group_from_proto(r).to_toml())
    elif args.what == "chain-info":
        r = await cc.stub.ChainInfo(drand_pb2.ChainInfoRequest(metadata=md),
                                    timeout=10)
        from drand_tpu.core import convert
        print(convert.info_from_proto(r).to_json().decode())
    elif args.what == "public":
        r = await cc.stub.PublicKey(drand_pb2.PublicKeyRequest(metadata=md),
                                    timeout=10)
        print(r.pubKey.hex())
    elif args.what == "private":
        r = await cc.stub.PrivateKey(drand_pb2.PrivateKeyRequest(metadata=md),
                                     timeout=10)
        print(r.priKey.hex())
    await cc.close()


async def cmd_relay(args):
    from drand_tpu.client import new_client
    from drand_tpu.relay import HTTPRelay
    upstream = new_client(urls=args.url,
                          chain_hash=bytes.fromhex(args.chain_hash))
    relay = HTTPRelay(upstream, args.listen)
    await relay.start()
    print(f"HTTP relay serving on :{relay.port}")
    while True:
        await asyncio.sleep(3600)


async def cmd_relay_pubsub(args):
    from drand_tpu.client import new_client
    from drand_tpu.relay import GossipRelayNode, PubSubRelayNode
    chain_hash = bytes.fromhex(args.chain_hash)
    if not args.url and not args.bootstrap:
        raise SystemExit("pass --url (upstream) and/or --bootstrap (mesh)")
    upstream = None
    if args.url:
        upstream = new_client(urls=args.url, chain_hash=chain_hash,
                              auto_watch=True)
    if args.bootstrap:
        peers = [p.strip() for p in args.bootstrap.split(",") if p.strip()]
        from drand_tpu.relay.gossip import is_wildcard_listen
        if is_wildcard_listen(args.listen) and not args.advertise:
            raise SystemExit(
                "--listen binds a wildcard address: peers would learn an "
                "undialable 0.0.0.0 — pass --advertise <host:port>")
        if upstream is not None:
            info = await upstream.info()
        else:
            info = await _fetch_mesh_chain_info(peers, chain_hash)
        node = GossipRelayNode(upstream, args.listen, info,
                               bootstrap=peers, degree=args.degree,
                               advertise=args.advertise or None)
        kind = "gossip relay"
    else:
        node = PubSubRelayNode(upstream, args.listen)
        kind = "pubsub relay"
    await node.start()
    print(f"{kind} serving on {node.address}")
    while True:
        await asyncio.sleep(3600)


async def _fetch_mesh_chain_info(peers: list[str], chain_hash: bytes):
    """A pure mesh node pins its root of trust by fetching chain info
    from a bootstrap peer — GrpcClient.info() already does the fetch,
    conversion, and pinned-hash validation."""
    from drand_tpu.client.grpc import GrpcClient
    last_exc = None
    for addr in peers:
        c = GrpcClient(addr, chain_hash=chain_hash)
        try:
            return await c.info()
        except Exception as exc:
            last_exc = exc
        finally:
            await c.close()
    raise SystemExit(f"no bootstrap peer served chain info: {last_exc}")


async def cmd_relay_s3(args):
    """Object-store relay (cmd/relay-s3/main.go:40-50): boto3 bucket when
    importable, filesystem backend otherwise (or with --fs)."""
    from drand_tpu.client import new_client
    from drand_tpu.relay.s3 import FileStoreBackend, S3Relay
    backend = None
    if not args.fs:
        try:
            import boto3  # not in this image; real deployments have it
            backend = boto3.resource("s3").Bucket(args.bucket)
            backend = _Boto3Backend(backend)
        except ImportError:
            print("boto3 not installed; using filesystem backend at "
                  f"{args.bucket}", file=sys.stderr)
    if backend is None:
        backend = FileStoreBackend(args.bucket)
    upstream = new_client(urls=args.url,
                          chain_hash=bytes.fromhex(args.chain_hash))
    relay = S3Relay(upstream, backend, prefix=args.prefix)
    await relay.start()
    print(f"s3 relay uploading to {args.bucket}/{args.prefix}")
    while True:
        await asyncio.sleep(3600)


def _objectsync_backend(args):
    from drand_tpu.objectsync import FilesystemBackend, HTTPBackend
    if bool(args.dir) == bool(args.url):
        raise SystemExit("objectsync needs exactly one of --dir / --url")
    return FilesystemBackend(args.dir) if args.dir else HTTPBackend(args.url)


async def cmd_objectsync(args):
    """Objectsync tier (drand_tpu/objectsync/; supersedes relay-s3's
    per-round JSON uploads): one-shot publish of sealed segments from a
    local chain db, verify-then-commit sync of a local db from published
    objects, or backend status."""
    from drand_tpu import objectsync as osync
    backend = _objectsync_backend(args)
    try:
        if args.action == "status":
            try:
                m = osync.Manifest.from_json(
                    await backend.get(osync.MANIFEST_NAME))
            except osync.ObjectNotFound:
                print(json.dumps({"backend": backend.describe(),
                                  "manifest": None}))
                return
            print(json.dumps({
                "backend": backend.describe(),
                "chain_hash": m.chain_hash,
                "scheme": m.scheme_id,
                "segment_rounds": m.segment_rounds,
                "segments": len(m.segments),
                "tip": m.tip,
            }, indent=1))
            return

        if not args.db or not args.chain_hash:
            raise SystemExit(
                f"objectsync {args.action} needs --db and --chain-hash")
        from drand_tpu.chain.scheme import scheme_by_id
        from drand_tpu.chain.store import (AppendStore, SchemeStore,
                                           SqliteStore)
        scheme = scheme_by_id(args.scheme or None)
        chain_hash = bytes.fromhex(args.chain_hash)

        if args.action == "publish":
            store = SqliteStore(args.db)
            try:
                pub = osync.ObjectPublisher(
                    store, backend, chain_hash=chain_hash,
                    scheme_id=scheme.id,
                    segment_rounds=(args.segment_rounds
                                    or osync.DEFAULT_SEGMENT_ROUNDS))
                await pub.load_manifest()
                published = await pub.publish_sealed()
                snap = pub.snapshot()
                snap["published_now"] = published
                print(json.dumps(snap, indent=1))
                if pub.last_error:
                    raise SystemExit(1)
            finally:
                store.close()
            return

        # sync: verify every fetched segment against the LOCAL anchor
        # before committing — the object store is fully untrusted
        if not args.public_key:
            raise SystemExit("objectsync sync needs --public-key")
        from drand_tpu.chain.beacon import Beacon
        from drand_tpu.chain.store import BeaconNotFound
        from drand_tpu.chain.verify import ChainVerifier
        from drand_tpu.resilience import Resilience
        base = SqliteStore(args.db)
        store = SchemeStore(AppendStore(base), scheme.decouple_prev_sig)
        try:
            try:
                store.last()
            except BeaconNotFound:
                if not args.genesis_seed:
                    raise SystemExit(
                        "empty store: pass --genesis-seed to anchor "
                        "round 0")
                store.put(Beacon(round=0,
                                 signature=bytes.fromhex(
                                     args.genesis_seed)))
            verifier = ChainVerifier(scheme,
                                     bytes.fromhex(args.public_key))
            client = osync.ObjectSyncClient(
                backend, store, verifier, chain_hash=chain_hash,
                resilience=Resilience())
            result = await client.sync(up_to=args.up_to)
            out = result.to_dict()
            out["stats"] = dict(client.stats)
            print(json.dumps(out, indent=1))
            if not result.ok:
                raise SystemExit(1)
        finally:
            base.close()
    finally:
        await backend.close()


async def cmd_chaos(args):
    """Chaos subcommand: list sites/scenarios, run/replay a seeded
    scenario through the in-process multi-node harness."""
    from drand_tpu.chaos import failpoints
    if args.action == "list":
        from drand_tpu.chaos import runner as _r   # jax path; list needs
        print("failpoint sites:")
        for site, doc in sorted(failpoints.SITES.items()):
            print(f"  {site:18s} {doc}")
        print("\nscenarios (drand-tpu chaos run <name> --seed S):")
        for name, spec in sorted(_r.SCENARIOS.items()):
            tag = " [slow]" if spec.slow else ""
            print(f"  {name:22s}{tag} {spec.doc}")
        print(f"  {'mesh-churn':22s} seeded kill/restart waves + one-way "
              "partition over an N-node gossip relay mesh "
              "(--nodes, default 24; drand_tpu/chaos/mesh.py)")
        return
    if not args.scenario:
        raise SystemExit("chaos run/replay needs a scenario name "
                         "(see `drand-tpu chaos list`)")
    from drand_tpu.chaos import runner
    if args.scenario != "mesh-churn" \
            and args.scenario not in runner.SCENARIOS:
        raise SystemExit(f"unknown scenario {args.scenario!r} "
                         f"(known: {sorted(runner.SCENARIOS) + ['mesh-churn']})")
    from drand_tpu.chaos.invariants import InvariantViolation
    try:
        if args.scenario == "mesh-churn":
            from drand_tpu.chaos import mesh
            # --nodes keeps its protocol-harness default of 3; the mesh
            # floor is where churn gets interesting
            report = await mesh.run_mesh_scenario(
                args.seed, nodes=args.nodes if args.nodes > 3 else 24)
        else:
            report = await runner.run_scenario(
                args.scenario, args.seed, nodes=args.nodes,
                threshold=args.threshold or None, scheme=args.scheme,
                sanitize=True if args.sanitize else None)
    except (InvariantViolation, AssertionError) as exc:
        print(f"FAIL seed={args.seed} scenario={args.scenario}: {exc}",
              file=sys.stderr)
        print(f"replay with: drand-tpu chaos replay {args.scenario} "
              f"--seed {args.seed}", file=sys.stderr)
        raise SystemExit(1)
    if args.chaos_json:
        print(json.dumps(report.to_dict(), indent=2))
        if getattr(report, "sanitized", False) and report.sanitizer_reports:
            raise SystemExit(1)
        return
    print(f"scenario {report.scenario} seed={report.seed} "
          f"nodes={report.nodes} thr={report.threshold}: OK")
    print(f"  final rounds:  {report.final_rounds}")
    print(f"  invariants:    {', '.join(report.invariants_passed)}")
    print(f"  injections:    {len(report.injections)} "
          f"({len(report.summary)} distinct)")
    print(f"  decisions:     {len(report.decisions)} retry/breaker "
          f"({len(report.decision_summary)} distinct)")
    if getattr(report, "sanitized", False):   # mesh reports lack it
        print(f"  sanitizer:     armed, "
              f"{len(report.sanitizer_reports)} report(s)")
        for r in report.sanitizer_reports:
            print(f"    [{r['kind']}] {r['what']} — {r['detail']}")
        if report.sanitizer_reports:
            # a sanitized run is a race gate: reports are failures
            # (exit-coded so check.sh and CI treat them like a
            # violated invariant), with the full stacks on stderr
            for r in report.sanitizer_reports:
                print(f"[{r['kind']}] {r['what']} — {r['detail']}\n"
                      f"{r['stack']}", file=sys.stderr)
            raise SystemExit(1)
    if args.action == "replay":
        # the replay view: the full deterministic injection log, then
        # the resilience layer's retry/breaker decision log
        for entry in report.injections:
            print("  " + json.dumps(entry, sort_keys=True))
        for entry in report.decisions:
            print("  " + json.dumps(entry, sort_keys=True))


class _WarmMetricsShim:
    """A daemon-shaped object for MetricsServer when the warm
    orchestrator (no daemon, no beacons) serves its exposition: the
    registry's warm/AOT collectors plus /debug/spans for the per-stage
    tracing spans."""

    processes: dict = {}


async def cmd_warm(args):
    """Warm-pipeline orchestrator: run/resume/status a declarative
    warm chain, or run the environment doctor standalone.  Jax-free on
    purpose — stages pay backend init in their own subprocesses, and
    the doctor probes it from a subprocess precisely because it can
    hang."""
    from drand_tpu.warm import doctor as wdoctor
    from drand_tpu.warm import runner as wrunner
    from drand_tpu.warm import specs as wspecs
    from drand_tpu.warm.spec import repo_root

    if args.action == "list":
        for name, spec in sorted(wspecs.SPECS.items()):
            print(f"{name}: {len(spec.stages)} stages — {spec.doc}")
            for st in spec.order():
                deps = f" (after {', '.join(st.deps)})" if st.deps else ""
                print(f"  {st.name:20s} timeout={int(st.timeout_s)}s"
                      f"{deps}")
        return

    if args.action == "doctor":
        spec = wspecs.get(args.pipeline) if args.pipeline else None
        workdir = args.workdir or os.path.join(
            repo_root(), spec.workdir if spec else "warm_logs")
        results = await asyncio.to_thread(
            wdoctor.run_doctor, workdir, args.fast_doctor)
        if args.warm_json:
            print(json.dumps([{"name": r.name, "ok": r.ok,
                               "verdict": r.verdict} for r in results],
                             indent=2))
        ok = wdoctor.print_results(results)
        if not ok:
            raise SystemExit(2)
        return

    if not args.pipeline:
        raise SystemExit(f"warm {args.action} needs a pipeline name "
                         "(see `drand-tpu warm list`)")
    spec = wspecs.get(args.pipeline)
    runner = wrunner.PipelineRunner(
        spec, args.workdir or None, seed=args.seed,
        heartbeat_s=args.heartbeat)

    if args.action == "status":
        st = runner.status()
        if args.warm_json:
            print(json.dumps(st, indent=2, sort_keys=True))
        else:
            print(f"pipeline {st['pipeline']} "
                  f"({'complete' if st['complete'] else 'incomplete'}) "
                  f"— state: {st['state_file']}")
            for row in st["stages"]:
                print(f"  {row['stage']:20s} {row['status']:8s} "
                      f"attempts={row['attempts']} next={row['next']} "
                      f"({row['why']})")
        return

    # run / resume
    if not args.no_doctor:
        results = await asyncio.to_thread(
            wdoctor.run_doctor, runner.workdir, args.fast_doctor)
        if not wdoctor.print_results(results):
            raise SystemExit(2)
    metrics_srv = None
    if args.warm_metrics >= 0:
        from drand_tpu.metrics import MetricsServer
        metrics_srv = MetricsServer(_WarmMetricsShim(), args.warm_metrics)
        await metrics_srv.start()
    try:
        await runner.run(resume=(args.action == "resume"))
    except wrunner.StageFailure:
        raise SystemExit(1)    # the runner already printed the verdict
    finally:
        if metrics_srv is not None:
            await metrics_srv.stop()
    print(f"warm {spec.name}: complete (state: {runner.state_path})")


class _Boto3Backend:
    """Adapt a boto3 Bucket to the put(key, body) backend protocol."""

    def __init__(self, bucket):
        self.bucket = bucket

    def put(self, key: str, body: bytes) -> None:
        self.bucket.put_object(Key=key, Body=body,
                               ContentType="application/json")


async def cmd_util(args):
    md = make_metadata(args.beacon_id)
    if args.what == "fsck":
        # Offline integrity check against a chain db file — no daemon,
        # no control port, no jax: the structural scan (codec decode,
        # round contiguity, prev-sig linkage) from
        # drand_tpu/chain/recovery.py, working on mixed JSON/binary
        # stores.  Exit 0 on a clean chain, 1 when damage was found
        # (fsck convention: non-zero means something needed attention,
        # repaired or not).
        if not args.target:
            raise SystemExit("util fsck needs a chain db path: "
                             "drand-tpu util fsck <store.db> "
                             "[--repair] [--json]")
        if not os.path.exists(args.target):
            raise SystemExit(f"no such db: {args.target}")
        from drand_tpu.chain.recovery import repair_store, scan_store
        from drand_tpu.chain.store import SqliteStore
        store = SqliteStore(args.target)
        try:
            report = await scan_store(store, None,
                                      beacon_id=args.beacon_id)
            summary = None
            if args.repair and not report.ok:
                summary = repair_store(store, report)
            if args.json_out:
                out = report.to_dict()
                out["repair"] = summary
                print(json.dumps(out))
            else:
                d = report.to_dict()
                print(f"scanned {report.scanned} rows "
                      f"(rounds {report.first_round}..{report.tip_round}) "
                      f"in {report.elapsed_s:.3f}s")
                for k in ("corrupt", "missing", "unlinked", "bad_sigs"):
                    if d[k]:
                        print(f"  {k}: {d[k]}")
                if report.ok:
                    print("chain OK")
                elif summary is not None:
                    print(f"repaired: quarantined "
                          f"{summary['quarantined']} damaged + "
                          f"{summary['truncated']} rolled-back rows; "
                          f"tip now {summary['verified_tip']} "
                          f"(re-sync the suffix from peers)")
                else:
                    print(f"DAMAGE FOUND (verified prefix ends at "
                          f"{report.verified_tip}); run with --repair "
                          f"to quarantine and roll back")
        finally:
            store.close()
        raise SystemExit(0 if report.ok else 1)
    if args.what == "health":
        # operator liveness probe against the node's public HTTP API
        # (the reference's curl-/health runbook step as a subcommand):
        # exit 0 on 200/caught-up, 1 on 503/behind or unreachable.
        if not args.target:
            raise SystemExit("util health needs the node's public HTTP "
                             "address: drand-tpu util health <host:port>")
        base = args.target if args.target.startswith("http") \
            else f"http://{args.target}"
        import aiohttp
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base.rstrip('/')}/health",
                                 timeout=aiohttp.ClientTimeout(
                                     total=10)) as r:
                    body = await r.json()
                    print(json.dumps({"status": r.status, **body}))
                    if r.status != 200:
                        raise SystemExit(1)
        except aiohttp.ClientError as exc:
            raise SystemExit(f"health probe failed: {exc}")
        return
    if args.what == "fleet":
        # group-wide observatory view: any member's metrics port serves
        # /debug/fleet (its own exposition + every group peer's, scraped
        # over the node-to-node metrics RPC), rendered as one table.
        # Stays jax-free: the render consumes the JSON shape only.
        if not args.target:
            raise SystemExit("util fleet needs a group member's metrics "
                             "address: drand-tpu util fleet <host:port>")
        base = args.target if args.target.startswith("http") \
            else f"http://{args.target}"
        import aiohttp
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base.rstrip('/')}/debug/fleet",
                                 timeout=aiohttp.ClientTimeout(
                                     total=30)) as r:
                    if r.status != 200:
                        raise SystemExit(
                            f"/debug/fleet returned {r.status}: "
                            f"{await r.text()}")
                    snap = await r.json()
        except aiohttp.ClientError as exc:
            raise SystemExit(f"fleet probe failed: {exc}")
        if args.json_out:
            print(json.dumps(snap, indent=1))
        else:
            from drand_tpu.observatory.fleet import render_table
            print(render_table(snap))
        unreachable = [n["address"] for n in snap.get("nodes", [])
                       if not n.get("ok")]
        raise SystemExit(1 if unreachable else 0)
    if args.what == "journey":
        # reconstruct one round's cross-node journey: pull the round's
        # trace spans from every peer's metrics port and merge them into
        # a single wall-ordered timeline + canonical hop record (the
        # offline twin of each node's live /debug/journey view).
        if not args.target:
            raise SystemExit("util journey needs a round number: "
                             "drand-tpu util journey <round> "
                             "--nodes host:port[,host:port...]")
        try:
            round_ = int(args.target)
        except ValueError:
            raise SystemExit(f"not a round number: {args.target!r}")
        nodes = [n.strip() for n in args.nodes.split(",") if n.strip()]
        if not nodes:
            raise SystemExit("util journey needs --nodes: comma-"
                             "separated metrics addresses (host:port) "
                             "to pull /debug/spans from")
        from drand_tpu import tracing
        from drand_tpu.profiling import journey as journey_mod
        trace_id = tracing.round_trace_id(args.beacon_id, round_)
        import aiohttp
        spans, errors = [], {}
        async with aiohttp.ClientSession() as s:
            for node in nodes:
                base = node if node.startswith("http") \
                    else f"http://{node}"
                url = f"{base.rstrip('/')}/debug/spans/{trace_id}"
                try:
                    async with s.get(url, timeout=aiohttp.ClientTimeout(
                            total=10)) as r:
                        if r.status == 404:
                            errors[node] = "no spans for this round"
                            continue
                        body = await r.json()
                        for d in body.get("spans", []):
                            d.setdefault("node", node)
                            spans.append(d)
                except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                    errors[node] = str(exc) or type(exc).__name__
        merged = journey_mod.collate(spans, beacon_id=args.beacon_id,
                                     round_=round_)
        merged = {"round": round_, "trace_id": trace_id, **merged}
        if errors:
            merged["errors"] = errors
        print(json.dumps(merged, indent=1))
        if not spans:
            raise SystemExit(1)
        return
    if args.what == "migrate":
        from drand_tpu.core.migration import migrate_old_folder_structure
        moved = migrate_old_folder_structure(args.folder)
        print("migrated" if moved else "nothing to migrate")
        return
    if args.what == "self-sign":
        from drand_tpu.key.store import FileStore
        ks = FileStore(args.folder, args.beacon_id)
        pair = ks.load_key_pair()
        pair.self_sign()
        ks.save_key_pair(pair)
        print("keypair re-signed")
        return
    if args.what == "reset":
        import shutil
        target = os.path.join(args.folder, "multibeacon", args.beacon_id,
                              "db")
        if os.path.isdir(target):
            shutil.rmtree(target)
        print(f"chain data for {args.beacon_id} removed")
        return
    if args.what == "del-beacon":
        import shutil
        target = os.path.join(args.folder, "multibeacon", args.beacon_id)
        if os.path.isdir(target):
            shutil.rmtree(target)
        print(f"beacon {args.beacon_id} removed")
        return

    cc = ControlClient(args.control)
    if args.what == "ping":
        await cc.ping(args.beacon_id)
        print("pong")
    elif args.what == "status":
        r = await cc.stub.Status(drand_pb2.StatusRequest(metadata=md),
                                 timeout=10)
        print(json.dumps({
            "beacon": {"running": r.beacon.is_running},
            "chain": {"last_round": r.chain_store.last_round,
                      "length": r.chain_store.length,
                      "empty": r.chain_store.is_empty}}))
    elif args.what == "list-schemes":
        r = await cc.stub.ListSchemes(
            drand_pb2.ListSchemesRequest(metadata=md), timeout=10)
        print("\n".join(r.ids))
    elif args.what == "list-ids":
        r = await cc.stub.ListBeaconIDs(
            drand_pb2.ListBeaconIDsRequest(metadata=md), timeout=10)
        print("\n".join(r.ids))
    elif args.what == "check":
        async for p in cc.stub.StartCheckChain(
                drand_pb2.StartSyncRequest(metadata=md)):
            print(f"\rcheck {p.current}/{p.target}", end="", flush=True)
        print()
    elif args.what == "backup":
        if not args.target:
            raise SystemExit("util backup needs an output path")
        await cc.stub.BackupDatabase(drand_pb2.BackupDBRequest(
            output_file=args.target, metadata=md), timeout=120)
        print(f"backup written to {args.target}")
    elif args.what == "remote-status":
        req = drand_pb2.RemoteStatusRequest(metadata=md)
        for a in (args.target or "").split(","):
            if a:
                req.addresses.append(drand_pb2.Address(address=a))
        r = await cc.stub.RemoteStatus(req, timeout=30)
        out = {a: {"last_round": s.chain_store.last_round}
               for a, s in r.statuses.items()}
        print(json.dumps(out))
    await cc.close()


def cmd_lint(args) -> int:
    """Run the project linter (tools/lint).  Synchronous and jax-free:
    the gate must be cheap enough to run on every edit.  Resolves the
    repo root from this file so `drand-tpu lint` works from anywhere
    inside a checkout."""
    import pathlib
    root = pathlib.Path(__file__).resolve().parents[2]
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))
    try:
        from tools.lint.__main__ import run as lint_run
    except ImportError:
        print("error: tools/lint not importable — `drand-tpu lint` needs "
              "a repo checkout", file=sys.stderr)
        return 2
    argv = list(args.paths) + ["--format", args.lint_format]
    for name in args.lint_rules or []:
        argv += ["--rule", name]
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.list_rules:
        argv.append("--list-rules")
    return lint_run(argv)


def cmd_perf(args) -> int:
    """Perf trajectory utilities (tools/perf).  Synchronous and
    jax-free, like `lint`: gating a bench artifact or reading the
    history must not pay the device-stack import."""
    import pathlib
    root = pathlib.Path(__file__).resolve().parents[2]
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))
    try:
        from tools.perf import gate, schema
    except ImportError:
        print("error: tools/perf not importable — `drand-tpu perf` "
              "needs a repo checkout", file=sys.stderr)
        return 2
    if args.action == "gate":
        if not args.artifacts:
            print("perf gate needs artifact paths: "
                  "drand-tpu perf gate BENCH_foo.json [...]",
                  file=sys.stderr)
            return 2
        argv = list(args.artifacts)
        if args.baseline:
            argv += ["--baseline", args.baseline]
        if args.history:
            argv += ["--history", args.history]
        if args.no_history:
            argv.append("--no-history")
        return gate.main(argv)
    # history: newest gated entries, optionally one metric's trajectory
    entries = gate.read_history(args.history or gate.DEFAULT_HISTORY,
                                limit=args.limit, metric=args.metric)
    if not entries:
        print("no gated history"
              + (f" for {args.metric}" if args.metric else ""))
        return 0
    for e in entries:
        rec = e.get("record", {})
        delta = e.get("delta_frac")
        print(f"{e.get('gated_at', 0):.0f}  [{e.get('status', '?'):9s}] "
              f"{schema.metric_key(rec)}: {rec.get('value')} "
              f"{rec.get('unit', '')}"
              + (f"  ({delta:+.1%})" if delta is not None else ""))
    return 0


_COMMANDS = {
    "start": cmd_start, "stop": cmd_stop,
    "generate-keypair": cmd_generate_keypair, "share": cmd_share,
    "load": cmd_load, "sync": cmd_sync, "get": cmd_get,
    "show": cmd_show, "util": cmd_util,
    "relay": cmd_relay, "relay-pubsub": cmd_relay_pubsub,
    "relay-s3": cmd_relay_s3, "objectsync": cmd_objectsync,
    "chaos": cmd_chaos, "warm": cmd_warm,
}


def _ensure_jax_backend() -> None:
    """Fall back to the CPU backend when the configured platform is
    unavailable (e.g. JAX_PLATFORMS points at a TPU plugin that isn't on
    this operator machine).  The daemon's live protocol path runs on host
    crypto; the device kernels only accelerate batch verification, and
    XLA:CPU serves those fine."""
    try:
        import jax
        jax.devices()
    except Exception:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.devices()
        except Exception as exc:  # pragma: no cover
            print(f"warning: no usable JAX backend ({exc}); "
                  "batch verification disabled", file=sys.stderr)


# commands that touch the JAX device path (daemon verification, client
# verification, chain sync); everything else skips the multi-second import
_NEEDS_JAX = {"start", "get", "sync", "share", "relay", "relay-pubsub",
              "relay-s3", "chaos", "objectsync"}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "lint":     # sync, jax-free
        return cmd_lint(args)
    if args.command == "perf":     # sync, jax-free
        return cmd_perf(args)
    if args.command == "chaos":
        # the scenario nets sync only dozens of rounds: pin the small
        # verify bucket the default test suite already warms, instead of
        # paying a fresh multi-minute XLA compile for the 512 bucket
        os.environ.setdefault("DRAND_TPU_BUCKETS", "64")
    if args.command in _NEEDS_JAX:
        _ensure_jax_backend()
    try:
        asyncio.run(_COMMANDS[args.command](args))
        return 0
    except KeyboardInterrupt:
        return 130
    except SystemExit as e:
        raise
    except Exception as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
