import sys

from drand_tpu.cli.main import main

sys.exit(main())
