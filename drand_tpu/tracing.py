"""Per-round distributed tracing: spans, context propagation, recording.

The reference daemon answers "where did round N spend its time?" with
pprof-on-metrics (metrics/pprof/pprof.go) plus zap's hierarchical
loggers; neither survives a network hop or lines up with an XLA device
timeline.  This module is the TPU-native replacement (SURVEY §5.1):

  - `Span`: one timed stage.  Durations come from `time.perf_counter`
    (monotonic — fake-clock tests advance protocol time without
    corrupting measured latencies); the wall-clock *start stamp* is kept
    separately so operators can correlate a span with their incident
    timeline, and is injectable for tests (`set_wall_clock`).
  - per-round trace identity: `round_trace_id(beacon_id, round)` is a
    deterministic hash, so the partial-aggregation task, the store
    commit thread, and the batched-verify resolver all join round N's
    trace without threading a context object through every queue hop.
  - asyncio `contextvars` propagation: `span(...)` installs itself as
    the current span for the enclosing task; children parent to it.
  - RPC propagation: `inject()` stamps the current span into the
    protobuf `Metadata` every node-to-node request already carries
    (net/client.py make_metadata); `server_span()` re-roots the
    handler's context from it (net/rpc.py), so a peer's spans record
    the caller's span as parent.
  - `SpanRecorder`: bounded in-process ring buffer behind the
    `/debug/spans` routes on the metrics port (drand_tpu/metrics.py).
  - device bridge: `device=True` opens a `jax.profiler.TraceAnnotation`
    for the span's lifetime, so host spans wrapping device work appear
    by the same name in the TensorBoard xplane trace captured via
    `/debug/jax-profile`.

Every ended span also feeds the `drand_stage_duration_seconds{stage,
beacon_id}` Prometheus histogram (drand_tpu/metrics.py), which is how
perf PRs get their before/after stage numbers for free.

Non-context-manager use MUST balance `begin_span()` with `Span.end()`
(the tools/lint `span-balance` rule enforces this mechanically); prefer
`with tracing.span(...)` wherever the stage is lexically scoped.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from drand_tpu import log as dlog
log = dlog.get("tracing")

TRACE_ID_LEN = 16      # bytes; hex-encoded in span dicts and metadata
SPAN_ID_LEN = 8

# wall-clock stamps exist purely so operators can line a span up with
# logs / incident timelines; durations never touch this — injectable
# for tests via set_wall_clock
_wall = time.time  # lint: disable=no-wall-clock

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "drand_tpu_current_span", default=None)


def set_wall_clock(fn) -> None:
    """Inject the wall-clock source (tests pass a fake; None resets)."""
    global _wall
    _wall = fn if fn is not None else time.time  # lint: disable=no-wall-clock


def new_trace_id() -> str:
    return os.urandom(TRACE_ID_LEN).hex()


def new_span_id() -> str:
    return os.urandom(SPAN_ID_LEN).hex()


def round_trace_id(beacon_id: str, round_: int) -> str:
    """Deterministic trace id for one (beacon chain, round): every node
    in the group derives the same id, so even spans with no causal RPC
    link (each node's own broadcast, verify, commit) collate into one
    cross-cluster view of round N."""
    h = hashlib.sha256(f"round:{beacon_id}:{round_}".encode()).digest()
    return h[:TRACE_ID_LEN].hex()


@dataclass
class Span:
    """One timed stage of a round (or request) lifecycle."""
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    beacon_id: str = ""
    round: int | None = None
    attrs: dict = field(default_factory=dict)
    status: str = "ok"
    start_wall: float = 0.0
    duration_s: float | None = None     # set by end()
    _start_mono: float = 0.0
    _annotation: object = None
    _ended: bool = False

    def start(self) -> "Span":
        self.start_wall = _wall()
        self._start_mono = time.perf_counter()
        return self

    def end(self, status: str | None = None) -> "Span":
        """Close the span: fix the duration, record it, feed the stage
        histogram, close the device annotation.  Idempotent."""
        if self._ended:
            return self
        self._ended = True
        self.duration_s = time.perf_counter() - self._start_mono
        if status is not None:
            self.status = status
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:
                pass
            self._annotation = None
        RECORDER.record(self)
        try:
            from drand_tpu import metrics as M
            M.STAGE_DURATION.labels(self.name, self.beacon_id or "-") \
                .observe(self.duration_s)
        except Exception:
            log.debug("stage histogram observe failed", exc_info=True)
        # journey hops ride the same close: the collator ignores spans
        # that are not hop material (profiling/journey._SPAN_HOPS)
        try:
            from drand_tpu.profiling import journey
            journey.feed_span(self)
        except Exception:
            log.debug("journey feed failed", exc_info=True)
        return self

    def annotate_device(self) -> None:
        """Open a jax.profiler.TraceAnnotation for this span's lifetime
        so it shows up by name in the XLA timeline (profiling.annotate).
        Never fails the caller — tracing must not break verification."""
        try:
            from drand_tpu import profiling
            ann = profiling.annotate(self.name)
            ann.__enter__()
            self._annotation = ann
        except Exception:
            self._annotation = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "beacon_id": self.beacon_id, "round": self.round,
            "start": round(self.start_wall, 6),
            "duration_s": (round(self.duration_s, 9)
                           if self.duration_s is not None else None),
            "status": self.status, "attrs": dict(self.attrs),
        }

    # context-manager protocol: `with begin_span(...) as sp:` also works
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end("error" if exc_type is not None else None)


class SpanRecorder:
    """Bounded in-process ring buffer of ended spans.

    Thread-safe: spans end on the event loop, the crypto worker thread,
    and the store callback pool alike.  Reads scan the ring — it is a
    debug surface sized in the low thousands, not a query engine."""

    def __init__(self, maxlen: int = 4096):
        self._spans: deque[Span] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def trace(self, trace_id: str) -> list[Span]:
        return [s for s in self.spans() if s.trace_id == trace_id]

    def traces(self, limit: int = 50, offset: int = 0) -> dict:
        """Newest-first trace summaries with explicit pagination state
        (total + truncated flag — never a silent cap)."""
        by_trace: dict[str, list[Span]] = {}
        order: list[str] = []
        for s in self.spans():
            if s.trace_id not in by_trace:
                by_trace[s.trace_id] = []
                order.append(s.trace_id)
            by_trace[s.trace_id].append(s)
        order.reverse()                  # newest trace first
        page = order[offset:offset + limit]
        out = []
        for tid in page:
            spans = by_trace[tid]
            out.append({
                "trace_id": tid,
                "beacon_id": next((s.beacon_id for s in spans
                                   if s.beacon_id), ""),
                "round": next((s.round for s in spans
                               if s.round is not None), None),
                "spans": len(spans),
                "stages": sorted({s.name for s in spans}),
                "start": min(s.start_wall for s in spans),
                "total_duration_s": round(
                    sum(s.duration_s or 0.0 for s in spans), 9),
            })
        return {"traces": out, "total": len(order), "offset": offset,
                "truncated": offset + limit < len(order)}

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


RECORDER = SpanRecorder()


def current() -> Span | None:
    return _current.get()


def begin_span(name: str, *, beacon_id: str = "", round_: int | None = None,
               trace_id: str | None = None, parent_id: str | None = None,
               device: bool = False, **attrs) -> Span:
    """Start a span WITHOUT making it the context's current span — the
    split start/end form for stages whose close happens in a different
    scope (e.g. a batched verify's dispatch vs its resolver).  Callers
    MUST balance with `.end()` (lint: span-balance).

    Trace identity resolves in order: explicit trace_id > the current
    context span (parent link) > the deterministic per-round trace >
    a fresh random trace."""
    parent = _current.get()
    if trace_id is None:
        if parent is not None:
            trace_id = parent.trace_id
            if parent_id is None:
                parent_id = parent.span_id
        elif round_ is not None:
            trace_id = round_trace_id(beacon_id, round_)
        else:
            trace_id = new_trace_id()
    if parent is not None and not beacon_id:
        beacon_id = parent.beacon_id
    if parent is not None and round_ is None:
        round_ = parent.round
    sp = Span(name=name, trace_id=trace_id, span_id=new_span_id(),
              parent_id=parent_id, beacon_id=beacon_id, round=round_,
              attrs=dict(attrs)).start()
    if device:
        sp.annotate_device()
    return sp


@contextlib.contextmanager
def span(name: str, *, beacon_id: str = "", round_: int | None = None,
         trace_id: str | None = None, parent_id: str | None = None,
         device: bool = False, **attrs):
    """Context-managed span, installed as the task's current span so
    children (including RPCs via `inject`) parent to it."""
    sp = begin_span(name, beacon_id=beacon_id, round_=round_,
                    trace_id=trace_id, parent_id=parent_id, device=device,
                    **attrs)
    token = _current.set(sp)
    try:
        yield sp
    except BaseException:
        sp.end("error")
        raise
    finally:
        try:
            _current.reset(token)
        except ValueError:
            # a span wrapping an async generator (server streams,
            # net/rpc.stream_traced) can be finalized by athrow() from a
            # DIFFERENT context than the one that entered it — e.g. a
            # mesh client dropping mid-stream under churn.  The token is
            # unusable there; the contextvar died with the origin
            # context, so there is nothing to restore.
            pass
        sp.end()


# -- RPC propagation (protobuf Metadata fields 4/5) -----------------------


def inject(metadata) -> None:
    """Stamp the current span's context onto an outgoing request's
    Metadata (called by net.client.make_metadata on every RPC)."""
    sp = _current.get()
    if sp is None:
        return
    try:
        metadata.trace_id = bytes.fromhex(sp.trace_id)
        metadata.span_id = bytes.fromhex(sp.span_id)
    except (AttributeError, ValueError):
        pass    # pre-upgrade Metadata or malformed ids: send untraced


def extract(metadata) -> tuple[str | None, str | None]:
    """(trace_id, parent_span_id) carried by an incoming request's
    Metadata, or (None, None) when the caller sent no trace context."""
    try:
        tid = bytes(metadata.trace_id)
        sid = bytes(metadata.span_id)
    except (AttributeError, TypeError):
        return None, None
    return (tid.hex() if len(tid) == TRACE_ID_LEN else None,
            sid.hex() if len(sid) == SPAN_ID_LEN else None)


@contextlib.contextmanager
def server_span(name: str, metadata, round_: int | None = None):
    """Server-side RPC span re-rooted from the caller's trace context
    (net/rpc.py wraps every service method in one).  With no inbound
    context the span still joins the per-round trace when the request
    names a round."""
    trace_id, parent_id = (None, None) if metadata is None \
        else extract(metadata)
    beacon_id = getattr(metadata, "beaconID", "") if metadata is not None \
        else ""
    with span(name, beacon_id=beacon_id, round_=round_, trace_id=trace_id,
              parent_id=parent_id) as sp:
        yield sp
