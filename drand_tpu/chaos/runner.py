"""Seeded chaos scenarios over the in-process multi-node harness.

:class:`ScenarioNet` is the library form of the scenario discipline the
test suite pioneered (tests/test_scenario.py, which now imports it from
here): n full daemons with real gRPC on localhost ports, one shared
:class:`~drand_tpu.beacon.clock.FakeClock` advanced manually — the
reference's ``DrandTestScenario``/``BatchNewDrand``
(core/util_test.go:48-150) plus the clockwork discipline (SURVEY §4).

On top of it, :func:`run_scenario` executes one named, seeded chaos
scenario: arm a deterministic failpoint :class:`Schedule`
(drand_tpu/chaos/failpoints.py), drive the net through the fault window
(including node-level crash/restart actions the inline sites cannot
express), heal, settle, and assert every protocol invariant
(drand_tpu/chaos/invariants.py).  The same entry point backs
``drand-tpu chaos run/replay`` and the tier-1 scenario matrix
(tests/test_chaos_scenarios.py).

Replay contract: node identities are aliased to stable ``node<i>``
labels before decision hashing and logging, so
``run_scenario(name, seed)`` yields the same injection summary across
runs and across machines despite OS-assigned ports.
"""

from __future__ import annotations

import asyncio
import os
import random
import tempfile
from dataclasses import dataclass, field

from drand_tpu import sanitizer
from drand_tpu.beacon.clock import Clock, FakeClock
from drand_tpu.chain.time import current_round
from drand_tpu.chaos import failpoints, faults, invariants
from drand_tpu.resilience import policy as res_policy

PERIOD = 4          # fake seconds per round
DKG_TIMEOUT = 20    # real-seconds backstop; fast-sync path finishes sooner


class TipWaiter:
    """Commit-driven settle: await the stores' tail callbacks instead of
    polling with wall-clock budgets (the flake source VERDICT r5 #5
    called out).  Each commit marshals onto the loop and wakes waiters;
    readers re-check tips on wake, so a wake per COMMIT is enough."""

    def __init__(self, stores, loop=None):
        self.loop = loop or asyncio.get_running_loop()
        self._event = asyncio.Event()
        self._stores = list(stores)
        self._ids: list[tuple[object, str]] = []
        for i, s in enumerate(self._stores):
            cb_id = f"tipwaiter-{id(self):x}-{i}"
            if hasattr(s, "add_tail_callback"):
                s.add_tail_callback(cb_id, self._on_commit)
            else:
                s.add_callback(cb_id, self._on_commit)
            self._ids.append((s, cb_id))

    def _on_commit(self, _beacon) -> None:
        try:
            self.loop.call_soon_threadsafe(self._fire)
        except RuntimeError:
            pass                       # loop closed during teardown

    def _fire(self) -> None:
        ev, self._event = self._event, asyncio.Event()
        ev.set()

    def rounds(self) -> list[int]:
        out = []
        for s in self._stores:
            try:
                out.append(s.last().round)
            except Exception:
                out.append(-1)
        return out

    async def wait_min(self, target: int, timeout: float) -> bool:
        """True once every store's tip >= target; False on timeout.
        Wakes on commits, not on a polling cadence."""
        deadline = self.loop.time() + timeout
        while True:
            ev = self._event       # grab BEFORE reading (no lost wakeup)
            if min(self.rounds()) >= target:
                return True
            remaining = deadline - self.loop.time()
            if remaining <= 0:
                return False
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return False

    async def wait_commit(self, timeout: float) -> bool:
        """True when ANY store commits within `timeout` (the per-step
        settle for clock-driving loops)."""
        ev = self._event
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def close(self) -> None:
        for s, cb_id in self._ids:
            try:
                s.remove_callback(cb_id)
            except Exception:
                pass


class ScenarioNet:
    """n in-process daemons, real gRPC, one shared fake clock.

    `beacon_ids` grows the net PAST one chain per daemon: each id is a
    full beacon process (own keypair, own DKG, own store) multiplexed
    on the shared daemon runtime — the reference's multibeacon folder
    layout (core/drand_daemon.go:248-275) driven at k>2 scale."""

    def __init__(self, n: int, thr: int, scheme_id: str,
                 clock: Clock | None = None,
                 node_clocks: "dict[int, Clock] | None" = None,
                 beacon_ids=("default",)):
        self.n, self.thr, self.scheme_id = n, thr, scheme_id
        self.beacon_ids = list(beacon_ids)
        self.clock = clock or FakeClock(start=1_700_000_000.0)
        # per-node clock overrides (e.g. a faults.SkewClock over the
        # shared base): the clock-skew fault at the injection seam
        self.node_clocks = dict(node_clocks or {})
        self.daemons: list = []
        self.dirs: list[str] = []
        self.schedule: failpoints.Schedule | None = None

    async def start_daemons(self):
        from drand_tpu.core import Config, DrandDaemon
        from drand_tpu.key.keys import Pair
        from drand_tpu.key.store import FileStore
        for i in range(self.n):
            folder = tempfile.mkdtemp(prefix=f"drand-node{i}-")
            cfg = Config(folder=folder, private_listen="127.0.0.1:0",
                         control_port=0,
                         clock=self.node_clocks.get(i, self.clock),
                         dkg_timeout_s=DKG_TIMEOUT)
            d = DrandDaemon(cfg)
            await d.start()
            addr = d.private_addr()
            for bid in self.beacon_ids:
                ks = FileStore(folder, bid)
                # "default" keeps its pre-multibeacon key seed so seeded
                # single-chain scenarios replay unchanged
                key_seed = f"node{i}" if bid == "default" \
                    else f"node{i}-{bid}"
                ks.save_key_pair(Pair.generate(addr,
                                               seed=key_seed.encode()))
                d.instantiate(bid)
            self.daemons.append(d)
            self.dirs.append(folder)

    async def run_dkg(self, beacon_id: str = "default") -> list:
        from drand_tpu.net.client import make_metadata
        from drand_tpu.protogen import drand_pb2
        secret = f"scenario-secret-{beacon_id}".encode() \
            if beacon_id != "default" else b"scenario-secret"
        leader = self.daemons[0]
        leader_addr = leader.private_addr()

        def init_packet(is_leader):
            info = drand_pb2.SetupInfoPacket(
                leader=is_leader, leader_address=leader_addr,
                nodes=self.n, threshold=self.thr, timeout=DKG_TIMEOUT,
                secret=secret)
            return drand_pb2.InitDKGPacket(
                info=info, beacon_period=PERIOD, catchup_period=1,
                schemeID=self.scheme_id,
                metadata=make_metadata(beacon_id))

        svc = [d._control_service for d in self.daemons]
        tasks = [asyncio.create_task(svc[0].InitDKG(init_packet(True), None))]
        await asyncio.sleep(0.05)
        for s in svc[1:]:
            tasks.append(asyncio.create_task(s.InitDKG(init_packet(False),
                                                       None)))
        groups = await asyncio.wait_for(asyncio.gather(*tasks), 90)
        return groups

    async def run_all_dkgs(self) -> dict:
        """One DKG per beacon id (sequential — the reference's operator
        flow starts beacons one `drand share` at a time on the shared
        daemon); returns {beacon_id: groups}."""
        return {bid: await self.run_dkg(bid) for bid in self.beacon_ids}

    # -- chaos plumbing -----------------------------------------------------

    def process(self, i: int, beacon_id: str = "default"):
        return self.daemons[i].processes[beacon_id]

    def aliases(self) -> dict[str, str]:
        """Ephemeral host:port -> stable node<i> labels (replay contract)."""
        return {d.private_addr(): f"node{i}"
                for i, d in enumerate(self.daemons)}

    def arm(self, seed: int, rules) -> failpoints.Schedule:
        """Build, alias, and arm a seeded schedule over this net.  The
        resilience decision log shares the aliases so retry/breaker
        entries replay with stable node labels too."""
        sched = failpoints.Schedule(seed, rules)
        sched.set_aliases(self.aliases())
        res_policy.LOG.set_aliases(self.aliases())
        failpoints.arm(sched)
        self.schedule = sched
        return sched

    async def wait_for_injections(self, pred, timeout: float = 20.0,
                                  nudge_s: float = 0.5,
                                  max_nudge: float = 0.0) -> bool:
        """Event-driven fault-window closure: poll the armed schedule's
        injection log until ``pred(log)`` holds.  Replay determinism
        needs the SET of injections closed before a drive disarms —
        "advance N rounds and hope everything fired" was the flake
        shape this replaces.  ``max_nudge`` > 0 additionally advances
        the fake clock in ``nudge_s`` steps (bounded, so the nudging
        cannot cross into the next round and mint NEW injections) for
        clock-cadenced traffic such as watchdog pings."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        nudged = 0.0
        while True:
            log = self.schedule.injection_log() if self.schedule else []
            if pred(log):
                return True
            if loop.time() > deadline:
                return False
            if nudged + nudge_s <= max_nudge:
                nudged += nudge_s
                await self.clock.advance(nudge_s)
            await asyncio.sleep(0.05)   # let in-flight RPCs land

    async def drain_retries(self, timeout: float = 30.0) -> None:
        """Advance the fake clock until no retry backoff is sleeping:
        every retry chain runs to its logged conclusion, which keeps the
        decision log deterministic across replays (a chain truncated by
        scenario teardown would log a different tail per run)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while res_policy.inflight() and loop.time() < deadline:
            await self.clock.advance(1.0)
            await asyncio.sleep(0.02)   # let woken retries issue their RPC

    def crash(self, i: int) -> None:
        """Kill node i's beacon engine (the orchestrator-style node
        failure, demo/lib/orchestrator.go:530-577)."""
        self.process(i).stop()

    async def restart(self, i: int) -> None:
        """Rejoin node i in catch-up mode and queue a sync request."""
        bp = self.process(i)
        await bp.start(catchup=True)
        bp.sync_manager.request_sync(self.last_rounds()[i] + 1)

    # -- observation / clock driving ---------------------------------------

    def stores(self, beacon_id: str = "default"):
        return [d.processes[beacon_id]._store for d in self.daemons]

    def last_rounds(self, beacon_id: str = "default"):
        out = []
        for s in self.stores(beacon_id):
            try:
                out.append(s.last().round)
            except Exception:
                out.append(-1)
        return out

    def _rounds_of(self, daemons, beacon_id: str = "default"):
        out = []
        for d in daemons:
            try:
                out.append(d.processes[beacon_id]._store.last().round)
            except Exception:
                out.append(-1)
        return out

    async def advance_to_round(self, target: int, timeout: float = 60.0,
                               daemons=None, beacon_id: str = "default"):
        """Advance the fake clock period by period until every (selected)
        daemon's store holds `target`."""
        daemons = daemons if daemons is not None else self.daemons
        group = daemons[0].processes[beacon_id].group
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            rounds = self._rounds_of(daemons, beacon_id)
            if all(r >= target for r in rounds):
                return
            if loop.time() > deadline:
                raise AssertionError(
                    f"timeout waiting for round {target}: {rounds}")
            now = self.clock.now()
            next_time = group.genesis_time if now < group.genesis_time \
                else now + group.period
            await self.clock.set_time(next_time)
            # Crypto runs OFF the event loop (crypto_backend worker thread),
            # so real time keeps flowing while partials verify/aggregate.
            # Wait for this tick's round to land everywhere before advancing
            # again — advancing early would push in-flight partials outside
            # the handler's (current, current+1) round window.
            tick_round = current_round(next_time, group.period,
                                       group.genesis_time)
            settle = loop.time() + 10.0
            while loop.time() < deadline:
                rounds = self._rounds_of(daemons, beacon_id)
                want = min(target, tick_round)
                if all(r >= want for r in rounds):
                    break
                if loop.time() >= settle and any(r >= want for r in rounds):
                    # at least one member landed this tick's round: the
                    # network works; remaining laggards are structurally
                    # behind (e.g. waiting for a future transition round)
                    # and will gap-sync — advance the clock again.  While
                    # NOBODY has landed it (crypto still grinding in the
                    # worker thread under machine load), advancing would
                    # push in-flight partials outside the round window.
                    break
                await asyncio.sleep(0.02)

    async def advance_until(self, target: int, step: float | None = None,
                            timeout: float = 60.0, daemons=None,
                            settle_s: float = 1.0):
        """Advance the fake clock `step` seconds at a time (default: one
        period) until every selected daemon's tip holds `target`,
        settling between steps on store-commit EVENTS rather than fixed
        wall-clock budgets.  The right driver for catchup-cadence
        recovery: step=group.catchup_period walks the fast-forward path
        one commit at a time."""
        daemons = daemons if daemons is not None else self.daemons
        group = daemons[0].processes["default"].group
        step = step if step is not None else group.period
        waiter = TipWaiter(
            [d.processes["default"]._store for d in daemons])
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        try:
            while min(waiter.rounds()) < target:
                if loop.time() > deadline:
                    raise AssertionError(
                        f"timeout waiting for round {target}: "
                        f"{waiter.rounds()}")
                now = self.clock.now()
                t = group.genesis_time if now < group.genesis_time \
                    else now + step
                await self.clock.set_time(t)
                # commit-driven settle: wake the moment a beacon lands;
                # a short bound covers steps that land nothing (e.g.
                # sub-period steps walking toward the next boundary)
                await waiter.wait_commit(settle_s)
        finally:
            waiter.close()

    async def run_reshare(self, new_n: int, new_thr: int,
                          beacon_id: str = "default",
                          timeout_s: float | None = None) -> list:
        """Reshare the running chain to a resized group (the reference's
        `drand share --transition` flow, tests/test_reshare.py's driving
        pattern made a library helper).  Growing brings up joiner
        daemons (appended to `self.daemons`) that receive the previous
        group file; shrinking keeps only the first `new_n` daemons as
        participants — the tail's dealers go dark and the deal phase
        closes on its timeout.  Returns the participants' InitReshare
        results (leader first)."""
        import os

        from drand_tpu.core import Config, DrandDaemon
        from drand_tpu.key.keys import Pair
        from drand_tpu.key.store import FileStore
        from drand_tpu.net.client import make_metadata
        from drand_tpu.protogen import drand_pb2

        old_group = self.process(0, beacon_id).group
        joiners = []
        while len(self.daemons) < new_n:
            j = len(self.daemons)
            folder = tempfile.mkdtemp(prefix=f"drand-joiner{j}-")
            cfg = Config(folder=folder, private_listen="127.0.0.1:0",
                         control_port=0, clock=self.clock,
                         dkg_timeout_s=DKG_TIMEOUT)
            d = DrandDaemon(cfg)
            await d.start()
            ks = FileStore(folder, beacon_id)
            ks.save_key_pair(Pair.generate(
                d.private_addr(), seed=f"joiner{j}-{beacon_id}".encode()))
            d.instantiate(beacon_id)
            self.daemons.append(d)
            self.dirs.append(folder)
            joiners.append(d)
        participants = self.daemons[:new_n]
        timeout = timeout_s or DKG_TIMEOUT
        secret = b"scenario-reshare-" + beacon_id.encode()
        leader_addr = self.daemons[0].private_addr()
        old_path = ""
        if joiners:
            old_path = os.path.join(self.dirs[-1], "old_group.toml")

            def _write(path=old_path, text=old_group.to_toml()):
                with open(path, "w") as f:
                    f.write(text)
            await asyncio.to_thread(_write)

        def pkt(is_leader, old=""):
            info = drand_pb2.SetupInfoPacket(
                leader=is_leader, leader_address=leader_addr,
                nodes=new_n, threshold=new_thr, timeout=int(timeout),
                secret=secret)
            p = drand_pb2.InitResharePacket(
                info=info, metadata=make_metadata(beacon_id))
            if old:
                p.old.path = old
            return p

        svc = [d._control_service for d in participants]
        tasks = [asyncio.create_task(svc[0].InitReshare(pkt(True), None))]
        await asyncio.sleep(0.05)
        for d, s in zip(participants[1:], svc[1:]):
            tasks.append(asyncio.create_task(s.InitReshare(
                pkt(False, old_path if d in joiners else ""), None)))
        return await asyncio.wait_for(asyncio.gather(*tasks),
                                      timeout * 6 + 120)

    async def stop(self):
        for d in self.daemons:
            try:
                await d.stop()
            except Exception:
                pass


# -- scenario definitions ---------------------------------------------------

@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    doc: str
    drive: object          # async (net, seed, rng) -> expected final round
    slow: bool = False     # excluded from the tier-1 matrix / smoke
    # ceremony scenarios run on chaos/ceremony.CeremonyNet (no daemons,
    # no clock, no chain invariants) with drive signature
    # async (seed, rng, nodes, thr, **kw) -> (CeremonyNet, [invariant])
    ceremony: bool = False


async def _drive_partition_heal(net: ScenarioNet, seed: int,
                                rng: random.Random) -> int:
    """Symmetric partition isolates a seeded victim; the majority keeps
    producing through it; heal; the victim gap-syncs back."""
    victim = rng.randrange(net.n)
    vic = f"node{victim}"
    others = [f"node{i}" for i in range(net.n) if i != victim]
    net.arm(seed, faults.partition([vic], others))
    base = max(net.last_rounds())
    majority = [d for i, d in enumerate(net.daemons) if i != victim]
    await net.advance_to_round(base + 3, daemons=majority)
    if net.last_rounds()[victim] >= base + 3:
        raise AssertionError(
            f"partition had no effect: victim node{victim} kept up "
            f"({net.last_rounds()})")

    # Close the fault window on EVENTS before healing: the victim's
    # gap-triggered sync must have been cut by every donor, and every
    # partitioned pair's watchdog ping must have been dropped.  Those
    # are the injections the seeded schedule deterministically owes;
    # disarming on a round count alone left their arrival racing the
    # disarm (the replay-test flake).
    want_pings = {(d, vic) for d in others} | {(vic, d) for d in others}

    def closed(log) -> bool:
        sync_srcs = {e["src"] for e in log
                     if e["site"] == "net.sync_recv" and e["dst"] == vic}
        pings = {(e["src"], e["dst"]) for e in log
                 if e["site"] == "net.ping"}
        return set(others) <= sync_srcs and want_pings <= pings

    if not await net.wait_for_injections(closed, timeout=20.0,
                                         max_nudge=PERIOD - 1.0):
        raise AssertionError(
            "fault window never closed: "
            f"{net.schedule.injection_summary()}")
    failpoints.disarm()     # heal
    target = base + 4
    await net.advance_to_round(target, timeout=90.0)
    return target


async def _drive_leader_crash(net: ScenarioNet, seed: int,
                              rng: random.Random) -> int:
    """The DKG leader dies mid-round at a seeded height; t-of-n keeps the
    chain alive; the leader rejoins via catch-up sync."""
    crash_at = max(net.last_rounds()) + 1 + rng.randrange(2)
    await net.advance_to_round(crash_at)
    net.crash(0)
    survivors = net.daemons[1:]
    await net.advance_to_round(crash_at + 2, daemons=survivors)
    if net.last_rounds()[0] >= crash_at + 2:
        raise AssertionError("crash had no effect: node0 kept appending")
    await net.restart(0)
    target = crash_at + 3
    await net.advance_to_round(target, timeout=120.0)
    return target


async def _drive_store_errors_catchup(net: ScenarioNet, seed: int,
                                      rng: random.Random) -> int:
    """A node rejoins from downtime onto a failing disk: its first
    catch-up commit attempts raise StoreError; the sync retry path must
    absorb the burst and still close the gap."""
    base = max(net.last_rounds())
    victim = net.n - 1
    net.crash(victim)
    survivors = net.daemons[:victim]
    await net.advance_to_round(base + 2, daemons=survivors)
    burst = 1 + rng.randrange(2)
    net.arm(seed, faults.store_commit_errors(owner=f"node{victim}",
                                             times=burst))
    await net.restart(victim)
    target = base + 3
    await net.advance_to_round(target, timeout=120.0)
    failpoints.disarm()
    if not net.schedule.injection_log():
        raise AssertionError("store-error schedule never fired")
    return target


async def _drive_skewed_node(net: ScenarioNet, seed: int,
                             rng: random.Random) -> int:
    """One node's clock runs ahead of the group (installed at net build
    via faults.SkewClock, below the one-round drift the partial window
    tolerates): rounds must keep flowing and agreeing."""
    target = max(net.last_rounds()) + 4
    await net.advance_to_round(target, timeout=90.0)
    return target


async def _drive_retry_storm(net: ScenarioNet, seed: int,
                             rng: random.Random) -> int:
    """Acceptance (a) for the resilience layer: a seeded (src, dst) pair's
    partial send for one round is dropped a bounded number of times; the
    RetryPolicy's seeded-backoff retries must push it through within the
    round's deadline budget, visible in the decision log as
    retry → retry → success."""
    base = max(net.last_rounds())
    r0 = base + 2
    src = rng.randrange(net.n)
    dst = rng.choice([i for i in range(net.n) if i != src])
    # times=2 < RetryPolicy max attempts (4) and < breaker trip (5): the
    # third attempt must land, with the breaker still closed
    net.arm(seed, [failpoints.Rule.make(
        "net.send_partial", "drop", rounds=(r0, r0), times=2,
        match={"src": f"node{src}", "dst": f"node{dst}"})])
    await net.advance_to_round(r0)
    # Walk the clock through the retry window in sub-budget steps (with
    # real time between steps for the resent RPC's roundtrip): a whole-
    # period jump would strand the resend — dispatched at T+backoff but
    # processed server-side after the fake clock already passed the
    # period/2 deadline, i.e. shed as doomed work.  Sub-second steps
    # keep the server's view of the budget live, which is exactly how
    # real time behaves.
    loop = asyncio.get_running_loop()
    bound = loop.time() + 20.0
    while res_policy.inflight() or not any(
            e.get("outcome") == "success" and e.get("key") == f"r{r0}"
            for e in res_policy.LOG.entries()):
        if loop.time() > bound:
            break               # the assertions below report the log
        await net.clock.advance(0.2)
        await asyncio.sleep(0.05)
    failpoints.disarm()
    target = r0 + 2
    await net.advance_to_round(target, timeout=90.0)
    retries = [e for e in res_policy.LOG.entries()
               if e.get("kind") == "retry"
               and e.get("site") == "net.send_partial"
               and e.get("peer") == f"node{dst}"]
    if not any(e["outcome"] == "retry" for e in retries):
        raise AssertionError(f"dropped send never retried: {retries}")
    if not any(e["outcome"] == "success" for e in retries):
        raise AssertionError(
            f"retries never succeeded within the budget: {retries}")
    return target


async def _drive_breaker_trip_heal(net: ScenarioNet, seed: int,
                                   rng: random.Random) -> int:
    """Acceptance (b): a partitioned peer's breakers trip OPEN on the
    surviving side (observed via the metrics port's drand_breaker_state
    gauge), then heal back to CLOSED after the partition lifts, with the
    full transition cycle in the decision log."""
    import aiohttp

    from drand_tpu.metrics import MetricsServer
    victim = rng.randrange(net.n)
    observer = next(i for i in range(net.n) if i != victim)
    victim_addr = net.daemons[victim].private_addr()
    ms = MetricsServer(net.daemons[observer], 0)
    await ms.start()

    async def breaker_gauge() -> float:
        url = f"http://127.0.0.1:{ms.port}/metrics"
        async with aiohttp.ClientSession() as s:
            async with s.get(url) as resp:
                text = await resp.text()
        needle = f'drand_breaker_state{{peer="{victim_addr}"}}'
        for line in text.splitlines():
            if line.startswith(needle):
                return float(line.rsplit(" ", 1)[1])
        raise AssertionError(f"{needle} not in exposition")

    async def wait_gauge(value: float, note: str) -> None:
        """Poll (real time — a half-open probe settles without clock
        movement) until the gauge reads `value`."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 10.0
        while True:
            v = await breaker_gauge()
            if v == value:
                return
            if loop.time() > deadline:
                raise AssertionError(f"{note}: drand_breaker_state is "
                                     f"{v}, wanted {value}")
            await asyncio.sleep(0.1)

    try:
        others = [f"node{i}" for i in range(net.n) if i != victim]
        net.arm(seed, faults.partition([f"node{victim}"], others))
        base = max(net.last_rounds())
        majority = [d for i, d in enumerate(net.daemons) if i != victim]
        # enough rounds of failed sends (plus failed watchdog pings) to
        # cross the trip threshold on every survivor
        await net.advance_to_round(base + 3, daemons=majority)
        await net.drain_retries()
        await wait_gauge(1.0, "breaker for the partitioned peer did "
                              "not OPEN")
        failpoints.disarm()     # heal
        # past the breaker reset timeout: half-open probes (and watchdog
        # pings) must close the breakers, and the victim must gap-sync
        target = base + 7
        await net.advance_to_round(target, timeout=120.0)
        await net.drain_retries()
        await wait_gauge(0.0, "breaker did not CLOSE after heal")
        trans = [(e["from"], e["to"]) for e in res_policy.LOG.entries()
                 if e.get("kind") == "breaker"
                 and e.get("peer") == f"node{victim}"]
        if ("closed", "open") not in trans:
            raise AssertionError(f"no closed->open transition: {trans}")
        if not any(t[1] == "closed" for t in trans):
            raise AssertionError(f"breaker never healed to closed: {trans}")
        return target
    finally:
        await ms.stop()


async def _drive_crash_recover(net: ScenarioNet, seed: int,
                               rng: random.Random) -> int:
    """Crash-safe storage acceptance (ISSUE 15), clean-crash half: a
    seeded node goes down; while it is down a REAL subprocess
    (drand_tpu/chaos/crashwriter.py) replays a survivor's rows into its
    closed db as catch-up-shaped put_many segments and is SIGKILLed
    mid-write — an actual kill -9, not an injected exception.  On
    restart the startup integrity scan must find a verified prefix at a
    segment boundary, quarantine NOTHING (WAL + one-transaction-per-
    segment means a torn segment is never visible), and the node must
    heal to the tip via peer re-sync.  Counter-asserted on
    drand_store_integrity and drand_store_quarantined_total."""
    import os
    import sys

    import drand_tpu as _pkg
    from drand_tpu.metrics import REGISTRY
    victim = rng.randrange(net.n)
    base = max(net.last_rounds())
    await net.advance_to_round(base + 1)
    net.crash(victim)
    survivors = [d for i, d in enumerate(net.daemons) if i != victim]
    await net.advance_to_round(base + 4, daemons=survivors)
    donor = next(i for i in range(net.n) if i != victim)
    q_before = REGISTRY.get_sample_value(
        "drand_store_quarantined_total") or 0.0
    kill_after = 1 + rng.randrange(2)     # seeded kill point (segments)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "drand_tpu.chaos.crashwriter",
        net.process(donor).db_path(), net.process(victim).db_path(),
        "--segment", "1", "--sleep-s", "0.1",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.DEVNULL, cwd=repo_root)
    seen = 0
    try:
        while seen < kill_after:
            line = await asyncio.wait_for(proc.stdout.readline(), 20.0)
            if not line or line.startswith(b"DONE"):
                raise AssertionError(
                    f"crashwriter finished before the kill point "
                    f"({seen}/{kill_after} segments)")
            if line.startswith(b"SEGMENT"):
                seen += 1
        proc.kill()                       # SIGKILL — the real thing
    finally:
        if proc.returncode is None:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        await proc.wait()
    if proc.returncode != -9:
        raise AssertionError(
            f"crashwriter exited {proc.returncode}, expected SIGKILL (-9)")
    await net.restart(victim)
    bp = net.process(victim)
    rep = bp.integrity_report
    if rep is None or not rep.ok:
        raise AssertionError(
            "startup scan after a clean kill -9 found damage: "
            f"{rep and rep.to_dict()}")
    if bp._store.insecure.quarantined():
        raise AssertionError("clean crash quarantined rows")
    q_after = REGISTRY.get_sample_value(
        "drand_store_quarantined_total") or 0.0
    if q_after != q_before:
        raise AssertionError(
            "drand_store_quarantined_total moved on a clean crash: "
            f"{q_before} -> {q_after}")
    integ = REGISTRY.get_sample_value("drand_store_integrity",
                                      {"beacon_id": "default"})
    if integ != 1.0:
        raise AssertionError(f"drand_store_integrity={integ}, wanted 1")
    target = base + 5
    await net.advance_to_round(target, timeout=120.0)
    return target


async def _drive_torn_write_heal(net: ScenarioNet, seed: int,
                                 rng: random.Random) -> int:
    """Crash-safe storage acceptance (ISSUE 15), corruption half: a
    seeded node goes down and its closed db suffers a torn write plus a
    bit flip (faults.torn_write / faults.bit_rot — direct disk surgery,
    the damage failpoints cannot express).  On restart the startup scan
    must quarantine EXACTLY the damaged rounds, roll the tip back to the
    verified prefix, and heal the suffix from peers with bit-identical
    restored rows."""
    from drand_tpu.metrics import REGISTRY
    victim = rng.randrange(net.n)
    base = max(net.last_rounds())
    await net.advance_to_round(base + 2)
    vic_tip = net.last_rounds()[victim]
    net.crash(victim)
    survivors = [d for i, d in enumerate(net.daemons) if i != victim]
    await net.advance_to_round(base + 4, daemons=survivors)
    db = net.process(victim).db_path()
    torn, rotted = rng.sample(range(2, vic_tip + 1), 2)
    faults.torn_write(db, torn)
    faults.bit_rot(db, rotted, offset=3)   # flip inside the round field
    q_before = REGISTRY.get_sample_value(
        "drand_store_quarantined_total") or 0.0
    await net.restart(victim)
    bp = net.process(victim)
    rep = bp.integrity_report
    if rep is None or rep.ok:
        raise AssertionError("startup scan missed injected corruption: "
                             f"{rep and rep.to_dict()}")
    if set(rep.corrupt) != {torn, rotted}:
        raise AssertionError(f"wrong corrupt set {rep.corrupt}, wanted "
                             f"{sorted((torn, rotted))}")
    want_tip = min(torn, rotted) - 1
    if rep.verified_tip != want_tip:
        raise AssertionError(f"verified_tip {rep.verified_tip}, wanted "
                             f"{want_tip}")
    quarantined = {r for r, _ in bp._store.insecure.quarantined()}
    if not {torn, rotted} <= quarantined:
        raise AssertionError(f"damaged rounds not quarantined: "
                             f"{sorted(quarantined)}")
    q_after = REGISTRY.get_sample_value(
        "drand_store_quarantined_total") or 0.0
    if q_after - q_before != vic_tip - want_tip:
        raise AssertionError(
            f"quarantine counter moved {q_after - q_before}, wanted "
            f"{vic_tip - want_tip} (tip {vic_tip} -> {want_tip})")
    integ = REGISTRY.get_sample_value("drand_store_integrity",
                                      {"beacon_id": "default"})
    if integ != 0.0:
        raise AssertionError(f"drand_store_integrity={integ}, wanted 0")
    target = base + 5
    await net.advance_to_round(target, timeout=120.0)
    # the healed rows must be bit-identical to the donor's stored bytes
    donor = next(i for i in range(net.n) if i != victim)
    vic_store = bp._store.insecure
    don_store = net.process(donor)._store.insecure
    for r in sorted((torn, rotted)):
        a = vic_store.raw_rows(r, 1)
        b = don_store.raw_rows(r, 1)
        if not a or not b or a[0] != b[0]:
            raise AssertionError(f"healed round {r} not bit-identical "
                                 f"to the donor's row")
    return target


def _truncate_object(path: str, keep: int) -> None:
    """Seeded object damage (worker thread): cut the file to `keep`
    bytes — what a half-replicated CDN edge serves."""
    with open(path, "r+b") as f:
        f.truncate(keep)


def _flip_object_byte(path: str, off: int) -> None:
    """Seeded object damage (worker thread): flip one byte in place —
    storage-layer bit rot."""
    with open(path, "r+b") as f:
        f.seek(off)
        byte = f.read(1)
        f.seek(off)
        f.write(bytes([byte[0] ^ 0xFF]))


async def _drive_object_sync_poisoned(net: ScenarioNet, seed: int,
                                      rng: random.Random) -> int:
    """Objectsync acceptance (ISSUE 18): a seeded donor node publishes
    its chain as content-addressed segment objects; a fresh client store
    syncs purely from those objects with the donor's REAL verifier.
    Then the object tier is poisoned by direct file surgery — a stale
    manifest, a truncated segment object, a bit-rotted one — and the
    client must stop at EXACTLY the verified segment boundary with zero
    damaged rounds committed, recovering bit-identically once clean
    objects reappear.  No failpoints: a dumb object store has no inline
    sites, damage is what the disk serves."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.scheme import scheme_by_id
    from drand_tpu.chain.store import AppendStore, SchemeStore, SqliteStore
    from drand_tpu.objectsync import (FilesystemBackend, Manifest,
                                      ObjectPublisher, ObjectSyncClient,
                                      content_hash, encode_segment)
    from drand_tpu.objectsync import format as ofmt

    seg_rounds = 2
    base = max(net.last_rounds())
    target = base + 6                     # >= 3 sealed 2-round segments
    await net.advance_to_round(target)

    donor_i = rng.randrange(net.n)
    bp = net.process(donor_i)
    donor_store = bp._store.insecure
    info = bp.group.chain_info()
    root = tempfile.mkdtemp(prefix="chaos-objectsync-")
    backend = FilesystemBackend(os.path.join(root, "objects"))
    pub = ObjectPublisher(donor_store, backend, chain_hash=info.hash(),
                          scheme_id=bp.group.scheme_id,
                          segment_rounds=seg_rounds)
    await pub.load_manifest()
    await pub.publish_sealed()
    segs = pub.manifest.segments
    if len(segs) < 3:
        raise AssertionError(f"only {len(segs)} sealed segments at tip "
                             f"{max(net.last_rounds())}; drive needs 3")
    full_manifest = pub.manifest.to_json()

    def fresh_client(path):
        cbase = SqliteStore(os.path.join(root, path))
        scheme = scheme_by_id(bp.group.scheme_id)
        cstore = SchemeStore(AppendStore(cbase), scheme.decouple_prev_sig)
        # anchor: round 0 carrying round 1's prev linkage (genesis seed
        # for chained schemes, empty for unchained)
        cstore.put(Beacon(round=0,
                          signature=donor_store.read_fields(1, 1)[0][2]))
        return cbase, cstore

    # phase 1 — stale manifest (a CDN edge serving yesterday's index):
    # NOT an error, just a shorter verified chain
    stale = Manifest.from_json(full_manifest)
    stale.segments = stale.segments[:1]
    stale.tip = stale.segments[-1].end
    await backend.put(ofmt.MANIFEST_NAME, stale.to_json())
    cbase, cstore = fresh_client("client.sqlite")
    cli = ObjectSyncClient(backend, cstore, bp.verifier,
                           chain_hash=info.hash())
    res = await cli.sync()
    if not res.ok or res.synced_to != segs[0].end:
        raise AssertionError(f"stale-manifest sync: ok={res.ok} "
                             f"synced_to={res.synced_to} "
                             f"(wanted {segs[0].end}): {res.error}")

    # phase 2 — fresh manifest, but two seeded later segments damaged on
    # disk: one truncated, one bit-rotted.  FIFO commit must stop at the
    # boundary BEFORE the first damaged segment.
    await backend.put(ofmt.MANIFEST_NAME, full_manifest)
    vt, vr = sorted(rng.sample(range(1, len(segs)), 2))
    objdir = os.path.join(root, "objects")
    t_path = os.path.join(objdir, segs[vt].name)
    keep = rng.randrange(1, os.path.getsize(t_path))
    await asyncio.to_thread(_truncate_object, t_path, keep)
    r_path = os.path.join(objdir, segs[vr].name)
    off = rng.randrange(os.path.getsize(r_path))
    await asyncio.to_thread(_flip_object_byte, r_path, off)
    res = await cli.sync()
    want_tip = segs[vt].start - 1
    if res.ok or res.synced_to != want_tip:
        raise AssertionError(f"poisoned sync: ok={res.ok} "
                             f"synced_to={res.synced_to} "
                             f"(wanted stop at {want_tip}): {res.error}")
    if "content hash mismatch" not in res.error:
        raise AssertionError(f"poisoned sync failed for the wrong "
                             f"reason: {res.error}")
    if cstore.last().round != want_tip:
        raise AssertionError(f"client tip {cstore.last().round} != "
                             f"verified prefix {want_tip}")
    if cbase.read_fields(want_tip + 1, 8):
        raise AssertionError("rounds past the verified prefix committed")
    for r in range(1, want_tip + 1):
        a, b = cbase.raw_rows(r, 1), donor_store.raw_rows(r, 1)
        if not a or not b or a[0] != b[0]:
            raise AssertionError(f"verified prefix round {r} not "
                                 f"bit-identical to the donor's row")

    # phase 3 — clean objects reappear (re-encoded from the donor:
    # content addressing makes them byte-identical, hash and all)
    for vi in (vt, vr):
        blob = encode_segment(info.hash(), bp.group.scheme_id,
                              donor_store.read_fields(segs[vi].start,
                                                      segs[vi].count))
        if content_hash(blob) != segs[vi].hash:
            raise AssertionError(f"re-encoded segment {segs[vi].name} "
                                 f"hash drifted")
        await backend.put(segs[vi].name, blob)
    res = await cli.sync()
    if not res.ok or res.synced_to != segs[-1].end:
        raise AssertionError(f"healed sync: ok={res.ok} "
                             f"synced_to={res.synced_to}: {res.error}")
    for r in range(1, segs[-1].end + 1):
        a, b = cbase.raw_rows(r, 1), donor_store.raw_rows(r, 1)
        if not a or not b or a[0] != b[0]:
            raise AssertionError(f"healed round {r} not bit-identical "
                                 f"to the donor's row")
    cbase.close()
    return target


async def _drive_fork_detect(net: ScenarioNet, seed: int,
                             rng: random.Random) -> int:
    """Fleet-observatory acceptance (ISSUE 19): one seeded probe sample
    is answered with a forged divergent signature (probe.sample / error
    — an injected equivocation), and the observing node's consistency
    prober must record a typed ForkReport within a bounded number of
    rounds.  The forged bytes derive only from the sampled round and the
    probe.sample ctx carries no round/time, so the injection log replays
    byte-identically."""
    observer = rng.randrange(net.n)
    peer = rng.choice([i for i in range(net.n) if i != observer])
    net.arm(seed, [failpoints.Rule.make(
        "probe.sample", "error", times=1,
        match={"src": f"node{observer}", "dst": f"node{peer}"})])
    base = max(net.last_rounds())
    bound = base + 4               # detection must land inside this window
    peer_addr = net.daemons[peer].private_addr()
    prober = net.daemons[observer].consistency

    def forged(log) -> bool:
        return any(e["site"] == "probe.sample" and e["kind"] == "error"
                   for e in log)

    target = base
    while True:
        target += 1
        await net.advance_to_round(target)
        # The prober is clock-cadenced: advancing rounds walked the fake
        # clock past its wake-ups; give the in-flight samples real time
        # to land before deciding this round's tick missed.
        if await net.wait_for_injections(forged, timeout=5.0):
            break
        if target >= bound:
            raise AssertionError(
                f"forged probe.sample never fired by round {bound}: "
                f"{net.schedule.injection_summary()}")
    # the forged signature is diffed synchronously after the failpoint
    # raises, but the probe coroutine needs a beat to finish its tick
    loop = asyncio.get_running_loop()
    settle = loop.time() + 5.0
    while not prober.forks and loop.time() < settle:
        await asyncio.sleep(0.05)
    if not prober.forks:
        raise AssertionError("forged sample fired but no ForkReport "
                             f"recorded: {prober.snapshot()}")
    rep = prober.forks[0]
    if rep.peer != peer_addr:
        raise AssertionError(
            f"fork attributed to {rep.peer}, wanted {peer_addr}")
    if not 1 <= rep.round <= bound:
        raise AssertionError(
            f"fork at round {rep.round}, outside (0, {bound}]")
    snap = prober.snapshot()
    if snap["fork_count"] != 1 or len(snap["forks"]) != 1:
        raise AssertionError(f"fork bookkeeping off: {snap}")
    failpoints.disarm()
    # the fork is observational — the chain itself must keep flowing
    target += 1
    await net.advance_to_round(target, timeout=90.0)
    return target


async def _drive_signer_loss(net: ScenarioNet, seed: int,
                             rng: random.Random) -> int:
    """Fleet-observatory acceptance (ISSUE 19): a seeded signer dies and
    EVERY survivor's participation ledger must move — the victim's rate
    drops, its miss streak crosses the chronic threshold, and the FINAL
    threshold margin falls from n-t to (n-1)-t — then heal back once the
    victim rejoins.  An ordinary outage must raise no fork reports."""
    healthy_margin = net.n - net.thr
    base = max(net.last_rounds())
    # a few healthy rounds first: every ledger must show the full margin
    await net.advance_to_round(base + 3)
    victim = rng.randrange(1, net.n)          # keep the DKG leader alive
    vic_addr = net.daemons[victim].private_addr()
    surv_idx = [i for i in range(net.n) if i != victim]
    survivors = [net.daemons[i] for i in surv_idx]
    group = net.process(surv_idx[0]).group
    vic_signer = next(n.index for n in group.nodes
                      if n.address == vic_addr)
    for i in surv_idx:
        led = net.process(i).handler.ledger
        if led.last_final_margin != healthy_margin:
            raise AssertionError(
                f"node{i} healthy margin {led.last_final_margin}, "
                f"wanted {healthy_margin}")
    crash_at = max(net.last_rounds())
    net.crash(victim)
    # enough sealed rounds for the chronic-miss threshold (3) to trip
    down_end = crash_at + 5
    await net.advance_to_round(down_end, daemons=survivors, timeout=120.0)
    if net.last_rounds()[victim] >= down_end:
        raise AssertionError("crash had no effect: victim kept appending")
    for i in surv_idx:
        led = net.process(i).handler.ledger
        if led.rate(vic_signer) >= 1.0:
            raise AssertionError(
                f"node{i}: dead signer {vic_signer} rate did not drop "
                f"({led.snapshot(limit=8)})")
        if led.miss_streak(vic_signer) < 3:
            raise AssertionError(
                f"node{i}: miss streak {led.miss_streak(vic_signer)} < 3")
        if vic_signer not in led.missing_signers():
            raise AssertionError(
                f"node{i}: signer {vic_signer} not chronically missing")
        if led.last_final_margin != healthy_margin - 1:
            raise AssertionError(
                f"node{i}: outage margin {led.last_final_margin}, "
                f"wanted {healthy_margin - 1}")
    await net.restart(victim)
    # heal: the margin must return to n-t on every survivor once the
    # victim's partials flow again (bounded rounds, not "eventually")
    heal_bound = down_end + 6
    target = down_end
    while True:
        target += 1
        await net.advance_to_round(target, timeout=120.0)
        if all(net.process(i).handler.ledger.last_final_margin ==
               healthy_margin for i in surv_idx):
            break
        if target >= heal_bound:
            snaps = {i: net.process(i).handler.ledger.snapshot(limit=4)
                     for i in surv_idx}
            raise AssertionError(
                f"margin never healed to {healthy_margin} by round "
                f"{heal_bound}: {snaps}")
    for i in surv_idx:
        led = net.process(i).handler.ledger
        if led.miss_streak(vic_signer) != 0:
            raise AssertionError(
                f"node{i}: healed signer still streaking "
                f"({led.miss_streak(vic_signer)})")
        if vic_signer in led.missing_signers():
            raise AssertionError(
                f"node{i}: healed signer still chronically missing")
        forks = net.daemons[i].consistency.snapshot()["fork_count"]
        if forks:
            raise AssertionError(
                f"node{i}: ordinary outage raised {forks} fork report(s)")
    return target


async def _drive_reshare_mid_traffic(net: ScenarioNet, seed: int,
                                     rng: random.Random) -> int:
    """Zero-blip reshare acceptance (ISSUE 20): the group reshares to a
    grown membership WHILE a bench_serve-style HTTP load hammers
    /public/latest + /info on a member — zero failed public reads,
    beacon cadence uninterrupted (every round present, no holes), and
    the three epoch-invalidation seams observed firing exactly once,
    together, on every original member:

      1. signer-key table epoch (ChainStore.update_group ->
         backend.update_group -> SignerKeyTable.update),
      2. ResponseCache.invalidate (via chain_store.on_group_update),
      3. the daemon's chains_version bump (bp.on_group_transition ->
         daemon.note_group_update).

    The in-place engine swap must also have held: same store object,
    same ResponseCache object across the transition (a full rebuild
    would pass the read checks but reset the cache epoch)."""
    import aiohttp

    from drand_tpu.http.server import PublicHTTPServer

    originals = list(net.daemons)
    observed = rng.randrange(net.n)
    d_obs = net.daemons[observed]
    srv = PublicHTTPServer(d_obs, "127.0.0.1:0")
    await srv.start()
    base_url = f"http://127.0.0.1:{srv.port}"

    before = []
    for d in originals:
        bp = d.processes["default"]
        before.append({
            "store": bp._store,
            "cache": bp.response_cache,
            "cache_epoch": bp.response_cache.epoch,
            "table_epoch": bp.chain_store.backend.table.epoch,
            "chains_version": d.chains_version,
        })

    stats = {"reads": 0, "failures": []}
    stop = asyncio.Event()

    async def load():
        async with aiohttp.ClientSession() as s:
            i = 0
            while not stop.is_set():
                path = "/public/latest" if i % 3 else "/info"
                try:
                    async with s.get(base_url + path) as r:
                        body = await r.read()
                        stats["reads"] += 1
                        if r.status != 200:
                            stats["failures"].append(
                                (path, r.status, body[:160]))
                except Exception as exc:     # noqa: BLE001 - recorded
                    stats["failures"].append((path, repr(exc)))
                i += 1
                # paced load generator, not a retry loop
                await asyncio.sleep(0.01)  # lint: disable=no-adhoc-retry

    loader = asyncio.get_running_loop().create_task(load())
    try:
        groups = await net.run_reshare(net.n + 1, net.thr + 1)
        # the engine swap fires at the transition round (~3 DKG
        # timeouts out, group_setup.compute_genesis) — cross it with
        # traffic still flowing, plus two post-transition rounds on
        # the new group
        g = originals[0].processes["default"].group
        t_round = current_round(groups[0].transition_time, g.period,
                                g.genesis_time)
        target = t_round + 2
        await net.advance_to_round(target, timeout=240.0,
                                   daemons=originals)
        # a settle beat of pure serving on the post-reshare engine
        await asyncio.sleep(0.3)
    finally:
        stop.set()
        await loader
        await srv.stop()

    if stats["failures"]:
        raise AssertionError(
            f"{len(stats['failures'])} failed public reads during the "
            f"reshare: {stats['failures'][:5]}")
    if stats["reads"] < 10:
        raise AssertionError(f"load too thin to prove anything: "
                             f"{stats['reads']} reads")

    # cadence: every round present on the observed member, no holes
    store = d_obs.processes["default"]._store
    tip = store.last().round
    missing = [r for r in range(1, tip + 1)
               if not _has_round(store, r)]
    if missing:
        raise AssertionError(f"rounds dropped across the reshare: "
                             f"{missing}")

    for i, (d, b) in enumerate(zip(originals, before)):
        bp = d.processes["default"]
        if bp._store is not b["store"]:
            raise AssertionError(
                f"node{i}: store object swapped — the zero-blip "
                f"in-place transition did not hold")
        if bp.response_cache is not b["cache"]:
            raise AssertionError(
                f"node{i}: ResponseCache rebuilt instead of invalidated")
        seams = {
            "response-cache epoch":
                bp.response_cache.epoch - b["cache_epoch"],
            "signer-table epoch":
                bp.chain_store.backend.table.epoch - b["table_epoch"],
            "chains_version": d.chains_version - b["chains_version"],
        }
        wrong = {k: v for k, v in seams.items() if v != 1}
        if wrong:
            raise AssertionError(
                f"node{i}: epoch seams must each fire exactly once, "
                f"got deltas {seams}")
    return target


def _has_round(store, r: int) -> bool:
    try:
        return store.get(r) is not None
    except Exception:
        return False


async def _drive_random_soak(net: ScenarioNet, seed: int,
                             rng: random.Random) -> int:
    """Seeded random fault mix over a longer horizon: lossy/slow network
    plus a bounded store-error burst, then heal and settle."""
    base = max(net.last_rounds())
    rules = (faults.message_drop(pct=rng.uniform(5, 20))
             + faults.message_delay(pct=rng.uniform(10, 30),
                                    delay_s=rng.uniform(0.01, 0.1))
             + faults.store_commit_errors(
                 pct=50, owner=f"node{rng.randrange(net.n)}",
                 times=rng.randrange(1, 4)))
    net.arm(seed, rules)
    await net.advance_to_round(base + 8, timeout=240.0)
    failpoints.disarm()
    target = base + 9
    await net.advance_to_round(target, timeout=120.0)
    return target


async def _drive_dkg_under_fire(seed: int, rng: random.Random,
                                nodes: int, thr: int, **kw):
    # lazy import: chaos/ceremony.py pulls the crypto stack, which the
    # daemon-scenario path never needs at module load
    from drand_tpu.chaos import ceremony
    return await ceremony.drive_dkg_under_fire(seed, rng, nodes, thr, **kw)


SCENARIOS: dict[str, ScenarioSpec] = {
    "partition-heal": ScenarioSpec(
        "partition-heal",
        "symmetric partition isolates one seeded node for 3 rounds, "
        "then heals; the victim must gap-sync back",
        _drive_partition_heal),
    "leader-crash": ScenarioSpec(
        "leader-crash",
        "the DKG leader crashes mid-round at a seeded height and "
        "rejoins via catch-up",
        _drive_leader_crash),
    "store-errors-catchup": ScenarioSpec(
        "store-errors-catchup",
        "a rejoining node's catch-up commits fail with StoreError for a "
        "seeded burst; sync retries must close the gap",
        _drive_store_errors_catchup),
    "skewed-node": ScenarioSpec(
        "skewed-node",
        "one node's clock runs a seeded sub-round offset ahead of the "
        "group; rounds keep flowing and agreeing",
        _drive_skewed_node),
    "retry-storm": ScenarioSpec(
        "retry-storm",
        "a seeded peer pair's partial send is dropped a bounded number "
        "of times; seeded-backoff retries must land it within the "
        "round's deadline budget (decision log shows retry->success)",
        _drive_retry_storm),
    "breaker-trip-heal": ScenarioSpec(
        "breaker-trip-heal",
        "a partitioned peer's circuit breakers trip OPEN (observed on "
        "the metrics port), then heal to CLOSED after the partition "
        "lifts; the victim gap-syncs back",
        _drive_breaker_trip_heal),
    "crash-recover": ScenarioSpec(
        "crash-recover",
        "a real subprocess writer (crashwriter.py) is SIGKILLed "
        "mid-catchup-segment against a downed node's db; the restart "
        "scan must find a verified prefix, quarantine nothing, and the "
        "node heals to the tip via peer re-sync",
        _drive_crash_recover),
    "torn-write-heal": ScenarioSpec(
        "torn-write-heal",
        "a downed node's db suffers a torn row write plus a round-field "
        "bit flip; the restart scan quarantines exactly those rounds, "
        "rolls back to the verified prefix, and peers restore the "
        "suffix bit-identically",
        _drive_torn_write_heal),
    "object-sync-poisoned": ScenarioSpec(
        "object-sync-poisoned",
        "a donor publishes content-addressed segment objects; a stale "
        "manifest, a truncated object, and a bit-rotted object must "
        "stop a fresh client at exactly the verified segment boundary "
        "with zero damage committed, then heal bit-identically once "
        "clean objects reappear",
        _drive_object_sync_poisoned),
    "fork-detect": ScenarioSpec(
        "fork-detect",
        "one seeded probe sample is answered with a forged divergent "
        "signature (injected equivocation); the observer's consistency "
        "prober must record a typed ForkReport within a bounded number "
        "of rounds, replay-deterministically",
        _drive_fork_detect),
    "signer-loss": ScenarioSpec(
        "signer-loss",
        "a seeded signer dies; every survivor's participation ledger "
        "must show the dropped rate, chronic miss streak, and shrunken "
        "threshold margin, then heal after the victim rejoins",
        _drive_signer_loss),
    "dkg-under-fire": ScenarioSpec(
        "dkg-under-fire",
        "n-node DKG ceremony under seeded fanout drops/delays, a seeded "
        "one-way partition, crashed dealers, and a cross-ceremony "
        "stale-nonce replay injection; QUAL >= t with identical group "
        "keys and typed phase outcomes on every live node "
        "(--nodes 128 --threshold 65 is the acceptance shape)",
        _drive_dkg_under_fire, ceremony=True),
    "reshare-mid-traffic": ScenarioSpec(
        "reshare-mid-traffic",
        "reshare to a grown group while an HTTP load hammers a member: "
        "zero failed public reads, no dropped rounds, and the three "
        "epoch-invalidation seams (signer-table epoch, response-cache "
        "invalidate, chains_version) fire exactly once, together, on "
        "every original member",
        _drive_reshare_mid_traffic),
    "random-soak": ScenarioSpec(
        "random-soak",
        "seeded random drop/delay/store-error mix over ~8 rounds, then "
        "heal (longer; not in the tier-1 matrix)",
        _drive_random_soak, slow=True),
}


@dataclass
class ChaosReport:
    """One scenario run's verdict: what fired, what held.  `decisions`
    is the resilience layer's half of the replay contract: every retry
    backoff and breaker transition the run produced (aliased, seeded —
    byte-identical across replays like `summary`)."""
    scenario: str
    seed: int
    nodes: int
    threshold: int
    scheme: str
    final_rounds: list[int] = field(default_factory=list)
    invariants_passed: list[str] = field(default_factory=list)
    injections: list[dict] = field(default_factory=list)
    summary: list[tuple] = field(default_factory=list)
    decisions: list[dict] = field(default_factory=list)
    decision_summary: list[tuple] = field(default_factory=list)
    sanitized: bool = False
    sanitizer_reports: list[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"scenario": self.scenario, "seed": self.seed,
                "nodes": self.nodes, "threshold": self.threshold,
                "scheme": self.scheme, "final_rounds": self.final_rounds,
                "invariants_passed": self.invariants_passed,
                "injected": len(self.injections),
                "injections": self.injections,
                "summary": [list(t) for t in self.summary],
                "decisions": self.decisions,
                "decision_summary": [list(t) for t in
                                     self.decision_summary],
                "sanitized": self.sanitized,
                "sanitizer_reports": self.sanitizer_reports}


# Loop-block threshold while a chaos run is sanitized: chaos schedules
# legitimately make loop callbacks slower than a serving daemon's (fault
# bookkeeping, seeded delays resolved inline), so the default is looser
# than the sanitizer's; DRAND_TPU_ASYNC_SANITIZE_THRESHOLD still wins.
CHAOS_SANITIZE_THRESHOLD_S = 1.0


async def run_ceremony_scenario(spec: ScenarioSpec, seed: int, nodes: int,
                                threshold: int | None, scheme: str,
                                **drive_kw) -> ChaosReport:
    """Ceremony scenarios: no daemons, no fake clock, no chain
    invariants — the drive runs a chaos/ceremony.CeremonyNet DKG and
    returns ``(net, invariant_names)``.  The asyncio sanitizer is
    deliberately NOT armed: a host-path ceremony blocks the loop in the
    crypto by design (the compute runs inline at n^2 scale), which is
    exactly the noise the sanitizer exists to flag on SERVING daemons.
    ``final_rounds`` carries each live node's QUAL size instead of a
    chain tip."""
    rng = random.Random(seed)
    thr = threshold or (nodes // 2 + 1)
    report = ChaosReport(spec.name, seed, nodes, thr, scheme)
    res_policy.LOG.reset()
    res_policy.set_seed_override(seed)
    try:
        net, passed = await spec.drive(seed, rng, nodes, thr, **drive_kw)
        report.invariants_passed = list(passed)
        report.final_rounds = [
            len(net.bps[i].dkg_status.qual)
            if net.bps[i].dkg_status is not None else -1
            for i in net.live]
        if net.schedule is not None:
            report.injections = net.schedule.injection_log()
            report.summary = net.schedule.injection_summary()
        report.decisions = res_policy.LOG.entries()
        report.decision_summary = res_policy.LOG.summary()
        return report
    finally:
        res_policy.set_seed_override(None)
        failpoints.disarm()


async def run_scenario(name: str, seed: int, nodes: int = 3,
                       threshold: int | None = None,
                       scheme: str = "pedersen-bls-unchained",
                       sanitize: bool | None = None,
                       **drive_kw) -> ChaosReport:
    """Run one named scenario under `seed`; raises InvariantViolation /
    AssertionError when the protocol contract does not survive it.

    `sanitize` (default: DRAND_TPU_ASYNC_SANITIZE) arms the runtime
    asyncio sanitizer across the fault window — every schedule doubles
    as a dynamic race probe; reports land in the returned
    :class:`ChaosReport`, they do not fail the run by themselves.
    Ceremony scenarios (``spec.ceremony``) take the daemon-less path;
    `drive_kw` (e.g. ``k_crash``, ``dkg_timeout``) is forwarded to
    their drive."""
    spec = SCENARIOS[name]
    if spec.ceremony:
        return await run_ceremony_scenario(spec, seed, nodes, threshold,
                                           scheme, **drive_kw)
    rng = random.Random(seed)
    thr = threshold or (nodes // 2 + 1)
    node_clocks = {}
    base_clock = FakeClock(start=1_700_000_000.0)
    if name == "skewed-node":
        # skew stays under half a period: within the one-round drift
        # window the partial handler tolerates by design
        node_clocks[rng.randrange(nodes)] = faults.SkewClock(
            base_clock, rng.uniform(0.3, PERIOD / 2 - 0.5))
    net = ScenarioNet(nodes, thr, scheme, clock=base_clock,
                      node_clocks=node_clocks)
    report = ChaosReport(name, seed, nodes, thr, scheme)
    # one seed pins everything: injection decisions (Schedule) AND retry
    # backoff hashing (resilience policies in every daemon), so the
    # decision log replays byte-identically even for decisions taken
    # after a mid-scenario disarm (heal)
    res_policy.LOG.reset()
    res_policy.set_seed_override(seed)
    if sanitize is None:
        sanitize = sanitizer.enabled_by_env()
    san = None
    try:
        await net.start_daemons()
        res_policy.LOG.set_aliases(net.aliases())
        await net.run_dkg()
        await net.advance_to_round(2)
        if sanitize:
            # armed AFTER warm-up: DKG runs one-time crypto and JAX
            # compilation whose loop cost is not what the probe hunts
            thr_s = sanitizer.env_threshold() \
                if os.environ.get(sanitizer.ENV_THRESHOLD) \
                else CHAOS_SANITIZE_THRESHOLD_S
            san = sanitizer.arm(sanitizer.AsyncSanitizer(
                block_threshold_s=thr_s))
        expected = await spec.drive(net, seed, rng)
        failpoints.disarm()
        await net.drain_retries()
        if san is not None:
            sanitizer.disarm()
            report.sanitized = True
            report.sanitizer_reports = [vars(r) for r in san.reports]
            san = None
        report.final_rounds = net.last_rounds()
        report.invariants_passed = invariants.run_all(
            [net.process(i) for i in range(net.n)], expected)
        if net.schedule is not None:
            report.injections = net.schedule.injection_log()
            report.summary = net.schedule.injection_summary()
        report.decisions = res_policy.LOG.entries()
        report.decision_summary = res_policy.LOG.summary()
        return report
    finally:
        if san is not None:          # a failed drive: capture then disarm
            sanitizer.disarm()
            report.sanitized = True
            report.sanitizer_reports = [vars(r) for r in san.reports]
        res_policy.set_seed_override(None)
        failpoints.disarm()
        await net.stop()
