"""SIGKILL target for the crash-recover chaos scenario (ISSUE 15).

A real kill -9 cannot be modelled in-process (the failpoint kinds raise
exceptions; a crashed process raises nothing — it just stops), so the
scenario runs THIS module as a subprocess and SIGKILLs it mid-write:

    python -m drand_tpu.chaos.crashwriter <src.db> <dst.db>

It replays the source store's rows into the destination store as
`put_many` segments — the exact write shape of a catch-up sync commit —
printing ``SEGMENT <n>`` after each committed transaction and sleeping
briefly between them so the parent can SIGKILL it at a seeded segment
count.  The durability contract under test: whenever the kill lands,
the destination database reopens at a segment boundary — fully-applied
segments only, nothing torn (WAL + synchronous>=NORMAL + one
transaction per segment, chain/store.py).

Deliberately jax-free and decorator-free: it writes through the bare
SqliteStore, because the contract being falsified is the PHYSICAL
store's, not the append-only discipline above it.
"""

from __future__ import annotations

import argparse
import time

from drand_tpu.chain import codec as row_codec
from drand_tpu.chain.store import SqliteStore


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay src rows into dst as put_many segments "
                    "(crash-recover SIGKILL target)")
    ap.add_argument("src", help="source db (a survivor's chain)")
    ap.add_argument("dst", help="destination db (the crashed victim)")
    ap.add_argument("--segment", type=int, default=1,
                    help="rounds per put_many transaction")
    ap.add_argument("--sleep-s", type=float, default=0.05,
                    help="pause after each committed segment (the kill "
                         "window)")
    args = ap.parse_args(argv)

    src = SqliteStore(args.src)
    dst = SqliteStore(args.dst)
    try:
        start = dst.last().round + 1
    except Exception:
        start = 0
    n = 0
    next_round = start
    while True:
        rows = src.raw_rows(next_round, args.segment)
        if not rows:
            break
        dst.put_many([row_codec.decode_beacon(blob) for _, blob in rows])
        n += 1
        print(f"SEGMENT {n}", flush=True)
        next_round = rows[-1][0] + 1
        time.sleep(args.sleep_s)
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
