"""Composable fault models over the failpoint registry.

Each builder returns plain :class:`~drand_tpu.chaos.failpoints.Rule`
lists, so models compose by concatenation into one seeded
:class:`~drand_tpu.chaos.failpoints.Schedule`:

    rules = partition(["node2"], ["node0", "node1"]) \
          + message_delay(pct=20, delay_s=0.1)
    failpoints.arm(failpoints.Schedule(seed, rules))

Node-level actions the inline sites cannot express — killing and
restarting a daemon, skewing one node's clock — are modelled here too:
:class:`NodeAction` entries are interpreted by the scenario runner
(drand_tpu/chaos/runner.py), and :class:`SkewClock` wraps a node's
injected clock (the ticker/clock seam) with a constant offset.

Reference map (SURVEY §5.3): the reference exercises these paths with a
deny-listed broadcast (``TestRunDKGBroadcastDeny``), orchestrator node
kill/restart, and corrupt-signature mocks; this module is the same idea
as a first-class, seedable library.
"""

from __future__ import annotations

from dataclasses import dataclass

from drand_tpu.beacon.clock import Clock
from drand_tpu.chaos.failpoints import Rule

# Sites that carry a message between two nodes (src/dst ctx): the
# surface partitions and message faults apply to.  net.ping rides along
# so a partition is visible to the health watchdog's connectivity
# probes, not just the protocol traffic.
MESSAGE_SITES = ("net.send_partial", "net.sync_recv", "partial.recv",
                 "dkg.fanout", "net.ping")

# The gossip-mesh overlay's message seams (relay/gossip.py): round
# delivery on a mesh pump and the peer-exchange RPC.  Separate from
# MESSAGE_SITES because mesh nodes are relays, not group members — a
# mesh partition must not imply a protocol partition.
MESH_SITES = ("relay.mesh_recv", "relay.exchange")


def mesh_partition(side_a: list[str], side_b: list[str],
                   rounds: tuple[int, int] | None = None) -> list[Rule]:
    """Symmetric gossip-overlay partition between two sets of mesh-node
    labels (``mesh0``… once the schedule's aliases are set)."""
    return partition(side_a, side_b, rounds, sites=MESH_SITES)


def mesh_partition_oneway(src_side: list[str],
                          dst_side: list[str],
                          rounds: tuple[int, int] | None = None
                          ) -> list[Rule]:
    """Asymmetric overlay partition: deliveries FROM `src_side` TO
    `dst_side` go dark (and exchanges in that direction fail) while the
    reverse path still works — the one-way reachability failure a mesh
    must survive by pulling from peers it can still hear."""
    return partition_oneway(src_side, dst_side, rounds, sites=MESH_SITES)


def partition(side_a: list[str], side_b: list[str],
              rounds: tuple[int, int] | None = None,
              sites=MESSAGE_SITES) -> list[Rule]:
    """Symmetric partition: every message crossing the A|B cut is
    dropped, both directions.  Node labels are aliased identifiers
    (``node0``… once the schedule's aliases are set)."""
    return (partition_oneway(side_a, side_b, rounds, sites)
            + partition_oneway(side_b, side_a, rounds, sites))


def partition_oneway(src_side: list[str], dst_side: list[str],
                     rounds: tuple[int, int] | None = None,
                     sites=MESSAGE_SITES) -> list[Rule]:
    """Asymmetric partition: messages FROM `src_side` TO `dst_side` are
    dropped; the reverse direction still flows (the classic one-way
    reachability failure a symmetric model can't reproduce)."""
    return [Rule.make(site, "drop", rounds=rounds,
                      match={"src": list(src_side), "dst": list(dst_side)})
            for site in sites]


def message_drop(pct: float, rounds: tuple[int, int] | None = None,
                 match: dict | None = None,
                 sites=MESSAGE_SITES) -> list[Rule]:
    """Lossy network: each message independently (hash-)dropped with
    probability `pct`."""
    return [Rule.make(site, "drop", pct=pct, rounds=rounds, match=match)
            for site in sites]


def message_delay(pct: float, delay_s: float = 0.05,
                  rounds: tuple[int, int] | None = None,
                  match: dict | None = None,
                  sites=MESSAGE_SITES) -> list[Rule]:
    """Slow network: selected messages stall `delay_s` before the send /
    delivery proceeds.  Composing two delay models with different pcts
    yields effective reordering (later messages overtake stalled ones) —
    the asyncio transport has no ordering guarantee across tasks to
    preserve."""
    return [Rule.make(site, "delay", pct=pct, delay_s=delay_s,
                      rounds=rounds, match=match) for site in sites]


def store_commit_errors(pct: float = 100.0, owner: str | None = None,
                        rounds: tuple[int, int] | None = None,
                        times: int | None = None) -> list[Rule]:
    """Failing disk on the append path: store.commit raises StoreError
    (the site supplies the type its callers are hardened against).
    `times` bounds the failure burst so recovery paths — idempotent
    re-put, catch-up sync retry — are actually reached."""
    match = {"owner": owner} if owner else None
    return [Rule.make("store.commit", "error", pct=pct, rounds=rounds,
                      match=match, times=times)]


def store_read_errors(pct: float = 100.0, owner: str | None = None,
                      times: int | None = None) -> list[Rule]:
    """Failing disk on the point-read path (store.read -> StoreError)."""
    match = {"owner": owner} if owner else None
    return [Rule.make("store.read", "error", pct=pct, match=match,
                      times=times)]


def sync_segment_errors(pct: float = 100.0, times: int | None = None,
                        owner: str | None = None) -> list[Rule]:
    """Catch-up segment dispatch fails before the device verify: the
    sync manager must fall back to another peer / a later retry."""
    match = {"owner": owner} if owner else None
    return [Rule.make("sync.segment", "error", pct=pct, match=match,
                      times=times)]


def missed_ticks(pct: float, rounds: tuple[int, int] | None = None,
                 times: int | None = None) -> list[Rule]:
    """The ticker fires but the tick is swallowed (GC pause, loop stall):
    subscribers see a gap and must recover via catch-up."""
    return [Rule.make("tick.fire", "error", pct=pct, rounds=rounds,
                      times=times)]


# -- disk-corruption faults (direct DB surgery) -----------------------------
#
# Failpoint kinds (delay/error/drop) raise exceptions; they cannot make
# the STORED BYTES wrong.  Torn writes and bit-rot are therefore modelled
# as direct surgery on the (closed / crashed) node's sqlite file — the
# same observable state a real partial sector write or flipped disk bit
# leaves behind — which the startup integrity scan and `util fsck` must
# then detect, quarantine, and heal.


def torn_write(db_path: str, round_: int, keep_bytes: int = 7) -> None:
    """Truncate one stored row's blob to `keep_bytes` — a write that
    stopped mid-row.  The binary codec's declared-length check turns this
    into a per-row CodecError on the next read."""
    import sqlite3
    conn = sqlite3.connect(db_path)
    try:
        with conn:
            row = conn.execute("SELECT data FROM beacons WHERE round = ?",
                               (round_,)).fetchone()
            if row is None:
                raise ValueError(f"round {round_} not stored in {db_path}")
            conn.execute("UPDATE beacons SET data = ? WHERE round = ?",
                         (bytes(row[0])[:keep_bytes], round_))
    finally:
        conn.close()


def bit_rot(db_path: str, round_: int, offset: int | None = None,
            bit: int = 0) -> None:
    """Flip one bit of one stored row's blob at byte `offset` (negative
    indexes from the end; None flips in the signature/prev region).  An
    offset inside the 8-byte round field (bytes 1..8 of a binary row)
    yields a key/round mismatch — structurally detectable without BLS;
    a flip in the signature region needs the verifier (or shows up as
    the successor's broken linkage)."""
    import sqlite3
    conn = sqlite3.connect(db_path)
    try:
        with conn:
            row = conn.execute("SELECT data FROM beacons WHERE round = ?",
                               (round_,)).fetchone()
            if row is None:
                raise ValueError(f"round {round_} not stored in {db_path}")
            blob = bytearray(row[0])
            i = (len(blob) - 1) if offset is None else offset
            if i < 0:
                i += len(blob)
            blob[i] ^= (1 << (bit & 7))
            conn.execute("UPDATE beacons SET data = ? WHERE round = ?",
                         (bytes(blob), round_))
    finally:
        conn.close()


# -- node-level actions (interpreted by the runner) -------------------------

@dataclass(frozen=True)
class NodeAction:
    """A scheduled node-level fault the runner executes: ``crash`` stops
    the node's beacon process at `at_round`; a non-None `restart_after`
    restarts it (catchup mode) once the survivors reach
    ``at_round + restart_after``."""

    kind: str                  # "crash" | "clock_skew"
    node: int                  # index into the scenario net
    at_round: int = 0
    restart_after: int | None = None
    skew_s: float = 0.0


def node_crash(node: int, at_round: int,
               restart_after: int | None = None) -> NodeAction:
    return NodeAction("crash", node, at_round=at_round,
                      restart_after=restart_after)


def clock_skew(node: int, skew_s: float) -> NodeAction:
    return NodeAction("clock_skew", node, skew_s=skew_s)


class SkewClock(Clock):
    """A node-local clock running `offset_s` ahead of (behind, if
    negative) the base clock — the clock-skew fault at the injection
    seam every protocol component already reads time through.  Sleeps
    delegate to the base clock so a fake-clock scenario still controls
    wake-ups; only `now()` (and therefore round arithmetic and
    `sleep_until` deadlines) is skewed."""

    def __init__(self, base: Clock, offset_s: float):
        self.base = base
        self.offset_s = float(offset_s)

    def now(self) -> float:
        return self.base.now() + self.offset_s

    async def sleep(self, seconds: float) -> None:
        await self.base.sleep(seconds)

    async def sleep_until(self, t: float) -> None:
        # deadline is in SKEWED time: convert to a base-clock delta
        delta = t - self.now()
        if delta > 0:
            await self.base.sleep(delta)
