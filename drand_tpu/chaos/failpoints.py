"""Named fault-injection sites (the gofail/etcd failpoint discipline).

Every failure this repo shipped before PR 3 — blocking I/O on the loop,
dropped coroutines, sync stalls — was found *after* the fact.  This
module makes failure a first-class, test-drivable input: code paths that
can fail in production declare a named **site** (`failpoint("net.send")`
style), and a seeded :class:`Schedule` decides, deterministically, which
hits inject which fault.

Design contract:

  - **Disabled is a guaranteed no-op.**  When nothing is armed, a site
    is one module-global load and an ``is None`` test — no allocation,
    no logging, no lock.  The hygiene gate (tests/test_hygiene.py)
    asserts the default state is disarmed and every literal site name
    used in the tree is declared in :data:`SITES`.
  - **Determinism is structural, not stream-based.**  A decision is a
    pure hash of ``(seed, rule, site, canonical-context)`` — NOT a draw
    from a shared RNG stream — so concurrent sites racing on the event
    loop cannot perturb each other's outcomes.  Same seed + same
    (site, round, src, dst) hit ⇒ same decision, regardless of
    arrival order.  Ephemeral details (localhost ports) are canonicalised
    away through :meth:`Schedule.set_aliases` before hashing/logging, so
    two runs of a scenario produce identical injection logs.
  - **Faults speak the seam's language.**  Each call site passes the
    exception type its callers are hardened against (``StoreError`` at
    store seams, the default :class:`FaultInjectedError` at network
    seams), so injection exercises real recovery paths instead of
    crashing tasks no production fault could crash.

Arming: programmatic (:func:`arm`), environment (:func:`arm_from_env`
reads ``DRAND_CHAOS`` — a JSON schedule spec — at daemon start), or the
localhost ``/debug/chaos`` routes on the metrics port
(drand_tpu/metrics.py).  Injections increment
``drand_chaos_injected_total{site,kind}`` and emit a ``chaos.inject``
span so chaos runs are legible in the PR-2 trace/metrics views.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field

# -- site registry ----------------------------------------------------------

# The canonical list of injection sites.  A site name used at a call
# site but missing here (or vice versa) fails the hygiene gate: the
# registry IS the operator-facing catalogue (`drand-tpu chaos list`).
SITES: dict[str, str] = {
    "net.send_partial": "outbound partial-beacon RPC (net/client.py); "
                        "ctx: src, dst, round",
    "net.sync_recv":    "one wire message received on a SyncChain stream "
                        "(net/client.py); ctx: src, dst, round (a chunk "
                        "logs its START round — the replay-stable cut "
                        "position)",
    "partial.recv":     "inbound partial accepted for verification "
                        "(beacon/node.py); ctx: src, dst, round",
    "net.ping":         "outbound peer status/health ping "
                        "(net/client.py); ctx: src, dst",
    "dkg.fanout":       "one DKG echo-broadcast send (core/broadcast.py); "
                        "ctx: src, dst",
    "store.commit":     "chain-store append transaction (chain/store.py); "
                        "ctx: owner, beacon_id, round; raises StoreError",
    "store.read":       "chain-store point read (chain/store.py); "
                        "ctx: owner, round; raises StoreError",
    "sync.segment":     "batched segment verify dispatch "
                        "(beacon/sync_manager.py); ctx: owner, round, batch",
    "tick.fire":        "round-boundary tick before subscriber fan-out "
                        "(beacon/ticker.py); error = missed tick; "
                        "ctx: round",
    "relay.mesh_recv":  "one round received on a gossip-mesh pump "
                        "(relay/gossip.py); drop = suppress delivery, "
                        "stream stays up; ctx: src, dst, round",
    "relay.exchange":   "outbound gossip peer-exchange RPC "
                        "(relay/gossip.py); ctx: src, dst",
    "warm.stage_exec":  "one warm-pipeline stage attempt before its "
                        "subprocess spawns (warm/runner.py); error = a "
                        "tunnel-drop-shaped transient the RetryPolicy "
                        "must recover; ctx: pipeline, stage, attempt",
    "probe.sample":     "one consistency-probe signature sample "
                        "(observatory/consistency.py); drop = probe "
                        "suppressed, error = the sampled peer serves a "
                        "forged divergent signature (the fork-detect "
                        "injection vector); ctx: src, dst",
}

KINDS = ("delay", "error", "drop")

MAX_LOG = 10_000      # injection-log ring bound (soaks must not OOM)


class FaultInjectedError(Exception):
    """A fault injected by an armed chaos schedule (kind=error)."""

    def __init__(self, site: str, kind: str = "error"):
        super().__init__(f"chaos: injected {kind} at {site}")
        self.site = site
        self.kind = kind


class PacketDropped(FaultInjectedError):
    """A message dropped by an armed chaos schedule (kind=drop)."""

    def __init__(self, site: str):
        super().__init__(site, "drop")


@dataclass(frozen=True)
class Rule:
    """One injection rule: WHERE (site + match), WHEN (round window),
    WHAT (kind), and HOW OFTEN (pct, times)."""

    site: str
    kind: str                       # delay | error | drop
    pct: float = 100.0              # decision probability, hash-derived
    rounds: tuple[int, int] | None = None   # inclusive ctx-round window
    # ctx equality filter; values may be a scalar or a collection
    # (membership).  Matched AFTER aliasing, so node labels work.
    match: tuple[tuple[str, object], ...] = ()
    delay_s: float = 0.05           # kind=delay: fixed, deterministic
    times: int | None = None        # fire at most N times (None = ∞)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown failpoint site {self.site!r} "
                             f"(known: {sorted(SITES)})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    @classmethod
    def make(cls, site: str, kind: str, *, pct: float = 100.0,
             rounds: tuple[int, int] | None = None,
             match: dict | None = None, delay_s: float = 0.05,
             times: int | None = None) -> "Rule":
        items = tuple(sorted((k, _freeze(v)) for k, v in
                             (match or {}).items()))
        return cls(site=site, kind=kind, pct=pct,
                   rounds=tuple(rounds) if rounds else None,
                   match=items, delay_s=delay_s, times=times)

    def to_spec(self) -> dict:
        d: dict = {"site": self.site, "kind": self.kind, "pct": self.pct}
        if self.rounds:
            d["rounds"] = list(self.rounds)
        if self.match:
            d["match"] = {k: (list(v) if isinstance(v, tuple) else v)
                          for k, v in self.match}
        if self.kind == "delay":
            d["delay_s"] = self.delay_s
        if self.times is not None:
            d["times"] = self.times
        return d


def _freeze(v):
    if isinstance(v, (list, set, tuple)):
        return tuple(sorted(str(x) for x in v))
    return v


class Schedule:
    """A seeded, deterministic injection schedule over the site registry.

    Decisions are pure functions of (seed, rule index, site, canonical
    context) — see the module docstring.  The schedule also keeps the
    injection log (bounded) and per-rule fire counts."""

    def __init__(self, seed: int, rules: list[Rule]):
        self.seed = int(seed)
        self.rules = list(rules)
        self.aliases: dict[str, str] = {}
        self._log: list[dict] = []
        self._fired: dict[int, int] = {}       # rule index -> count
        self._lock = threading.Lock()          # sites fire on many threads

    # -- canonicalisation --------------------------------------------------

    def set_aliases(self, aliases: dict[str, str]) -> None:
        """Map ephemeral identifiers (host:port with OS-assigned ports)
        to stable labels (``node0``…): applied to ctx values before both
        decision hashing and logging, so seeded runs replay identically
        across processes."""
        self.aliases = dict(aliases)

    def _alias(self, v):
        return self.aliases.get(v, v) if isinstance(v, str) else v

    def _canon(self, ctx: dict) -> dict:
        return {k: self._alias(v) for k, v in sorted(ctx.items())}

    # -- decisions ---------------------------------------------------------

    def _decide(self, idx: int, rule: Rule, site: str, canon: dict) -> bool:
        if rule.pct >= 100.0:
            return True
        key = ",".join(f"{k}={v}" for k, v in canon.items())
        h = hashlib.sha256(
            f"{self.seed}|{idx}|{site}|{key}".encode()).digest()
        return int.from_bytes(h[:8], "big") % 1_000_000 \
            < int(rule.pct * 10_000)

    def _matches(self, rule: Rule, site: str, canon: dict) -> bool:
        if rule.site != site:
            return False
        if rule.rounds is not None:
            r = canon.get("round")
            if r is None or not (rule.rounds[0] <= r <= rule.rounds[1]):
                return False
        for k, want in rule.match:
            got = canon.get(k)
            if isinstance(want, tuple):
                if got not in want:
                    return False
            elif got != want:
                return False
        return True

    def plan(self, site: str, ctx: dict) -> list[tuple[str, Rule]]:
        """The (kind, rule) actions this hit triggers, in rule order.
        Consumes `times` budgets under the lock."""
        canon = self._canon(ctx)
        out: list[tuple[str, Rule]] = []
        for idx, rule in enumerate(self.rules):
            if not self._matches(rule, site, canon):
                continue
            if not self._decide(idx, rule, site, canon):
                continue
            with self._lock:
                fired = self._fired.get(idx, 0)
                if rule.times is not None and fired >= rule.times:
                    continue
                self._fired[idx] = fired + 1
            out.append((rule.kind, rule))
        return out

    # -- logging -----------------------------------------------------------

    def _note(self, site: str, kind: str, ctx: dict) -> None:
        entry = {"site": site, "kind": kind, **self._canon(ctx)}
        with self._lock:
            if len(self._log) < MAX_LOG:
                self._log.append(entry)
        try:
            from drand_tpu import metrics as M
            M.CHAOS_INJECTED.labels(site, kind).inc()
        except Exception:
            pass
        try:
            from drand_tpu import tracing
            with tracing.span("chaos.inject",
                              beacon_id=str(ctx.get("beacon_id", "")),
                              round_=ctx.get("round"),
                              site=site, kind=kind):
                pass
        except Exception:
            pass

    def injection_log(self) -> list[dict]:
        """Every injection, in arrival order (aliased ctx)."""
        with self._lock:
            return [dict(e) for e in self._log]

    def injection_summary(self) -> list[tuple]:
        """Sorted, deduplicated injections — the replay-comparison form.
        Arrival ORDER is scheduling-dependent (two nodes race on the
        loop); the SET of (site, kind, ctx) injections is the seeded
        schedule's deterministic output."""
        seen = {tuple(sorted((k, str(v)) for k, v in e.items()))
                for e in self.injection_log()}
        return sorted(seen)

    # -- firing ------------------------------------------------------------

    def fire_sync(self, site: str, exc: type | None, ctx: dict) -> None:
        for kind, rule in self.plan(site, ctx):
            self._note(site, kind, ctx)
            if kind == "delay":
                # sync sites run off the loop (store pool / crypto
                # thread) or model a slow-disk stall ON it; real, short
                time.sleep(min(rule.delay_s, 0.25))
            elif kind == "drop":
                raise PacketDropped(site)
            else:
                raise (exc or FaultInjectedError)(site)

    async def fire(self, site: str, exc: type | None, ctx: dict) -> None:
        import asyncio
        for kind, rule in self.plan(site, ctx):
            self._note(site, kind, ctx)
            if kind == "delay":
                # real-time delay, NOT the protocol clock: fake-clock
                # scenarios advance rounds explicitly, and a fault must
                # not deadlock against the advancing test
                await asyncio.sleep(min(rule.delay_s, 0.25))
            elif kind == "drop":
                raise PacketDropped(site)
            else:
                raise (exc or FaultInjectedError)(site)

    # -- spec form (env / control route / CLI) -----------------------------

    @classmethod
    def from_spec(cls, spec: "dict | str") -> "Schedule":
        """Build from the JSON spec form:
        ``{"seed": 7, "rules": [{"site": ..., "kind": ..., "pct": 50,
        "rounds": [3, 6], "match": {"src": "node2"}, "delay_s": 0.05,
        "times": 2}, ...], "aliases": {...}}``"""
        if isinstance(spec, str):
            spec = json.loads(spec)
        rules = [Rule.make(r["site"], r["kind"],
                           pct=float(r.get("pct", 100.0)),
                           rounds=tuple(r["rounds"]) if r.get("rounds")
                           else None,
                           match=r.get("match"),
                           delay_s=float(r.get("delay_s", 0.05)),
                           times=r.get("times"))
                 for r in spec.get("rules", [])]
        sched = cls(int(spec.get("seed", 0)), rules)
        if spec.get("aliases"):
            sched.set_aliases(dict(spec["aliases"]))
        return sched

    def to_spec(self) -> dict:
        return {"seed": self.seed,
                "rules": [r.to_spec() for r in self.rules],
                "aliases": dict(self.aliases)}


# -- module arming state ----------------------------------------------------

_schedule: Schedule | None = None


def arm(schedule: Schedule) -> None:
    """Install `schedule` as the process-wide active schedule."""
    global _schedule
    _schedule = schedule


def disarm() -> None:
    global _schedule
    _schedule = None


def is_armed() -> bool:
    return _schedule is not None


def active() -> Schedule | None:
    return _schedule


def arm_from_env() -> bool:
    """Arm from the ``DRAND_CHAOS`` env var (JSON schedule spec) if set.
    Called once at daemon start; returns True when something was armed."""
    spec = os.environ.get("DRAND_CHAOS", "")
    if not spec:
        return False
    arm(Schedule.from_spec(spec))
    return True


# -- the injection sites' entry points --------------------------------------

def failpoint_sync(site: str, exc: type | None = None, **ctx) -> None:
    """Synchronous site (store/thread seams).  Disabled ⇒ exact no-op."""
    sch = _schedule
    if sch is None:
        return
    sch.fire_sync(site, exc, ctx)


async def failpoint(site: str, exc: type | None = None, **ctx) -> None:
    """Async site (network/loop seams).  Disabled ⇒ exact no-op."""
    sch = _schedule
    if sch is None:
        return
    await sch.fire(site, exc, ctx)
