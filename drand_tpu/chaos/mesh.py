"""Gossip-mesh churn at scale: the 50-100 node half of the chaos story.

The protocol harness (:mod:`drand_tpu.chaos.runner`) runs full daemons —
real DKG, real aggregation — which caps it at a handful of nodes per
process.  The fan-out layer that actually faces "millions of users" is
the gossip relay mesh (relay/gossip.py), and its failure modes are
membership-scale ones: kill/restart waves, asymmetric partitions, mesh
degree collapse.  This module runs that layer at 24 nodes in tier-1 and
100 under ``-m slow``: one real single-node chain supplies
cryptographically valid rounds (every mesh message still passes the
topic validator), a seeded drive applies churn waves and overlay
partitions through the ``relay.mesh_recv`` / ``relay.exchange``
failpoints, and the scenario ends with the same invariant discipline as
the protocol runner:

  - **monotonic-rounds**: every node's accepted-round history is
    strictly increasing (keep-latest, no regressions);
  - **no-fork**: a round accepted by any two nodes carries one
    signature (the validator makes forging impossible; this catches
    relaying bugs that would surface stale or crossed buffers);
  - **liveness**: after heal, every live node converges to the head
    round within a bound;
  - **mesh-degree**: every live node maintains ``min(degree, |known|)``
    live subscriptions after churn (GossipSub's degree maintenance).

The same entry point backs ``drand-tpu chaos run mesh-churn --seed S
--nodes N`` and the tier-1/slow tests (tests/test_mesh_churn.py).
"""

from __future__ import annotations

import asyncio
import random

from drand_tpu import log as dlog
from drand_tpu.chaos import failpoints, faults
from drand_tpu.chaos.invariants import InvariantViolation
from drand_tpu.chaos.runner import ChaosReport, ScenarioNet
from drand_tpu.client.base import Client, RandomData
from drand_tpu.relay.gossip import GossipRelayNode

log = dlog.get("chaos")

HEARTBEAT_S = 0.25          # mesh maintenance cadence under test
SETTLE_POLL_S = 0.05


class FeedClient(Client):
    """Root upstream: watch() drains rounds the drive feeds in."""

    def __init__(self, info):
        self._info = info
        self.queue: asyncio.Queue = asyncio.Queue()

    async def info(self):
        return self._info

    async def get(self, round_: int = 0):
        raise NotImplementedError

    async def watch(self):
        while True:
            yield await self.queue.get()

    async def close(self):
        pass


class MeshNode(GossipRelayNode):
    """A gossip relay that records its accepted-round history — the
    per-node evidence the invariants run over."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.history: list[tuple[int, bytes]] = []

    def publish(self, d: RandomData) -> None:
        if self._latest is None or d.round > self._latest.round:
            self.history.append((d.round, bytes(d.signature)))
        super().publish(d)


class MeshNet:
    """n mesh nodes (node 0 = root with the upstream feed) plus the
    beacons of a real chain to replay through them."""

    def __init__(self, n: int, info, beacons: list,
                 degree: int = 3, heartbeat_s: float = HEARTBEAT_S):
        self.n = n
        self.info = info
        self.beacons = beacons          # chain.beacon.Beacon, rounds 1..R
        self.degree = degree
        self.heartbeat_s = heartbeat_s
        self.feed = FeedClient(info)
        self.nodes: list[MeshNode | None] = []   # owner: mesh driver; None = currently dead
        self._addrs: list[str] = []              # stable per index
        self.schedule: failpoints.Schedule | None = None

    async def start(self):
        root = MeshNode(self.feed, "127.0.0.1:0", self.info,
                        degree=self.degree, heartbeat_s=self.heartbeat_s)
        await root.start()
        self.nodes.append(root)
        self._addrs.append(root.address)
        for _ in range(1, self.n):
            node = MeshNode(None, "127.0.0.1:0", self.info,
                            bootstrap=[root.address], degree=self.degree,
                            heartbeat_s=self.heartbeat_s)
            await node.start()
            self.nodes.append(node)
            self._addrs.append(node.address)

    def aliases(self) -> dict[str, str]:
        """Stable ``mesh<i>`` labels over OS-assigned ports (the replay
        contract, like the protocol runner's ``node<i>``)."""
        return {addr: f"mesh{i}" for i, addr in enumerate(self._addrs)}

    def arm(self, seed: int, rules) -> failpoints.Schedule:
        sched = failpoints.Schedule(seed, rules)
        sched.set_aliases(self.aliases())
        failpoints.arm(sched)
        self.schedule = sched
        return sched

    def alive(self) -> list[MeshNode]:
        return [n for n in self.nodes if n is not None]

    def publish(self, round_: int) -> None:
        b = self.beacons[round_ - 1]
        assert b.round == round_, (b.round, round_)
        self.feed.queue.put_nowait(RandomData(
            round=b.round, signature=b.signature,
            previous_signature=b.previous_sig))

    async def kill(self, i: int) -> None:
        node = self.nodes[i]
        if node is None:
            return
        self.nodes[i] = None
        await node.stop()

    async def restart(self, i: int) -> None:
        """Rejoin on the node's OLD address (the alias map stays valid),
        bootstrapped at the root like any cold start."""
        assert self.nodes[i] is None, f"node {i} is alive"
        node = MeshNode(None, self._addrs[i], self.info,
                        bootstrap=[self.nodes[0].address],
                        degree=self.degree, heartbeat_s=self.heartbeat_s)
        await node.start()
        self.nodes[i] = node

    async def settle(self, round_: int, nodes=None,
                     timeout: float = 30.0) -> bool:
        """True once every selected live node's latest reached `round_`."""
        group = nodes if nodes is not None else self.alive()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            if all(n._latest is not None and n._latest.round >= round_
                   for n in group if n is not None):
                return True
            await asyncio.sleep(SETTLE_POLL_S)
        return False

    def latest_rounds(self) -> list[int]:
        return [(-1 if n is None else
                 (n._latest.round if n._latest else 0))
                for n in self.nodes]

    async def stop(self):
        for n in self.nodes:
            if n is not None:
                try:
                    await n.stop()
                except Exception:
                    pass
        self.nodes = []


# -- invariants --------------------------------------------------------------

def check_mesh_invariants(net: MeshNet, head: int) -> list[str]:
    """The mesh's safety/liveness contract after heal + settle; returns
    the list of invariant names that held (raises on the first that
    does not)."""
    sig_by_round: dict[int, bytes] = {}
    for i, node in enumerate(net.nodes):
        if node is None:
            continue
        prev = None
        for r, sig in node.history:
            if prev is not None and r <= prev:
                raise InvariantViolation(
                    "monotonic-rounds",
                    f"mesh{i}: accepted round {r} after {prev}")
            prev = r
            other = sig_by_round.setdefault(r, sig)
            if other != sig:
                raise InvariantViolation(
                    "no-fork",
                    f"round {r}: mesh{i} accepted {sig[:8].hex()}…, "
                    f"another node {other[:8].hex()}…")
    stale = [f"mesh{i}" for i, n in enumerate(net.nodes)
             if n is not None and (n._latest is None
                                   or n._latest.round < head)]
    if stale:
        raise InvariantViolation(
            "liveness", f"nodes below head {head} after heal: {stale} "
                        f"({net.latest_rounds()})")
    weak = [f"mesh{i}" for i, n in enumerate(net.nodes)
            if n is not None
            and len(n._mesh) < min(n.degree, len(n.known))]
    if weak:
        raise InvariantViolation(
            "mesh-degree",
            f"nodes below mesh degree after churn: {weak}")
    return ["monotonic-rounds", "no-fork", "liveness", "mesh-degree"]


# -- the seeded scenario -----------------------------------------------------

async def _build_feed_chain(rounds: int):
    """One real single-node chain supplies `rounds` valid beacons (the
    mesh validator verifies every message — garbage feeds test nothing)."""
    sc = ScenarioNet(1, 1, "pedersen-bls-unchained")
    try:
        await sc.start_daemons()
        await sc.run_dkg()
        await sc.advance_to_round(rounds, timeout=120.0)
        bp = sc.daemons[0].processes["default"]
        info = bp.chain_info()
        beacons = [bp._store.get(r) for r in range(1, rounds + 1)]
        return info, beacons
    finally:
        await sc.stop()


async def run_mesh_scenario(seed: int, nodes: int = 24,
                            settle_timeout: float = 60.0) -> ChaosReport:
    """Seeded churn/partition/degree-maintenance drive over `nodes` mesh
    relays.  Phases: converge → kill wave → survivors converge →
    restart wave → converge → asymmetric partition (victims starve
    while the majority converges) → heal → full convergence; then the
    invariant sweep.  Raises InvariantViolation/AssertionError when the
    mesh contract does not survive."""
    rng = random.Random(seed)
    total_rounds = 6
    info, beacons = await _build_feed_chain(total_rounds)
    net = MeshNet(nodes, info, beacons)
    report = ChaosReport("mesh-churn", seed, nodes, 0,
                         "pedersen-bls-unchained")
    try:
        await net.start()

        # phase 1: discovery + first convergence
        net.publish(1)
        net.publish(2)
        assert await net.settle(2, timeout=settle_timeout), \
            f"initial convergence failed: {net.latest_rounds()}"

        # phase 2: kill wave (never the root — the feed must survive to
        # keep the scenario falsifiable; root death is the upstream-loss
        # scenario, a different test)
        wave = rng.sample(range(1, nodes), max(2, nodes // 6))
        for i in wave:
            await net.kill(i)
        net.publish(3)
        assert await net.settle(3, timeout=settle_timeout), \
            f"survivors failed to converge after kill wave: " \
            f"{net.latest_rounds()}"

        # phase 3: restart wave — rejoined nodes converge on the NEXT
        # round (the mesh carries no history: rounds published while
        # down are the documented loss bound)
        for i in wave:
            await net.restart(i)
        net.publish(4)
        assert await net.settle(4, timeout=settle_timeout), \
            f"restarted nodes failed to converge: {net.latest_rounds()}"

        # phase 4: asymmetric partition — deliveries TO the victims go
        # dark while victims can still dial out (one-way reachability)
        victims = rng.sample([i for i in range(1, nodes) if i not in wave],
                             max(2, nodes // 5))
        others = [f"mesh{i}" for i in range(nodes) if i not in victims]
        net.arm(seed, faults.mesh_partition_oneway(
            others, [f"mesh{i}" for i in victims]))
        net.publish(5)
        majority = [n for i, n in enumerate(net.nodes)
                    if n is not None and i not in victims]
        assert await net.settle(5, nodes=majority,
                                timeout=settle_timeout), \
            f"majority failed to converge under partition: " \
            f"{net.latest_rounds()}"
        starved = [i for i in victims
                   if net.nodes[i]._latest is None
                   or net.nodes[i]._latest.round < 5]
        assert starved, (
            f"one-way partition had no effect: victims {victims} all "
            f"reached round 5 ({net.latest_rounds()})")

        # phase 5: heal; everyone converges on the next publish
        failpoints.disarm()
        net.publish(6)
        assert await net.settle(6, timeout=settle_timeout), \
            f"mesh failed to converge after heal: {net.latest_rounds()}"

        # give grafting a few heartbeats: a pump that died in the churn
        # is re-grafted at the next maintenance pass, and the degree
        # invariant judges the steady state, not the in-between
        loop = asyncio.get_running_loop()
        deg_deadline = loop.time() + 15.0
        while loop.time() < deg_deadline:
            if all(len(n._mesh) >= min(n.degree, len(n.known))
                   for n in net.alive()):
                break
            await asyncio.sleep(0.1)

        report.final_rounds = net.latest_rounds()
        report.invariants_passed = check_mesh_invariants(net, head=6)
        if net.schedule is not None:
            report.injections = net.schedule.injection_log()
            report.summary = net.schedule.injection_summary()
        if not report.injections:
            raise AssertionError("partition schedule never fired")
        return report
    finally:
        failpoints.disarm()
        await net.stop()
