"""Deterministic fault injection: failpoints, fault models, scenario
runner, and protocol invariant checkers (README §Chaos, SURVEY §5.3).

Import discipline: this package root re-exports only the light,
dependency-free failpoint layer — protocol modules instrument their
seams via ``from drand_tpu.chaos import failpoints`` without pulling
the runner (which imports the daemon, and with it JAX)."""

from drand_tpu.chaos.failpoints import (FaultInjectedError, PacketDropped,
                                        Rule, Schedule, SITES, arm,
                                        arm_from_env, disarm, failpoint,
                                        failpoint_sync, is_armed)

__all__ = ["FaultInjectedError", "PacketDropped", "Rule", "Schedule",
           "SITES", "arm", "arm_from_env", "disarm", "failpoint",
           "failpoint_sync", "is_armed"]
