"""In-process ceremony harness: DKG at n=32..128 under seeded fire.

:class:`CeremonyNet` runs one full DKG across n nodes WITHOUT daemons,
gRPC, or chains: every node gets the real `DkgProtocol`, the real
`EchoBroadcast` board (bounded per-peer fanout queues), and the real
phaser (`core/dkg_runner.run_ceremony`) — only the wire is replaced by
an in-process loopback whose `BroadcastDKG` lands directly on the
target's board.  The loopback sits BEHIND `EchoBroadcast._send_one`,
so the `dkg.fanout` failpoint, the retry policy, and the per-peer
breakers all stay on the path: a seeded :class:`failpoints.Schedule`
injects drops/delays exactly where a real network would suffer them.

Crashed dealers are nodes that never exist on the loopback: sends to
them raise `ConnectionError` through the retry/breaker machinery, their
bundles never appear, and the phaser's timeout path plus the
justification short-circuit (accused dealers that never dealt) must
carry the ceremony to QUAL >= t.

Replay contract: node addresses are deterministic and aliased to
``node<i>`` labels before decision hashing, polynomial entropy is a
seeded counter stream, and the `dkg.fanout` ctx is (src, dst) only —
so `injection_summary()` is byte-identical across runs of the same
seed (tests/test_chaos_scenarios.py pins it).
"""

from __future__ import annotations

import asyncio
import hashlib

from drand_tpu import log as dlog
from drand_tpu.chaos import failpoints
from drand_tpu.key.group import Group, Node
from drand_tpu.key.keys import Pair
from drand_tpu.resilience import Resilience

log = dlog.get("chaos")


def det_entropy(tag: bytes):
    """Deterministic entropy stream (sha256 counter over `tag`): pins
    every node's secret polynomial so a replay reruns the byte-identical
    ceremony.  Chaos harness only — production ceremonies keep the OS
    CSPRNG default."""
    state = {"ctr": 0}

    def read(n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += hashlib.sha256(
                tag + state["ctr"].to_bytes(4, "big")).digest()
            state["ctr"] += 1
        return out[:n]

    return read


class _LoopbackStub:
    """One peer's Protocol stub: BroadcastDKG lands on the target node's
    live board.  The digest is computed once sender-side and handed to
    `on_incoming` so an n=128 echo storm does not re-serialize the same
    packet n times per hop."""

    __slots__ = ("_net", "_addr")

    def __init__(self, net: "LoopbackPeers", addr: str):
        self._net = net
        self._addr = addr

    async def BroadcastDKG(self, req, timeout=None):
        bp = self._net.bps.get(self._addr)
        board = bp.dkg_board if bp is not None else None
        if board is None:
            # crashed node, or a ceremony that has not opened its board
            # yet — the caller's retry policy re-delivers the latter
            raise ConnectionError(f"dkg peer {self._addr} unreachable")
        pkt = req.dkg
        digest = hashlib.sha256(
            pkt.SerializeToString(deterministic=True)).digest()
        await board.on_incoming(pkt, digest=digest)


class LoopbackPeers:
    """net.PeerClients stand-in: protocol(addr) resolves to the loopback
    stub; board lookup is lazy (via `bp.dkg_board`), so ceremonies can
    start in any order."""

    def __init__(self):
        self.bps: dict[str, _CeremonyBp] = {}

    def protocol(self, addr: str, tls: bool = False) -> _LoopbackStub:
        return _LoopbackStub(self, addr)


class _CeremonyBp:
    """The minimal BeaconProcess surface `run_ceremony` touches."""

    def __init__(self, keypair: Pair, peers: LoopbackPeers,
                 beacon_id: str, resilience: Resilience):
        self.keypair = keypair
        self.peers = peers
        self.beacon_id = beacon_id
        self.resilience = resilience
        self.dkg_board = None
        self.dkg_status = None


class CeremonyNet:
    """n ceremony participants on an in-process loopback; `crashed`
    indices never come up (dealers that go dark before phase 1)."""

    def __init__(self, n: int, thr: int, crashed=(), seed: int = 0,
                 beacon_id: str = "default"):
        self.n, self.thr = n, thr
        self.crashed = frozenset(crashed)
        self.beacon_id = beacon_id
        self.pairs = [Pair.generate(f"127.0.0.1:{7001 + i}",
                                    seed=f"ceremony-node{i}".encode())
                      for i in range(n)]
        nodes = [Node(key=p.public.key, address=p.public.address,
                      signature=p.public.signature, index=i)
                 for i, p in enumerate(self.pairs)]
        self.group = Group(threshold=thr, period=4, nodes=nodes,
                           genesis_time=1_700_000_000,
                           scheme_id="pedersen-bls-unchained",
                           beacon_id=beacon_id)
        self.peers = LoopbackPeers()
        self.bps: dict[int, _CeremonyBp] = {}
        for i, p in enumerate(self.pairs):
            if i in self.crashed:
                continue
            bp = _CeremonyBp(p, self.peers, beacon_id,
                             Resilience(seed=seed))
            self.bps[i] = bp
            self.peers.bps[p.public.address] = bp
        self.schedule: failpoints.Schedule | None = None
        self._protocols: dict[int, object] = {}

    @property
    def live(self) -> list[int]:
        return sorted(self.bps)

    def aliases(self) -> dict[str, str]:
        return {p.public.address: f"node{i}"
                for i, p in enumerate(self.pairs)}

    def arm(self, seed: int, rules) -> failpoints.Schedule:
        from drand_tpu.resilience import policy as res_policy
        sched = failpoints.Schedule(seed, rules)
        sched.set_aliases(self.aliases())
        res_policy.LOG.set_aliases(self.aliases())
        failpoints.arm(sched)
        self.schedule = sched
        return sched

    async def run(self, dkg_timeout: float) -> dict[int, object]:
        """Run the full ceremony on every live node concurrently; returns
        {index: key.Share | None}.  Phase verdicts land on each node's
        `bp.dkg_status` (CeremonyStatus) and each live protocol stays
        reachable via `self._protocols` for post-mortem assertions."""
        from drand_tpu.core import dkg_runner

        async def one(i: int, bp: _CeremonyBp):
            share = await dkg_runner.run_ceremony(
                bp, self.group, dkg_timeout,
                entropy=det_entropy(b"ceremony-entropy-%d" % i))
            return i, share

        # capture each ceremony's protocol the moment its board opens:
        # run_ceremony clears bp.dkg_board in its finally, and the
        # under-fire drive asserts on protocol state (deals' session
        # ids, QUAL) after completion
        async def capture(i: int, bp: _CeremonyBp):
            while bp.dkg_board is None:
                await asyncio.sleep(0.005)
            self._protocols[i] = bp.dkg_board.protocol

        caps = [asyncio.get_running_loop().create_task(capture(i, bp))
                for i, bp in self.bps.items()]
        try:
            results = await asyncio.gather(
                *(one(i, bp) for i, bp in self.bps.items()))
        finally:
            for c in caps:
                c.cancel()
        return dict(results)

    def protocol(self, i: int):
        """The live (or finished) DkgProtocol of node i."""
        bp = self.bps[i]
        if bp.dkg_board is not None:
            return bp.dkg_board.protocol
        return self._protocols.get(i)

    def stale_deal_packet(self, dealer_i: int):
        """A correctly signed deal bundle from a DIFFERENT ceremony: same
        nodes, different group (shifted genesis) => different session
        nonce.  Every board must reject it — session ids bind bundles to
        exactly one ceremony (core/dkg_runner.session_nonce)."""
        from drand_tpu.core import dkg_runner
        from drand_tpu.core.broadcast import bundle_to_proto
        from drand_tpu.crypto import dkg as dkgm
        prev = Group(threshold=self.thr, period=self.group.period,
                     nodes=self.group.nodes,
                     genesis_time=self.group.genesis_time - 12345,
                     scheme_id=self.group.scheme_id,
                     beacon_id=self.beacon_id)
        stale_nonce = dkg_runner.session_nonce(prev)
        assert stale_nonce != dkg_runner.session_nonce(self.group)
        conf = dkgm.DkgConfig(
            longterm=self.pairs[dealer_i].secret,
            new_nodes=dkg_runner._dkg_nodes(prev),
            threshold=self.thr, nonce=stale_nonce,
            entropy=det_entropy(b"stale-ceremony-%d" % dealer_i))
        bundle = dkgm.DkgProtocol(conf).make_deal_bundle()
        return bundle_to_proto(bundle)


async def inject_stale_deal(net: CeremonyNet, target_i: int,
                            dealer_i: int) -> None:
    """Cross-ceremony replay injection: wait for the target's board,
    then deliver a stale-nonce deal bundle straight into `on_incoming`
    (the RPC entry).  The drive asserts afterwards that no accepted
    deal carries the stale session id."""
    bp = net.bps[target_i]
    while bp.dkg_board is None:
        await asyncio.sleep(0.005)
    await bp.dkg_board.on_incoming(net.stale_deal_packet(dealer_i))


def _auto_params(n: int, k_crash: int | None, dkg_timeout: float | None):
    """Scale crash count and phase timeout to the ceremony size.  The
    host-path crypto costs ~0.045*n^2 seconds end to end (measured on
    the CPU golden path), and with crashed dealers the deal AND response
    phases run to their full timeout — so the timeout tracks the
    compute cost instead of a fixed constant."""
    if k_crash is None:
        k_crash = max(1, n // 8) if n >= 8 else 0
    if dkg_timeout is None:
        dkg_timeout = max(6.0, 0.05 * n * n)
    return k_crash, dkg_timeout


async def drive_dkg_under_fire(seed: int, rng, n: int, thr: int,
                               k_crash: int | None = None,
                               dkg_timeout: float | None = None
                               ) -> tuple[CeremonyNet, list[str]]:
    """The dkg-under-fire drive: n-node ceremony under seeded fanout
    drops + delays + a one-way partition, k crashed dealers, and one
    cross-ceremony stale-nonce replay injection.  Asserts QUAL >= t,
    identical QUAL and group key on every live node, typed phase
    outcomes, and the replay rejection; returns the net (for the
    injection summary) and the invariant names that held."""
    from drand_tpu.crypto.bls12381 import curve as C

    k_crash, dkg_timeout = _auto_params(n, k_crash, dkg_timeout)
    crashed = sorted(rng.sample(range(1, n), k_crash)) if k_crash else []
    net = CeremonyNet(n, thr, crashed=crashed, seed=seed)
    live = net.live

    # seeded fire on the fanout seam: lossy links, slow links, and a
    # one-way partition between two small seeded slices of the live
    # set.  ctx is (src, dst) only, so every verdict is structural —
    # a link is dropped for the WHOLE ceremony or not at all, and the
    # echo overlay must route around it.
    labels = [f"node{i}" for i in live]
    cut = max(1, len(labels) // 8)
    side_a = rng.sample(labels, cut)
    side_b = rng.sample([x for x in labels if x not in side_a], cut)
    rules = [
        failpoints.Rule.make("dkg.fanout", "drop", pct=10.0),
        failpoints.Rule.make("dkg.fanout", "delay", pct=15.0,
                             delay_s=0.05),
        failpoints.Rule.make("dkg.fanout", "drop",
                             match={"src": side_a, "dst": side_b}),
    ]
    net.arm(seed, rules)

    replay = asyncio.get_running_loop().create_task(
        inject_stale_deal(net, target_i=live[0],
                          dealer_i=live[1 % len(live)]))
    try:
        shares = await net.run(dkg_timeout)
    finally:
        replay.cancel()
        try:
            await replay
        except asyncio.CancelledError:
            pass
    invariants: list[str] = []

    held = {i: s for i, s in shares.items() if s is not None}
    if set(held) != set(live):
        raise AssertionError(
            f"live nodes without a share: {sorted(set(live) - set(held))}")
    quals = {i: tuple(net.bps[i].dkg_status.qual) for i in live}
    want_qual = tuple(live)
    for i, q in quals.items():
        if q != want_qual:
            raise AssertionError(
                f"node{i} QUAL {q} != live set {want_qual}")
    if len(want_qual) < thr:
        raise AssertionError(f"QUAL {len(want_qual)} < t={thr}")
    invariants.append("qual-covers-live")

    key0 = held[live[0]].commits[0]
    for i in live[1:]:
        if held[i].commits[0] != key0:
            raise AssertionError(f"node{i} disagrees on the group key")
    invariants.append("group-key-consistent")

    # typed phase outcomes: with crashed dealers the deal and response
    # phases must close as timeouts holding exactly the live bundles;
    # without crashes every phase completes on the fast-sync path
    want = "timeout" if crashed else "complete"
    for i in live:
        st = net.bps[i].dkg_status
        if st.state != "done":
            raise AssertionError(f"node{i} ceremony state {st.state!r}")
        by = {p.phase: p for p in st.phases}
        for phase in ("deal", "response"):
            p = by[phase]
            if p.outcome != want or p.have != len(live):
                raise AssertionError(
                    f"node{i} {phase} phase {p.to_dict()} (want "
                    f"outcome={want}, have={len(live)})")
        jp = by.get("justification")
        if crashed:
            # complaints name only dark dealers: the phase must have
            # short-circuited (zero live accused), not burned a timeout
            if jp is None or jp.want != 0 or jp.outcome != "complete":
                raise AssertionError(
                    f"node{i} justification phase "
                    f"{jp and jp.to_dict()} (want instant complete)")
    invariants.append("phase-outcomes-typed")

    # the replay injection really landed and was really rejected
    from drand_tpu.core.dkg_runner import session_nonce
    nonce = session_nonce(net.group)
    proto = net.protocol(live[0])
    if proto is None:
        raise AssertionError("target protocol not captured")
    bad = [d for d, b in proto.deals.items() if b.session_id != nonce]
    if bad:
        raise AssertionError(f"stale-session deals accepted: {bad}")
    if set(proto.deals) != set(live):
        raise AssertionError(
            f"deal set {sorted(proto.deals)} != live {live}")
    invariants.append("stale-nonce-rejected")

    # threshold-sign with the new shares: the ceremony's output is usable
    from drand_tpu.crypto import tbls
    msg = b"dkg-under-fire round 1"
    sample = live[:thr]
    partials = [tbls.sign_partial(held[i].pri_share, msg) for i in sample]
    full = tbls.recover(held[live[0]].public().pub_poly(), msg,
                        partials, thr, n)
    if not tbls.verify_recovered(C.g1_from_bytes(key0), msg, full):
        raise AssertionError("recovered signature does not verify")
    invariants.append("threshold-signable")
    return net, invariants
