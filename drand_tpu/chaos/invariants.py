"""Protocol invariant checkers asserted after every chaos scenario.

Each checker inspects post-scenario state (stores, aggregation caches)
and raises :class:`InvariantViolation` with enough context to debug the
seed.  They encode the beacon's externally-observable safety contract —
what a drand client may assume no matter which faults fired:

  - **no fork** (`check_no_fork`): one chain per beacon id — every round
    held by ≥2 nodes carries the same signature.
  - **monotonic rounds** (`check_monotonic`): each store is a gapless,
    strictly-increasing prefix of the chain (the append-only discipline
    survived injected commit errors).
  - **beacons verify** (`check_beacons_verify`): every stored beacon
    passes chain verification — injected faults never let an invalid
    signature reach disk.
  - **liveness** (`check_liveness`): after the faults heal, every node
    reached the expected round within the catch-up bound — the
    t-of-n promise that rounds keep flowing.
  - **no partial leak** (`check_no_partial_leak`): no node retains
    cached partial signatures for settled rounds — the aggregation cache
    flushed at-or-below-tip entries, so a crashed round can't be
    re-aggregated from stale threshold material.
  - **store integrity** (`check_store_integrity`): the bytes on disk are
    sound — every live row decodes to its key, the chain is contiguous
    and prev-sig-linked, and no quarantined damage copy is still the
    live row (a healed round may legitimately be live again beside its
    forensic copy).  This is the structural half of the startup scan
    (drand_tpu/chain/recovery.py) asserted as a post-scenario fact:
    whatever faults fired, a node that survived them must be restartable
    from its own disk.

The checkers take plain stores/verifiers (not the runner's net) so a
test can feed them forged state and prove each one is able to fail —
a checker that can't fail checks nothing (tests/test_chaos.py).
"""

from __future__ import annotations


class InvariantViolation(AssertionError):
    """A protocol invariant did not survive the scenario."""

    def __init__(self, invariant: str, detail: str):
        super().__init__(f"invariant {invariant!r} violated: {detail}")
        self.invariant = invariant
        self.detail = detail


def check_no_fork(stores) -> None:
    """Every round stored by more than one node has ONE signature.
    `stores` iterate Beacons (chain.store.Store API)."""
    seen: dict[int, bytes] = {}
    for idx, store in enumerate(stores):
        for b in store.iter_range(1):
            prev = seen.setdefault(b.round, b.signature)
            if prev != b.signature:
                raise InvariantViolation(
                    "no-fork",
                    f"round {b.round}: store {idx} holds "
                    f"{b.signature[:8].hex()}…, another node holds "
                    f"{prev[:8].hex()}…")


def check_monotonic(store, label: str = "") -> None:
    """Rounds are a contiguous, strictly-increasing sequence."""
    prev = None
    for b in store.iter_range(0):
        if prev is not None and b.round != prev + 1:
            raise InvariantViolation(
                "monotonic-rounds",
                f"store {label or '?'}: round {b.round} follows {prev} "
                f"(gap or regression)")
        prev = b.round


def check_beacons_verify(store, verifier, label: str = "") -> None:
    """Every stored beacon passes chain verification.  Round 0 (genesis)
    is the anchor, not a signature, and is skipped."""
    for b in store.iter_range(1):
        if not verifier.verify_beacon(b):
            raise InvariantViolation(
                "beacons-verify",
                f"store {label or '?'}: round {b.round} failed "
                f"verification")


def check_liveness(stores, expected_round: int, slack: int = 0) -> None:
    """After heal + settle, every node's tip reached `expected_round`
    (minus `slack` rounds of tolerance for in-flight commits)."""
    tips = []
    for store in stores:
        try:
            tips.append(store.last().round)
        except Exception:
            tips.append(-1)
    floor = expected_round - slack
    if any(t < floor for t in tips):
        raise InvariantViolation(
            "liveness",
            f"tips {tips} below expected round {expected_round} "
            f"(slack {slack})")


def check_no_partial_leak(chain_store, label: str = "") -> None:
    """No cached partial-signature material at or below the chain tip:
    settled rounds must have been flushed from the aggregation cache
    (beacon/cache.py flush_rounds) — stale threshold shares for a
    settled round are re-aggregation material a replayed packet could
    trigger on."""
    tip = chain_store.tip_round()
    stale = [r for r in chain_store.cache.rounds() if r <= tip]
    if stale:
        raise InvariantViolation(
            "no-partial-leak",
            f"node {label or '?'}: cached partials for settled rounds "
            f"{sorted(stale)} (tip {tip})")


def check_store_integrity(store, label: str = "") -> None:
    """The bytes on disk are sound (structural half of the startup scan,
    drand_tpu/chain/recovery.py): every live row decodes to its own key,
    rounds are contiguous, chained prev-sigs link, and no quarantined
    DAMAGE copy is still the live row — a healed round may be live again
    beside its forensic copy (the restored bytes differ, or the copy is
    a rolled-back-good-suffix row peers restored bit-identically), but a
    damage-reason blob that equals the live blob means the repair never
    actually removed what it quarantined.  `store` is the UNDECORATED
    SqliteStore (raw_rows sees damaged blobs instead of raising)."""
    from drand_tpu.chain import codec as row_codec

    def bad(detail: str):
        return InvariantViolation("store-integrity",
                                  f"store {label or '?'}: {detail}")

    qmap: dict[int, tuple[bytes, str]] = {}
    if hasattr(store, "quarantined_rows"):
        qmap = {r: (data, reason)
                for r, data, reason in store.quarantined_rows()}
    prev: tuple[int, bytes] | None = None
    next_round = 0
    while True:
        rows = store.raw_rows(next_round, 1024)
        if not rows:
            break
        for r, blob in rows:
            try:
                rr, sig, prev_sig = row_codec.decode_fields(blob)
            except row_codec.CodecError as exc:
                raise bad(f"round {r} fails decode: {exc}")
            if rr != r:
                raise bad(f"round {r} decodes to round {rr}")
            if prev is not None:
                if r != prev[0] + 1:
                    raise bad(f"gap: round {r} follows {prev[0]}")
                if prev_sig and prev_sig != prev[1]:
                    raise bad(f"round {r} prev-sig does not link")
            prev = (r, sig)
            if r in qmap:
                qdata, reason = qmap[r]
                if qdata == blob and not reason.startswith("rollback"):
                    raise bad(f"round {r} live bytes identical to its "
                              f"quarantined damage copy ({reason!r})")
        next_round = rows[-1][0] + 1


def run_all(processes, expected_round: int, slack: int = 0) -> list[str]:
    """Run every checker over a scenario's BeaconProcesses; returns the
    list of invariant names that passed (raises on the first failure)."""
    stores = [bp._store for bp in processes]
    check_no_fork(stores)
    for i, bp in enumerate(processes):
        check_monotonic(bp._store, label=f"node{i}")
        check_beacons_verify(bp._store, bp.verifier, label=f"node{i}")
        check_no_partial_leak(bp.chain_store, label=f"node{i}")
        base = getattr(bp._store, "insecure", None)
        if base is not None and hasattr(base, "raw_rows"):
            check_store_integrity(base, label=f"node{i}")
    check_liveness(stores, expected_round, slack=slack)
    return ["no-fork", "monotonic-rounds", "beacons-verify",
            "no-partial-leak", "store-integrity", "liveness"]
