"""Minimal TOML writer (stdlib has tomllib for reading but no writer).

Supports the subset the key/group stores need: str/int/bool scalars, flat
tables, and arrays of tables — the same shapes as the reference's TOML
artifacts (`key/group.go:189-302`, `key/store.go`).
"""

from __future__ import annotations


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(v, list) and all(isinstance(x, (str, int, float, bool)) for x in v):
        return "[" + ", ".join(_fmt_value(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value {type(v)}")


def dumps(doc: dict) -> str:
    """dict -> TOML.  List-of-dict values become [[array of tables]];
    dict values become [tables]; everything else top-level scalars."""
    lines: list[str] = []
    tables: list[tuple[str, dict]] = []
    array_tables: list[tuple[str, list]] = []
    for k, v in doc.items():
        if isinstance(v, dict):
            tables.append((k, v))
        elif isinstance(v, list) and v and all(isinstance(x, dict) for x in v):
            array_tables.append((k, v))
        else:
            lines.append(f"{k} = {_fmt_value(v)}")
    for name, tbl in tables:
        lines.append("")
        lines.append(f"[{name}]")
        for k, v in tbl.items():
            lines.append(f"{k} = {_fmt_value(v)}")
    for name, items in array_tables:
        for item in items:
            lines.append("")
            lines.append(f"[[{name}]]")
            for k, v in item.items():
                lines.append(f"{k} = {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


def loads(text: str) -> dict:
    try:
        import tomllib
    except ImportError:  # Python <3.11 without tomli: parse our own subset
        return _loads_minimal(text)
    return tomllib.loads(text)


# ---------------------------------------------------------------------------
# Fallback reader for the writer's subset (scalars, [tables],
# [[arrays of tables]], flat scalar arrays) — enough to round-trip every
# TOML artifact this package emits when the stdlib reader is absent.
# ---------------------------------------------------------------------------

def _loads_minimal(text: str) -> dict:
    root: dict = {}
    target: dict = root
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[["):
            name = line[2:-2].strip()
            target = {}
            root.setdefault(name, []).append(target)
        elif line.startswith("["):
            target = root.setdefault(line[1:-1].strip(), {})
        else:
            k, eq, v = line.partition("=")
            if not eq:
                raise ValueError(f"unparseable TOML line: {raw!r}")
            target[k.strip()] = _parse_value(v.strip())
    return root


def _parse_value(s: str):
    if s.startswith('"'):
        val, consumed = _parse_str(s)
        if s[consumed:].strip():
            raise ValueError(f"trailing data after string: {s!r}")
        return val
    if s.startswith("["):
        return _parse_list(s)
    return _parse_scalar_token(s)


def _parse_scalar_token(s: str):
    if s == "true":
        return True
    if s == "false":
        return False
    try:
        return int(s)
    except ValueError:
        return float(s)


def _parse_str(s: str) -> tuple[str, int]:
    """Parse a leading basic string; returns (value, chars consumed)."""
    out: list[str] = []
    i = 1
    while i < len(s):
        c = s[i]
        if c == "\\":
            if i + 1 >= len(s):
                break
            nxt = s[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n",
                        "t": "\t", "r": "\r"}.get(nxt, nxt))
            i += 2
        elif c == '"':
            return "".join(out), i + 1
        else:
            out.append(c)
            i += 1
    raise ValueError(f"unterminated TOML string: {s!r}")


def _parse_list(s: str) -> list:
    items: list = []
    i = 1
    while i < len(s):
        while i < len(s) and s[i] in " \t,":
            i += 1
        if i >= len(s) or s[i] == "]":
            return items
        if s[i] == '"':
            val, consumed = _parse_str(s[i:])
            items.append(val)
            i += consumed
        else:
            j = i
            while j < len(s) and s[j] not in ",]":
                j += 1
            items.append(_parse_scalar_token(s[i:j].strip()))
            i = j
    raise ValueError(f"unterminated TOML array: {s!r}")
