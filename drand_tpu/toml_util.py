"""Minimal TOML writer (stdlib has tomllib for reading but no writer).

Supports the subset the key/group stores need: str/int/bool scalars, flat
tables, and arrays of tables — the same shapes as the reference's TOML
artifacts (`key/group.go:189-302`, `key/store.go`).
"""

from __future__ import annotations


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(v, list) and all(isinstance(x, (str, int, float, bool)) for x in v):
        return "[" + ", ".join(_fmt_value(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value {type(v)}")


def dumps(doc: dict) -> str:
    """dict -> TOML.  List-of-dict values become [[array of tables]];
    dict values become [tables]; everything else top-level scalars."""
    lines: list[str] = []
    tables: list[tuple[str, dict]] = []
    array_tables: list[tuple[str, list]] = []
    for k, v in doc.items():
        if isinstance(v, dict):
            tables.append((k, v))
        elif isinstance(v, list) and v and all(isinstance(x, dict) for x in v):
            array_tables.append((k, v))
        else:
            lines.append(f"{k} = {_fmt_value(v)}")
    for name, tbl in tables:
        lines.append("")
        lines.append(f"[{name}]")
        for k, v in tbl.items():
            lines.append(f"{k} = {_fmt_value(v)}")
    for name, items in array_tables:
        for item in items:
            lines.append("")
            lines.append(f"[[{name}]]")
            for k, v in item.items():
                lines.append(f"{k} = {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


def loads(text: str) -> dict:
    import tomllib
    return tomllib.loads(text)
