"""The health model: expected round vs stored tip.

Counterpart of the reference `/health` handler (http/server.go:491-535):
derive the round the clock says should exist (`chain/time.py` over the
injectable Clock) and compare it to the chain tip.  The tip comes from
the ChainStore's in-memory tip cache (beacon/chain.py) — a health probe
must never contend with the protocol loop on a sqlite read.

Every check refreshes `drand_beacon_lag_rounds{beacon_id}`, so the
gauge is live whether the refresh came from the watchdog's periodic
tick or an operator hitting `/health`.
"""

from __future__ import annotations

from dataclasses import dataclass

from drand_tpu import log as dlog
from drand_tpu import metrics as M
from drand_tpu.chain.time import current_round

log = dlog.get("health")

# A node one round behind is still catching the current round's partials
# (the reference tolerates the same slack, http/server.go:523-527).
HEALTHY_LAG_ROUNDS = 1


@dataclass(frozen=True)
class HealthStatus:
    """One beacon chain's verdict at one instant."""

    beacon_id: str
    current: int                 # stored chain tip round
    expected: int                # round the clock says should exist

    @property
    def lag(self) -> int:
        return max(self.expected - self.current, 0)

    @property
    def healthy(self) -> bool:
        return self.lag <= HEALTHY_LAG_ROUNDS

    def to_dict(self) -> dict:
        return {"current": self.current, "expected": self.expected,
                "lag": self.lag, "healthy": self.healthy}


def check_process(bp, clock) -> HealthStatus | None:
    """Judge one BeaconProcess; None when it has no servable chain yet
    (keypair-only, mid-DKG, or engine torn down)."""
    group = bp.group
    chain = getattr(bp, "chain_store", None)
    if group is None or chain is None:
        return None
    tip = chain.tip_round()
    if tip < 0:
        # no genesis committed yet: pre-DKG-completion or a fresh store
        tip = 0
    expected = current_round(clock.now(), group.period, group.genesis_time)
    st = HealthStatus(beacon_id=bp.beacon_id, current=tip, expected=expected)
    M.BEACON_LAG_ROUNDS.labels(bp.beacon_id).set(st.lag)
    return st
