"""Rolling-window SLO tracking over round lateness.

The objective is the one the group itself encodes: a round should be
published within `catchup_period` of its scheduled time (the group's
recovery cadence — if rounds routinely land later than that, the chain
is effectively always in catch-up).  Each committed round contributes
one boolean sample; attainment is the good fraction over each rolling
window and the burn rate is how fast the error budget is being spent
(burn 1.0 = exactly the rate the SLO target allows; >1 = on track to
blow the budget — the SRE-workbook multiwindow framing).

Samples are timestamped from the injectable clock seam, so fake-clock
tests drive windows deterministically.  Gauges:
`drand_slo_attainment_ratio{beacon_id,window}` and
`drand_slo_error_budget_burn{beacon_id,window}`; the JSON view is
`/debug/slo` on the metrics port.
"""

from __future__ import annotations

import threading
from collections import deque

from drand_tpu import metrics as M

# rolling windows (seconds) — short enough that a fake-clock test spans
# one, long enough that the hour view means something in production
DEFAULT_WINDOWS = (60.0, 600.0, 3600.0)
DEFAULT_TARGET = 0.99
MAX_SAMPLES = 8192


def _window_label(seconds: float) -> str:
    return f"{int(seconds)}s"


class SLOTracker:
    """One beacon's published-on-time objective over rolling windows."""

    def __init__(self, beacon_id: str, threshold_s: float, clock_now,
                 windows: tuple[float, ...] = DEFAULT_WINDOWS,
                 target: float = DEFAULT_TARGET):
        self.beacon_id = beacon_id
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        self.windows = tuple(windows)
        self._now = clock_now                     # injectable clock seam
        # (timestamp, round, ok) newest-last; bounded — at one sample per
        # round this outlives the longest window at any sane period
        self._samples: deque[tuple[float, int, bool]] = deque(
            maxlen=MAX_SAMPLES)
        # commits land from the event loop AND the sync worker thread
        self._lock = threading.Lock()

    def record(self, round_: int, lateness_s: float) -> bool:
        """Add one committed round's sample; returns whether it met the
        objective.  Refreshes the window gauges."""
        ok = lateness_s <= self.threshold_s
        with self._lock:
            self._samples.append((self._now(), round_, ok))
        self.refresh_gauges()
        return ok

    def window_stats(self, window_s: float) -> tuple[int, int]:
        """(total, good) samples inside the trailing window."""
        cutoff = self._now() - window_s
        with self._lock:
            items = list(self._samples)
        total = good = 0
        for ts, _, ok in items:
            if ts >= cutoff:
                total += 1
                good += ok
        return total, good

    def attainment(self, window_s: float) -> float | None:
        total, good = self.window_stats(window_s)
        return (good / total) if total else None

    def burn_rate(self, window_s: float) -> float | None:
        """Error-budget burn: observed error rate / allowed error rate.
        None with no samples; capped implicitly by the sample count."""
        att = self.attainment(window_s)
        if att is None:
            return None
        budget = 1.0 - self.target
        if budget <= 0:
            return 0.0 if att >= 1.0 else float("inf")
        return (1.0 - att) / budget

    def refresh_gauges(self) -> None:
        for w in self.windows:
            label = _window_label(w)
            att = self.attainment(w)
            if att is None:
                continue
            M.SLO_ATTAINMENT.labels(self.beacon_id, label).set(att)
            burn = self.burn_rate(w)
            if burn is not None and burn != float("inf"):
                M.SLO_BURN_RATE.labels(self.beacon_id, label).set(burn)

    def snapshot(self) -> dict:
        """JSON view for /debug/slo and the CLI probe."""
        out = {"beacon_id": self.beacon_id,
               "objective": {
                   "description": "round published within threshold "
                                  "of its scheduled time",
                   "threshold_s": self.threshold_s,
                   "target": self.target},
               "windows": []}
        for w in self.windows:
            total, good = self.window_stats(w)
            att = (good / total) if total else None
            out["windows"].append({
                "window": _window_label(w),
                "samples": total,
                "good": good,
                "attainment": round(att, 6) if att is not None else None,
                "burn_rate": (round(b, 6)
                              if (b := self.burn_rate(w)) is not None
                              and b != float("inf") else None),
            })
        return out
