"""Node health & SLO monitoring (the judgments layer over raw metrics).

The reference daemon's operability surface is its `/health` handler
(http/server.go:491-535, stored tip vs the round the clock says should
exist) and the `metrics` package's per-peer `GroupConnectivity` gauge.
This package is that surface grown into a subsystem:

  - :mod:`model` — the health verdict: expected round (chain/time + the
    injectable Clock) vs the ChainStore tip cache, exported as
    `drand_beacon_lag_rounds{beacon_id}` and the upgraded `/health`
    (200 `{current, expected}` / 503 behind).
  - :mod:`watchdog` — the periodic judge: stalled round production,
    per-peer missed partials, and peer connectivity pings over the
    cached node-to-node channels (`drand_group_connectivity{peer}`),
    logging state CHANGES rather than states.
  - :mod:`slo` — rolling-window attainment of "round published within
    catchup_period" and error-budget burn rate, served at `/debug/slo`.

Log lines emitted while judging carry the current tracing span's ids
(drand_tpu/log.py), so a health incident pivots straight into
`/debug/spans/{trace_id}` and `/debug/logs?trace_id=...`.
"""

from drand_tpu.health.model import HEALTHY_LAG_ROUNDS, HealthStatus, \
    check_process
from drand_tpu.health.slo import SLOTracker
from drand_tpu.health.watchdog import PeerStateTracker, Watchdog

__all__ = ["HEALTHY_LAG_ROUNDS", "HealthStatus", "check_process",
           "SLOTracker", "PeerStateTracker", "Watchdog"]
