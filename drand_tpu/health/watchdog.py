"""The node watchdog: the component that *judges* node state.

Raw signals existed before this package — spans, gauges, failpoints —
but nothing watched them.  The watchdog is a single periodic task per
daemon (interval on the injectable Clock, so fake-clock tests drive it
deterministically) that, each tick and per beacon process:

  - refreshes the health verdict (model.check_process) and logs
    healthy <-> behind TRANSITIONS with the lag that crossed;
  - detects stalled round production: the expected round advancing
    while the stored tip does not (a dead ticker, a wedged aggregator,
    or a failing store all look like this from the outside);
  - tracks per-peer partial recency from the Handler's accept
    bookkeeping (`drand_peer_partial_lag_rounds{beacon_id,peer}`) and
    flags members whose partials stopped arriving;
  - pings every group peer over the existing cached node-to-node
    channels (net/client.py) and feeds
    `drand_group_connectivity{peer}` through a
    :class:`PeerStateTracker`, which logs only state CHANGES.

The SLO trackers (health/slo.py) also live here: the per-commit
lateness samples arrive via :meth:`Watchdog.note_round`, fed from the
chain store's latency callback (core/process.py).
"""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu import metrics as M
from drand_tpu.health import model
from drand_tpu.health.slo import SLOTracker

log = dlog.get("health", "watchdog")

DEFAULT_INTERVAL_S = 5.0
PING_TIMEOUT_S = 5.0            # real seconds; RPCs resolve in real time
# a peer is "missing partials" when its newest accepted partial sits
# this many rounds behind the tip (and the chain is old enough to tell)
MISSED_PARTIAL_ROUNDS = 3


class PeerStateTracker:
    """Up/down bookkeeping behind `drand_group_connectivity{peer}`.

    Shared by the daemon watchdog (group-member pings) and the relay
    gossip mesh (subscription liveness): both report observations; the
    tracker owns the gauge and logs transitions exactly once."""

    def __init__(self, logger=None, context: str = "peer"):
        self._log = logger or log
        self._context = context
        self._up: dict[str, bool] = {}

    def note(self, peer: str, ok: bool) -> bool:
        """Record one observation; returns True when the state CHANGED."""
        prev = self._up.get(peer)
        self._up[peer] = ok
        M.GROUP_CONNECTIVITY.labels(peer).set(1 if ok else 0)
        if prev is None and ok:
            return False          # first sight of a healthy peer: quiet
        if prev == ok:
            return False
        if ok:
            self._log.info("%s %s is back (connectivity restored)",
                           self._context, peer)
        else:
            self._log.warning("%s %s is unreachable (marked down)",
                              self._context, peer)
        return True

    def forget(self, peer: str) -> None:
        self._up.pop(peer, None)

    def is_up(self, peer: str) -> bool | None:
        return self._up.get(peer)

    def snapshot(self) -> dict:
        return dict(self._up)


class Watchdog:
    """One daemon's periodic health judge (start/stop with the daemon)."""

    def __init__(self, daemon, interval_s: float | None = None):
        self.daemon = daemon
        self.clock = daemon.config.clock
        self.interval_s = interval_s if interval_s is not None else \
            getattr(daemon.config, "health_interval_s", DEFAULT_INTERVAL_S)
        self.peer_states = PeerStateTracker(log, context="group peer")
        self._slo: dict[str, SLOTracker] = {}
        self._healthy: dict[str, bool] = {}        # last verdict per beacon
        self._stalled: dict[str, bool] = {}
        self._last_seen: dict[str, tuple[int, int]] = {}  # (tip, expected)
        # participation-ledger verdicts (observatory, ISSUE 19): loud on
        # the TRANSITION only, same discipline as the STALLED flag
        self._missing: dict[str, tuple[int, ...]] = {}
        self._margin_zero: dict[str, bool] = {}
        self._task: asyncio.Task | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.tick_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the judge must outlive whatever it is judging
                log.exception("watchdog tick failed")
            await self.clock.sleep(self.interval_s)

    # -- SLO sample intake (store latency callback via core/process.py) -----

    def note_round(self, beacon_id: str, round_: int,
                   latency_ms: float, group) -> None:
        """One committed round's lateness sample.  The objective
        threshold is the group's own recovery cadence: catchup_period
        when set, else the period."""
        tracker = self._slo.get(beacon_id)
        if tracker is None:
            threshold = float(getattr(group, "catchup_period", 0) or
                              getattr(group, "period", 1) or 1)
            tracker = SLOTracker(beacon_id, threshold, self.clock.now)
            self._slo[beacon_id] = tracker
        tracker.record(round_, max(latency_ms, 0.0) / 1000.0)

    # -- the periodic judgment ----------------------------------------------

    async def tick_once(self) -> None:
        for bid, bp in list(self.daemon.processes.items()):
            st = model.check_process(bp, self.clock)
            if st is None:
                continue
            self._judge_verdict(bid, st)
            self._judge_stall(bid, st)
            self._judge_partials(bid, bp, st)
            self._judge_participation(bid, bp)
            await self._ping_peers(bp)

    def _judge_verdict(self, bid: str, st: model.HealthStatus) -> None:
        prev = self._healthy.get(bid)
        self._healthy[bid] = st.healthy
        if prev is None or prev == st.healthy:
            return
        if st.healthy:
            log.info("beacon %s healthy again (tip %d, expected %d)",
                     bid, st.current, st.expected)
        else:
            log.warning("beacon %s behind: tip %d, expected %d "
                        "(lag %d rounds)", bid, st.current, st.expected,
                        st.lag)

    def _judge_stall(self, bid: str, st: model.HealthStatus) -> None:
        """Stalled = the clock promised a new round since the last tick
        but the tip did not move, and we are out of the healthy slack —
        a dead ticker / wedged aggregator signature.  The flag clears
        only on tip PROGRESS (or full health), not on a quiet tick: two
        observations inside the same round carry no new information."""
        prev = self._last_seen.get(bid)
        self._last_seen[bid] = (st.current, st.expected)
        if prev is None:
            return
        prev_tip, prev_expected = prev
        was = self._stalled.get(bid, False)
        if st.healthy or st.current > prev_tip:
            stalled = False
        elif st.expected > prev_expected:
            stalled = True
        else:
            stalled = was
        self._stalled[bid] = stalled
        if stalled and not was:
            log.warning("beacon %s round production STALLED at tip %d "
                        "(expected %d)", bid, st.current, st.expected)
        elif was and not stalled:
            log.info("beacon %s round production resumed (tip %d)",
                     bid, st.current)

    def _judge_partials(self, bid: str, bp, st: model.HealthStatus) -> None:
        handler = getattr(bp, "handler", None)
        group = bp.group
        if handler is None or group is None:
            return
        seen = getattr(handler, "partial_seen", {})
        own = getattr(handler, "index", -1)
        for node in group.nodes:
            idx = getattr(node, "index", None)
            if idx is None or idx == own:
                continue
            last = seen.get(idx, 0)
            lag = max(st.current - last, 0)
            M.PEER_PARTIAL_LAG.labels(bid, node.address).set(lag)
            if lag > MISSED_PARTIAL_ROUNDS and st.current > \
                    MISSED_PARTIAL_ROUNDS and last > 0:
                log.warning("beacon %s: no partial from %s since round %d "
                            "(tip %d)", bid, node.address, last, st.current)

    def _judge_participation(self, bid: str, bp) -> None:
        """Chronic signer absence and an exhausted threshold margin,
        judged from the participation ledger (drand_tpu/observatory).
        Both are loud LOG TRANSITIONS, not per-tick noise: a signer
        entering/leaving the chronically-missing set and the final
        margin crossing 0 each log exactly once (STALLED discipline).
        The ledger and `_judge_partials` read the SAME Handler accept
        feed (Handler.partial_seen is a view over the ledger), so the
        two judgments can never disagree about who was heard from."""
        ledger = getattr(getattr(bp, "handler", None), "ledger", None)
        group = bp.group
        if ledger is None or group is None:
            return
        missing = tuple(ledger.missing_signers(MISSED_PARTIAL_ROUNDS))
        prev = self._missing.get(bid, ())
        for idx in missing:
            if idx not in prev:
                node = group.node(idx)
                addr = getattr(node, "address", None) or f"#{idx}"
                log.warning("beacon %s: signer %d (%s) chronically "
                            "MISSING — no partial in the last %d finalized "
                            "rounds (participation %.2f)", bid, idx, addr,
                            ledger.miss_streak(idx), ledger.rate(idx))
        for idx in prev:
            if idx not in missing:
                log.info("beacon %s: signer %d participating again "
                         "(rate %.2f)", bid, idx, ledger.rate(idx))
        self._missing[bid] = missing
        margin = ledger.last_final_margin
        was = self._margin_zero.get(bid, False)
        exhausted = margin is not None and margin <= 0
        if exhausted and not was:
            log.warning("beacon %s: threshold margin EXHAUSTED (margin "
                        "%d) — one more silent signer halts the chain",
                        bid, margin)
        elif was and not exhausted:
            log.info("beacon %s: threshold margin restored (margin %s)",
                     bid, margin)
        self._margin_zero[bid] = exhausted

    async def _ping_peers(self, bp) -> None:
        group = bp.group
        network = getattr(bp, "network", None)
        keypair = getattr(bp, "keypair", None)
        if group is None or network is None:
            return
        own = keypair.public.address if keypair else ""
        peers = [n for n in group.nodes if n.address != own]
        if not peers:
            return
        results = await asyncio.gather(
            *[self._ping_one(network, n) for n in peers])
        # NOTE: pings deliberately do NOT feed the circuit breakers
        # (drand_tpu/resilience/breaker.py).  Breakers are fed only by
        # RetryPolicy-gated traffic, whose failure sequences are
        # deterministic in fake time — mixing in ping observations would
        # make trip points depend on event-loop ordering and break the
        # chaos replay byte-identity contract.  The reverse direction IS
        # wired: breaker transitions land on this tracker via the
        # daemon's on_transition hook (core/daemon.py).
        for node, ok in zip(peers, results):
            self.peer_states.note(node.address, ok)

    @staticmethod
    async def _ping_one(network, node) -> bool:
        try:
            await asyncio.wait_for(network.status(node), PING_TIMEOUT_S)
            return True
        except asyncio.CancelledError:
            raise
        except Exception:
            return False

    # -- debug surfaces ------------------------------------------------------

    def slo_snapshot(self) -> dict:
        return {"beacons": {bid: t.snapshot()
                            for bid, t in sorted(self._slo.items())}}

    def snapshot(self) -> dict:
        """Operator view: verdicts + peer states + SLO windows."""
        beacons = {}
        for bid, bp in self.daemon.processes.items():
            st = model.check_process(bp, self.clock)
            beacons[bid] = {
                "status": st.to_dict() if st is not None else None,
                "stalled": self._stalled.get(bid, False),
            }
        out = {"beacons": beacons,
               "peers": self.peer_states.snapshot(),
               "slo": self.slo_snapshot()["beacons"]}
        # signer participation (observatory ledger, ISSUE 19): per-signer
        # rates, chronic-absence flags, and whether the threshold margin
        # is exhausted — the group-liveness axis of this operator view
        participation = {}
        for bid, bp in self.daemon.processes.items():
            ledger = getattr(getattr(bp, "handler", None), "ledger", None)
            if ledger is not None:
                s = ledger.snapshot(limit=8)
                s["margin_exhausted"] = self._margin_zero.get(bid, False)
                s["chronically_missing"] = list(self._missing.get(bid, ()))
                participation[bid] = s
        if participation:
            out["participation"] = participation
        # the serving surface's admission lanes (inflight/waiting/shed)
        # belong in the same operator view the SLO windows live in: a
        # burning error budget with a climbing shed count is overload,
        # the same pair with zero shed is a protocol stall
        adm = getattr(getattr(self.daemon, "http_server", None),
                      "admission", None)
        if adm is not None:
            out["serve"] = adm.snapshot()
        # the device-efficiency axis (profiling/dispatch): per-seam fill
        # ratio and padding totals — a healthy protocol burning device
        # time on chronically under-filled buckets is a perf incident
        # this view would otherwise hide
        try:
            from drand_tpu.profiling import dispatch
            seams = dispatch.DISPATCH.seam_summary()
            if seams:
                out["device"] = seams
        except Exception:
            pass
        return out
