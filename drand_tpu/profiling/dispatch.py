"""Dispatch flight recorder: per-dispatch records around batched seams.

Every batched crypto seam pads the requested work up to a bucket shape
(drand_tpu/verify.py `_bucket`, DeviceBackend's partial buckets,
parallel/sharded per-device rounding) — a chronically under-filled
bucket wastes device time that no aggregate counter surfaces.  This
module keeps a bounded ring of per-dispatch records capturing the
requested n, the chosen bucket, the fill ratio, the padding-rounds
wasted, queue-wait vs device-wall time, and the amortized per-round
cost — the flight-recorder view behind `/debug/dispatch`, the Watchdog
"device" snapshot key, and the `drand_dispatch_*` metrics.

Seams:
  verify     Verifier.verify_batch_async (chain catch-up batches)
  partials   DeviceBackend/HostBackend.verify_partials (one round)
  rounds     DeviceBackend.verify_partials_rounds (multi-round table)
  sharded    parallel/sharded.py multi-device dispatch
  aggregate  AsyncPartialVerifier coalescing (queue-wait measured here)
  native     native C++ single-verify (n = bucket = 1)

Recording is O(1), lock-guarded, and never raises into the caller — a
broken metrics backend must not fail a verification.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

SEAMS = ("verify", "partials", "rounds", "sharded", "aggregate", "native")


@dataclass
class DispatchRecord:
    """One batched dispatch through a padded seam."""
    seam: str
    n: int                      # rounds/partials actually requested
    bucket: int                 # padded dispatch size the kernel saw
    device_s: float             # wall seconds inside the backend call
    queue_wait_s: float = 0.0   # enqueue -> dispatch (coalescing seams)
    wall: float = 0.0           # wall-clock stamp (operator correlation)
    attrs: dict = field(default_factory=dict)

    @property
    def fill_ratio(self) -> float:
        return (self.n / self.bucket) if self.bucket > 0 else 0.0

    @property
    def padding_rounds(self) -> int:
        return max(self.bucket - self.n, 0)

    @property
    def us_per_round(self) -> float:
        """Amortized device microseconds per REQUESTED round — padding
        makes this worse than device_s/bucket, which is the point."""
        return (self.device_s / self.n * 1e6) if self.n > 0 else 0.0

    def to_dict(self) -> dict:
        return {
            "seam": self.seam, "n": self.n, "bucket": self.bucket,
            "fill_ratio": round(self.fill_ratio, 4),
            "padding_rounds": self.padding_rounds,
            "device_s": round(self.device_s, 9),
            "queue_wait_s": round(self.queue_wait_s, 9),
            "us_per_round": round(self.us_per_round, 3),
            "wall": round(self.wall, 6),
            "attrs": dict(self.attrs),
        }


class DispatchRecorder:
    """Bounded ring of DispatchRecords plus per-seam running totals.

    Thread-safe: dispatches land from the event loop, the crypto worker
    thread, and batched-verify resolvers alike."""

    def __init__(self, maxlen: int = 2048):
        self._ring: deque[DispatchRecord] = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        # seam -> running totals since process start (the ring forgets;
        # the totals are what the watchdog and perf deltas read)
        self._totals: dict[str, dict] = {}

    def record(self, seam: str, n: int, bucket: int, device_s: float,
               queue_wait_s: float = 0.0, **attrs) -> DispatchRecord:
        rec = DispatchRecord(seam=seam, n=int(n), bucket=int(bucket),
                             device_s=float(device_s),
                             queue_wait_s=float(queue_wait_s),
                             wall=_wall_stamp(), attrs=attrs)
        with self._lock:
            self._ring.append(rec)
            tot = self._totals.setdefault(seam, {
                "dispatches": 0, "rounds": 0, "padding_rounds": 0,
                "device_s": 0.0, "queue_wait_s": 0.0})
            tot["dispatches"] += 1
            tot["rounds"] += rec.n
            tot["padding_rounds"] += rec.padding_rounds
            tot["device_s"] += rec.device_s
            tot["queue_wait_s"] += rec.queue_wait_s
        try:
            from drand_tpu import metrics as M
            M.DISPATCH_SECONDS.labels(seam, str(rec.bucket)) \
                .observe(rec.device_s)
            M.DISPATCH_FILL_RATIO.labels(seam).set(rec.fill_ratio)
            if rec.padding_rounds:
                M.DISPATCH_PADDING.labels(seam).inc(rec.padding_rounds)
        except Exception:
            pass    # metrics must never fail a dispatch
        return rec

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def records(self, seam: str | None = None,
                limit: int = 100) -> list[DispatchRecord]:
        with self._lock:
            recs = list(self._ring)
        if seam is not None:
            recs = [r for r in recs if r.seam == seam]
        return recs[-limit:]

    def seam_summary(self) -> dict:
        """Per-seam totals with derived efficiency numbers — the view a
        chronically under-filled bucket is visible in."""
        with self._lock:
            totals = {seam: dict(tot) for seam, tot in self._totals.items()}
        for seam, tot in totals.items():
            dispatched = tot["rounds"] + tot["padding_rounds"]
            tot["avg_fill_ratio"] = round(
                tot["rounds"] / dispatched, 4) if dispatched else 0.0
            tot["amortized_us_per_round"] = round(
                tot["device_s"] / tot["rounds"] * 1e6, 3) \
                if tot["rounds"] else 0.0
            tot["device_s"] = round(tot["device_s"], 6)
            tot["queue_wait_s"] = round(tot["queue_wait_s"], 6)
        return totals

    def snapshot(self, limit: int = 50) -> dict:
        return {
            "seams": self.seam_summary(),
            "recent": [r.to_dict() for r in self.records(limit=limit)][::-1],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._totals.clear()


def _wall_stamp() -> float:
    """Wall stamp for operator correlation only (never a duration);
    routed through tracing's injectable clock so fake-clock tests stay
    coherent across spans and dispatch records."""
    try:
        from drand_tpu import tracing
        return tracing._wall()
    except Exception:
        return time.time()  # lint: disable=no-wall-clock


DISPATCH = DispatchRecorder()


def record_dispatch(seam: str, n: int, bucket: int, device_s: float,
                    queue_wait_s: float = 0.0, **attrs) -> None:
    """Module-level convenience used by the instrumented seams; never
    raises (the flight recorder is an observer, not a participant)."""
    try:
        DISPATCH.record(seam, n, bucket, device_s,
                        queue_wait_s=queue_wait_s, **attrs)
    except Exception:
        pass


class timed_dispatch:
    """Context manager timing one device call for a seam:

        with timed_dispatch("verify", n=n, bucket=m):
            ok = kernel(...)

    `.extend()` lets split dispatch/resolve paths add the resolver's
    blocking wall before the record is cut (see verify.py)."""

    def __init__(self, seam: str, n: int, bucket: int,
                 queue_wait_s: float = 0.0, **attrs):
        self.seam = seam
        self.n = n
        self.bucket = bucket
        self.queue_wait_s = queue_wait_s
        self.attrs = attrs
        self._t0 = 0.0
        self.device_s = 0.0

    def __enter__(self) -> "timed_dispatch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.device_s = time.perf_counter() - self._t0
        record_dispatch(self.seam, self.n, self.bucket, self.device_s,
                        queue_wait_s=self.queue_wait_s, **self.attrs)
