"""Tracing / profiling hooks (SURVEY §5.1).

The reference mounts net/http/pprof on its metrics mux
(`metrics/pprof/pprof.go:12-23`, wired at `core/drand_daemon.go:271`).
The TPU-native equivalent is the JAX profiler: XLA device traces (op
timelines, HBM usage, fusion boundaries) captured on demand, plus the
same "debug handler on the metrics port" pattern (drand_tpu.metrics
mounts `/debug/jax-profile`).

Beyond the capture hooks this package carries the always-on performance
observability layer:

  - `dispatch`: the dispatch flight recorder — a bounded ring of
    per-dispatch records around every batched seam (verify buckets,
    partial coalescing, sharded fan-out, native single-verify), feeding
    `drand_dispatch_*` metrics and the `/debug/dispatch` route.
  - `journey`: per-round hop timelines collated from the tracing spans
    (tick → broadcast → partials → aggregate → commit → serve), feeding
    `drand_round_journey_seconds{hop}` and `/debug/journey`.

Usage:
  - programmatic: `with profiling.trace("/tmp/trace"): run_kernels()`
  - one-shot:     `profiling.capture("/tmp/trace", seconds=2.0)`
  - daemon:       GET /debug/jax-profile?seconds=2  on the metrics port
  - perf work:    `python -m drand_tpu.profiling out_dir -- cmd ...`
                  runs `cmd` in a subprocess with a JAX trace captured
                  around its whole lifetime (see __main__.py);
                  tools/profile_verify.py remains the verify-specific
                  harness.

Traces are TensorBoard-compatible (`xplane.pb` under the out dir); on the
axon backend only device traces are trustworthy — host-side wall times
include the remote tunnel (~120 ms/call).
"""

from __future__ import annotations

import contextlib
import os
import time

from drand_tpu.profiling import dispatch, journey  # noqa: F401
from drand_tpu.profiling.dispatch import DISPATCH, record_dispatch  # noqa: F401
from drand_tpu.profiling.journey import JOURNEY  # noqa: F401


@contextlib.contextmanager
def trace(out_dir: str):
    """Capture a JAX profiler trace around a block."""
    import jax
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)
    try:
        yield out_dir
    finally:
        jax.profiler.stop_trace()


def capture(out_dir: str, seconds: float = 2.0) -> str:
    """Record whatever device activity happens in the next `seconds`."""
    with trace(out_dir):
        time.sleep(seconds)
    return out_dir


def annotate(name: str):
    """Named span visible in the trace timeline (TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def manifest(out_dir: str) -> dict:
    """Describe a captured trace directory: the files the profiler wrote
    (relative paths + sizes), for the `/debug/jax-profile` response and
    the `-m` runner's summary."""
    files = []
    total = 0
    for root, _dirs, names in os.walk(out_dir):
        for name in sorted(names):
            path = os.path.join(root, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            total += size
            files.append({"path": os.path.relpath(path, out_dir),
                          "bytes": size})
    return {"trace_dir": out_dir, "files": files,
            "num_files": len(files), "total_bytes": total}
