"""Round-journey timelines: per-round hop latencies from tracing spans.

The spans already record every stage of a round's life; what no surface
answered was "where does round N spend its time, hop by hop, and what
is the p99 of each hop?".  This module collates ended spans per
(beacon_id, round) into one hop record:

    tick -> broadcast -> partial_first -> partial_last -> aggregate
         -> commit -> serve

Hop timestamps are wall-clock completion stamps (tracing's injectable
wall source, so fake-clock tests stay coherent); hop OFFSETS are
seconds since the round's tick (or its earliest observed hop), which
makes a journey monotonic by construction of the protocol.  Rolling
p50/p99/p999 per hop feed `drand_round_journey_seconds{hop}` and the
`/debug/journey` route; `collate()` merges raw span dicts pulled from
several nodes' `/debug/spans/{trace_id}` into one cross-node timeline
for `drand-tpu util journey <round>`.

Feeding happens from `tracing.Span.end()` (same pattern as the stage
histogram) and from the public serve path's first-byte note; both are
O(1) and never raise into the caller.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

HOPS = ("tick", "broadcast", "partial_first", "partial_last",
        "aggregate", "commit", "serve")

# span name -> journey hop; partial.verify lands twice (first completion
# and the running last completion)
_SPAN_HOPS = {
    "round.tick": "tick",
    "partial.broadcast": "broadcast",
    "partial.verify": None,         # special-cased: first/last
    "partial.aggregate": "aggregate",
    "store.commit": "commit",
}


def _pct(values: list[float], q: float) -> float | None:
    """Nearest-rank percentile over a non-empty sorted list."""
    if not values:
        return None
    idx = max(0, min(len(values) - 1, int(round(q * len(values) + 0.5)) - 1))
    return values[idx]


class JourneyCollator:
    """Bounded per-round hop collation + rolling per-hop percentiles."""

    def __init__(self, max_rounds: int = 512, window: int = 4096):
        # (beacon_id, round) -> {"hops": {hop: wall}, "finalized": bool}
        self._rounds: "OrderedDict[tuple, dict]" = OrderedDict()
        self._max_rounds = max_rounds
        self._window: dict[str, deque] = {
            hop: deque(maxlen=window) for hop in HOPS}
        self._lock = threading.Lock()

    # -- feeding -----------------------------------------------------------

    def feed_span(self, span) -> None:
        """Called by tracing.Span.end() for every ended span; ignores
        spans that are not journey hops or carry no round identity."""
        hop = _SPAN_HOPS.get(span.name, "missing") \
            if span.name in _SPAN_HOPS else "missing"
        if hop == "missing" or span.round is None:
            return
        done = span.start_wall + (span.duration_s or 0.0)
        if span.name == "round.tick":
            # the tick hop is the round's t=0: stamp its START, not its
            # (zero-length) completion
            self._note(span.beacon_id, span.round, "tick", span.start_wall)
            return
        if span.name == "partial.verify":
            self._note_partial(span.beacon_id, span.round, done)
            return
        self._note(span.beacon_id, span.round, hop, done)
        if hop == "commit":
            self._finalize(span.beacon_id, span.round)

    def note_serve(self, beacon_id: str, round_: int) -> None:
        """First served byte for a round on the public surface.  O(1)
        and only the FIRST serve per round records — the hot latest
        path pays one dict probe per request."""
        key = (beacon_id, round_)
        with self._lock:
            entry = self._rounds.get(key)
            if entry is None or "serve" in entry["hops"]:
                return
        self._note(beacon_id, round_, "serve", _wall())
        self._observe(beacon_id, round_, only=("serve",))

    def _entry(self, key: tuple) -> dict:
        entry = self._rounds.get(key)
        if entry is None:
            entry = {"hops": {}, "finalized": False}
            self._rounds[key] = entry
            while len(self._rounds) > self._max_rounds:
                self._rounds.popitem(last=False)
        return entry

    def _note(self, beacon_id: str, round_: int, hop: str,
              wall: float) -> None:
        with self._lock:
            entry = self._entry((beacon_id, round_))
            if entry["finalized"] and hop != "serve":
                return    # a straggler span must not mutate an observed journey
            hops = entry["hops"]
            if hop not in hops:
                hops[hop] = wall

    def _note_partial(self, beacon_id: str, round_: int,
                      done: float) -> None:
        with self._lock:
            entry = self._entry((beacon_id, round_))
            # partial_last means "the straggler that GATED aggregation":
            # a partial verified after the round already aggregated (a
            # slow peer's extra beyond threshold) is not on the journey's
            # critical path and would break hop monotonicity
            if entry["finalized"] or "aggregate" in entry["hops"]:
                return
            hops = entry["hops"]
            first = hops.get("partial_first")
            hops["partial_first"] = done if first is None \
                else min(first, done)
            hops["partial_last"] = max(hops.get("partial_last", done), done)

    # -- finalization ------------------------------------------------------

    def _finalize(self, beacon_id: str, round_: int) -> None:
        """Commit landed: the aggregation half of the journey is over —
        fold every present hop into the rolling windows and the
        histogram exactly once.  (`serve` arrives later, if ever, and
        observes separately.)"""
        with self._lock:
            entry = self._rounds.get((beacon_id, round_))
            if entry is None or entry["finalized"]:
                return
            entry["finalized"] = True
        self._observe(beacon_id, round_,
                      only=tuple(h for h in HOPS if h != "serve"))

    def _observe(self, beacon_id: str, round_: int,
                 only: tuple) -> None:
        with self._lock:
            entry = self._rounds.get((beacon_id, round_))
            if entry is None:
                return
            offsets = _offsets(entry["hops"])
            for hop in only:
                if hop in offsets:
                    self._window[hop].append(offsets[hop])
        try:
            from drand_tpu import metrics as M
            for hop in only:
                if hop in offsets:
                    M.JOURNEY_SECONDS.labels(hop).observe(offsets[hop])
        except Exception:
            pass

    # -- reading -----------------------------------------------------------

    def percentiles(self) -> dict:
        out = {}
        with self._lock:
            windows = {hop: sorted(w) for hop, w in self._window.items() if w}
        for hop, vals in windows.items():
            out[hop] = {"count": len(vals),
                        "p50": round(_pct(vals, 0.50), 6),
                        "p99": round(_pct(vals, 0.99), 6),
                        "p999": round(_pct(vals, 0.999), 6)}
        return out

    def round_record(self, beacon_id: str, round_: int) -> dict | None:
        with self._lock:
            entry = self._rounds.get((beacon_id, round_))
            if entry is None:
                return None
            hops = dict(entry["hops"])
        return _record(beacon_id, round_, hops)

    def snapshot(self, limit: int = 20) -> dict:
        with self._lock:
            keys = list(self._rounds.keys())[-limit:]
            entries = [(k, dict(self._rounds[k]["hops"])) for k in keys]
        return {
            "rounds": [_record(bid, rnd, hops)
                       for (bid, rnd), hops in reversed(entries)],
            "percentiles": self.percentiles(),
        }

    def clear(self) -> None:
        with self._lock:
            self._rounds.clear()
            for w in self._window.values():
                w.clear()


def _offsets(hops: dict) -> dict:
    """Seconds-since-tick per hop (earliest hop when no tick landed)."""
    if not hops:
        return {}
    base = hops.get("tick", min(hops.values()))
    return {hop: max(hops[hop] - base, 0.0) for hop in hops}


def _record(beacon_id: str, round_: int, hops: dict) -> dict:
    from drand_tpu import tracing
    offsets = _offsets(hops)
    return {
        "beacon_id": beacon_id, "round": round_,
        "trace_id": tracing.round_trace_id(beacon_id, round_),
        "hops": {hop: {"wall": round(hops[hop], 6),
                       "offset_s": round(offsets[hop], 6)}
                 for hop in HOPS if hop in hops},
    }


def collate(span_dicts: list[dict], beacon_id: str = "",
            round_: int | None = None) -> dict:
    """Merge raw span dicts (as served by /debug/spans/{trace_id},
    possibly from SEVERAL nodes with a `node` key stamped on) into one
    cross-node timeline: every span sorted by wall start, plus the
    canonical hop record derived with the same rules the live collator
    uses."""
    collator = JourneyCollator(max_rounds=4)

    class _S:     # minimal span shim over a dict
        def __init__(self, d):
            self.name = d.get("name", "")
            self.beacon_id = d.get("beacon_id", "") or beacon_id
            self.round = d.get("round", round_)
            self.start_wall = float(d.get("start", 0.0))
            self.duration_s = float(d.get("duration_s") or 0.0)

    for d in span_dicts:
        collator.feed_span(_S(d))
    timeline = sorted(span_dicts, key=lambda d: d.get("start", 0.0))
    base = min((d.get("start", 0.0) for d in timeline), default=0.0)
    rounds = sorted({d.get("round") for d in span_dicts
                     if d.get("round") is not None})
    bids = sorted({d.get("beacon_id") for d in span_dicts
                   if d.get("beacon_id")}) or [beacon_id]
    rec = None
    if rounds:
        rec = collator.round_record(bids[0], round_ if round_ is not None
                                    else rounds[0])
    return {
        "spans": len(span_dicts),
        "nodes": sorted({d.get("node", "?") for d in span_dicts}),
        "journey": rec,
        "timeline": [{
            "offset_s": round(d.get("start", 0.0) - base, 6),
            "duration_s": d.get("duration_s"),
            "name": d.get("name"), "node": d.get("node", "?"),
            "round": d.get("round"), "status": d.get("status"),
        } for d in timeline],
    }


def _wall() -> float:
    from drand_tpu import tracing
    return tracing._wall()


JOURNEY = JourneyCollator()


def feed_span(span) -> None:
    """tracing.Span.end() hook — must never raise into a closing span."""
    try:
        JOURNEY.feed_span(span)
    except Exception:
        pass


def note_serve(beacon_id: str, round_: int) -> None:
    try:
        JOURNEY.note_serve(beacon_id, round_)
    except Exception:
        pass
