"""Subprocess profiler: `python -m drand_tpu.profiling out_dir -- cmd ...`.

Runs `cmd` in a subprocess with a JAX profiler trace captured around its
whole lifetime, then prints a JSON manifest of the files written — the
one-shot wrapper the package docstring promises, for profiling anything
(a bench, a smoke script, a REPL one-liner) without editing it.

The trace is captured in THIS process: XLA device activity of the child
is not visible across processes, so the wrapper sets
JAX_PROFILER_PORT-free defaults and is most useful for (a) host-side
timeline framing of a run and (b) children that opt into the same trace
dir via jax.profiler themselves.  For in-process kernel traces use
`profiling.trace(...)` or tools/profile_verify.py.
"""

from __future__ import annotations

import json
import subprocess
import sys


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("usage: python -m drand_tpu.profiling OUT_DIR -- CMD [ARG ...]")
        return 0 if argv else 2
    out_dir = argv[0]
    rest = argv[1:]
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        print("error: no command given (usage: python -m "
              "drand_tpu.profiling OUT_DIR -- CMD [ARG ...])",
              file=sys.stderr)
        return 2

    from drand_tpu import profiling
    with profiling.trace(out_dir):
        proc = subprocess.run(rest)
    man = profiling.manifest(out_dir)
    man["command"] = rest
    man["returncode"] = proc.returncode
    print(json.dumps(man, indent=2))
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
