"""Entropy sources for DKG secrets (reference `entropy/entropy.go`).

`get_random(source, n)` reads n bytes from a user-provided source with a
fallback to the OS CSPRNG (`:16-30`); `ScriptReader` runs an external
executable (`--source` flag) whose stdout is the entropy stream (`:33-58`).
User entropy is always mixed with crypto/rand unless user_only is set.
"""

from __future__ import annotations

import os
import subprocess


class ScriptReader:
    """Entropy from a user executable's stdout (entropy.go:33-58)."""

    def __init__(self, path: str):
        self.path = path

    def read(self, n: int) -> bytes:
        out = subprocess.run([self.path], capture_output=True, timeout=30,
                             check=True).stdout
        if len(out) < n:
            raise ValueError(
                f"entropy script produced {len(out)} < {n} bytes")
        return out[:n]


def get_random(source, n: int, user_only: bool = False) -> bytes:
    """n random bytes from `source` (object with .read(n)), XOR-mixed with
    the OS CSPRNG unless user_only (entropy.go:16-30)."""
    if source is None:
        return os.urandom(n)
    try:
        user = source.read(n)
    except Exception:
        return os.urandom(n)
    if user_only:
        return user
    system = os.urandom(n)
    return bytes(a ^ b for a, b in zip(user, system))
