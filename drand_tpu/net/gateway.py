"""Listeners: private node-to-node gateway and localhost control listener.

Counterpart of `net/gateway.go:17-105` + `net/listener.go` +
`net/control.go:29-52`: the PrivateGateway binds the Protocol and Public
gRPC services on the WAN-facing address (TLS optional), the
ControlListener binds the Control service on localhost only.
"""

from __future__ import annotations

import grpc
import grpc.aio

from drand_tpu.net.rpc import service_handler

# gRPC call timeout default mirrors the reference (net/client_grpc.go:37).
# This is the BACKSTOP only: hot-path RPCs carry per-operation deadline
# budgets derived from round timing instead (drand_tpu/resilience/deadline
# — a PartialBeacon send gets period/2, capped by this value).
DEFAULT_TIMEOUT_S = 60.0
# SyncChain server-stream buffer (net/client_grpc.go:220)
SYNC_BUFFER = 500


def _server(options=()):
    return grpc.aio.server(options=[
        ("grpc.max_send_message_length", 32 * 1024 * 1024),
        ("grpc.max_receive_message_length", 32 * 1024 * 1024),
        *options,
    ])


class PrivateGateway:
    """WAN-facing gRPC server hosting Protocol + Public services
    (net/gateway.go:17-80)."""

    def __init__(self, bind_addr: str, protocol_impl, public_impl,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 metrics_impl=None):
        self.bind_addr = bind_addr
        self.server = _server()
        handlers = [
            service_handler("Protocol", protocol_impl, validate_version=True),
            service_handler("Public", public_impl, validate_version=True),
        ]
        if metrics_impl is not None:
            # metrics federation rides the same authenticated channel
            # (reference net/client_grpc.go:336-371 httpgrpc tunnel)
            handlers.append(service_handler("MetricsService", metrics_impl))
        self.server.add_generic_rpc_handlers(tuple(handlers))
        if tls_cert and tls_key:
            with open(tls_key, "rb") as f:
                key = f.read()
            with open(tls_cert, "rb") as f:
                cert = f.read()
            creds = grpc.ssl_server_credentials([(key, cert)])
            self.port = self.server.add_secure_port(bind_addr, creds)
        else:
            self.port = self.server.add_insecure_port(bind_addr)

    async def start(self):
        await self.server.start()

    async def stop(self, grace: float = 1.0):
        await self.server.stop(grace)


class ControlListener:
    """Localhost-only Control service (net/control.go:29-52)."""

    def __init__(self, control_impl, port: int, host: str = "127.0.0.1"):
        self.server = _server()
        self.server.add_generic_rpc_handlers(
            (service_handler("Control", control_impl),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")

    async def start(self):
        await self.server.start()

    async def stop(self, grace: float = 0.5):
        await self.server.stop(grace)
