"""TLS certificate utilities.

Counterpart of `net/certs.go` (CertManager trust pool) and the reference
test helpers that generate self-signed certs for local TLS networks: a
folder of PEM certs acts as the trust pool handed to PeerClients, and
`generate_self_signed` creates a node's cert/key pair.
"""

from __future__ import annotations

import datetime
import ipaddress
import os


def generate_self_signed(host: str, cert_path: str, key_path: str,
                         days: int = 365) -> None:
    """Write a self-signed cert + key PEM pair for `host`."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    # UNIQUE subject DN per certificate: hostname matching rides the SAN,
    # and the verifier's root lookup is subject-keyed — a trust pool of
    # several same-DN self-signed roots (every node of a group named
    # "127.0.0.1") makes candidate iteration unreliable
    # (CERTIFICATE_VERIFY_FAILED for all but one node; reproduced with
    # BoringSSL, round 5).  A random OU disambiguates the DNs.
    name = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, host),
        x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME,
                           os.urandom(8).hex()),
    ])
    try:
        san: x509.GeneralName = x509.IPAddress(ipaddress.ip_address(host))
    except ValueError:
        san = x509.DNSName(host)
    # certificate validity windows are real-world time by definition —
    # a fake clock here would mint certs peers reject
    now = datetime.datetime.now(  # lint: disable=no-wall-clock
        datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName([san]),
                           critical=False)
            .sign(key, hashes.SHA256()))
    os.makedirs(os.path.dirname(cert_path) or ".", exist_ok=True)
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    os.chmod(key_path, 0o600)


class CertManager:
    """Trust pool: concatenated PEM roots for client channels
    (net/certs.go:14-45)."""

    def __init__(self):
        self._pems: list[bytes] = []

    def add(self, cert_path: str) -> None:
        with open(cert_path, "rb") as f:
            self._pems.append(f.read())

    def add_folder(self, folder: str) -> None:
        for name in sorted(os.listdir(folder)):
            if name.endswith((".pem", ".crt", ".cert")):
                self.add(os.path.join(folder, name))

    def pool_pem(self) -> bytes:
        # dedup by content: a node's own cert is often both in the shared
        # certs folder and added individually
        seen, out = set(), []
        for pem in self._pems:
            if pem not in seen:
                seen.add(pem)
                out.append(pem)
        return b"".join(out)
