"""Per-peer async gRPC clients with connection caching.

Counterpart of `net/client_grpc.go:29-49,286-334` (per-peer cached
grpc.ClientConn, 1-minute default call timeout) and the streaming clients
for SyncChain / PublicRandStream (`:220-258`, `:106-147`).  Also the
transport implementation behind the beacon Handler's `BeaconNetwork`
interface (drand_tpu/beacon/node.py).
"""

from __future__ import annotations

import asyncio

import grpc
import grpc.aio

from drand_tpu import log as dlog
from drand_tpu.beacon.chain import PartialPacket
from drand_tpu.beacon.node import BeaconNetwork
from drand_tpu.chain.beacon import Beacon
from drand_tpu.net.gateway import DEFAULT_TIMEOUT_S
from drand_tpu.net.rpc import ServiceStub
from drand_tpu.protogen import common_pb2, drand_pb2

log = dlog.get("net")


def make_metadata(beacon_id: str = "default",
                  chain_hash: bytes = b"") -> common_pb2.Metadata:
    from drand_tpu import tracing
    from drand_tpu.common import VERSION
    md = common_pb2.Metadata(
        node_version=common_pb2.NodeVersion(
            major=VERSION.major, minor=VERSION.minor, patch=VERSION.patch),
        beaconID=beacon_id, chain_hash=chain_hash)
    # trace-context propagation: every outgoing RPC carries the calling
    # task's active span, so the peer's spans parent to ours
    tracing.inject(md)
    return md


class PeerClients:
    """Cached channels/stubs keyed by peer address
    (net/client_grpc.go:286-334)."""

    def __init__(self, tls_ca: str | None = None,
                 trust_pem: bytes | None = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        """tls_ca: path to a root PEM; trust_pem: in-memory PEM pool (a
        net.certs.CertManager.pool_pem())."""
        self._channels: dict[tuple[str, bool], grpc.aio.Channel] = {}
        self._tls_ca = tls_ca
        self._trust_pem = trust_pem
        self.timeout_s = timeout_s

    def channel(self, address: str, tls: bool = False) -> grpc.aio.Channel:
        key = (address, tls)
        if key not in self._channels:
            if tls:
                pem = self._trust_pem
                if pem is None and self._tls_ca:
                    with open(self._tls_ca, "rb") as f:
                        pem = f.read()
                creds = grpc.ssl_channel_credentials(pem)
                self._channels[key] = grpc.aio.secure_channel(address, creds)
            else:
                self._channels[key] = grpc.aio.insecure_channel(address)
        return self._channels[key]

    def protocol(self, address: str, tls: bool = False) -> ServiceStub:
        return ServiceStub(self.channel(address, tls), "Protocol")

    def public(self, address: str, tls: bool = False) -> ServiceStub:
        return ServiceStub(self.channel(address, tls), "Public")

    def metrics(self, address: str, tls: bool = False) -> ServiceStub:
        return ServiceStub(self.channel(address, tls), "MetricsService")

    async def close(self):
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()


class GrpcBeaconNetwork(BeaconNetwork):
    """Protocol-service transport for the beacon Handler: partial fan-out,
    chain sync streams, peer status.  Every unary send routes through the
    resilience hub (drand_tpu/resilience): seeded-backoff retries inside
    a deadline budget, gated by the target peer's circuit breaker."""

    # this node's own protocol address (set by BeaconProcess once the
    # keypair loads): the `src` half of chaos failpoint contexts, so
    # seeded partitions can target (src, dst) pairs
    local_addr: str = ""

    def __init__(self, peers: PeerClients, beacon_id: str = "default",
                 resilience=None):
        from drand_tpu.resilience import Resilience
        self.peers = peers
        self.beacon_id = beacon_id
        self.resilience = resilience or Resilience()

    async def send_partial(self, node, packet: PartialPacket,
                           deadline=None) -> None:
        from drand_tpu import tracing
        from drand_tpu.chaos import failpoints as chaos
        from drand_tpu.resilience import Deadline, deadline as dl_mod
        res = self.resilience
        # default budget = the legacy flat timeout; the Handler passes a
        # round-derived Deadline (period/2) on the hot path
        dl = deadline or Deadline.after(res.clock, self.peers.timeout_s)
        stub = self.peers.protocol(node.address, getattr(node, "tls", False))
        breaker = res.breakers.get(node.address)
        with tracing.span("partial.send", beacon_id=packet.beacon_id,
                          round_=packet.round, peer=node.address):
            async def attempt(_n):
                # the failpoint sits INSIDE the retried attempt so chaos
                # drop/delay rules exercise the retry path; `times`-capped
                # rules let a later attempt through (the recovery proof)
                await chaos.failpoint("net.send_partial", src=self.local_addr,
                                      dst=node.address, round=packet.round)
                req = drand_pb2.PartialBeaconPacket(
                    round=packet.round,
                    previous_sig=packet.previous_signature,
                    partial_sig=packet.partial_sig,
                    metadata=make_metadata(packet.beacon_id))
                dl_mod.stamp(req.metadata, dl)
                await stub.PartialBeacon(
                    req, timeout=dl.timeout(cap=self.peers.timeout_s))

            await res.retry.call("net.send_partial", attempt,
                                 peer=node.address, key=f"r{packet.round}",
                                 deadline=dl, breaker=breaker)

    async def sync_chain(self, node, from_round: int):
        import os as _os

        from drand_tpu.chain.segment import WIRE_CHUNK_DEFAULT, PackedBeacons
        from drand_tpu.chaos import failpoints as chaos
        from drand_tpu.core import convert
        stub = self.peers.protocol(node.address, getattr(node, "tls", False))
        # advertise chunk capability (ISSUE 13): reference servers ignore
        # the unknown field and keep streaming per-beacon — the consumer
        # handles both shapes below.  0 disables chunking (the bench A/B
        # control and an escape hatch).
        wire_chunk = int(_os.environ.get("DRAND_TPU_SYNC_WIRE_CHUNK",
                                         str(WIRE_CHUNK_DEFAULT)))
        req = drand_pb2.SyncRequest(from_round=from_round,
                                    chunk_size=max(0, wire_chunk),
                                    metadata=make_metadata(self.beacon_id))
        call = stub.SyncChain(req)
        async for pkt in call:
            item = convert.packet_to_item(pkt)
            packed = isinstance(item, PackedBeacons)
            # drop = the stream is cut mid-flight (the consumer's peer
            # loop falls back); delay = a slow stream.  src is the
            # SERVING peer: chaos ctx follows message direction.  One
            # site visit per wire MESSAGE — for a chunk that is one
            # visit per 512 rounds, the protocol-level win made visible
            # to chaos rules.  The ctx round is the chunk's START (the
            # cut position): the stream start is pinned by the request's
            # from_round, while the chunk END rides the serving peer's
            # tip — a value that races the rest of the scenario and
            # would make seeded injection logs unreplayable.
            await chaos.failpoint(
                "net.sync_recv", src=node.address, dst=self.local_addr,
                round=item.start_round if packed else item.round)
            try:
                from drand_tpu import metrics as M
                M.SYNC_ROUNDS.labels(
                    self.beacon_id,
                    "chunk" if packed else "single").inc(
                        len(item) if packed else 1)
            except Exception:
                pass
            yield item

    async def status(self, node) -> dict:
        from drand_tpu.chaos import failpoints as chaos
        stub = self.peers.protocol(node.address, getattr(node, "tls", False))
        # the health watchdog's connectivity probe rides this RPC: the
        # chaos seam makes a partition visible to it (drop = peer down).
        # Deliberately NOT breaker-gated — this IS the probe path; the
        # watchdog records its outcome into the breaker registry
        # (health/watchdog.py), covering timeouts this frame can't see.
        await chaos.failpoint("net.ping", src=self.local_addr,
                              dst=node.address)
        resp = await stub.Status(
            drand_pb2.StatusRequest(metadata=make_metadata(self.beacon_id)),
            timeout=self.peers.timeout_s)
        return {
            "beacon": {"is_running": resp.beacon.is_running,
                       "is_serving": resp.beacon.is_serving},
            "chain_store": {"last_round": resp.chain_store.last_round,
                            "length": resp.chain_store.length,
                            "is_empty": resp.chain_store.is_empty},
        }

    async def get_identity(self, address: str, tls: bool = False):
        stub = self.peers.protocol(address, tls)
        return await stub.GetIdentity(
            drand_pb2.IdentityRequest(metadata=make_metadata(self.beacon_id)),
            timeout=self.peers.timeout_s)


class ControlClient:
    """Localhost control-plane client used by the CLI
    (net/control.go:55-426)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self._channel = grpc.aio.insecure_channel(f"{host}:{port}")
        self.stub = ServiceStub(self._channel, "Control")
        self.timeout_s = timeout_s

    async def ping(self, beacon_id: str = "default"):
        await self.stub.PingPong(
            drand_pb2.Ping(metadata=make_metadata(beacon_id)),
            timeout=self.timeout_s)

    async def close(self):
        await self._channel.close()
