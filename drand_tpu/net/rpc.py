"""Descriptor-driven gRPC plumbing.

The image ships grpcio + protoc but not grpcio-tools, so instead of
generated *_pb2_grpc stubs this module derives servicers and client stubs
directly from the protobuf service descriptors — one code path for all
three drand services (Protocol, Public, Control), always in sync with the
.proto files.
"""

from __future__ import annotations

import os

import grpc
from google.protobuf import message_factory

from drand_tpu.protogen import drand_pb2

_SERVICES = drand_pb2.DESCRIPTOR.services_by_name


def _msg_class(desc):
    return message_factory.GetMessageClass(desc)


def _methods(service_name: str):
    svc = _SERVICES[service_name]
    for m in svc.methods:
        yield m.name, _msg_class(m.input_type), _msg_class(m.output_type), \
            m.server_streaming


def _version_ok(req) -> bool:
    """Server-side node-version compatibility gate (the reference's
    NodeVersionValidator interceptor, `net/listener.go:55-58` +
    `core/drand_daemon_interceptors.go:18-60`): requests carrying metadata
    with a node_version must match our major.minor; requests without
    metadata pass (the reference lets them through too)."""
    if os.environ.get("DISABLE_VERSION_CHECK") == "1":
        return True
    try:
        md = getattr(req, "metadata", None)
        if md is None or not md.HasField("node_version"):
            return True
        v = md.node_version
    except Exception:
        return True
    from drand_tpu.common import VERSION
    return v.major == VERSION.major and v.minor == VERSION.minor


_VERSION_ERR = "incompatible node version"


def service_handler(service_name: str, impl,
                    validate_version: bool = False) -> grpc.GenericRpcHandler:
    """Build a generic handler for `impl`, an object with async methods
    named after the service's RPCs (missing methods -> UNIMPLEMENTED).

    validate_version=True wraps every method with the node-version gate
    (used on the private gateway's Protocol/Public services, matching the
    reference's interceptor placement)."""
    handlers = {}
    for name, req_cls, _resp, streaming in _methods(service_name):
        fn = getattr(impl, name, None)
        if fn is None:
            continue
        if validate_version:
            fn = _with_version_check(fn, streaming)
        # outermost: server-side tracing span re-rooted from the
        # caller's trace context in request metadata — even
        # version-rejected requests leave an error span behind
        fn = _with_server_span(fn, service_name, name, streaming)
        if streaming:
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())
        else:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())
    return grpc.method_handlers_generic_handler(
        f"drand.{service_name}", handlers)


def _req_round(req) -> int | None:
    """The round a request addresses, when it names one (span attr)."""
    r = getattr(req, "round", 0) or getattr(req, "from_round", 0)
    return int(r) if r else None


def _with_server_span(fn, service: str, method: str, streaming: bool):
    """Wrap a service method in a tracing.server_span: the span adopts
    the caller's (trace_id, span_id) from the request `metadata` field —
    the same field the version gate below reads — so spans opened while
    handling the RPC parent to the caller's span across the wire."""
    from drand_tpu import tracing
    span_name = f"rpc.{service}.{method}"
    if streaming:
        async def stream_traced(req, ctx):
            with tracing.server_span(span_name,
                                     getattr(req, "metadata", None),
                                     round_=_req_round(req)):
                async for item in fn(req, ctx):
                    yield item
        return stream_traced

    async def unary_traced(req, ctx):
        with tracing.server_span(span_name, getattr(req, "metadata", None),
                                 round_=_req_round(req)):
            return await fn(req, ctx)
    return unary_traced


def _with_version_check(fn, streaming: bool):
    if streaming:
        async def stream_wrapped(req, ctx):
            if not _version_ok(req):
                await ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                _VERSION_ERR)
            async for item in fn(req, ctx):
                yield item
        return stream_wrapped

    async def unary_wrapped(req, ctx):
        if not _version_ok(req):
            await ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, _VERSION_ERR)
        return await fn(req, ctx)
    return unary_wrapped


class ServiceStub:
    """Client stub over a grpc.aio channel, methods resolved on attribute
    access: `stub.PartialBeacon(req, timeout=...)`."""

    def __init__(self, channel: "grpc.aio.Channel", service_name: str):
        self._channel = channel
        self._service = service_name
        self._cache = {}
        self._meta = {n: (req, resp, stream)
                      for n, req, resp, stream in _methods(service_name)}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._meta:
            raise AttributeError(f"{self._service} has no RPC {name}")
        if name not in self._cache:
            req_cls, resp_cls, streaming = self._meta[name]
            path = f"/drand.{self._service}/{name}"
            if streaming:
                self._cache[name] = self._channel.unary_stream(
                    path, request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString)
            else:
                self._cache[name] = self._channel.unary_unary(
                    path, request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString)
        return self._cache[name]
