"""Descriptor-driven gRPC plumbing.

The image ships grpcio + protoc but not grpcio-tools, so instead of
generated *_pb2_grpc stubs this module derives servicers and client stubs
directly from the protobuf service descriptors — one code path for all
three drand services (Protocol, Public, Control), always in sync with the
.proto files.
"""

from __future__ import annotations

import grpc
from google.protobuf import message_factory

from drand_tpu.protogen import drand_pb2

_SERVICES = drand_pb2.DESCRIPTOR.services_by_name


def _msg_class(desc):
    return message_factory.GetMessageClass(desc)


def _methods(service_name: str):
    svc = _SERVICES[service_name]
    for m in svc.methods:
        yield m.name, _msg_class(m.input_type), _msg_class(m.output_type), \
            m.server_streaming


def service_handler(service_name: str, impl) -> grpc.GenericRpcHandler:
    """Build a generic handler for `impl`, an object with async methods
    named after the service's RPCs (missing methods -> UNIMPLEMENTED)."""
    handlers = {}
    for name, req_cls, _resp, streaming in _methods(service_name):
        fn = getattr(impl, name, None)
        if fn is None:
            continue
        if streaming:
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())
        else:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())
    return grpc.method_handlers_generic_handler(
        f"drand.{service_name}", handlers)


class ServiceStub:
    """Client stub over a grpc.aio channel, methods resolved on attribute
    access: `stub.PartialBeacon(req, timeout=...)`."""

    def __init__(self, channel: "grpc.aio.Channel", service_name: str):
        self._channel = channel
        self._service = service_name
        self._cache = {}
        self._meta = {n: (req, resp, stream)
                      for n, req, resp, stream in _methods(service_name)}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._meta:
            raise AttributeError(f"{self._service} has no RPC {name}")
        if name not in self._cache:
            req_cls, resp_cls, streaming = self._meta[name]
            path = f"/drand.{self._service}/{name}"
            if streaming:
                self._cache[name] = self._channel.unary_stream(
                    path, request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString)
            else:
                self._cache[name] = self._channel.unary_unary(
                    path, request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString)
        return self._cache[name]
