"""Network transport layer: gRPC gateways, per-peer clients, control plane.

Counterpart of the reference's `net/` package (net/gateway.go:17-105,
net/client_grpc.go, net/control.go): a PrivateGateway serving the Protocol
and Public services node-to-node, a localhost ControlListener for the CLI,
and cached per-peer async clients.
"""

from drand_tpu.net.rpc import service_handler, ServiceStub  # noqa: F401
