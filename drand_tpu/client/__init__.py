"""Client SDK: composable middleware over randomness sources.

Counterpart of the reference `client/` package (client/client.go:47-107):
`new_client(...)` builds the middleware stack

    sources -> verifying (per source) -> optimizing (latency-racing)
            -> caching (LRU) -> watch aggregation

with the chain hash or full chain info as the root of trust.  The
verifying layer batch-verifies catch-up walks on the device — the
reference's sequential Get+verify loop (client/verify.go:118-180) is the
client-side seam SURVEY.md §5.7 calls out.
"""

from drand_tpu.client.aggregator import WatchAggregator  # noqa: F401
from drand_tpu.client.base import Client, RandomData  # noqa: F401
from drand_tpu.client.cache import CachingClient  # noqa: F401
from drand_tpu.client.client import new_client  # noqa: F401
from drand_tpu.client.http import HTTPClient  # noqa: F401
from drand_tpu.client.optimizing import OptimizingClient  # noqa: F401
from drand_tpu.client.verify import VerifyingClient  # noqa: F401
