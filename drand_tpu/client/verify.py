"""Verifying client: every result checked against the chain's public key.

Counterpart of `client/verify.go` — with the batched twist: where the
reference walks trust-point -> round one Get + one VerifyBeacon at a time
(`:118-180`, the client-side hot loop), this client fetches the needed
prefix and batch-verifies the whole contiguous segment in one device call.
"""

from __future__ import annotations

import asyncio

import numpy as np

from drand_tpu import log as dlog
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.verify import ChainVerifier
from drand_tpu.client.base import Client, RandomData

log = dlog.get("client")

FETCH_CONCURRENCY = 16


class VerifyingClient(Client):
    def __init__(self, inner: Client, full_verify: bool = False):
        """full_verify: walk and verify the whole chain back to the trust
        point for chained schemes (WithFullChainVerification)."""
        self.inner = inner
        self.full_verify = full_verify
        self._verifier: ChainVerifier | None = None
        # point of trust: last verified (round, signature)
        self._trusted_round = 0
        self._trusted_sig = b""

    async def _get_verifier(self) -> ChainVerifier:
        if self._verifier is None:
            info = await self.inner.info()
            self._verifier = ChainVerifier(info.scheme, info.public_key)
            self._genesis_seed = info.genesis_seed
        return self._verifier

    async def get(self, round_: int = 0) -> RandomData:
        v = await self._get_verifier()
        data = await self.inner.get(round_)
        return await self._verify(v, data)

    async def _verify(self, v: ChainVerifier, data: RandomData) -> RandomData:
        chained = not v.scheme.decouple_prev_sig
        if self.full_verify and chained and data.round > self._trusted_round:
            # walk the chain from the last point of trust regardless of
            # what the response claims as previous signature — the
            # response's prev-sig is the server's word, the walk is ours
            await self._walk_verify(v, data)
        elif not chained or data.previous_signature:
            beacon = Beacon(round=data.round, signature=data.signature,
                            previous_sig=data.previous_signature)
            if not v.verify_beacon(beacon):
                raise ValueError(f"round {data.round} failed verification")
        else:
            # chained scheme, no prev-sig in the response: fetch the
            # predecessor to reconstruct the digest
            prev = await self.inner.get(data.round - 1) \
                if data.round > 1 else None
            prev_sig = prev.signature if prev else self._genesis_seed
            beacon = Beacon(round=data.round, signature=data.signature,
                            previous_sig=prev_sig)
            if not v.verify_beacon(beacon):
                raise ValueError(f"round {data.round} failed verification")
        self._trusted_round = max(self._trusted_round, data.round)
        self._trusted_sig = data.signature
        # randomness must be derived, never trusted (verify.go:207)
        return RandomData(round=data.round, signature=data.signature,
                          previous_signature=data.previous_signature)

    async def _walk_verify(self, v: ChainVerifier, data: RandomData) -> None:
        """Full chain walk: concurrent fetch of the missing prefix, ONE
        batched device verification for the whole contiguous segment."""
        start = self._trusted_round + 1 if self._trusted_sig else 1
        rounds = list(range(start, data.round))
        sem = asyncio.Semaphore(FETCH_CONCURRENCY)

        async def fetch(r):
            async with sem:
                return await self.inner.get(r)

        got = await asyncio.gather(*[fetch(r) for r in rounds])
        sig_list = [g.signature for g in got] + [data.signature]
        anchor = self._trusted_sig or self._genesis_seed
        sigs = np.stack([np.frombuffer(s, np.uint8) for s in sig_list])
        ok = v._verifier.verify_chain_segment(
            start, sigs, np.frombuffer(anchor, np.uint8))
        if not bool(np.all(ok)):
            bad = [start + int(i) for i in np.nonzero(~ok)[0][:5]]
            raise ValueError(f"chain walk failed at rounds {bad}")

    async def watch(self):
        v = await self._get_verifier()
        async for data in self.inner.watch():
            try:
                yield await self._verify(v, data)
            except Exception as exc:
                log.warning("watch verification failed: %s", exc)

    async def info(self):
        return await self.inner.info()

    def round_at(self, t: float) -> int:
        return self.inner.round_at(t)

    async def close(self) -> None:
        await self.inner.close()
