"""Optimizing client: latency-ranked racing over multiple sources.

Counterpart of `client/optimizing.go`: periodic background speed tests
(`:55-58,171-212`), `get` races the fastest `race_width` sources with a
per-call timeout (`:231-264,286-348`), watch picks the fastest source.
"""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu.client.base import Client, RandomData

log = dlog.get("client")

DEFAULT_REQUEST_TIMEOUT_S = 5.0
DEFAULT_SPEED_TEST_INTERVAL_S = 300.0
DEFAULT_RACE_WIDTH = 2
DEFAULT_WATCH_RETRY_S = 2.0


class OptimizingClient(Client):
    def __init__(self, clients: list[Client],
                 request_timeout: float = DEFAULT_REQUEST_TIMEOUT_S,
                 speed_test_interval: float = DEFAULT_SPEED_TEST_INTERVAL_S,
                 race_width: int = DEFAULT_RACE_WIDTH,
                 watch_retry_interval: float = DEFAULT_WATCH_RETRY_S):
        assert clients
        self.clients = list(clients)
        self.request_timeout = request_timeout
        self.speed_test_interval = speed_test_interval
        self.race_width = race_width
        self.watch_retry_interval = watch_retry_interval
        self._rtt = {id(c): 0.0 for c in clients}      # 0 = untested
        self._task: asyncio.Task | None = None

    def start_speed_tests(self):
        if self._task is None and self.speed_test_interval > 0:
            self._task = asyncio.get_event_loop().create_task(
                self._speed_loop())

    async def _speed_loop(self):
        while True:
            await self._speed_test()
            await asyncio.sleep(self.speed_test_interval)

    async def _speed_test(self):
        loop = asyncio.get_event_loop()

        async def one(c):
            t0 = loop.time()
            try:
                await asyncio.wait_for(c.get(0), self.request_timeout)
                self._rtt[id(c)] = loop.time() - t0
            except Exception:
                self._rtt[id(c)] = float("inf")

        await asyncio.gather(*[one(c) for c in self.clients])

    def _ranked(self) -> list[Client]:
        return sorted(self.clients, key=lambda c: self._rtt[id(c)])

    async def get(self, round_: int = 0) -> RandomData:
        """Race the fastest sources; first SUCCESS wins — a source failing
        fast must not cancel a slower source that would have answered."""
        ranked = self._ranked()
        last_exc: Exception | None = None
        for i in range(0, len(ranked), self.race_width):
            group = ranked[i:i + self.race_width]
            pending = {asyncio.create_task(c.get(round_)) for c in group}
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.request_timeout
            try:
                while pending:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    done, pending = await asyncio.wait(
                        pending, timeout=remaining,
                        return_when=asyncio.FIRST_COMPLETED)
                    for t in done:
                        exc = t.exception()
                        if exc is None:
                            return t.result()
                        last_exc = exc
            finally:
                for t in pending:
                    t.cancel()
        raise last_exc or TimeoutError("all sources failed")

    async def watch(self):
        """Failover watch (optimizing.go:373-460 watchState): subscribe to
        the fastest source; when its stream ends or errors, demote it,
        re-rank, and resubscribe to the next-best after
        watch_retry_interval — yielding only strictly newer rounds, so a
        failover replay is invisible to the consumer.  Like the
        reference, the watch never ends on its own: a fully-dead source
        set keeps retrying at the interval until the consumer cancels."""
        latest = 0
        dead: set = set()      # failed since the last successful yield
        while True:
            ranked = self._ranked()
            candidates = [c for c in ranked if id(c) not in dead]
            if not candidates:
                # every source failed this rotation: start a fresh pass
                # (the retry sleep below paces the loop)
                dead.clear()
                candidates = ranked
            src = candidates[0]
            try:
                async for d in src.watch():
                    if d.round > latest:
                        latest = d.round
                        dead.clear()
                        yield d
            except Exception as exc:
                log.debug("optimizing watch: source failed: %s", exc)
            # stream ended or errored: demote until the next speed test
            # re-measures it, and skip it for the rest of this rotation
            self._rtt[id(src)] = float("inf")
            dead.add(id(src))
            await asyncio.sleep(self.watch_retry_interval)

    async def info(self):
        last_exc = None
        for c in self._ranked():
            try:
                return await c.info()
            except Exception as exc:
                last_exc = exc
        raise last_exc

    def round_at(self, t: float) -> int:
        return self.clients[0].round_at(t)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        await asyncio.gather(*[c.close() for c in self.clients],
                             return_exceptions=True)
