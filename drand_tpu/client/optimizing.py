"""Optimizing client: latency-ranked hedged requests over multiple
sources.

Counterpart of `client/optimizing.go`: periodic background speed tests
(`:55-58,171-212`) keep a per-source RTT ranking; `get` now runs the
tail-at-scale hedged form (drand_tpu/resilience/hedge.py) instead of the
reference's fixed-width race — the best source launches first, the next
launches after `hedge_delay` (or immediately on a fast failure), the
first SUCCESS wins and losers are cancelled; `watch` subscribes to the
best source and fails over.

Failures are charged to a source's score IMMEDIATELY (`_note_failure`):
the old behavior demoted a failed watch source only until the next
speed test re-measured it, so a rotation could re-pick a known-dead
source first.  The score is measured RTT plus a failure penalty that
decays one step per successful speed test.

With `verify_info` set (ISSUE 12), get/watch results are themselves
verified against the chain's public key through the native
single-verify tier (~3 ms warm, off the event loop) and a BAD answer
counts as a source failure: the hedge moves on to the next source and
a watch rotates, instead of a fast-but-lying source winning the race.
The `new_client` stack wraps each source in VerifyingClient already —
this knob is for direct constructions (custom relays, embedders) that
bypass the builder.
"""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu.client.base import Client, RandomData

log = dlog.get("client")

DEFAULT_REQUEST_TIMEOUT_S = 5.0
DEFAULT_SPEED_TEST_INTERVAL_S = 300.0
DEFAULT_RACE_WIDTH = 2
DEFAULT_WATCH_RETRY_S = 2.0
# hedge window: how long the best source gets to answer alone before the
# next one launches (Dean & Barroso pick ~p95; half the request timeout's
# tenth is a serviceable static default for randomness beacons)
DEFAULT_HEDGE_DELAY_S = 0.5
# one recorded failure weighs like this many seconds of RTT in the
# ranking — a failing source outranks only other failing sources
FAIL_PENALTY_S = 30.0


class OptimizingClient(Client):
    def __init__(self, clients: list[Client],
                 request_timeout: float = DEFAULT_REQUEST_TIMEOUT_S,
                 speed_test_interval: float = DEFAULT_SPEED_TEST_INTERVAL_S,
                 race_width: int = DEFAULT_RACE_WIDTH,
                 watch_retry_interval: float = DEFAULT_WATCH_RETRY_S,
                 hedge_delay: float = DEFAULT_HEDGE_DELAY_S,
                 resilience=None, verify_info=None):
        from drand_tpu.resilience import Resilience, RetryPolicy
        assert clients
        self.verify_info = verify_info      # chain Info; None = no checks
        self._result_verifier = None        # ChainVerifier, built lazily
        self.clients = list(clients)
        self.request_timeout = request_timeout
        self.speed_test_interval = speed_test_interval
        self.race_width = race_width            # kept for API compat;
        # hedging supersedes fixed-width racing on the get path
        self.watch_retry_interval = watch_retry_interval
        self.hedge_delay = hedge_delay
        self.resilience = resilience or Resilience()
        # watch failover pacing: full-jitter backoff over the configured
        # retry interval, so a fleet of watchers on a dead source set
        # spreads out instead of resubscribing in lockstep
        self._watch_policy = RetryPolicy(
            base_s=watch_retry_interval,
            cap_s=max(watch_retry_interval * 8, watch_retry_interval),
            clock=self.resilience.clock)
        self._rtt = {id(c): 0.0 for c in clients}      # 0 = untested
        self._fails = {id(c): 0 for c in clients}      # undecayed failures
        self._task: asyncio.Task | None = None

    def start_speed_tests(self):
        if self._task is None and self.speed_test_interval > 0:
            self._task = asyncio.get_running_loop().create_task(
                self._speed_loop())

    async def _speed_loop(self):
        while True:
            await self._speed_test()
            await asyncio.sleep(self.speed_test_interval)

    async def _speed_test(self):
        loop = asyncio.get_running_loop()

        async def one(c):
            t0 = loop.time()
            try:
                await asyncio.wait_for(c.get(0), self.request_timeout)
            except Exception:
                self._rtt[id(c)] = float("inf")
                self._fails[id(c)] += 1
            else:
                self._rtt[id(c)] = loop.time() - t0
                # decay, don't clear: a dead WATCH stream can coexist
                # with a healthy cached get — one good probe must not
                # erase the evidence
                self._fails[id(c)] = max(self._fails[id(c)] - 1, 0)

        await asyncio.gather(*[one(c) for c in self.clients])

    def _score(self, c) -> float:
        return self._rtt[id(c)] + FAIL_PENALTY_S * self._fails[id(c)]

    def _note_failure(self, c) -> None:
        """Charge a failure to the source NOW: the next ranking sees it
        without waiting for a speed test."""
        self._fails[id(c)] += 1
        self._rtt[id(c)] = float("inf")

    def _ranked(self) -> list[Client]:
        return sorted(self.clients, key=self._score)

    async def _check_result(self, d) -> bool:
        """Verify one get/watch result when `verify_info` was given: the
        native single-verify tier through ChainVerifier, in the crypto
        worker thread.  Chained beacons served without their previous
        signature cannot be digested here and pass through — the
        per-source VerifyingClient shape handles those."""
        if self.verify_info is None:
            return True
        if self._result_verifier is None:
            from drand_tpu.chain.verify import ChainVerifier
            self._result_verifier = ChainVerifier(
                self.verify_info.scheme, self.verify_info.public_key)
        v = self._result_verifier
        if not v.scheme.decouple_prev_sig and not d.previous_signature:
            return True
        from drand_tpu.beacon.crypto_backend import run_in_crypto_thread
        from drand_tpu.chain.beacon import Beacon
        beacon = Beacon(round=d.round, signature=d.signature,
                        previous_sig=d.previous_signature)
        return bool(await run_in_crypto_thread(v.verify_beacon, beacon))

    async def get(self, round_: int = 0) -> RandomData:
        """Hedged fetch: best source first, next after `hedge_delay` (or
        immediately on failure), first SUCCESS wins, losers cancelled —
        a source failing fast never cancels a slower source that would
        have answered."""
        from drand_tpu.resilience import hedge
        loop = asyncio.get_running_loop()

        def launcher(c):
            async def run():
                t0 = loop.time()
                try:
                    d = await asyncio.wait_for(c.get(round_),
                                               self.request_timeout)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    self._note_failure(c)
                    raise
                if not await self._check_result(d):
                    # a fast-but-invalid answer is a FAILURE, not a win:
                    # charge it and let the hedge race the next source
                    self._note_failure(c)
                    raise ValueError(
                        f"source served invalid beacon for round {d.round}")
                self._rtt[id(c)] = loop.time() - t0
                return d
            return run

        return await hedge.first_success(
            "client.optimizing.get",
            [launcher(c) for c in self._ranked()],
            delay_s=self.hedge_delay, clock=self.resilience.clock)

    async def watch(self):
        """Failover watch (optimizing.go:373-460 watchState): subscribe to
        the fastest source; when its stream ends or errors, charge the
        failure to its score, re-rank, and resubscribe to the next-best
        after a jittered backoff — yielding only strictly newer rounds,
        so a failover replay is invisible to the consumer.  Like the
        reference, the watch never ends on its own: a fully-dead source
        set keeps retrying until the consumer cancels."""
        latest = 0
        dead: set = set()      # failed since the last successful yield
        rotations = 0          # consecutive failovers without progress
        while True:
            ranked = self._ranked()
            candidates = [c for c in ranked if id(c) not in dead]
            if not candidates:
                # every source failed this rotation: start a fresh pass
                # (the backoff below paces the loop)
                dead.clear()
                candidates = ranked
            src = candidates[0]
            try:
                async for d in src.watch():
                    if d.round > latest:
                        if not await self._check_result(d):
                            # invalid stream data: treat like a stream
                            # error — rotate to the next source
                            raise ValueError(
                                f"invalid beacon for round {d.round}")
                        latest = d.round
                        dead.clear()
                        rotations = 0
                        yield d
            except Exception as exc:
                log.debug("optimizing watch: source failed: %s", exc)
            # stream ended or errored: record the failure in the score
            # immediately — the next rotation must not re-pick a
            # known-dead source first — and pace the resubscribe
            self._note_failure(src)
            dead.add(id(src))
            rotations += 1
            await self._watch_policy.pace("client.optimizing.watch",
                                          rotations)

    async def info(self):
        last_exc = None
        for c in self._ranked():
            try:
                return await c.info()
            except Exception as exc:
                last_exc = exc
        raise last_exc

    def round_at(self, t: float) -> int:
        return self.clients[0].round_at(t)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        await asyncio.gather(*[c.close() for c in self.clients],
                             return_exceptions=True)
