"""LRU caching client (reference `client/cache.go:64-118`)."""

from __future__ import annotations

from collections import OrderedDict

from drand_tpu.client.base import Client, RandomData

DEFAULT_CACHE_SIZE = 32


class CachingClient(Client):
    def __init__(self, inner: Client, size: int = DEFAULT_CACHE_SIZE):
        self.inner = inner
        self.size = size
        self._lru: OrderedDict[int, RandomData] = OrderedDict()

    def _put(self, d: RandomData) -> None:
        self._lru[d.round] = d
        self._lru.move_to_end(d.round)
        while len(self._lru) > self.size:
            self._lru.popitem(last=False)

    async def get(self, round_: int = 0) -> RandomData:
        if round_ and round_ in self._lru:
            self._lru.move_to_end(round_)
            return self._lru[round_]
        d = await self.inner.get(round_)
        if d.round:
            self._put(d)
        return d

    async def watch(self):
        async for d in self.inner.watch():
            self._put(d)
            yield d

    async def info(self):
        return await self.inner.info()

    def round_at(self, t: float) -> int:
        return self.inner.round_at(t)

    async def close(self) -> None:
        await self.inner.close()
