"""The Client interface and result type.

Counterpart of `client/interface.go:13-34` (`Get/Watch/Info/RoundAt/Close`)
and `client/random.go` (`RandomData`).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from drand_tpu.chain.info import Info
from drand_tpu.chain.time import current_round


@dataclass
class RandomData:
    round: int
    signature: bytes
    previous_signature: bytes = b""
    randomness: bytes = b""

    def __post_init__(self):
        if not self.randomness and self.signature:
            self.randomness = hashlib.sha256(self.signature).digest()


class Client:
    """Async randomness source."""

    async def get(self, round_: int = 0) -> RandomData:
        """Round 0 = latest."""
        raise NotImplementedError

    def watch(self):
        """Async iterator of RandomData as new rounds appear."""
        raise NotImplementedError

    async def info(self) -> Info:
        raise NotImplementedError

    def round_at(self, t: float) -> int:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class InfoBackedClient(Client):
    """Base for clients holding chain info."""

    _info: Info | None = None

    async def info(self) -> Info:
        if self._info is None:
            raise RuntimeError("no chain info")
        return self._info

    def round_at(self, t: float) -> int:
        if self._info is None:
            raise RuntimeError("no chain info")
        return current_round(t, self._info.period, self._info.genesis_time)
