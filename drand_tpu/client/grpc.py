"""gRPC randomness client against the Public service
(reference `client/grpc/client.go`): `get` via PublicRand (`:72-83`),
`watch` via PublicRandStream (`:85-120`)."""

from __future__ import annotations


from drand_tpu import log as dlog
from drand_tpu.client.base import InfoBackedClient, RandomData
from drand_tpu.core import convert
from drand_tpu.net.client import PeerClients, make_metadata
from drand_tpu.protogen import drand_pb2

log = dlog.get("client")


class GrpcClient(InfoBackedClient):
    def __init__(self, address: str, tls: bool = False,
                 beacon_id: str = "default", chain_hash: bytes | None = None,
                 peers: PeerClients | None = None):
        self.address = address
        self.tls = tls
        self.beacon_id = beacon_id
        self.chain_hash = chain_hash
        self.peers = peers or PeerClients()
        self._stub = self.peers.public(address, tls)

    def _meta(self):
        return make_metadata(self.beacon_id, self.chain_hash or b"")

    @staticmethod
    def _to_rand(resp) -> RandomData:
        return RandomData(round=resp.round, signature=resp.signature,
                          previous_signature=resp.previous_signature,
                          randomness=resp.randomness)

    async def get(self, round_: int = 0) -> RandomData:
        resp = await self._stub.PublicRand(
            drand_pb2.PublicRandRequest(round=round_, metadata=self._meta()),
            timeout=5.0)
        return self._to_rand(resp)

    async def watch(self):
        call = self._stub.PublicRandStream(
            drand_pb2.PublicRandRequest(round=0, metadata=self._meta()))
        async for resp in call:
            yield self._to_rand(resp)

    async def info(self):
        if self._info is None:
            pkt = await self._stub.ChainInfo(
                drand_pb2.ChainInfoRequest(metadata=self._meta()),
                timeout=5.0)
            info = convert.info_from_proto(pkt)
            if self.chain_hash and info.hash() != self.chain_hash:
                raise ValueError("chain info does not match pinned hash")
            self._info = info
        return self._info

    async def close(self) -> None:
        await self.peers.close()
