"""HTTP randomness client (reference `client/http/http.go`).

REST client against the public API: chain-info fetch with hash check
(`:235-301`), `get` with a 5s default timeout (`:309-360`), watch via
round-boundary polling (`:362-384`, client/poll.go).
"""

from __future__ import annotations

import asyncio
import json

import aiohttp

from drand_tpu import log as dlog
from drand_tpu.chain.info import Info
from drand_tpu.client.base import InfoBackedClient, RandomData

log = dlog.get("client")

GET_TIMEOUT_S = 5.0


def _retry_after_s(resp) -> float:
    """Parse a Retry-After header (delta-seconds form; HTTP-date is not
    worth the dependency — admission-controlled drand nodes send
    integers).  0.0 when absent or unparseable."""
    raw = resp.headers.get("Retry-After", "")
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return 0.0


def raise_for_shed(resp, url: str = "") -> None:
    """Map an overload shed (429/503 + Retry-After) to the typed
    :class:`~drand_tpu.resilience.RetryAfterError` so retry wrappers
    (relay upstream fetch, RetryPolicy.call) honor the server's hint
    instead of hammering its queue."""
    if resp.status in (429, 503):
        from drand_tpu.resilience import RetryAfterError
        raise RetryAfterError(resp.status, _retry_after_s(resp) or 1.0,
                              url=url)


def _parse_rand(d: dict) -> RandomData:
    return RandomData(
        round=int(d["round"]),
        signature=bytes.fromhex(d["signature"]),
        previous_signature=bytes.fromhex(d.get("previous_signature", "")),
        randomness=bytes.fromhex(d.get("randomness", "")))


class HTTPClient(InfoBackedClient):
    def __init__(self, base_url: str, chain_hash: bytes | None = None,
                 info: Info | None = None, clock=None, retry=None):
        self.base_url = base_url.rstrip("/")
        self.chain_hash = chain_hash or (info.hash() if info else None)
        self._info = info
        # optional RetryPolicy: get() then retries transient failures
        # in-source, honoring server Retry-After hints on 429/503.  The
        # default (None) keeps one-shot semantics — the optimizing
        # client's failover owns cross-source retries.
        self._retry = retry
        self._session: aiohttp.ClientSession | None = None
        import time as _t
        # wall-clock fallback is the seam default: round_at() maps real
        # time onto the chain schedule; tests inject `clock`
        self._now = clock or _t.time  # lint: disable=no-wall-clock

    def _url(self, path: str) -> str:
        if self.chain_hash is not None:
            return f"{self.base_url}/{self.chain_hash.hex()}/{path}"
        return f"{self.base_url}/{path}"

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=GET_TIMEOUT_S))
        return self._session

    async def info(self) -> Info:
        """Fetch and pin chain info; verify against the trust-root hash
        (http.go:235-301)."""
        if self._info is not None:
            return self._info
        sess = await self._sess()
        async with sess.get(self._url("info")) as resp:
            resp.raise_for_status()
            body = await resp.read()
        info = Info.from_json(body)   # includes the embedded-hash self-check
        if self.chain_hash is not None and info.hash() != self.chain_hash:
            raise ValueError(
                f"chain info from {self.base_url} does not match pinned "
                f"hash {self.chain_hash.hex()}")
        self._info = info
        return info

    async def get(self, round_: int = 0) -> RandomData:
        if self._retry is not None:
            return await self._retry.call(
                "client.http.get", lambda attempt: self._get_once(round_),
                key=f"r{round_}")
        return await self._get_once(round_)

    async def _get_once(self, round_: int) -> RandomData:
        from drand_tpu import tracing
        sess = await self._sess()
        path = "public/latest" if round_ == 0 else f"public/{round_}"
        url = self._url(path)
        with tracing.span("client.request",
                          round_=round_ if round_ else None,
                          source=self.base_url, op="get"):
            async with sess.get(url) as resp:
                raise_for_shed(resp, url=url)
                resp.raise_for_status()
                return _parse_rand(json.loads(await resp.text()))

    async def watch(self):
        """Poll each round boundary (client/poll.go:13-61)."""
        info = await self.info()
        from drand_tpu.chain.time import next_round_at
        while True:
            _, t = next_round_at(self._now(), info.period, info.genesis_time)
            delay = max(t - self._now(), 0) + 0.2
            # schedule-driven poll cadence (next round boundary), not
            # retry pacing: backoff/jitter would only delay the fetch
            # past the round it is timed to catch
            await asyncio.sleep(delay)  # lint: disable=no-adhoc-retry
            try:
                yield await self.get(0)
            except Exception as exc:
                log.debug("watch poll failed: %s", exc)

    def round_at(self, t: float) -> int:
        if self._info is None:
            raise RuntimeError("info() not fetched yet")
        return super().round_at(t)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
