"""Client builder: assemble the middleware stack.

Counterpart of `client.New(options...)` (client/client.go:20-107): per
source verifying wrappers -> optimizing -> caching -> watch aggregation.
"""

from __future__ import annotations

from drand_tpu.chain.info import Info
from drand_tpu.client.aggregator import WatchAggregator
from drand_tpu.client.base import Client
from drand_tpu.client.cache import CachingClient
from drand_tpu.client.http import HTTPClient
from drand_tpu.client.optimizing import OptimizingClient
from drand_tpu.client.verify import VerifyingClient


def new_client(urls: list[str] | None = None,
               grpc_addrs: list[str] | None = None,
               chain_hash: bytes | None = None,
               chain_info: Info | None = None,
               insecure: bool = False,
               full_chain_verification: bool = False,
               cache_size: int = 32,
               auto_watch: bool = False,
               speed_test_interval: float = 300.0,
               with_metrics: bool = False) -> Client:
    """Build a verified randomness client from HTTP and/or gRPC sources.

    A root of trust (chain_hash or chain_info) is required unless
    `insecure` — matching the reference's hard requirement
    (client/client.go:124-151).  `with_metrics` instruments every source
    with per-request counters/latency and watch lag through
    `drand_tpu.metrics` (the reference's `WithPrometheus` option,
    client/metric.go)."""
    if chain_hash is None and chain_info is not None:
        chain_hash = chain_info.hash()
    if chain_hash is None and not insecure:
        raise ValueError(
            "no root of trust: pass chain_hash/chain_info or insecure=True")

    sources: list[Client] = []
    for url in urls or []:
        c: Client = HTTPClient(url, chain_hash=chain_hash, info=chain_info)
        if with_metrics:
            from drand_tpu.client.metrics import MetricsClient
            c = MetricsClient(c, url)
        if not insecure:
            c = VerifyingClient(c, full_verify=full_chain_verification)
        sources.append(c)
    for addr in grpc_addrs or []:
        from drand_tpu.client.grpc import GrpcClient
        c = GrpcClient(addr, chain_hash=chain_hash)
        if with_metrics:
            from drand_tpu.client.metrics import MetricsClient
            c = MetricsClient(c, addr)
        if not insecure:
            c = VerifyingClient(c, full_verify=full_chain_verification)
        sources.append(c)
    if not sources:
        raise ValueError("no sources given")

    stack: Client = sources[0] if len(sources) == 1 else OptimizingClient(
        sources, speed_test_interval=speed_test_interval)
    if isinstance(stack, OptimizingClient) and speed_test_interval > 0:
        stack.start_speed_tests()
    stack = CachingClient(stack, size=cache_size)
    return WatchAggregator(stack, auto_watch=auto_watch)
