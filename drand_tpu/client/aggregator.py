"""Watch aggregation: one upstream watch fanned out to many subscribers,
with de-duplication and auto-restart (reference `client/aggregator.go`)."""

from __future__ import annotations

import asyncio

from drand_tpu import log as dlog
from drand_tpu.client.base import Client, RandomData

log = dlog.get("client")


class WatchAggregator(Client):
    def __init__(self, inner: Client, auto_watch: bool = False,
                 resilience=None):
        from drand_tpu.resilience import Resilience
        self.inner = inner
        self.resilience = resilience or Resilience()
        self._subs: list[asyncio.Queue] = []
        self._task: asyncio.Task | None = None
        self._latest_round = 0
        if auto_watch:
            self._ensure_watch()

    def _ensure_watch(self):
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self):
        # RetryPolicy-paced restart (full jitter, reset on progress)
        # instead of the old fixed 1 s sleep
        failures = 0
        while True:
            try:
                async for d in self.inner.watch():
                    if d.round <= self._latest_round:
                        continue            # dedup across restarts
                    failures = 0
                    self._latest_round = d.round
                    for q in list(self._subs):
                        try:
                            q.put_nowait(d)
                        except asyncio.QueueFull:
                            pass
            except asyncio.CancelledError:
                return
            except Exception as exc:
                failures += 1
                log.warning("aggregated watch failed (%d consecutive), "
                            "restarting: %s", failures, exc)
            await self.resilience.retry.pace("client.aggregator.watch",
                                             failures)

    async def get(self, round_: int = 0) -> RandomData:
        return await self.inner.get(round_)

    async def watch(self):
        self._ensure_watch()
        q: asyncio.Queue = asyncio.Queue(maxsize=16)
        self._subs.append(q)
        try:
            while True:
                yield await q.get()
        finally:
            self._subs.remove(q)

    async def info(self):
        return await self.inner.info()

    def round_at(self, t: float) -> int:
        return self.inner.round_at(t)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        await self.inner.close()
