"""Client-side metrics middleware.

Counterpart of the reference's instrumented client stack: per-source
request counters/latency (`client/http/http.go:146-177` wraps the HTTP
transport in promhttp instrumentation) and the watch-latency observer
(`client/metric.go:11-52` measures each watched round against its
expected wall-clock time).  Collectors live in `drand_tpu.metrics`'s
shared REGISTRY, so a daemon or relay embedding the SDK exports them
through the same /metrics endpoint as the protocol gauges.
"""

from __future__ import annotations

import time

from drand_tpu import metrics as M
from drand_tpu.client.base import Client, RandomData


class MetricsClient(Client):
    """Wrap a source with request/watch instrumentation.

    `source` is the metric label (the upstream URL or gRPC address).
    """

    def __init__(self, inner: Client, source: str, clock=None):
        self.inner = inner
        self.source = source
        # watch latency compares arrival against the round's scheduled
        # wall time; tests inject `clock`, production reads the system
        self._now = clock or time.time  # lint: disable=no-wall-clock

    async def _timed(self, op: str, coro):
        t0 = time.monotonic()
        try:
            result = await coro
        except Exception:
            M.CLIENT_REQUESTS.labels(self.source, op, "error").inc()
            raise
        M.CLIENT_REQUESTS.labels(self.source, op, "ok").inc()
        M.CLIENT_REQUEST_LATENCY.labels(self.source, op).set(
            1000.0 * (time.monotonic() - t0))
        return result

    async def get(self, round_: int = 0) -> RandomData:
        return await self._timed("get", self.inner.get(round_))

    async def info(self):
        return await self._timed("info", self.inner.info())

    async def watch(self):
        """Pass rounds through, setting the watch-latency gauge to
        arrival-minus-expected per round (client/metric.go:28-45).  The
        chain info is fetched lazily; without it the rounds still flow,
        uninstrumented."""
        info = None
        try:
            info = await self.inner.info()
        except Exception:
            pass
        async for d in self.inner.watch():
            if info is not None:
                expected = info.genesis_time + (d.round - 1) * info.period
                M.CLIENT_WATCH_LATENCY.labels(self.source).set(
                    1000.0 * (self._now() - expected))
            yield d

    def round_at(self, t: float) -> int:
        return self.inner.round_at(t)

    async def close(self) -> None:
        await self.inner.close()
