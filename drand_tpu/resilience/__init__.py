"""Unified resilience layer: every remote call shares one policy seam.

The reference daemon survives flaky networks with per-call gRPC backoff
and reconnect logic (`net/client_grpc.go:37-49`, lp2p reconnect); before
this package our port had none of it — one-shot RPCs under a flat 60 s
timeout, relays retrying on bare fixed sleeps, and the sync manager
re-shuffling peers blindly.  This package is that missing layer, grown
into four policies every remote-call site routes through:

  - :mod:`policy` — :class:`RetryPolicy`: exponential backoff with full
    jitter.  Backoff values are **pure hashes** of (seed, site, peer,
    key, attempt) — not draws from a shared RNG stream — and sleeps ride
    the injected Clock, so retry schedules are byte-deterministic under
    ``drand-tpu chaos replay`` and land in the same decision log the
    chaos subsystem prints.
  - :mod:`breaker` — per-peer circuit breakers (closed/open/half-open):
    trip on consecutive failures, probe on half-open, feed
    ``drand_breaker_state{peer}`` and the health watchdog's
    :class:`~drand_tpu.health.watchdog.PeerStateTracker`.
  - :mod:`deadline` — per-operation deadline budgets derived from round
    timing (a partial for round *r* is worthless once *r* settles, so
    its send gets ``period/2``, not 60 s), propagated over RPC via the
    Metadata ``deadline_ms`` field and honored server-side so doomed
    work is shed before it burns a verify slot.
  - :mod:`hedge` — hedged requests (Dean & Barroso, "The Tail at
    Scale"): delayed secondary launch, first success wins, losers
    cancelled — the client fetch path and the sync manager's peer
    dispatch.

:class:`Resilience` bundles the per-daemon instances (one shared hub
per daemon, like :class:`~drand_tpu.net.client.PeerClients`), all on
the daemon's injected clock.
"""

from __future__ import annotations

from drand_tpu.beacon.clock import Clock, SystemClock
from drand_tpu.resilience.admission import (AdmissionController,
                                            AdmissionShedError, ClassLimits)
from drand_tpu.resilience.breaker import (BreakerRegistry, CircuitBreaker,
                                          state_name)
from drand_tpu.resilience.deadline import Deadline, DeadlineExceededError, \
    partial_broadcast_budget
from drand_tpu.resilience.hedge import first_success
from drand_tpu.resilience.policy import (LOG, BreakerOpenError,
                                         RetryAfterError, RetryPolicy)


class Resilience:
    """One daemon's shared resilience hub: retry policy + breaker
    registry on the daemon's injected clock.  Components that can run
    standalone (relays, the client SDK) build their own when none is
    passed in."""

    def __init__(self, clock: Clock | None = None, seed: int = 0,
                 retry: RetryPolicy | None = None,
                 breakers: BreakerRegistry | None = None):
        self.clock = clock or SystemClock()
        self.retry = retry or RetryPolicy(clock=self.clock, seed=seed)
        self.breakers = breakers or BreakerRegistry(self.clock)

    def snapshot(self) -> dict:
        """Operator view (served at /debug/resilience)."""
        return {"breakers": self.breakers.snapshot(),
                "decisions": LOG.entries()[-200:]}


__all__ = ["Resilience", "RetryPolicy", "BreakerRegistry", "CircuitBreaker",
           "Deadline", "DeadlineExceededError", "BreakerOpenError",
           "AdmissionController", "AdmissionShedError", "ClassLimits",
           "RetryAfterError",
           "partial_broadcast_budget", "first_success", "state_name", "LOG"]
