"""Hedged requests: delayed secondary launch, first success wins.

The tail-latency playbook of Dean & Barroso, "The Tail at Scale" (CACM
2013): issue the request to the best candidate; if it has not answered
after `delay_s`, launch the next candidate *without* cancelling the
first; the first SUCCESS wins and every loser is cancelled.  A fast
*failure* skips the delay — the next candidate launches immediately —
so a dead primary costs one RTT, not one hedge window.

Used by the optimizing client's fetch path (client/optimizing.py) and
the sync manager's peer dispatch (beacon/sync_manager.py).  Launch and
win/loss counts land in ``drand_hedge_requests_total{site,outcome}``.
"""

from __future__ import annotations

import asyncio

from drand_tpu.beacon.clock import Clock


def _count(site: str, outcome: str) -> None:
    try:
        from drand_tpu import metrics as M
        M.HEDGE_REQUESTS.labels(site, outcome).inc()
    except Exception:
        pass


async def first_success(site: str, launchers, *, delay_s: float,
                        clock: Clock):
    """Run `launchers` (ordered best-first zero-arg callables returning
    awaitables) hedged: next candidate after `delay_s` on the injected
    clock, or immediately when every in-flight attempt has failed.
    Returns the first successful result; cancels the rest.  Raises the
    last failure when every candidate fails."""
    queue = list(launchers)
    if not queue:
        raise ValueError("first_success: no launchers")
    pending: set[asyncio.Task] = set()
    timer: asyncio.Task | None = None
    last_exc: BaseException | None = None
    launched = 0

    def launch() -> None:
        nonlocal launched
        fn = queue.pop(0)
        pending.add(asyncio.ensure_future(fn()))
        _count(site, "primary" if launched == 0 else "hedged")
        launched += 1

    try:
        launch()
        while pending:
            wait_set = set(pending)
            if queue and timer is None:
                timer = asyncio.ensure_future(clock.sleep(delay_s))
            if timer is not None:
                wait_set.add(timer)
            done, _ = await asyncio.wait(wait_set,
                                         return_when=asyncio.FIRST_COMPLETED)
            if timer is not None and timer in done:
                done.discard(timer)
                timer = None
                if queue:
                    launch()
            for t in done:
                pending.discard(t)
                exc = t.exception()
                if exc is None:
                    _count(site, "win")
                    return t.result()
                last_exc = exc
                if queue:
                    # fast failure: hedge immediately, reset the window
                    if timer is not None:
                        timer.cancel()
                        timer = None
                    launch()
        assert last_exc is not None
        raise last_exc
    finally:
        if timer is not None:
            timer.cancel()
        for t in pending:
            t.cancel()
        if pending:
            # retrieve cancellations so the loop never logs
            # "Task exception was never retrieved" for a hedged loser
            await asyncio.gather(*pending, return_exceptions=True)
