"""Deterministic retry policies: exponential backoff with full jitter.

Counterpart of the reference's per-call gRPC backoff
(`net/client_grpc.go:37-49` grpc_retry interceptor + reconnect loops).
Two properties the reference does not have, both required by the chaos
replay contract (drand_tpu/chaos):

  - **Backoff is structural, not stream-based.**  A delay is a pure
    hash of ``(seed, site, peer, key, attempt)`` — NOT a draw from a
    shared RNG — so concurrent retry chains racing on the event loop
    cannot perturb each other's schedules.  Same seed + same call
    context ⇒ same schedule, regardless of arrival order.  While a
    chaos schedule is armed its seed (or the scenario's explicit
    override) takes precedence, so ``chaos replay --seed S`` reproduces
    retry timing byte-for-byte.
  - **Sleeps ride the injected Clock**, so fake-clock scenarios drive
    retries deterministically and a drain loop can flush pending
    backoffs by advancing time.

Every decision lands in the module :data:`LOG` (bounded, aliased like
the chaos injection log) and the ``drand_retry_attempts_total``
counter, so a replayed scenario prints retries next to its injections.
"""

from __future__ import annotations

import asyncio
import hashlib
import threading

from drand_tpu.beacon.clock import Clock, SystemClock
from drand_tpu.resilience.deadline import Deadline, DeadlineExceededError

DEFAULT_MAX_ATTEMPTS = 4      # 1 try + 3 retries
DEFAULT_BASE_S = 0.25         # first-retry backoff ceiling
DEFAULT_CAP_S = 8.0           # backoff ceiling growth stops here
MAX_LOG = 10_000              # decision-log ring bound (soaks must not OOM)


class BreakerOpenError(ConnectionError):
    """A call refused because the target peer's circuit breaker is open
    (drand_tpu/resilience/breaker.py)."""

    def __init__(self, peer: str):
        super().__init__(f"circuit breaker open for peer {peer or '?'}")
        self.peer = peer


class RetryAfterError(ConnectionError):
    """A server shed the request with an explicit ``Retry-After`` hint
    (HTTP 429/503 from an admission-controlled node — the client half
    of drand_tpu/resilience/admission.py).  :meth:`RetryPolicy.call`
    honors ``retry_after_s``: the next attempt waits at least the hint,
    capped at the call's deadline budget — retrying sooner would only
    land back in the shedding server's queue."""

    def __init__(self, status: int, retry_after_s: float, url: str = ""):
        super().__init__(
            f"server shed ({status}) at {url or '?'}: retry after "
            f"{retry_after_s:.1f}s")
        self.status = int(status)
        self.retry_after_s = float(retry_after_s)
        self.url = url


# -- retryable-error classification -----------------------------------------

# gRPC codes that signal a transient transport/serving condition; the
# classification mirrors the reference's grpc_retry default set plus
# UNKNOWN (a fault injected inside a peer's handler surfaces as UNKNOWN
# on our side of the wire — exactly the case retries must cover).
_RETRYABLE_GRPC = frozenset({
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED", "ABORTED",
    "UNKNOWN",
})


def classify_error(exc: BaseException) -> bool:
    """True when `exc` is worth retrying: transient transport and
    injected-fault errors, not protocol/usage errors."""
    import grpc

    from drand_tpu.chaos.failpoints import FaultInjectedError
    if isinstance(exc, grpc.aio.AioRpcError):
        return exc.code().name in _RETRYABLE_GRPC
    if isinstance(exc, FaultInjectedError):
        # chaos models network faults at the send seam: retryable by
        # construction (the recovery path is what chaos exercises)
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, asyncio.TimeoutError,
                        OSError)):
        return True
    if isinstance(exc, grpc.RpcError):
        return True
    return False


# -- the decision log --------------------------------------------------------

class DecisionLog:
    """Bounded, thread-safe log of retry decisions and breaker
    transitions — the resilience half of the chaos replay contract.
    Peer identifiers are aliased to stable labels (``node0``…) the same
    way the chaos Schedule aliases its injection contexts, so two runs
    of a seeded scenario produce identical logs despite OS-assigned
    ports."""

    def __init__(self):
        self._entries: list[dict] = []
        self._aliases: dict[str, str] = {}
        self._lock = threading.Lock()

    def set_aliases(self, aliases: dict[str, str]) -> None:
        with self._lock:
            self._aliases = dict(aliases)

    def alias(self, v):
        if not isinstance(v, str):
            return v
        with self._lock:
            return self._aliases.get(v, v)

    def note(self, **entry) -> None:
        entry = {k: self.alias(v) for k, v in entry.items()}
        with self._lock:
            if len(self._entries) < MAX_LOG:
                self._entries.append(entry)

    def entries(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries]

    def summary(self) -> list[tuple]:
        """Sorted, deduplicated decisions — the replay-comparison form
        (arrival order is scheduling-dependent; the SET is the seeded
        policies' deterministic output)."""
        seen = {tuple(sorted((k, str(v)) for k, v in e.items()))
                for e in self.entries()}
        return sorted(seen)

    def reset(self) -> None:
        with self._lock:
            self._entries = []
            self._aliases = {}


LOG = DecisionLog()

# Scenario-wide seed override (drand_tpu/chaos/runner.py): backoff
# hashing prefers, in order, this override, the armed chaos schedule's
# seed, the policy instance's own seed — so one `--seed S` pins every
# policy in an in-process multi-node net without re-wiring daemons.
_seed_override: int | None = None


def set_seed_override(seed: int | None) -> None:
    global _seed_override
    _seed_override = seed


# In-flight backoff sleeps: scenario drains advance the fake clock until
# this reaches zero so every retry chain runs to its logged conclusion
# before the decision log is compared across runs.
_inflight = 0
_inflight_lock = threading.Lock()


def inflight() -> int:
    return _inflight


def _hash_frac(*parts) -> float:
    h = hashlib.sha256("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / 2 ** 64


class RetryPolicy:
    """Exponential backoff with full jitter over a deterministic hash.

    `call(site, fn, ...)` drives attempt loops for request/response
    sites; `pace(site, failures)` paces supervised watch loops (the
    relay shape, where the "attempt" is a long-lived stream)."""

    def __init__(self, max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 base_s: float = DEFAULT_BASE_S,
                 cap_s: float = DEFAULT_CAP_S,
                 seed: int = 0, clock: Clock | None = None):
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.seed = seed
        self.clock = clock or SystemClock()

    def _seed(self) -> int:
        if _seed_override is not None:
            return _seed_override
        from drand_tpu.chaos import failpoints
        sched = failpoints.active()
        return sched.seed if sched is not None else self.seed

    def backoff_s(self, site: str, attempt: int, peer: str = "",
                  key: str = "") -> float:
        """Full-jitter delay before retry `attempt` (1-based): uniform
        in [0, min(cap, base * 2^(attempt-1))), hash-derived."""
        ceiling = min(self.cap_s, self.base_s * (2 ** max(attempt - 1, 0)))
        frac = _hash_frac(self._seed(), site, LOG.alias(peer), key, attempt)
        return frac * ceiling

    async def _sleep(self, delay: float) -> None:
        global _inflight
        with _inflight_lock:
            _inflight += 1
        try:
            await self.clock.sleep(delay)
        finally:
            with _inflight_lock:
                _inflight -= 1

    def _count(self, site: str, outcome: str) -> None:
        try:
            from drand_tpu import metrics as M
            M.RETRY_ATTEMPTS.labels(site, outcome).inc()
        except Exception:
            pass

    async def call(self, site: str, fn, *, peer: str = "", key: str = "",
                   deadline: Deadline | None = None, breaker=None,
                   classify=classify_error):
        """Run ``await fn(attempt)`` until success, a non-retryable
        error, attempt/deadline exhaustion, or an open breaker.  `fn`
        receives the 0-based attempt index.  `breaker` (a
        :class:`~drand_tpu.resilience.breaker.CircuitBreaker`) gates
        each attempt and is fed every outcome."""
        attempt = 0
        while True:
            if breaker is not None and not breaker.allow():
                self._count(site, "breaker_open")
                LOG.note(kind="retry", site=site, peer=peer, key=key,
                         attempt=attempt, outcome="breaker_open")
                raise BreakerOpenError(peer)
            if deadline is not None and deadline.expired:
                self._count(site, "deadline")
                LOG.note(kind="retry", site=site, peer=peer, key=key,
                         attempt=attempt, outcome="deadline")
                raise DeadlineExceededError(
                    f"{site}: deadline spent before attempt {attempt}")
            try:
                result = await fn(attempt)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if breaker is not None:
                    breaker.record_failure()
                attempt += 1
                if not classify(exc):
                    self._count(site, "fatal")
                    raise
                if attempt >= self.max_attempts:
                    self._count(site, "exhausted")
                    LOG.note(kind="retry", site=site, peer=peer, key=key,
                             attempt=attempt, outcome="exhausted")
                    raise
                delay = self.backoff_s(site, attempt, peer=peer, key=key)
                # a server-provided Retry-After hint floors the backoff
                # (retrying sooner just re-joins the shed queue), capped
                # at the ceiling so a hostile hint can't pin the caller;
                # the deadline check below caps it at the budget
                hint = getattr(exc, "retry_after_s", 0.0) or 0.0
                if hint > 0:
                    delay = max(delay, min(float(hint), self.cap_s))
                if deadline is not None and deadline.remaining() <= delay:
                    self._count(site, "deadline")
                    LOG.note(kind="retry", site=site, peer=peer, key=key,
                             attempt=attempt, outcome="deadline")
                    raise
                self._count(site, "retry")
                LOG.note(kind="retry", site=site, peer=peer, key=key,
                         attempt=attempt, backoff_ms=int(delay * 1000),
                         outcome="retry")
                await self._sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                self._count(site, "success")
                if attempt:
                    # only logged when the call actually retried: a
                    # first-attempt success is the boring steady state
                    LOG.note(kind="retry", site=site, peer=peer, key=key,
                             attempt=attempt, outcome="success")
                return result

    async def pace(self, site: str, failures: int, key: str = "") -> float:
        """Backoff pacing for supervised watch loops: sleep the
        attempt-`failures` full-jitter delay on the injected clock and
        return it.  The loop owns the failure counter (reset it on
        progress); this owns the schedule, so a fleet of relays watching
        one dead upstream spreads out instead of hammering in lockstep."""
        delay = self.backoff_s(site, max(failures, 1), key=key)
        self._count(site, "retry")
        LOG.note(kind="retry", site=site, key=key,
                 attempt=max(failures, 1), backoff_ms=int(delay * 1000),
                 outcome="retry")
        await self._sleep(delay)
        return delay
