"""Admission control for the public serving surface.

The HTTP API and the relay frontend used to accept every connection and
let aiohttp fan them all onto the event loop — under overload the node
would not degrade, it would collapse: every request slower, including
the `/health` probe a load balancer uses to decide whether to keep
sending traffic.  This module is the SEDA-style bounded-queue admission
stage (Welsh et al.) in front of those handlers, the server-side half of
"The Tail at Scale": hedging and retries only flatten tails when an
overloaded server *sheds* excess load fast (503 + ``Retry-After``)
instead of queueing it into timeout territory.

Design:

  - **Priority classes.**  Each :class:`ClassLimits` entry is one
    isolated lane: its own concurrency bound and its own bounded FIFO
    pending queue.  ``public`` (randomness traffic) and ``probe``
    (health/debug — a load balancer's view of the node) never share a
    queue, so a flood of `/public/latest` cannot starve `/health` into
    flapping the whole node out of rotation.
  - **Bounded queue, immediate shed.**  A request past the concurrency
    bound waits in the lane's queue up to ``max_queue`` deep and
    ``queue_timeout_s`` long; past either bound it is shed *now* with a
    ``Retry-After`` hint instead of holding a connection it cannot
    serve.  Shed work costs one counter increment, not a worker.
  - **Metrics are the contract.**  ``drand_serve_inflight{class}``,
    ``drand_serve_shed_total{route,class}`` and
    ``drand_serve_latency_seconds{route,class}`` feed the same
    dashboard/SLO surface the health subsystem watches; the load
    harness (tools/bench_serve.py) and the serve smoke stage assert
    over them.

This module is transport-agnostic (raises :class:`AdmissionShedError`;
the aiohttp layers translate to 503) so the gRPC gateway can grow the
same stage later.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

PUBLIC = "public"       # randomness traffic: /public/*, /info, /chains
PROBE = "probe"         # health/debug probes: load-balancer lifeline


class AdmissionShedError(Exception):
    """Request shed by the admission stage (translate to HTTP 503)."""

    def __init__(self, cls: str, reason: str, retry_after_s: float):
        super().__init__(f"admission shed ({cls}/{reason}): retry after "
                         f"{retry_after_s:.1f}s")
        self.cls = cls
        self.reason = reason            # "queue_full" | "queue_timeout"
        self.retry_after_s = retry_after_s


@dataclass
class ClassLimits:
    """One priority lane's bounds.  Defaults size the public lane for a
    single-node deployment: 64 concurrent handlers (aiohttp handlers are
    cheap coroutines; the bound protects the stores and the loop, not
    threads) plus a 256-deep pending queue — past that the node is in
    overload and honesty (503 now) beats a timeout later."""

    max_concurrency: int = 64
    max_queue: int = 256
    queue_timeout_s: float = 2.0
    retry_after_s: float = 1.0          # shed hint floor


class _Lane:
    def __init__(self, name: str, limits: ClassLimits):
        self.name = name
        self.limits = limits
        self.inflight = 0
        self.waiting = 0
        self.admitted_total = 0
        self.shed_total = 0
        self._wakeups: "asyncio.Queue[None] | None" = None
        self._waiters: list[asyncio.Future] = []

    def _gauge(self) -> None:
        try:
            from drand_tpu import metrics as M
            M.SERVE_INFLIGHT.labels(self.name).set(self.inflight)
        except Exception:
            pass

    def acquire_now(self) -> bool:
        if self.inflight < self.limits.max_concurrency:
            self.inflight += 1
            self.admitted_total += 1
            self._gauge()
            return True
        return False

    def release(self) -> None:
        self.inflight -= 1
        self._gauge()
        # FIFO hand-off: wake the oldest waiter still pending
        while self._waiters:
            fut = self._waiters.pop(0)
            if not fut.done():
                fut.set_result(None)
                break

    def enqueue(self) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        return fut

    def forget(self, fut: asyncio.Future) -> None:
        if fut in self._waiters:
            self._waiters.remove(fut)


class AdmissionController:
    """Per-class bounded-concurrency/bounded-queue admission.

    Usage (the shape the admission-guard lint rule checks for on public
    aiohttp routes)::

        async with self.admission.slot(admission.PUBLIC, "latest"):
            ... handle ...
    """

    def __init__(self, limits: "dict[str, ClassLimits] | None" = None):
        base = {PUBLIC: ClassLimits(),
                PROBE: ClassLimits(max_concurrency=16, max_queue=0,
                                   queue_timeout_s=0.0,
                                   retry_after_s=1.0)}
        base.update(limits or {})
        self._lanes = {name: _Lane(name, lim) for name, lim in base.items()}

    def lane(self, cls: str) -> _Lane:
        return self._lanes[cls]

    def retry_after(self, cls: str) -> float:
        """Shed hint: how long until this lane plausibly has room.  Scales
        with backlog — a queue 2x the concurrency bound suggests at least
        two service generations of wait — floored at the configured
        hint so clients never hammer at sub-second cadence."""
        lane = self._lanes[cls]
        depth = lane.waiting + max(lane.inflight -
                                   lane.limits.max_concurrency, 0)
        gens = depth / max(lane.limits.max_concurrency, 1)
        return max(lane.limits.retry_after_s,
                   round(gens * lane.limits.retry_after_s, 1))

    def _shed(self, lane: _Lane, route: str, reason: str) -> None:
        lane.shed_total += 1
        try:
            from drand_tpu import metrics as M
            M.SERVE_SHED.labels(route, lane.name, reason).inc()
        except Exception:
            pass
        raise AdmissionShedError(lane.name, reason,
                                 self.retry_after(lane.name))

    def slot(self, cls: str, route: str) -> "_Slot":
        """Async context manager: admit (or shed) on enter, release and
        record ``drand_serve_latency_seconds{route,class}`` on exit."""
        return _Slot(self, self._lanes[cls], route)

    async def _admit(self, lane: _Lane, route: str) -> None:
        if lane.acquire_now():
            return
        if lane.waiting >= lane.limits.max_queue:
            self._shed(lane, route, "queue_full")
        lane.waiting += 1
        fut = lane.enqueue()
        try:
            await asyncio.wait_for(fut, lane.limits.queue_timeout_s)
        except asyncio.TimeoutError:
            lane.forget(fut)
            if fut.done() and not fut.cancelled():
                # a release() raced the timeout and handed us the slot:
                # pass it on rather than stranding it
                lane.inflight += 1
                lane.release()
            self._shed(lane, route, "queue_timeout")
        except asyncio.CancelledError:
            # client went away while queued: hand the wakeup (if any
            # arrived concurrently) to the next waiter instead of
            # stranding a slot
            lane.forget(fut)
            if fut.done() and not fut.cancelled():
                lane.inflight += 1
                lane.release()
            raise
        finally:
            lane.waiting -= 1
        # woken by release(): the releaser's slot transfers to us
        lane.inflight += 1
        lane.admitted_total += 1
        lane._gauge()

    def snapshot(self) -> dict:
        """Operator view (served at /debug/serve on the metrics port)."""
        out = {}
        for name, lane in self._lanes.items():
            out[name] = {
                "inflight": lane.inflight,
                "waiting": lane.waiting,
                "max_concurrency": lane.limits.max_concurrency,
                "max_queue": lane.limits.max_queue,
                "admitted_total": lane.admitted_total,
                "shed_total": lane.shed_total,
            }
        return out


class _Slot:
    def __init__(self, ctrl: AdmissionController, lane: _Lane, route: str):
        self.ctrl = ctrl
        self.lane = lane
        self.route = route
        self._t0 = 0.0

    async def __aenter__(self) -> "_Slot":
        await self.ctrl._admit(self.lane, self.route)
        self._t0 = asyncio.get_running_loop().time()
        return self

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self.lane.release()
        try:
            from drand_tpu import metrics as M
            M.SERVE_LATENCY.labels(self.route, self.lane.name).observe(
                asyncio.get_running_loop().time() - self._t0)
        except Exception:
            pass
