"""Per-operation deadline budgets derived from round timing.

The flat 60 s `DEFAULT_TIMEOUT_S` (net/gateway.py) is the wrong budget
for almost every RPC this daemon makes: a partial signature for round
*r* is worthless the moment *r* settles, so its send budget is half the
group period — a stuck peer costs half a round, not a minute of pinned
broadcast task (visible in `/debug/tasks` pre-PR5).

A :class:`Deadline` is an *absolute* point on the protocol clock (the
injected Clock seam — drand nodes already require agreeing clocks for
round arithmetic, so an absolute deadline is meaningful across the
group).  It propagates over RPC via the Metadata ``deadline_ms`` field
(field 6 — ours alone; the reference stops at 3 and proto3 ignores
unknown fields) and is honored server-side: a request whose budget
already expired in flight is shed before it burns a verify slot
(core/services.py).
"""

from __future__ import annotations

from drand_tpu.beacon.clock import Clock

# floor so pathological configs (sub-second periods) still give an RPC
# time to cross a real network
MIN_BUDGET_S = 1.0


class DeadlineExceededError(TimeoutError):
    """An operation's deadline budget was spent before it completed."""


class Deadline:
    """An absolute deadline on an injected clock."""

    __slots__ = ("clock", "at")

    def __init__(self, clock: Clock, at: float):
        self.clock = clock
        self.at = float(at)

    @classmethod
    def after(cls, clock: Clock, budget_s: float) -> "Deadline":
        return cls(clock, clock.now() + budget_s)

    def remaining(self) -> float:
        return self.at - self.clock.now()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def timeout(self, cap: float | None = None) -> float:
        """The transport-timeout form (a non-negative duration), capped
        so a far deadline never exceeds the legacy per-call ceiling."""
        t = max(self.remaining(), 0.0)
        return min(t, cap) if cap is not None else t

    def __repr__(self) -> str:
        return f"Deadline(at={self.at:.3f}, remaining={self.remaining():.3f})"


def partial_broadcast_budget(period_s: float) -> float:
    """Budget for one PartialBeacon send: half the round period (the
    partial must land, verify, and aggregate before the round settles),
    floored at MIN_BUDGET_S."""
    return max(float(period_s) / 2.0, MIN_BUDGET_S)


# -- RPC propagation (protobuf Metadata field 6) ----------------------------

def stamp(metadata, deadline: "Deadline | None") -> None:
    """Stamp an outgoing request's Metadata with the absolute deadline
    (epoch milliseconds).  Pre-upgrade Metadata (no field) sends
    unstamped — the server then applies no budget, as before."""
    if deadline is None:
        return
    try:
        metadata.deadline_ms = max(int(deadline.at * 1000), 1)
    except (AttributeError, ValueError):
        pass


def from_metadata(metadata, clock: Clock) -> Deadline | None:
    """The Deadline an incoming request carries, re-anchored on OUR
    clock (absolute epoch ms on the shared protocol clock), or None when
    the caller sent no budget."""
    ms = getattr(metadata, "deadline_ms", 0) if metadata is not None else 0
    if not ms:
        return None
    return Deadline(clock, ms / 1000.0)
