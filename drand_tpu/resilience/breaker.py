"""Per-peer circuit breakers: fail fast at a peer that keeps failing.

The reference gets an approximation of this from gRPC connection
backoff (`net/client_grpc.go` reconnect state) and lp2p's connection
manager; this module makes it explicit and observable:

  closed ──(trip_after consecutive failures)──▶ open
  open ──(reset_timeout_s on the injected clock)──▶ half-open
  half-open ──(one probe: success)──▶ closed
  half-open ──(one probe: failure)──▶ open

Every transition feeds the ``drand_breaker_state{peer}`` gauge
(0=closed, 1=open, 2=half-open), the resilience decision log (so chaos
replay prints breaker behavior next to injections), and an optional
``on_transition`` hook the daemon wires to the health watchdog's
:class:`~drand_tpu.health.watchdog.PeerStateTracker` — a tripped
breaker marks the peer down on the same surface the connectivity pings
feed.

Observations arrive ONLY from RetryPolicy-gated traffic (partial sends,
DKG fanout): those failure sequences are deterministic in fake time, so
trip points replay byte-identically under `chaos replay`.  Watchdog
pings and sync streams read breaker state (peer ranking, the
PeerStateTracker feed) but never write it — mixing their racy
observation timing into the counters would break the replay contract.
Healing therefore rides the half-open probe of the next gated send.
"""

from __future__ import annotations

import threading

from drand_tpu import log as dlog
from drand_tpu.beacon.clock import Clock
from drand_tpu.resilience.policy import LOG

log = dlog.get("resilience")

CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}

# Trip threshold sits ABOVE one RetryPolicy call's worth of failures
# (DEFAULT_MAX_ATTEMPTS - 1 = 3): a single flaky round must not open the
# breaker; a peer failing across rounds must.
DEFAULT_TRIP_AFTER = 5
DEFAULT_RESET_TIMEOUT_S = 10.0


def state_name(state: int) -> str:
    return _NAMES.get(state, str(state))


class CircuitBreaker:
    """One peer's breaker.  Thread-safe bookkeeping (observations arrive
    from loop tasks and the watchdog alike); the clock is the daemon's
    injected one, so fake-clock scenarios drive open→half-open by
    advancing time."""

    def __init__(self, peer: str, clock: Clock,
                 trip_after: int = DEFAULT_TRIP_AFTER,
                 reset_timeout_s: float = DEFAULT_RESET_TIMEOUT_S,
                 on_transition=None):
        self.peer = peer
        self.clock = clock
        self.trip_after = trip_after
        self.reset_timeout_s = reset_timeout_s
        self.on_transition = on_transition
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self._lock = threading.Lock()
        self._set_gauge(CLOSED)

    # -- observation ---------------------------------------------------------

    def allow(self) -> bool:
        """May a request go to this peer now?  Half-open admits exactly
        one in-flight probe; its outcome decides the next state."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock.now() - self._opened_at < self.reset_timeout_s:
                    return False
                self._transition(HALF_OPEN)
                self._probing = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN:
                self._probing = False
                self._opened_at = self.clock.now()
                self._transition(OPEN)
            elif self._state == CLOSED and \
                    self._consecutive >= self.trip_after:
                self._opened_at = self.clock.now()
                self._transition(OPEN)
            elif self._state == OPEN:
                # defensive: gated traffic can't reach here (allow()
                # refuses while open), but an out-of-band failure report
                # restarts the probe window — the peer is still down
                self._opened_at = self.clock.now()

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> int:
        return self._state

    def state_name(self) -> str:
        return state_name(self._state)

    def _transition(self, new: int) -> None:
        """Must hold self._lock."""
        old, self._state = self._state, new
        self._set_gauge(new)
        LOG.note(kind="breaker", peer=self.peer,
                 **{"from": state_name(old), "to": state_name(new)})
        if new == OPEN:
            log.warning("breaker OPEN for peer %s (%d consecutive failures)",
                        self.peer, self._consecutive)
        elif old != CLOSED and new == CLOSED:
            log.info("breaker closed for peer %s (peer healed)", self.peer)
        cb = self.on_transition
        if cb is not None:
            try:
                cb(self.peer, new)
            except Exception:
                pass        # observers must never break the data path

    def _set_gauge(self, state: int) -> None:
        try:
            from drand_tpu import metrics as M
            M.BREAKER_STATE.labels(self.peer).set(state)
        except Exception:
            pass


class BreakerRegistry:
    """Per-peer breakers created lazily, all on one clock.  `rank`
    orders peer candidates breaker-aware — closed first, half-open next,
    open last — the replacement for the sync manager's blind shuffle."""

    def __init__(self, clock: Clock, trip_after: int = DEFAULT_TRIP_AFTER,
                 reset_timeout_s: float = DEFAULT_RESET_TIMEOUT_S):
        self.clock = clock
        self.trip_after = trip_after
        self.reset_timeout_s = reset_timeout_s
        self.on_transition = None       # callable(peer, state)
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, peer: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(peer)
            if br is None:
                br = CircuitBreaker(peer, self.clock,
                                    trip_after=self.trip_after,
                                    reset_timeout_s=self.reset_timeout_s,
                                    on_transition=self._notify)
                self._breakers[peer] = br
            return br

    def _notify(self, peer: str, state: int) -> None:
        cb = self.on_transition
        if cb is not None:
            cb(peer, state)

    def state(self, peer: str) -> int:
        with self._lock:
            br = self._breakers.get(peer)
        return br.state if br is not None else CLOSED

    def rank(self, items, key=lambda x: x):
        """Stable-sort `items` by breaker state of `key(item)`: closed
        first, then half-open, then open.  Unknown peers count as
        closed, so fresh peers keep their incoming (shuffled) order."""
        order = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}
        return sorted(items, key=lambda it: order[self.state(key(it) or "")])

    def snapshot(self) -> dict:
        with self._lock:
            return {p: b.state_name() for p, b in sorted(self._breakers.items())}
